"""Redis server + client with AUTH (reference example/redis_c++: brpc as
both a redis-speaking client and a RedisService server)."""
from __future__ import annotations

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import brpc_tpu.policy  # noqa: F401
from brpc_tpu import rpc
from brpc_tpu.policy import redis as redis_proto
from brpc_tpu.policy.auth import RedisAuthenticator

PASSWORD = "open-sesame"


def make_service() -> redis_proto.RedisService:
    svc = redis_proto.RedisService()
    data = {}

    svc.add_handler("AUTH", lambda args: (
        redis_proto.RedisReply(redis_proto.REPLY_STATUS, "OK")
        if bytes(args[0]).decode() == PASSWORD
        else redis_proto.RedisReply(redis_proto.REPLY_ERROR, "ERR denied")))
    svc.add_handler("SET", lambda args: (
        data.__setitem__(bytes(args[0]), bytes(args[1])),
        redis_proto.RedisReply(redis_proto.REPLY_STATUS, "OK"))[1])
    svc.add_handler("GET", lambda args: data.get(bytes(args[0])))
    svc.add_handler("DEL", lambda args: int(
        data.pop(bytes(args[0]), None) is not None))
    return svc


def main() -> None:
    server = rpc.Server()
    server.add_service(make_service())
    assert server.start("mem://redis-example") == 0
    try:
        ch = rpc.Channel()
        ch.init("mem://redis-example", options=rpc.ChannelOptions(
            protocol="redis", timeout_ms=2000,
            auth=RedisAuthenticator(PASSWORD)))
        req = redis_proto.RedisRequest()
        req.add_command("SET", "fabric", "tpu")
        req.add_command("GET", "fabric")
        req.add_command("DEL", "fabric")
        cntl = rpc.Controller()
        resp = ch.call_method("redis", cntl, req, None)
        assert not cntl.failed(), cntl.error_text
        assert resp.reply(1).value == b"tpu"
        print("redis pipeline ->",
              [r.value for r in resp.replies])
    finally:
        server.stop()


if __name__ == "__main__":
    main()
