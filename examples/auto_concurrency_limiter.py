"""Auto concurrency limiter under overload —
example/auto_concurrency_limiter."""
from __future__ import annotations

import threading
import time

from examples.common import EchoRequest, EchoResponse, rpc


def main() -> None:
    opts = rpc.ServerOptions()
    opts.method_max_concurrency = {"EchoService.Echo": "auto"}
    server = rpc.Server(opts)

    from examples.common import EchoService
    server.add_service(EchoService())
    assert server.start("mem://example-autolimit") == 0
    try:
        ch = rpc.Channel()
        ch.init("mem://example-autolimit",
                options=rpc.ChannelOptions(timeout_ms=3000))
        oks = [0]; limited = [0]
        lock = threading.Lock()

        def worker():
            for _ in range(30):
                cntl = rpc.Controller()
                ch.call_method("EchoService.Echo", cntl,
                               EchoRequest(message="x", sleep_us=2000),
                               EchoResponse)
                with lock:
                    if cntl.failed():
                        limited[0] += 1
                    else:
                        oks[0] += 1

        ts = [threading.Thread(target=worker) for _ in range(8)]
        for t in ts: t.start()
        for t in ts: t.join()
        st = server.method_status("EchoService.Echo")
        print(f"ok={oks[0]} rejected={limited[0]} "
              f"adaptive max_concurrency={st.limiter.max_concurrency()}")
    finally:
        server.stop()


if __name__ == "__main__":
    main()
