"""Thrift framed-binary echo (reference example/thrift_extension_c++:
a ThriftService served alongside every other protocol)."""
from __future__ import annotations

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import brpc_tpu.policy  # noqa: F401
from brpc_tpu import rpc
from brpc_tpu.policy.thrift import ThriftMessage, ThriftService, TType

ARG_SPEC = {1: ("name", TType.STRING)}
RESULT_SPEC = {0: ("greeting", TType.STRING)}


def main() -> None:
    svc = ThriftService()
    svc.add_method("Greet",
                   lambda args: {"greeting":
                                 b"hello " + args.get("name", b"?")},
                   ARG_SPEC, RESULT_SPEC)
    server = rpc.Server()
    server.add_service(svc)
    assert server.start("mem://thrift-example") == 0
    try:
        ch = rpc.Channel()
        ch.init("mem://thrift-example",
                options=rpc.ChannelOptions(protocol="thrift",
                                           timeout_ms=2000))
        cntl = rpc.Controller()
        req = ThriftMessage("Greet", {"name": "fabric"}, ARG_SPEC,
                            RESULT_SPEC)
        resp = ch.call_method("Greet", cntl, req, None)
        assert not cntl.failed(), cntl.error_text
        print("thrift ->", resp.values["greeting"])
    finally:
        server.stop()


if __name__ == "__main__":
    main()
