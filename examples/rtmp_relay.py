"""RTMP publish→play relay (the reference's rtmp.h live-streaming API:
one client publishes, the server relays frames to players)."""
from __future__ import annotations

import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import brpc_tpu.policy  # noqa: F401
from brpc_tpu import rpc
from brpc_tpu.policy.rtmp import (RtmpClient, RtmpClientStream,
                                  RtmpServerStream, RtmpService)


class RelayService(RtmpService):
    def __init__(self):
        self.players = {}

    def new_stream(self, remote_side, connect_info):
        relay = self

        class Stream(RtmpServerStream):
            def on_play(s, name):
                relay.players.setdefault(name, []).append(s)
                return 0

            def on_video_message(s, timestamp, data):
                for p in relay.players.get(s.publish_name, []):
                    p.send_video_message(data, timestamp)
        return Stream()


def main() -> None:
    server = rpc.Server()
    server.add_service(RelayService())
    assert server.start("127.0.0.1:0") == 0
    target = f"127.0.0.1:{server.listen_port}"
    try:
        publisher = RtmpClient(target)
        pub = publisher.create_stream()
        assert pub.publish("cam0") == 0

        frames = []

        class Player(RtmpClientStream):
            def on_video_message(self, timestamp, data):
                frames.append((timestamp, len(data)))

        viewer = RtmpClient(target)
        play = viewer.create_stream(Player())
        assert play.play("cam0") == 0

        for i in range(10):
            pub.send_video_message(b"\x17\x01" + bytes(4096), i * 40)
        deadline = time.monotonic() + 5
        while len(frames) < 10 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(frames) == 10, frames
        print(f"relayed {len(frames)} video frames, ts 0..{frames[-1][0]}")
        publisher.stop()
        viewer.stop()
    finally:
        server.stop()


if __name__ == "__main__":
    main()
