"""RPC timeout/cancellation semantics — example/cancel_c++: a late server
response is dropped by the versioned correlation id."""
from __future__ import annotations

import time

from examples.common import EchoRequest, EchoResponse, start_echo_server, rpc
from brpc_tpu.rpc import errors


def main() -> None:
    server = start_echo_server("mem://example-cancel")
    try:
        ch = rpc.Channel()
        ch.init("mem://example-cancel",
                options=rpc.ChannelOptions(timeout_ms=50, max_retry=0))
        cntl = rpc.Controller()
        t0 = time.monotonic()
        ch.call_method("EchoService.Echo", cntl,
                       EchoRequest(message="slow", sleep_us=400_000),
                       EchoResponse)
        dt = (time.monotonic() - t0) * 1000
        assert cntl.error_code == errors.ERPCTIMEDOUT
        print(f"call timed out after {dt:.0f}ms as configured "
              f"({cntl.error_text}); the late response will be ignored")
        time.sleep(0.5)     # server finishes; stale response dropped silently
        print("no crash from the stale response: correlation versioning held")
    finally:
        server.stop()


if __name__ == "__main__":
    main()
