"""Async echo with completion callbacks — example/asynchronous_echo_c++."""
from __future__ import annotations

import threading

from examples.common import EchoRequest, EchoResponse, start_echo_server, rpc


def main() -> None:
    server = start_echo_server("mem://example-async")
    channel = rpc.Channel()
    channel.init("mem://example-async")
    done = threading.Event()
    remaining = [5]
    lock = threading.Lock()

    def on_done(cntl: rpc.Controller) -> None:
        if cntl.failed():
            print("failed:", cntl.error_text)
        else:
            print("async response:", cntl.response.message)
        with lock:
            remaining[0] -= 1
            if remaining[0] == 0:
                done.set()

    for i in range(5):
        channel.call_method("EchoService.Echo", rpc.Controller(),
                            EchoRequest(message=f"async-{i}"), EchoResponse,
                            on_done)
    assert done.wait(10)
    server.stop()


if __name__ == "__main__":
    main()
