"""Sync unary echo — the example/echo_c++ analogue (BASELINE config 1)."""
from __future__ import annotations

from examples.common import EchoRequest, EchoResponse, start_echo_server, rpc


def main() -> None:
    server = start_echo_server("mem://example-echo")
    try:
        channel = rpc.Channel()
        channel.init("mem://example-echo",
                     options=rpc.ChannelOptions(timeout_ms=1000, max_retry=3))
        for i in range(3):
            cntl = rpc.Controller()
            cntl.request_attachment.append(b"attached-bytes")
            response = channel.call_method(
                "EchoService.Echo", cntl,
                EchoRequest(message=f"hello-{i}"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            print(f"echo -> {response.message!r} "
                  f"(latency={cntl.latency_us}us, "
                  f"attachment={cntl.response_attachment.to_bytes()!r})")
    finally:
        server.stop()


if __name__ == "__main__":
    main()
