"""SelectiveChannel: LB between channels with retry-on-other —
example/selective_echo_c++."""
from __future__ import annotations

from examples.common import EchoRequest, EchoResponse, start_echo_server, rpc
from brpc_tpu import channels


def main() -> None:
    live = start_echo_server("mem://example-sel-live", tag="live")
    try:
        schan = channels.SelectiveChannel()
        dead = rpc.Channel()
        dead.init("mem://example-sel-dead")      # nobody listens here
        dead.options.timeout_ms = 200
        dead.options.max_retry = 0
        ok = rpc.Channel()
        ok.init("mem://example-sel-live")
        schan.add_channel(dead)
        schan.add_channel(ok)
        for i in range(4):
            cntl = rpc.Controller()
            resp = schan.call_method("EchoService.Echo", cntl,
                                     EchoRequest(message=f"sel-{i}"),
                                     EchoResponse)
            assert not cntl.failed(), cntl.error_text
            print(f"selected -> {resp.message} (retried={cntl.retried_count})")
    finally:
        live.stop()


if __name__ == "__main__":
    main()
