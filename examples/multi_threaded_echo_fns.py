"""Concurrent echo from tasklets (reference
example/multi_threaded_echo_fns_c++: callers are bthreads started with
bthread_start_background rather than pthreads — here, scheduler
tasklets)."""
from __future__ import annotations

import threading

from examples.common import EchoRequest, EchoResponse, start_echo_server, rpc
from brpc_tpu.bthread import scheduler
from brpc_tpu.bthread.countdown import CountdownEvent


def main(tasklets: int = 16, calls_per_tasklet: int = 5) -> None:
    server = start_echo_server("mem://echo-fns")
    try:
        channel = rpc.Channel()
        channel.init("mem://echo-fns",
                     options=rpc.ChannelOptions(timeout_ms=2000))
        done = CountdownEvent(tasklets)
        ok = [0]
        lock = threading.Lock()

        def worker(wid: int) -> None:
            try:
                for i in range(calls_per_tasklet):
                    cntl = rpc.Controller()
                    resp = channel.call_method(
                        "EchoService.Echo", cntl,
                        EchoRequest(message=f"w{wid}-{i}"), EchoResponse)
                    assert not cntl.failed(), cntl.error_text
                    assert resp.message == f"w{wid}-{i}"
                    with lock:
                        ok[0] += 1
            finally:
                done.signal()

        for wid in range(tasklets):
            scheduler.start_background(worker, wid, name=f"echo-fn-{wid}")
        assert done.wait(30) == 0, "tasklets did not finish"
        assert ok[0] == tasklets * calls_per_tasklet
        print(f"{ok[0]} echoes from {tasklets} tasklets OK")
    finally:
        server.stop()


if __name__ == "__main__":
    main()
