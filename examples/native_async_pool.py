"""Async completion and pooled connections on the native datapath.

The reference's async CallMethod-with-done and pooled-socket shapes
(example/asynchronous_echo_c++, socket.h:256-262), on our native client:
``call_method_async`` returns a future whose done-callback fires from the
channel's reader thread; ``NativePooledChannel`` round-robins N
connections so concurrent large calls overlap in the kernel.
"""
from __future__ import annotations

import threading

from examples.common import EchoRequest, EchoResponse, rpc
from brpc_tpu.butil import native
from brpc_tpu.rpc.native_fabric import (NativeChannel, NativePooledChannel,
                                        NativeServer)


class EchoService(rpc.Service):
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message[::-1]
        done()


def main() -> None:
    if not native.available():
        print("native core unavailable; skipping")
        return
    server = NativeServer()
    server.add_service(EchoService())
    port = server.start()

    # ---- async: fire 8 overlapping calls, completions via callbacks ----
    ch = NativeChannel()
    ch.init(f"127.0.0.1:{port}")
    done_count = [0]
    all_done = threading.Event()

    def on_done(cntl):
        done_count[0] += 1
        if done_count[0] == 8:
            all_done.set()

    futs = []
    for i in range(8):
        cntl = rpc.Controller()
        cntl.timeout_ms = 5000
        futs.append(ch.call_method_async(
            "EchoService.Echo", cntl, EchoRequest(message=f"async-{i}"),
            EchoResponse, done=on_done))
    assert all_done.wait(10)
    for i, fut in enumerate(futs):
        assert fut.wait(1) and not fut.cntl.failed()
        assert fut.response.message == f"async-{i}"[::-1]
    ch.close()
    print(f"async: {len(futs)} overlapping calls completed via callbacks")

    # ---- pooled: concurrent callers over 3 connections -----------------
    pool = NativePooledChannel()
    pool.init(f"127.0.0.1:{port}", nconns=3)
    errs = []

    def worker(wid):
        try:
            for i in range(10):
                cntl = rpc.Controller()
                cntl.timeout_ms = 5000
                resp = pool.call_method(
                    "EchoService.Echo", cntl,
                    EchoRequest(message=f"p{wid}-{i}"), EchoResponse)
                assert not cntl.failed() and \
                    resp.message == f"p{wid}-{i}"[::-1]
        except Exception as e:           # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    pool.close()
    server.stop()
    print("pooled: 4 threads x 10 calls over 3 connections, all verified")


if __name__ == "__main__":
    main()
