"""Memcache binary-protocol client (reference example/memcache_c++).
Runs against an in-process binary-protocol backend so the example is
self-contained; point `target` at a real memcached/couchbase to split."""
from __future__ import annotations

import struct
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import brpc_tpu.policy  # noqa: F401
from brpc_tpu import rpc
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.policy import memcache as mc


def start_backend(name: str):
    """Minimal in-process memcached (binary protocol, get/set only)."""
    from brpc_tpu.rpc.input_messenger import InputMessenger
    from brpc_tpu.rpc.mem_transport import mem_listen
    from brpc_tpu.rpc.protocol import ParseResult, Protocol

    data = {}

    def handle(frame: bytes) -> bytes:
        (magic, opcode, keylen, extraslen, _dt, _vb, bodylen, opaque,
         cas) = mc._HDR.unpack(frame[:24])
        body = frame[24:24 + bodylen]
        key = body[extraslen:extraslen + keylen]
        value = body[extraslen + keylen:]
        status, rextras, rvalue = mc.STATUS_OK, b"", b""
        if opcode == mc.OP_SET:
            data[key] = value
        elif opcode == mc.OP_GET:
            if key in data:
                rextras, rvalue = struct.pack(">I", 0), data[key]
            else:
                status = mc.STATUS_KEY_NOT_FOUND
        return mc._HDR.pack(mc.MAGIC_RESPONSE, opcode, 0, len(rextras), 0,
                            status, len(rextras) + len(rvalue), opaque,
                            cas) + rextras + rvalue

    def parse(source, socket, read_eof, arg):
        raw = source.fetch(len(source)) or b""
        frames, pos = [], 0
        while pos + 24 <= len(raw):
            bodylen = mc._HDR.unpack(raw[pos:pos + 24])[6]
            if pos + 24 + bodylen > len(raw):
                break
            frames.append(raw[pos:pos + 24 + bodylen])
            pos += 24 + bodylen
        if not frames:
            return ParseResult.not_enough_data()
        source.pop_front(pos)
        return ParseResult.ok(frames)

    def process(frames, socket, server):
        socket.write(IOBuf(b"".join(handle(f) for f in frames)))

    messenger = InputMessenger(
        protocols=[Protocol(name="mini_mc", parse=parse,
                            process_request=process)],
        server=object())
    return mem_listen(name, lambda s: setattr(s, "messenger", messenger))


def main() -> None:
    from brpc_tpu.rpc.mem_transport import mem_unlisten
    start_backend("memcache-example")
    try:
        target = "mem://memcache-example"
        ch = rpc.Channel()
        ch.init(target, options=rpc.ChannelOptions(protocol="memcache",
                                                   timeout_ms=2000))
        req = mc.MemcacheRequest()
        req.set("answer", b"42")
        req.get("answer")
        req.get("missing")
        cntl = rpc.Controller()
        resp = ch.call_method("memcache", cntl, req, None)
        assert not cntl.failed(), cntl.error_text
        assert resp.op(1).value == b"42"
        assert resp.op(2).status == mc.STATUS_KEY_NOT_FOUND
        print("memcache -> set ok, get:", resp.op(1).value,
              "miss status:", resp.op(2).status)
    finally:
        try:
            ch.close()
        except NameError:
            pass
        mem_unlisten("memcache-example")


if __name__ == "__main__":
    main()
