"""Cascade echo — a handler that itself calls a downstream server
(reference example/cascade_echo_c++: demonstrates client calls from
inside server code, with the downstream latency inside the upstream
deadline)."""
from __future__ import annotations

from examples.common import EchoRequest, EchoResponse, start_echo_server, rpc


class CascadeService(rpc.Service):
    """Echoes via a downstream echo server, tagging each hop."""

    def __init__(self, downstream_target: str):
        self.channel = rpc.Channel()
        self.channel.init(downstream_target,
                          options=rpc.ChannelOptions(timeout_ms=500))

    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        inner_cntl = rpc.Controller()
        inner = self.channel.call_method(
            "EchoService.Echo", inner_cntl,
            EchoRequest(message=request.message), EchoResponse)
        if inner_cntl.failed():
            cntl.set_failed(inner_cntl.error_code, inner_cntl.error_text)
        else:
            response.message = "front:" + inner.message
        done()


def main() -> None:
    back = start_echo_server("mem://cascade-back", tag="back")
    front = rpc.Server()
    front.add_service(CascadeService("mem://cascade-back"))
    assert front.start("mem://cascade-front") == 0
    try:
        ch = rpc.Channel()
        ch.init("mem://cascade-front",
                options=rpc.ChannelOptions(timeout_ms=1000))
        cntl = rpc.Controller()
        resp = ch.call_method("CascadeService.Echo", cntl,
                              EchoRequest(message="hop"), EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "front:back:hop"
        print(f"cascade -> {resp.message!r} (2 hops, "
              f"latency={cntl.latency_us}us)")
    finally:
        front.stop()
        back.stop()


if __name__ == "__main__":
    main()
