"""Parameter-server gradient push-pull with checkpoint/resume —
the rdma_performance "param-server" mode of BASELINE config 5, plus the
checkpointing SURVEY.md §5.4 calls out as the TPU build's responsibility.

A data-parallel trainer over the ICI mesh: each device computes a gradient
shard, ParallelChannel-merge-as-psum synchronizes them (one compiled
collective per step), and orbax checkpoints the replicated params so
training resumes exactly where it stopped.
"""
from __future__ import annotations

import os
import tempfile


def main(steps: int = 6, resume_at: int = 3) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import orbax.checkpoint as ocp

    from brpc_tpu.ici.mesh import IciMesh
    from brpc_tpu import channels

    mesh = IciMesh.default()
    n = mesh.size
    d = 32
    cc = channels.CollectiveChannel(mesh)

    # the "push-pull": every device pushes its gradient shard, pulls the sum
    cc.register("ParamServer.PushPull",
                lambda g_shard: g_shard,
                merge=channels.MERGE_SUM, mapping=channels.MAP_SHARD)

    key = jax.random.PRNGKey(0)
    w = jnp.zeros((d,), jnp.float32)
    target = jnp.linspace(0.0, 1.0, d)

    def local_grads(w, step):
        """Per-device gradient shards (n, d): simple quadratic loss with
        per-device minibatch noise."""
        g = 2 * (w - target)
        noise = jax.random.normal(
            jax.random.fold_in(key, step), (n, d)) * 0.01
        return cc.shard(g[None, :] + noise)

    ckpt_dir = tempfile.mkdtemp(prefix="brpc_tpu_ckpt_")
    ckptr = ocp.PyTreeCheckpointer()

    losses = []
    step = 0
    while step < steps:
        if step == resume_at:
            # simulate a restart: drop everything, restore from checkpoint
            restored = ckptr.restore(os.path.join(ckpt_dir, f"step_{step}"))
            w = jnp.asarray(restored["w"])
            assert int(restored["step"]) == step
            print(f"resumed from checkpoint at step {step}")
        grads = local_grads(w, step)
        g_sum = cc.call("ParamServer.PushPull", grads)   # psum over mesh
        w = w - 0.05 * (g_sum / n)
        loss = float(((w - target) ** 2).sum())
        losses.append(loss)
        step += 1
        if step == resume_at:
            ckptr.save(os.path.join(ckpt_dir, f"step_{step}"),
                       {"w": np.asarray(w), "step": step})
    print(f"losses: {[round(l, 4) for l in losses]}")
    assert losses[-1] < losses[0], "training must make progress"
    print(f"param-server push-pull over {n} devices: "
          f"loss {losses[0]:.4f} -> {losses[-1]:.4f} (checkpoint ok)")


if __name__ == "__main__":
    main()
