"""Shared example scaffolding: an in-process echo server (the examples run
client+server in one process, like the reference's test fixtures; point the
client flags at a remote address to split them)."""
from __future__ import annotations

import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])   # repo root

import brpc_tpu.policy  # noqa: F401  (registers protocols)
from brpc_tpu import rpc
from examples.example_echo_pb2 import EchoRequest, EchoResponse


class EchoService(rpc.Service):
    def __init__(self, tag: str = ""):
        self.tag = tag
        self.calls = 0

    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        self.calls += 1
        if request.sleep_us:
            time.sleep(request.sleep_us / 1e6)
        response.message = (self.tag + ":" if self.tag else "") + request.message
        if len(cntl.request_attachment):
            cntl.response_attachment.append(cntl.request_attachment)
        done()


def start_echo_server(addr: str, tag: str = "") -> rpc.Server:
    server = rpc.Server()
    server.add_service(EchoService(tag))
    rc = server.start(addr)
    assert rc == 0, f"server start failed: {rc}"
    return server
