"""ParallelChannel fan-out — example/parallel_echo_c++ (BASELINE config 4),
plus the TPU-native collective lowering of the same call shape."""
from __future__ import annotations

from examples.common import EchoRequest, EchoResponse, start_echo_server, rpc
from brpc_tpu import channels


class ConcatMerger(channels.ResponseMerger):
    def merge(self, response, sub_response):
        response.message = (response.message + "|" + sub_response.message
                            if response.message else sub_response.message)
        return self.MERGED


def main() -> None:
    servers = [start_echo_server(f"mem://example-par-{i}", tag=f"s{i}")
               for i in range(4)]
    try:
        pchan = channels.ParallelChannel(fail_limit=2)
        for i in range(4):
            ch = rpc.Channel()
            ch.init(f"mem://example-par-{i}")
            pchan.add_channel(ch, merger=ConcatMerger())
        cntl = rpc.Controller()
        resp = EchoResponse()
        pchan.call_method("EchoService.Echo", cntl,
                          EchoRequest(message="fanout"), resp)
        assert not cntl.failed(), cntl.error_text
        print("host-side fan-out merged:", sorted(resp.message.split("|")))
    finally:
        for s in servers:
            s.stop()

    # The same semantics on the device mesh: ONE compiled collective
    import jax.numpy as jnp
    from brpc_tpu.ici.mesh import IciMesh
    mesh = IciMesh.default()
    cc = channels.CollectiveChannel(mesh)
    cc.register("Echo.Sum", lambda row: row * 2,
                merge=channels.MERGE_SUM, mapping=channels.MAP_SHARD)
    x = cc.shard(jnp.ones((mesh.size, 8)))
    y = cc.call("Echo.Sum", x)
    print(f"collective lowering on {mesh.size}-device mesh: "
          f"sum(2*ones) = {float(y[0])} per element")


if __name__ == "__main__":
    main()
