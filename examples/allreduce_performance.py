"""Allreduce bandwidth benchmark — the example/rdma_performance analogue
(BASELINE config 5): data-parallel gradient push-pull over the ICI mesh,
both the XLA-native psum path and the explicit ring pipeline."""
from __future__ import annotations

import time


def main(size_mb: int = 64) -> None:
    import jax
    import jax.numpy as jnp
    from brpc_tpu.ici.mesh import IciMesh
    from brpc_tpu.ici.collective import Collectives
    from brpc_tpu.ici.ring import ring_all_reduce

    mesh = IciMesh.default()
    coll = Collectives(mesh)
    n = mesh.size
    elems = size_mb * 1024 * 1024 // 4
    grads = coll.shard(jnp.ones((n, max(elems // max(n, 1), 1)), jnp.float32))
    nbytes = grads.size * 4

    for name, fn in (("xla psum", coll.all_reduce),
                     ("explicit ring", lambda x: ring_all_reduce(x, mesh))):
        out = fn(grads)
        jax.block_until_ready(out)       # compile + warm
        reps = 5
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(grads)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / reps
        print(f"{name:14s}: {nbytes/1e6:.0f} MB allreduce over {n} devices "
              f"in {dt*1e3:.1f} ms -> {nbytes/dt/1e9:.2f} GB/s")


if __name__ == "__main__":
    main()
