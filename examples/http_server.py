"""HTTP/JSON access + builtin admin pages — example/http_c++."""
from __future__ import annotations

import json
import urllib.request

from examples.common import start_echo_server


def main() -> None:
    server = start_echo_server("127.0.0.1:0")
    port = server.listen_port
    try:
        base = f"http://127.0.0.1:{port}"
        req = urllib.request.Request(
            f"{base}/EchoService/Echo",
            data=json.dumps({"message": "over-http"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as r:
            print("JSON RPC:", json.loads(r.read()))
        for page in ("health", "status", "vars?filter=rpc_*", "brpc_metrics"):
            with urllib.request.urlopen(f"{base}/{page}", timeout=5) as r:
                body = r.read().decode()
                print(f"/{page}: {body[:80].strip()!r}...")
    finally:
        server.stop()


if __name__ == "__main__":
    main()
