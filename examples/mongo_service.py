"""A mongo-wire key-value service: the server answers OP_MSG commands
(insert/find/ping) with BSON documents — the mongo_protocol.cpp adaptor
pattern, usable without any external driver."""
from __future__ import annotations

from examples.common import rpc
from brpc_tpu.policy.mongo import MongoRequest, MongoResponse, MongoService


class KvMongo(MongoService):
    def __init__(self):
        self.store = {}

    def process(self, cntl, doc):
        if "ping" in doc:
            return {"ok": 1}
        if "insert" in doc:
            for d in doc.get("documents", []):
                self.store[d["_id"]] = d
            return {"ok": 1, "n": len(doc.get("documents", []))}
        if "find" in doc:
            key = doc.get("filter", {}).get("_id")
            hit = self.store.get(key)
            return {"ok": 1, "cursor": {"firstBatch": [hit] if hit else [],
                                        "id": 0}}
        return {"ok": 0, "errmsg": f"unknown command {list(doc)[:1]}"}


def main() -> None:
    server = rpc.Server()
    server.add_service(KvMongo())
    server.start("mem://example-mongo")
    try:
        ch = rpc.Channel()
        ch.init("mem://example-mongo",
                options=rpc.ChannelOptions(timeout_ms=2000,
                                           protocol="mongo"))
        cntl = rpc.Controller()
        r = ch.call_method("mongo", cntl, MongoRequest(
            {"insert": "kv", "documents": [{"_id": "a", "v": 1},
                                           {"_id": "b", "v": 2}]}),
            MongoResponse)
        assert not cntl.failed() and r.doc["n"] == 2
        cntl = rpc.Controller()
        r = ch.call_method("mongo", cntl, MongoRequest(
            {"find": "kv", "filter": {"_id": "b"}}), MongoResponse)
        assert not cntl.failed()
        batch = r.doc["cursor"]["firstBatch"]
        print(f"mongo find -> {batch}")
        assert batch[0]["v"] == 2
    finally:
        server.stop()


if __name__ == "__main__":
    main()
