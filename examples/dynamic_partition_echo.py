"""DynamicPartitionChannel: traffic migrates to the scheme with capacity —
example/dynamic_partition_echo_c++."""
from __future__ import annotations

import tempfile

from examples.common import EchoRequest, EchoResponse, start_echo_server, rpc
from brpc_tpu import channels
from examples.parallel_echo import ConcatMerger


def main() -> None:
    servers = [start_echo_server(f"mem://example-dp-{i}", tag=f"n{i}")
               for i in range(3)]
    listing = tempfile.NamedTemporaryFile("w", suffix=".cluster", delete=False)
    # scheme 1 has one replica, scheme 2 has two: capacity-weighted choice
    listing.write("mem://example-dp-0 100 0/1\n"
                  "mem://example-dp-1 100 0/2\n"
                  "mem://example-dp-2 100 1/2\n")
    listing.close()
    try:
        dpc = channels.DynamicPartitionChannel()
        assert dpc.init([1, 2], f"file://{listing.name}",
                        merger=ConcatMerger()) == 0
        scheme_hits = {1: 0, 2: 0}
        for _ in range(20):
            cntl = rpc.Controller()
            resp = EchoResponse()
            dpc.call_method("EchoService.Echo", cntl,
                            EchoRequest(message="d"), resp)
            assert not cntl.failed(), cntl.error_text
            scheme_hits[len(resp.message.split("|"))] += 1
        print(f"calls served by 1-partition scheme: {scheme_hits[1]}, "
              f"2-partition scheme: {scheme_hits[2]}")
    finally:
        for s in servers:
            s.stop()


if __name__ == "__main__":
    main()
