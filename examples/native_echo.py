"""Echo over the native C++ datapath (the deployment shape for the <10 µs
tier): a NativeServer hosting both a zero-Python native echo method and a
regular Python service, called through a NativeChannel."""
from __future__ import annotations

import statistics
import time

from examples.common import EchoRequest, EchoResponse, rpc
from brpc_tpu.butil import native
from brpc_tpu.rpc.native_fabric import NativeChannel, NativeServer


class EchoService(rpc.Service):
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


def main() -> None:
    if not native.available():
        print("native core unavailable; skipping")
        return
    server = NativeServer()
    server.add_service(EchoService())               # Python handler tier
    server.register_native_echo("RawEcho.Echo")     # zero-Python tier
    port = server.start()
    ch = NativeChannel()
    ch.init(f"127.0.0.1:{port}")
    try:
        lats = []
        for i in range(50):
            cntl = rpc.Controller()
            t0 = time.perf_counter_ns()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message=f"n{i}"), EchoResponse)
            lats.append((time.perf_counter_ns() - t0) / 1000)
            assert not cntl.failed(), cntl.error_text_
            assert resp.message == f"n{i}"
        print(f"python-service over native datapath: p50="
              f"{statistics.median(lats):.1f}us")
        # the all-native tier, measured inside C (no ctypes per call)
        p50 = native.native_rpc_echo_p50_us(iters=1000, payload=4096)
        print(f"full native stack echo (4KB): p50={p50:.1f}us")
    finally:
        ch.close()
        server.stop()


if __name__ == "__main__":
    main()
