"""Session-local and thread-local server data (reference
example/session_data_and_thread_local: per-RPC pooled data objects via
ServerOptions.session_local_data_factory + per-worker data via
thread_local_data_factory)."""
from __future__ import annotations

import itertools
import threading

from examples.common import EchoRequest, EchoResponse, rpc

_session_seq = itertools.count()
_thread_seq = itertools.count()


class SessionData:
    def __init__(self):
        self.id = next(_session_seq)
        self.uses = 0


class ThreadData:
    def __init__(self):
        self.id = next(_thread_seq)
        self.thread = threading.current_thread().name


class StatefulEcho(rpc.Service):
    def __init__(self):
        self.seen = []

    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        sd = cntl.session_local_data()       # pooled per-RPC object
        sd.uses += 1
        td = cntl.server.thread_local_data()  # per-worker object
        self.seen.append((sd.id, sd.uses, td.id))
        response.message = f"session={sd.id} use#{sd.uses} thread={td.id}"
        done()


def main() -> None:
    opts = rpc.ServerOptions()
    opts.session_local_data_factory = SessionData
    opts.thread_local_data_factory = ThreadData
    server = rpc.Server(opts)
    svc = StatefulEcho()
    server.add_service(svc)
    assert server.start("mem://session-example") == 0
    try:
        ch = rpc.Channel()
        ch.init("mem://session-example",
                options=rpc.ChannelOptions(timeout_ms=1000))
        for i in range(5):
            cntl = rpc.Controller()
            resp = ch.call_method("StatefulEcho.Echo", cntl,
                                  EchoRequest(message=str(i)),
                                  EchoResponse)
            assert not cntl.failed(), cntl.error_text
            print("->", resp.message)
        # sequential RPCs reuse the pooled session object (uses climbs,
        # ids don't): the factory ran far fewer times than 5
        assert max(uses for _, uses, _ in svc.seen) > 1
    finally:
        server.stop()


if __name__ == "__main__":
    main()
