"""gRPC echo — pb service served over HTTP/2+gRPC framing (reference
example/grpc_c++; the same service would answer tpu_std/http/grpc on one
port via protocol detection)."""
from __future__ import annotations

from examples.common import (EchoRequest, EchoResponse, EchoService,
                             rpc)


def main() -> None:
    server = rpc.Server()
    server.add_service(EchoService(tag="grpc"))
    assert server.start("127.0.0.1:0") == 0
    try:
        ch = rpc.Channel()
        ch.init(f"127.0.0.1:{server.listen_port}",
                options=rpc.ChannelOptions(protocol="grpc",
                                           timeout_ms=2000))
        for i in range(3):
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message=f"g{i}"),
                                  EchoResponse)
            assert not cntl.failed(), cntl.error_text
            print(f"grpc echo -> {resp.message!r}")
    finally:
        server.stop()


if __name__ == "__main__":
    main()
