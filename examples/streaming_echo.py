"""Streaming RPC with flow control — example/streaming_echo_c++
(BASELINE config 3)."""
from __future__ import annotations

import threading
import time

from examples.common import EchoRequest, EchoResponse, rpc
from brpc_tpu.butil.iobuf import IOBuf


class StreamingService(rpc.Service):
    @rpc.method(EchoRequest, EchoResponse)
    def StartStream(self, cntl, request, response, done):
        class EchoBack(rpc.StreamInputHandler):
            def __init__(self):
                self.stream = None

            def on_received_messages(self, sid, msgs):
                for m in msgs:
                    self.stream.write(IOBuf(b"echo:" + m.to_bytes()))

            def on_closed(self, sid):
                print("server stream closed")

        handler = EchoBack()
        handler.stream = rpc.stream_accept(
            cntl, rpc.StreamOptions(handler=handler))
        response.message = "stream accepted"
        done()


class ClientCollector(rpc.StreamInputHandler):
    def __init__(self, expect: int):
        self.got = []
        self.expect = expect
        self.done = threading.Event()

    def on_received_messages(self, sid, msgs):
        self.got.extend(m.to_bytes() for m in msgs)
        if len(self.got) >= self.expect:
            self.done.set()


def main() -> None:
    server = rpc.Server()
    server.add_service(StreamingService())
    assert server.start("mem://example-streaming") == 0
    try:
        channel = rpc.Channel()
        channel.init("mem://example-streaming")
        collector = ClientCollector(expect=10)
        cntl = rpc.Controller()
        stream = rpc.stream_create(
            cntl, rpc.StreamOptions(handler=collector, max_buf_size=4096))
        channel.call_method("StreamingService.StartStream", cntl,
                            EchoRequest(message="go"), EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert stream.wait_connected(5)
        for i in range(10):
            rc = stream.write(IOBuf(b"chunk-%d" % i), timeout=5)
            assert rc == 0, rc
        assert collector.done.wait(10)
        print(f"received {len(collector.got)} echoed chunks, "
              f"first={collector.got[0]!r}")
        stream.close()
    finally:
        server.stop()


if __name__ == "__main__":
    main()
