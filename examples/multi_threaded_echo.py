"""Concurrent echo QPS — the example/multi_threaded_echo_c++ analogue
(BASELINE config 2)."""
from __future__ import annotations

import threading
import time

from examples.common import EchoRequest, EchoResponse, start_echo_server, rpc


def main(threads: int = 8, seconds: float = 2.0) -> None:
    server = start_echo_server("mem://example-mt-echo")
    channel = rpc.Channel()
    channel.init("mem://example-mt-echo",
                 options=rpc.ChannelOptions(timeout_ms=5000))
    stop_at = time.monotonic() + seconds
    counts = [0] * threads
    errors = [0]

    def worker(idx: int) -> None:
        while time.monotonic() < stop_at:
            cntl = rpc.Controller()
            channel.call_method("EchoService.Echo", cntl,
                                EchoRequest(message="m"), EchoResponse)
            if cntl.failed():
                errors[0] += 1
            else:
                counts[idx] += 1

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    t0 = time.monotonic()
    for t in ts: t.start()
    for t in ts: t.join()
    dt = time.monotonic() - t0
    total = sum(counts)
    print(f"{total} calls in {dt:.2f}s over {threads} threads "
          f"-> {total/dt:.0f} qps, {errors[0]} errors")
    server.stop()


if __name__ == "__main__":
    main()
