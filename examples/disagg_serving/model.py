"""The toy model behind the disaggregated-serving example.

Deliberately tiny but shaped like the real thing:

  * **prefill** is compute-shaped: a whole prompt becomes a KV cache in
    one pass — here a deterministic transform of the token ids into a
    ``(layers, seq, d_model)`` tensor, QUANTIZED to uint8 blocks (KV
    quantization is standard serving practice, and a flat uint8 device
    array is exactly what the device plane moves);
  * **decode** is memory-shaped: each step reads the whole cache and
    emits one token — here a deterministic integer recurrence over the
    cache statistics, so any process (including the test client) can
    recompute the expected tokens bit-for-bit from the same prompt.

Determinism is the test contract: prefill on worker A, a fabric hop, and
decode on worker B must produce the exact tokens a single-process
reference run produces — any corruption in the KV handoff path changes
the output.
"""
from __future__ import annotations

from typing import List

KV_LAYERS = 4
KV_DMODEL = 256
VOCAB = 50257


def toy_kv_blocks(tokens: List[int], device=None):
    """Prefill: prompt token ids -> quantized KV-cache blocks, one flat
    uint8 device array of shape (KV_LAYERS * len(tokens) * KV_DMODEL,).
    Deterministic in the token ids."""
    import jax
    import jax.numpy as jnp
    t = jnp.asarray(tokens, jnp.float32)                      # (seq,)
    cols = jnp.arange(KV_DMODEL, dtype=jnp.float32) / KV_DMODEL
    base = jnp.outer(t + 1.0, cols)                           # (seq, d)
    layers = [jnp.sin(base * (l + 1)) + jnp.cumsum(base, axis=0) * 1e-3
              for l in range(KV_LAYERS)]
    kv = jnp.stack(layers)                                    # (L, seq, d)
    kv_q = (jnp.clip(kv, -4.0, 4.0) * 16.0 + 128.0).astype(jnp.uint8)
    flat = kv_q.reshape(-1)
    if device is not None:
        flat = jax.device_put(flat, device)
    return flat


def kv_nbytes(seq_len: int) -> int:
    return KV_LAYERS * seq_len * KV_DMODEL


def toy_decode(kv_u8, seq_len: int, last_token: int,
               steps: int) -> List[int]:
    """Decode: stream ``steps`` tokens out of the quantized cache.  Each
    step folds the per-position cache sums (the "attention read") into an
    integer recurrence — cheap, deterministic, and a function of every
    cache byte, so a corrupted handoff changes the output."""
    import numpy as np
    arr = np.asarray(kv_u8, dtype=np.uint8)
    kv = arr.reshape(KV_LAYERS, seq_len, KV_DMODEL)
    pos_sums = kv.astype(np.int64).sum(axis=(0, 2))           # (seq,)
    acc = int(pos_sums.sum())
    toks: List[int] = []
    prev = last_token
    for i in range(steps):
        read = int(pos_sums[(prev + i) % seq_len])
        prev = (acc + read * (i + 1) + prev * 31) % VOCAB
        toks.append(prev)
    return toks


def reference_generate(tokens: List[int], steps: int) -> List[int]:
    """Single-process reference: what the disaggregated pipeline must
    reproduce exactly."""
    kv = toy_kv_blocks(tokens)
    return toy_decode(kv, len(tokens), tokens[-1], steps)
