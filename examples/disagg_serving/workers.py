"""Disaggregated prefill/decode workers and the router front-end.

Three roles, each an ordinary brpc_tpu server:

  * **PrefillService** (``Prefill``): turns a prompt into quantized
    KV-cache blocks on its own device, then HANDS THEM OFF to the chosen
    decode worker — one ``DecodeService.LoadKv`` call whose request
    attachment is the KV tensor as a DEVICE payload.  Cross-process this
    rides the fabric's sequenced device plane (``ici_device_plane_xproc``;
    compiled collectives on TPU pods, bulk-carried under the same total
    order elsewhere); in-process it is a device-plane/ref-pass hop.  The
    prefill worker never talks to the client again — the point of
    disaggregation.
  * **DecodeService** (``LoadKv`` / ``Decode``): parks sessions' KV
    blocks and streams tokens out of them.  ``Decode`` releases the
    session when ``release`` is set.
  * **RouterService** (``Generate``): the front door — picks a prefill
    worker and a decode worker through load-balanced channels (any
    naming source: ``list://``, ``mesh://``, ``pod://``), orchestrates
    prefill → handoff → decode, and returns the tokens.

Request/response bodies are JSON in EchoRequest.message (the examples'
lingua franca); bulk bytes ride attachments, never the JSON.
"""
from __future__ import annotations

import json
import sys
import threading
import time
from typing import Dict, Optional

sys.path.insert(0, __file__.rsplit("/", 3)[0])   # repo root

import brpc_tpu.policy  # noqa: F401  (registers protocols)
from brpc_tpu import rpc
from examples.example_echo_pb2 import EchoRequest, EchoResponse

from .model import toy_kv_blocks, toy_decode, kv_nbytes


def _reply(response, done, **kw) -> None:
    response.message = json.dumps(kw)
    done()


class PrefillService(rpc.Service):
    SERVICE_NAME = "Prefill"

    def __init__(self, device=None,
                 channel_options: Optional[rpc.ChannelOptions] = None):
        self.device = device
        self.channel_options = channel_options or rpc.ChannelOptions(
            timeout_ms=60000)
        self._channels: Dict[str, rpc.Channel] = {}
        self._lock = threading.Lock()
        self.prefills = 0
        self.handoff_bytes = 0
        self.handoff_ns = 0      # cumulative LoadKv round-trip time

    def _channel_to(self, target: str) -> rpc.Channel:
        with self._lock:
            ch = self._channels.get(target)
            if ch is None:
                ch = rpc.Channel()
                ch.init(target, options=self.channel_options)
                self._channels[target] = ch
            return ch

    def close(self) -> None:
        with self._lock:
            chans, self._channels = list(self._channels.values()), {}
        for ch in chans:
            ch.close()

    @rpc.method(EchoRequest, EchoResponse)
    def Prefill(self, cntl, request, response, done):
        req = json.loads(request.message)
        session = req["session"]
        tokens = req["tokens"]
        decode_target = req["decode"]
        import jax
        t0 = time.perf_counter_ns()
        kv = toy_kv_blocks(tokens, device=self.device)
        jax.block_until_ready(kv)
        t1 = time.perf_counter_ns()
        # the KV-cache handoff: device payload to the decode worker
        ch = self._channel_to(decode_target)
        hand = rpc.Controller()
        hand.request_attachment.append_device_array(kv)
        load = EchoRequest(message=json.dumps(
            {"session": session, "seq_len": len(tokens),
             "last_token": tokens[-1]}))
        ch.call_method("Decode.LoadKv", hand, load, EchoResponse)
        t2 = time.perf_counter_ns()
        if hand.failed():
            cntl.set_failed(hand.error_code_,
                            f"kv handoff failed: {hand.error_text}")
            done()
            return
        with self._lock:
            self.prefills += 1
            self.handoff_bytes += kv_nbytes(len(tokens))
            self.handoff_ns += t2 - t1
        _reply(response, done, session=session,
               kv_bytes=kv_nbytes(len(tokens)),
               prefill_us=(t1 - t0) // 1000,
               handoff_us=(t2 - t1) // 1000)


class DecodeService(rpc.Service):
    SERVICE_NAME = "Decode"

    # an orphaned session — LoadKv landed but the router's Decode never
    # arrived (drain ELOGOFF with retries exhausted, router crash) —
    # would park its KV block forever; sweep stale entries past this
    # age opportunistically on every LoadKv (no reaper thread needed)
    SESSION_TTL_S = 120.0

    def __init__(self, device=None):
        self.device = device
        self._sessions: Dict[str, tuple] = {}
        self._lock = threading.Lock()
        self.loads = 0
        self.kv_bytes_in = 0
        self.decode_steps = 0
        self.sessions_expired = 0

    @rpc.method(EchoRequest, EchoResponse)
    def LoadKv(self, cntl, request, response, done):
        req = json.loads(request.message)
        session = req["session"]
        seq_len = req["seq_len"]
        want = kv_nbytes(seq_len)
        blob = cntl.request_attachment.to_bytes()
        if len(blob) != want:
            cntl.set_failed(rpc.errors.EREQUEST,
                            f"kv size {len(blob)} != {want}")
            done()
            return
        now = time.monotonic()
        with self._lock:
            stale = [s for s, e in self._sessions.items()
                     if now - e[3] > self.SESSION_TTL_S]
            for s in stale:
                del self._sessions[s]
            self.sessions_expired += len(stale)
            self._sessions[session] = (blob, seq_len, req["last_token"],
                                       now)
            self.loads += 1
            self.kv_bytes_in += want
        _reply(response, done, session=session, loaded=want)

    @rpc.method(EchoRequest, EchoResponse)
    def Decode(self, cntl, request, response, done):
        req = json.loads(request.message)
        session = req["session"]
        steps = req["steps"]
        with self._lock:
            entry = self._sessions.get(session)
        if entry is None:
            cntl.set_failed(rpc.errors.EREQUEST,
                            f"unknown session {session!r}")
            done()
            return
        blob, seq_len, last_token, _loaded_at = entry
        import numpy as np
        toks = toy_decode(np.frombuffer(blob, np.uint8), seq_len,
                          last_token, steps)
        with self._lock:
            self.decode_steps += steps
            if req.get("release", True):
                self._sessions.pop(session, None)
        _reply(response, done, session=session, tokens=toks)

    def live_sessions(self) -> int:
        with self._lock:
            return len(self._sessions)


class RouterService(rpc.Service):
    SERVICE_NAME = "Router"

    def __init__(self, prefill_targets: str, decode_targets: Dict[str, str],
                 channel_options: Optional[rpc.ChannelOptions] = None):
        """``prefill_targets``: naming url (or single endpoint) for the
        prefill pool.  ``decode_targets``: {decode worker endpoint url:
        same url} — the router addresses a SPECIFIC decode worker so the
        prefill worker knows where to push the KV; a dict keeps the
        choice explicit and round-robin-able."""
        opts = channel_options or rpc.ChannelOptions(timeout_ms=60000,
                                                     max_retry=2)
        from brpc_tpu.policy.naming import is_naming_url
        self._prefill = rpc.Channel()
        self._prefill.init(prefill_targets,
                           "rr" if is_naming_url(prefill_targets) else "",
                           options=opts)
        self._decode_urls = list(decode_targets)
        self._decode_chs: Dict[str, rpc.Channel] = {}
        for url in self._decode_urls:
            ch = rpc.Channel()
            ch.init(url, options=opts)
            self._decode_chs[url] = ch
        self._rr = 0
        self._lock = threading.Lock()
        self._next_session = 0

    def close(self) -> None:
        self._prefill.close()
        for ch in self._decode_chs.values():
            ch.close()

    def _pick_decode(self) -> str:
        with self._lock:
            url = self._decode_urls[self._rr % len(self._decode_urls)]
            self._rr += 1
            return url

    @rpc.method(EchoRequest, EchoResponse)
    def Generate(self, cntl, request, response, done):
        req = json.loads(request.message)
        tokens = req["tokens"]
        steps = req.get("steps", 8)
        with self._lock:
            self._next_session += 1
            session = f"s{self._next_session}"
        decode_url = self._pick_decode()
        pc = rpc.Controller()
        pre_resp = self._prefill.call_method(
            "Prefill.Prefill", pc,
            EchoRequest(message=json.dumps(
                {"session": session, "tokens": tokens,
                 "decode": decode_url})), EchoResponse)
        if pc.failed():
            cntl.set_failed(pc.error_code_,
                            f"prefill failed: {pc.error_text}")
            done()
            return
        pre = json.loads(pre_resp.message)
        dc = rpc.Controller()
        dec_resp = self._decode_chs[decode_url].call_method(
            "Decode.Decode", dc,
            EchoRequest(message=json.dumps(
                {"session": session, "steps": steps, "release": True})),
            EchoResponse)
        if dc.failed():
            cntl.set_failed(dc.error_code_,
                            f"decode failed: {dc.error_text}")
            done()
            return
        toks = json.loads(dec_resp.message)["tokens"]
        _reply(response, done, session=session, tokens=toks,
               decode_worker=decode_url, kv_bytes=pre.get("kv_bytes", 0))


def start_prefill_worker(addr: str, device=None,
                         options: Optional[rpc.ServerOptions] = None
                         ) -> rpc.Server:
    server = rpc.Server(options)
    server.add_service(PrefillService(device=device))
    rc = server.start(addr)
    assert rc == 0, f"prefill worker start failed: {rc}"
    return server


def start_decode_worker(addr: str, device=None,
                        options: Optional[rpc.ServerOptions] = None
                        ) -> rpc.Server:
    server = rpc.Server(options)
    server.add_service(DecodeService(device=device))
    rc = server.start(addr)
    assert rc == 0, f"decode worker start failed: {rc}"
    return server


def start_router(addr: str, prefill_targets: str,
                 decode_targets: Dict[str, str]) -> rpc.Server:
    server = rpc.Server()
    server.add_service(RouterService(prefill_targets, decode_targets))
    rc = server.start(addr)
    assert rc == 0, f"router start failed: {rc}"
    return server
