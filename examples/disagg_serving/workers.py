"""Disaggregated prefill/decode workers, rebuilt on ``brpc_tpu.serving``.

Three roles, each an ordinary brpc_tpu server — same wire surface as
the original example (JSON bodies in EchoRequest.message, bulk bytes in
attachments), but the decode side is now the REAL serving subsystem
(ROADMAP item 3), not a one-RPC-one-token toy:

  * **PrefillService** (``Prefill``): prompt → quantized KV blocks on
    its own device, HANDED OFF to the router-chosen decode worker as a
    DEVICE-payload attachment (``DecodeService.LoadKv``).  Cross-process
    this rides the fabric's sequenced device plane or the shm ring; on
    the native-ici plane the attachment moves under PR-12 custody (one
    parked handle).  Wherever it lands, LoadKv scatters the wire bytes
    DIRECTLY into the paged pool's reserved blocks (ISSUE 15): shm
    claims are consumed in place, parked handles taken segment-wise —
    one copy pass, no per-session host materialization
    (``serving_kv_load_*`` counters carry the per-route truth).
  * **DecodeService** (``LoadKv`` / ``Decode``): KV pages into a
    :class:`~brpc_tpu.serving.PagedKvPool` (admission-aware eviction,
    TimerThread expiry — an idle worker reclaims parked sessions with
    zero traffic, the ISSUE-14 bugfix) and tokens stream out of a
    :class:`~brpc_tpu.serving.ContinuousBatchScheduler`: one batched
    step per tick over every active session, admit/retire/preempt
    between steps.  ``Decode`` is an ASYNC handler — the RPC completes
    from the step loop when the session's tokens are done, so N
    concurrent sessions share each step instead of serializing.
    ``{"mode": "sync"}`` keeps the old one-RPC-one-shot path (the
    bench's A/B baseline).
  * **RouterService** (``Generate``): the front door — prefill via any
    LB channel, decode worker chosen by the LALB divided-weight
    balancer (:class:`~brpc_tpu.serving.LoadAwareRouter`): every decode
    outcome feeds the balancer, a dead/slow worker's weight collapses
    within one request time, and failures RETRY against another worker
    (re-prefill) so elastic scale-down/kill stays invisible to clients.
    ``decode_targets`` may be the original explicit dict, a list, or a
    naming url (``pod://name``) for elastic membership.
"""
from __future__ import annotations

import json
import sys
import threading
import time
from typing import Dict, Optional, Union

sys.path.insert(0, __file__.rsplit("/", 3)[0])   # repo root

import numpy as np

import brpc_tpu.policy  # noqa: F401  (registers protocols)
from brpc_tpu import rpc
from brpc_tpu.butil import debug_sync as _dbg
from brpc_tpu.serving import (BatchSchedulerOptions,
                              ContinuousBatchScheduler, KvPoolOptions,
                              LoadAwareRouter, PagedKvPool, PoolSaturated,
                              SessionBusy, StepRequest, kv_load_stats,
                              load_token_major_attachment,
                              load_wire_attachment, migrate_out)
from brpc_tpu.serving import kv_source as _kv_source
from brpc_tpu.serving import migration as _migration
from examples.example_echo_pb2 import EchoRequest, EchoResponse

from .model import (KV_DMODEL, KV_LAYERS, VOCAB, kv_nbytes, toy_decode,
                    toy_kv_blocks)

BYTES_PER_TOKEN = KV_LAYERS * KV_DMODEL


def _reply(response, done, **kw) -> None:
    response.message = json.dumps(kw)
    done()


class PrefillService(rpc.Service):
    SERVICE_NAME = "Prefill"

    _GUARDED_BY = {"_channels": "_lock", "prefills": "_lock",
                   "handoff_bytes": "_lock", "handoff_ns": "_lock"}

    def __init__(self, device=None,
                 channel_options: Optional[rpc.ChannelOptions] = None):
        self.device = device
        self.channel_options = channel_options or rpc.ChannelOptions(
            timeout_ms=60000)
        self._channels: Dict[str, rpc.Channel] = {}
        self._lock = _dbg.make_lock("PrefillService._lock")
        self.prefills = 0
        self.handoff_bytes = 0
        self.handoff_ns = 0      # cumulative LoadKv round-trip time

    def _channel_to(self, target: str) -> rpc.Channel:
        with self._lock:
            ch = self._channels.get(target)
            if ch is None:
                ch = rpc.Channel()
                ch.init(target, options=self.channel_options)
                self._channels[target] = ch
            return ch

    def close(self) -> None:
        with self._lock:
            chans, self._channels = list(self._channels.values()), {}
        for ch in chans:
            ch.close()

    @rpc.method(EchoRequest, EchoResponse)
    def Prefill(self, cntl, request, response, done):
        req = json.loads(request.message)
        session = req["session"]
        tokens = req["tokens"]
        decode_target = req["decode"]
        import jax
        t0 = time.perf_counter_ns()
        kv = toy_kv_blocks(tokens, device=self.device)
        jax.block_until_ready(kv)
        t1 = time.perf_counter_ns()
        # the KV-cache handoff: device payload to the decode worker
        # (the inbound call's priority/tenant/deadline budget cascade
        # onto this outbound call — PR-10 request context)
        ch = self._channel_to(decode_target)
        hand = rpc.Controller()
        hand.request_attachment.append_device_array(kv)
        load = EchoRequest(message=json.dumps(
            {"session": session, "seq_len": len(tokens),
             "last_token": tokens[-1]}))
        ch.call_method("Decode.LoadKv", hand, load, EchoResponse)
        t2 = time.perf_counter_ns()
        if hand.failed():
            cntl.retry_after_ms = hand.retry_after_ms
            cntl.set_failed(hand.error_code_,
                            f"kv handoff failed: {hand.error_text}")
            done()
            return
        with self._lock:
            self.prefills += 1
            self.handoff_bytes += kv_nbytes(len(tokens))
            self.handoff_ns += t2 - t1
        _reply(response, done, session=session,
               kv_bytes=kv_nbytes(len(tokens)),
               prefill_us=(t1 - t0) // 1000,
               handoff_us=(t2 - t1) // 1000)


class DecodeService(rpc.Service):
    SERVICE_NAME = "Decode"

    # ("loads" stays out of the guard map: the analyzer would match the
    # attribute name on any receiver, including json.loads — the counter
    # is still only written under _lock)
    _GUARDED_BY = {"kv_bytes_in": "_lock", "decode_steps": "_lock",
                   "_channels": "_lock"}

    def __init__(self, device=None,
                 pool_options: Optional[KvPoolOptions] = None,
                 sched_options: Optional[BatchSchedulerOptions] = None,
                 channel_options: Optional[rpc.ChannelOptions] = None):
        self.device = device
        self.pool = PagedKvPool(pool_options or KvPoolOptions(
            bytes_per_token=BYTES_PER_TOKEN, num_blocks=1024,
            block_tokens=16))
        self.scheduler = ContinuousBatchScheduler(
            self.pool, sched_options or BatchSchedulerOptions(
                vocab=VOCAB, max_batch=64))
        self._lock = _dbg.make_lock("DecodeService._lock")
        self.channel_options = channel_options or rpc.ChannelOptions(
            timeout_ms=60000)
        self._channels: Dict[str, rpc.Channel] = {}   # migrate peers
        #: chaos hook (ISSUE 19): an UNSET Event here black-holes
        #: MigrateIn — the handler parks until the test releases it,
        #: so the source's transfer-deadline latch is what fires
        self.migrate_in_gate: Optional[threading.Event] = None
        self.loads = 0
        self.kv_bytes_in = 0
        self.decode_steps = 0

    @property
    def sessions_expired(self) -> int:
        """TTL-reclaimed session count — now the pool's TIMER-driven
        policy (the old inline LoadKv sweep parked KV forever on an
        idle worker)."""
        return self.pool.expirations.get_value()

    def live_sessions(self) -> int:
        return self.pool.sessions()

    def close(self) -> None:
        self.scheduler.stop()
        self.pool.close()
        with self._lock:
            chans, self._channels = list(self._channels.values()), {}
        for ch in chans:
            ch.close()

    def _channel_to(self, target: str) -> rpc.Channel:
        with self._lock:
            ch = self._channels.get(target)
            if ch is None:
                ch = rpc.Channel()
                ch.init(target, options=self.channel_options)
                self._channels[target] = ch
            return ch

    def describe_serving(self) -> dict:
        """The /status serving block: step rate, batch occupancy, pool
        pages, evictions by reason/tenant, KV-load routes.  Unlike the
        per-instance scheduler/pool blocks, ``kv_load`` is the
        PROCESS-WIDE route ledger (the counters live in
        ``serving/kv_source.py``) — with several decode workers in one
        process it sums all of them, and says so via ``scope``."""
        return {"scheduler": self.scheduler.describe(),
                "pool": self.pool.describe(),
                "kv_load": {**kv_load_stats(), "scope": "process"}}

    @rpc.method(EchoRequest, EchoResponse)
    def LoadKv(self, cntl, request, response, done):
        req = json.loads(request.message)
        session = req["session"]
        seq_len = req["seq_len"]
        if seq_len <= 0:
            cntl.set_failed(rpc.errors.EREQUEST,
                            f"seq_len must be >= 1, got {seq_len}")
            done()
            return
        want = kv_nbytes(seq_len)
        att = cntl.request_attachment
        # len() answers from the descriptor total on every plane —
        # a parked NativeAttachment is NOT materialized by this check
        if len(att) != want:
            cntl.set_failed(rpc.errors.EREQUEST,
                            f"kv size {len(att)} != {want}")
            done()
            return
        try:
            if _kv_source.adopt_enabled():
                # ISSUE 15: the wire bytes scatter DIRECTLY into the
                # reserved pool blocks — shm ring claims consumed in
                # place (slot retired right after the fill), parked
                # native att segments taken block-wise, ONE copy pass;
                # the layer-major → token-major transpose happens
                # inside the strided scatter, never as its own pass
                load_wire_attachment(
                    self.pool, att, session, seq_len, KV_LAYERS,
                    KV_DMODEL, last_token=req["last_token"],
                    tenant=cntl.tenant or req.get("tenant", ""),
                    priority=cntl.priority)
                # drop the attachment refs NOW: the ring claim's
                # consume-to-release credit returns on this line, not
                # at controller recycle
                att.clear()
            else:
                # the PR-14 path, byte-for-byte (the A/B leg):
                # materialize (copy 1), transpose-reshape (copy 2),
                # pool fill (copy 3)
                blob = att.to_bytes()
                rows = np.frombuffer(blob, np.uint8).reshape(
                    KV_LAYERS, seq_len, KV_DMODEL).transpose(
                    1, 0, 2).reshape(seq_len, BYTES_PER_TOKEN)
                self.pool.load(session, rows,
                               last_token=req["last_token"],
                               tenant=cntl.tenant or req.get("tenant",
                                                             ""),
                               priority=cntl.priority)
                _kv_source.stats.record(_kv_source.MATERIALIZED, want, 3)
        except PoolSaturated:
            # memory pressure with nothing evictable in an equal-or-
            # less-protected band: a shed, not a failure
            cntl.retry_after_ms = 20
            cntl.set_failed(rpc.errors.ELIMIT,
                            "kv pool saturated (shed): retry later")
            done()
            return
        except SessionBusy as e:
            # re-prefill raced the running decode: retry once it
            # completes — freeing the rostered blocks mid-program
            # would corrupt the batched step.  Since ISSUE 16 this is
            # also the COMMIT-TIME abort of an outside-the-lock fill
            # (a concurrent LoadKv won the session id and its entry
            # got pinned before our re-check) — same shed, same retry
            cntl.retry_after_ms = 10
            cntl.set_failed(rpc.errors.ELIMIT, str(e))
            done()
            return
        with self._lock:
            self.loads += 1
            self.kv_bytes_in += want
        _reply(response, done, session=session, loaded=want)

    @rpc.method(EchoRequest, EchoResponse)
    def MigrateIn(self, cntl, request, response, done):
        """Destination half of a live migration (ISSUE 19): a peer
        pool's TOKEN-MAJOR block payload lands through the ordinary
        reserve/fill-outside-the-lock/commit path.  Refusals are the
        same retryable sheds as LoadKv — a saturated or busy
        destination aborts the migration cleanly, the SOURCE copy
        stays authoritative, no plane event."""
        gate = self.migrate_in_gate
        if gate is not None:
            gate.wait()          # chaos: black-hole until released
        req = json.loads(request.message)
        session = req["session"]
        seq_len = req["seq_len"]
        bpt = self.pool.options.bytes_per_token
        want = seq_len * bpt
        att = cntl.request_attachment
        if seq_len <= 0 or len(att) != want:
            cntl.set_failed(rpc.errors.EREQUEST,
                            f"migrate payload {len(att)} != {want}")
            done()
            return
        try:
            load_token_major_attachment(
                self.pool, att, session, seq_len,
                last_token=req["last_token"],
                tenant=req.get("tenant", ""),
                priority=req.get("priority"))
            att.clear()
        except PoolSaturated:
            cntl.retry_after_ms = 20
            cntl.set_failed(rpc.errors.ELIMIT,
                            "kv pool saturated (shed): migration "
                            "refused, source stays authoritative")
            done()
            return
        except SessionBusy as e:
            cntl.retry_after_ms = 10
            cntl.set_failed(rpc.errors.ELIMIT, str(e))
            done()
            return
        _migration.stats.migrations_in << 1
        with self._lock:
            self.kv_bytes_in += want
        _reply(response, done, session=session, loaded=want)

    @rpc.method(EchoRequest, EchoResponse)
    def MigrateOut(self, cntl, request, response, done):
        """Source half: ship one session to the ``dest`` decode worker
        (``Decode.MigrateIn`` there) under the transfer-deadline
        plane-health latch.  The source copy serves until the
        destination commits; only then is it released — an abort at
        any point leaves the source authoritative and reads as a
        retryable shed to the caller."""
        req = json.loads(request.message)
        session = req["session"]
        dest = req["dest"]
        ch = self._channel_to(dest)

        def send(meta, payload):
            mc = rpc.Controller()
            mc.request_attachment.append(payload)
            ch.call_method("Decode.MigrateIn", mc,
                           EchoRequest(message=json.dumps(meta)),
                           EchoResponse)
            if mc.failed():
                # ELIMIT from the destination is a clean shed
                # (saturated/busy), not a dead peer
                return (False, mc.error_text,
                        mc.error_code_ == rpc.errors.ELIMIT)
            return True, "", False
        ok, err = migrate_out(
            self.pool, session, send, scheduler=self.scheduler,
            deadline_ms=req.get("deadline_ms"))
        if not ok:
            # every abort is a shed: the source copy still serves, a
            # retry (here or around a re-prefill) stays cheap
            cntl.retry_after_ms = 10
            cntl.set_failed(rpc.errors.ELIMIT,
                            f"migration failed (shed): {err}")
            done()
            return
        _reply(response, done, session=session, migrated=True,
               dest=dest)

    @rpc.method(EchoRequest, EchoResponse)
    def Decode(self, cntl, request, response, done):
        req = json.loads(request.message)
        session = req["session"]
        steps = req["steps"]
        release = req.get("release", True)
        if steps <= 0:
            _reply(response, done, session=session, tokens=[])
            return
        if req.get("mode") == "sync":
            self._decode_sync(cntl, session, steps, release, response,
                              done)
            return
        self.pool.touch(session)
        deadline_us = None
        if cntl.deadline_left_ms:
            deadline_us = (time.monotonic_ns() // 1000
                           + cntl.deadline_left_ms * 1000)

        def emit(tokens):
            with self._lock:
                self.decode_steps += len(tokens)
            if release:
                self.pool.release(session)
            _reply(response, done, session=session, tokens=tokens)

        def fail(code, text, retry_after_ms):
            if retry_after_ms:
                cntl.retry_after_ms = retry_after_ms
            cntl.set_failed(code, text)
            done()

        # ASYNC: the RPC completes from the step loop when this
        # session's tokens are done — the handler thread is free
        self.scheduler.submit(StepRequest(
            session, steps, emit, fail, priority=cntl.priority,
            tenant=cntl.tenant, deadline_us=deadline_us))

    def _decode_sync(self, cntl, session, steps, release, response,
                     done) -> None:
        """The pre-batching one-RPC-one-shot path (bench A/B baseline):
        read the session out of the pool and decode inline.  The read
        is a zero-copy VIEW when the session's blocks are one
        contiguous extent (the ISSUE-15 materialize bugfix) — pinned
        for exactly the decode, unpinned before the release."""
        snap = self.pool.snapshot(session, view=True)
        if snap is None:
            reason = self.pool.evicted_reason(session)
            if reason is not None:
                cntl.retry_after_ms = 1
                cntl.set_failed(rpc.errors.ELIMIT,
                                f"kv {reason}-evicted: re-prefill")
            else:
                cntl.set_failed(rpc.errors.EREQUEST,
                                f"unknown session {session!r}")
            done()
            return
        rows, seq_len, last_token, is_view = snap
        try:
            # token-major rows → the model's layer-major flat layout
            flat = rows.reshape(seq_len, KV_LAYERS, KV_DMODEL).transpose(
                1, 0, 2).reshape(-1)
            toks = toy_decode(flat, seq_len, last_token, steps)
        finally:
            if is_view:
                self.pool.unpin(session)
        with self._lock:
            self.decode_steps += steps
        if release:
            self.pool.release(session)
        else:
            self.pool.touch(session)
        _reply(response, done, session=session, tokens=toks)


class RouterService(rpc.Service):
    SERVICE_NAME = "Router"

    _GUARDED_BY = {"_next_session": "_lock", "retries": "_lock",
                   "generate_failures": "_lock"}

    #: decode attempts per Generate (the elastic-chaos survival knob:
    #: a killed worker's in-flight sessions re-prefill elsewhere)
    MAX_DECODE_ATTEMPTS = 3

    def __init__(self, prefill_targets: str,
                 decode_targets: Union[Dict[str, str], list, str],
                 channel_options: Optional[rpc.ChannelOptions] = None):
        """``prefill_targets``: naming url (or single endpoint) for the
        prefill pool.  ``decode_targets``: explicit dict/list of decode
        worker urls, or a naming url (``pod://name``) for elastic
        membership — either way the LALB divided-weight balancer picks
        the worker and every outcome feeds back."""
        opts = channel_options or rpc.ChannelOptions(timeout_ms=60000,
                                                     max_retry=2)
        from brpc_tpu.policy.naming import is_naming_url
        self._prefill = rpc.Channel()
        self._prefill.init(prefill_targets,
                           "rr" if is_naming_url(prefill_targets) else "",
                           options=opts)
        if isinstance(decode_targets, dict):
            decode_targets = list(decode_targets)
        self._router = LoadAwareRouter(decode_targets,
                                       channel_options=opts)
        self._lock = _dbg.make_lock("RouterService._lock")
        self._next_session = 0
        self.retries = 0
        self.generate_failures = 0

    def close(self) -> None:
        self._prefill.close()
        self._router.close()

    # elastic membership (the autoscaler's registration surface; a
    # naming-url router tracks pod:// transitions by itself)
    def add_decode_target(self, url: str) -> bool:
        return self._router.add_target(url)

    def remove_decode_target(self, url: str) -> bool:
        return self._router.remove_target(url)

    def describe_serving(self) -> dict:
        with self._lock:
            extra = {"retries": self.retries,
                     "generate_failures": self.generate_failures}
        return {"router": {**self._router.describe(), **extra}}

    @rpc.method(EchoRequest, EchoResponse)
    def Generate(self, cntl, request, response, done):
        req = json.loads(request.message)
        tokens = req["tokens"]
        steps = req.get("steps", 8)
        with self._lock:
            self._next_session += 1
            base_session = self._next_session
        tried: set = set()
        last_err = (rpc.errors.EINTERNAL, "no decode worker available")
        for attempt in range(self.MAX_DECODE_ATTEMPTS):
            decode_url = self._router.pick(exclude=tried)
            if decode_url is None:
                break
            # one session id per attempt: a retry re-prefills, never
            # half-reuses a dead worker's parked KV.  When the retry
            # lands on the SAME worker, its LoadKv dedupes against the
            # original session's still-parked blocks (ISSUE 16 prefix
            # sharing) — the re-prefill's full blocks commit as
            # refcount bumps, not new arena pages
            session = f"s{base_session}" if attempt == 0 \
                else f"s{base_session}r{attempt}"
            pc = rpc.Controller()
            t_pre = time.perf_counter_ns()
            pre_resp = self._prefill.call_method(
                "Prefill.Prefill", pc,
                EchoRequest(message=json.dumps(
                    {"session": session, "tokens": tokens,
                     "decode": decode_url})), EchoResponse)
            pre_us = (time.perf_counter_ns() - t_pre) // 1000
            if pc.failed():
                if pc.error_code_ == rpc.errors.ELIMIT \
                        and "kv handoff failed" not in pc.error_text:
                    # the PREFILL admission shed this tenant: not the
                    # decode worker's fault — pass the shed (and its
                    # backoff hint) straight to the client.  (An ELIMIT
                    # whose text says the HANDOFF failed is the decode
                    # side's — saturated pool, busy session — and falls
                    # through to the punish-and-retry path below.)
                    if pc.retry_after_ms:
                        cntl.retry_after_ms = pc.retry_after_ms
                    cntl.set_failed(pc.error_code_,
                                    f"prefill shed: {pc.error_text}")
                    done()
                    return
                # the handoff INSIDE prefill failed against this decode
                # worker (dead/saturated): punish its weight and retry
                # another one.  The REAL elapsed time matters: LALB's
                # error punishment scales with the reported latency, so
                # a 0-µs error sample would INFLATE the dead worker's
                # weight instead of collapsing it
                self._router.feedback(decode_url, pc.error_code_,
                                      max(pre_us, 1))
                tried.add(decode_url)
                last_err = (pc.error_code_,
                            f"prefill failed: {pc.error_text}")
                with self._lock:
                    self.retries += 1
                continue
            pre = json.loads(pre_resp.message)
            dc = rpc.Controller()
            t0 = time.perf_counter_ns()
            dec_resp = self._router.channel(decode_url).call_method(
                "Decode.Decode", dc,
                EchoRequest(message=json.dumps(
                    {"session": session, "steps": steps,
                     "release": req.get("release", True),
                     **({"mode": req["mode"]} if "mode" in req
                        else {})})),
                EchoResponse)
            lat_us = (time.perf_counter_ns() - t0) // 1000
            self._router.feedback(decode_url, dc.error_code_
                                  if dc.failed() else 0, lat_us)
            if dc.failed():
                # ELIMIT is a SHED, not a dead worker: an evicted/
                # expired session just needs a re-prefill (possibly on
                # the SAME worker — with one worker, excluding it would
                # turn a recoverable shed into a client-visible
                # failure), and a saturated pool is already being
                # steered away from by the LALB weight punishment.
                # Anything else (dead socket, drain) excludes the
                # worker from this call's retries.
                if dc.error_code_ != rpc.errors.ELIMIT:
                    tried.add(decode_url)
                last_err = (dc.error_code_,
                            f"decode failed: {dc.error_text}")
                if dc.retry_after_ms:
                    cntl.retry_after_ms = dc.retry_after_ms
                with self._lock:
                    self.retries += 1
                continue
            toks = json.loads(dec_resp.message)["tokens"]
            _reply(response, done, session=session, tokens=toks,
                   decode_worker=decode_url,
                   kv_bytes=pre.get("kv_bytes", 0))
            return
        with self._lock:
            self.generate_failures += 1
        cntl.set_failed(last_err[0], last_err[1])
        done()


def start_prefill_worker(addr: str, device=None,
                         options: Optional[rpc.ServerOptions] = None
                         ) -> rpc.Server:
    server = rpc.Server(options)
    server.add_service(PrefillService(device=device))
    rc = server.start(addr)
    assert rc == 0, f"prefill worker start failed: {rc}"
    return server


def start_decode_worker(addr: str, device=None,
                        options: Optional[rpc.ServerOptions] = None,
                        pool_options: Optional[KvPoolOptions] = None,
                        sched_options: Optional[
                            BatchSchedulerOptions] = None
                        ) -> rpc.Server:
    server = rpc.Server(options)
    server.add_service(DecodeService(device=device,
                                     pool_options=pool_options,
                                     sched_options=sched_options))
    rc = server.start(addr)
    assert rc == 0, f"decode worker start failed: {rc}"
    return server


def start_router(addr: str, prefill_targets: str,
                 decode_targets: Union[Dict[str, str], list, str]
                 ) -> rpc.Server:
    server = rpc.Server()
    server.add_service(RouterService(prefill_targets, decode_targets))
    rc = server.start(addr)
    assert rc == 0, f"router start failed: {rc}"
    return server
