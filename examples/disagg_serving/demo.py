"""Runnable disaggregated-serving demo (single process, virtual mesh).

Starts one prefill worker (ici://1), two decode workers (ici://2,
ici://3), and a router (mem://), then generates a few completions and
verifies them against the single-process reference — the KV handoff
crossed the device plane, the tokens must be bit-identical.

    python -m examples.disagg_serving.demo

For the cross-process (pod) flavor — every worker its own process, KV
blocks crossing the fabric's sequenced device plane — see README.md and
bench.py's ``pod_prefill_decode`` tier.
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, __file__.rsplit("/", 3)[0])

# the virtual 8-device CPU mesh (the tests' fixture): without it a bare
# CPU jax exposes ONE device, every worker lands on it, and the KV
# handoff never needs to cross anything
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")


def main() -> int:
    import jax
    import brpc_tpu.policy  # noqa: F401
    from brpc_tpu import rpc
    from brpc_tpu.butil import flags as _fl
    import brpc_tpu.ici.device_plane  # noqa: F401 — defines the flags
    from examples.example_echo_pb2 import EchoRequest, EchoResponse
    from examples.disagg_serving.model import reference_generate
    from examples.disagg_serving.workers import (
        start_prefill_worker, start_decode_worker, start_router)

    # the device plane engages for the KV handoff on this host-memory
    # mesh (the identical datapath CI exercises; on TPU it is on by
    # default)
    _fl.set_flag("ici_device_plane_host_mesh", True)
    _fl.set_flag("ici_device_plane_threshold", 64 * 1024)
    # rpcz: the router→prefill→decode trace — including the KV
    # handoff's device-plane transfer spans — prints at the end
    _fl.set_flag("rpcz_enabled", True)

    devs = jax.devices()
    # trace fidelity: the native IN-PROCESS ici fast path creates client
    # spans only (no server span, no propagation into the handler —
    # ROADMAP item 1 keeps the whole native path native); the Python
    # plane traces end to end, and cross-process pods ride it anyway
    wopts = rpc.ServerOptions()
    wopts.native_ici = False
    prefill = start_prefill_worker("ici://1", device=devs[1 % len(devs)],
                                   options=wopts)
    decode_a = start_decode_worker("ici://2", device=devs[2 % len(devs)],
                                   options=rpc.ServerOptions(
                                       native_ici=False))
    decode_b = start_decode_worker("ici://3", device=devs[3 % len(devs)],
                                   options=rpc.ServerOptions(
                                       native_ici=False))
    router = start_router("mem://disagg-router", "ici://1",
                          {"ici://2": "ici://2", "ici://3": "ici://3"})
    try:
        ch = rpc.Channel()
        ch.init("mem://disagg-router",
                options=rpc.ChannelOptions(timeout_ms=60000))
        ok = 0
        for i in range(4):
            tokens = [(7 * i + j) % 997 for j in range(96 + 16 * i)]
            cntl = rpc.Controller()
            resp = ch.call_method(
                "Router.Generate", cntl,
                EchoRequest(message=json.dumps(
                    {"tokens": tokens, "steps": 8})), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            out = json.loads(resp.message)
            want = reference_generate(tokens, 8)
            assert out["tokens"] == want, (out["tokens"], want)
            ok += 1
            print(f"prompt {i}: {out['kv_bytes']} KV bytes -> "
                  f"{out['decode_worker']} -> tokens {out['tokens'][:4]}…"
                  f" verified")
        from brpc_tpu.ici.device_plane import DevicePlane
        stats = DevicePlane.instance().stats()
        print("device plane:", stats)
        # the serving subsystem's route assertion: the decode worker's
        # /status serving block (continuous-batching scheduler + paged
        # pool) — tokens came through the step loop, not a sync path
        for srv in (decode_a, decode_b):
            for name, svc in srv._services.items():
                if hasattr(svc, "describe_serving"):
                    d = svc.describe_serving()
                    print(f"serving[{name}@{srv.listen_endpoint}]: "
                          f"steps={d['scheduler']['steps']} "
                          f"pool_blocks_used={d['pool']['blocks_used']}")
        assert stats["transfers"] > 0, (
            "KV handoff never crossed the device plane", stats)
        # the last request's trace as one tree (single process here;
        # across a pod the SAME query on any member stitches every
        # process's spans — docs/OBSERVABILITY.md)
        import time as _time
        from brpc_tpu.rpc.span import find_trace
        from brpc_tpu.rpc.builtin.pod_scope import stitch_tree
        _time.sleep(0.2)                  # transfer completions store
        spans = [s.describe() for s in find_trace(cntl.trace_id)]
        for s in spans:
            s["aligned_start_us"] = s["start_real_us"]

        def show(node, depth=0):
            print("  " * depth
                  + f"rpcz {node['side']:>8} {node['method']} "
                    f"{node['latency_us']}us "
                    f"({len(node['annotations'])} annotations)")
            for c in node["children"]:
                show(c, depth + 1)

        for root in stitch_tree(spans):
            show(root)
        assert any(s["side"] == "transfer" for s in spans), (
            "KV handoff transfer spans missing from the trace")
        print(f"disagg_serving demo: {ok}/4 completions verified "
              f"({stats['transfers']} device-plane transfers)")
        ch.close()
        return 0
    finally:
        router.stop()
        decode_a.stop()
        decode_b.stop()
        prefill.stop()


if __name__ == "__main__":
    sys.exit(main())
