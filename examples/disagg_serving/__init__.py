"""Disaggregated prefill/decode serving over the pod fabric.

The scenario a million-user TPU serving fleet actually runs: prefill
workers burn compute turning prompts into KV-cache blocks, decode
workers burn memory bandwidth streaming tokens out of them, and the two
scale independently — which only works if KV-cache blocks move between
worker processes fast, as DEVICE payloads, without staging through the
host.  See README.md for the walkthrough.
"""
from .model import (toy_kv_blocks, toy_decode, reference_generate,
                    KV_LAYERS, KV_DMODEL)
from .workers import (PrefillService, DecodeService, RouterService,
                      start_prefill_worker, start_decode_worker,
                      start_router)
