"""nshead protocol extension (reference example/nshead_extension_c++:
serve a home-grown nshead-framed protocol by subclassing NsheadService)."""
from __future__ import annotations

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import brpc_tpu.policy  # noqa: F401
from brpc_tpu import rpc
from brpc_tpu.policy.nshead import NsheadMessage, NsheadService


class ReverseService(NsheadService):
    """The custom wire payload here is raw bytes, reversed."""

    def process_nshead_request(self, server, cntl, request, response,
                               done):
        response.body.append(request.body.to_bytes()[::-1])
        done()


def main() -> None:
    server = rpc.Server()
    server.add_service(ReverseService())
    assert server.start("mem://nshead-example") == 0
    try:
        ch = rpc.Channel()
        ch.init("mem://nshead-example",
                options=rpc.ChannelOptions(protocol="nshead",
                                           timeout_ms=2000))
        req = NsheadMessage()
        req.head.log_id = 7
        req.body.append(b"stressed")
        cntl = rpc.Controller()
        resp = ch.call_method("", cntl, req)
        assert not cntl.failed(), cntl.error_text
        assert resp.body.to_bytes() == b"desserts"
        print("nshead ->", resp.body.to_bytes())
    finally:
        server.stop()


if __name__ == "__main__":
    main()
