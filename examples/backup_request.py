"""Backup (hedged) requests — example/backup_request_c++ +
docs/cn/backup_request.md semantics: a second try fires after
backup_request_ms; the first response wins, the loser is ignored."""
from __future__ import annotations

import time

from examples.common import EchoRequest, EchoResponse, rpc


class SlowThenFastService(rpc.Service):
    SERVICE_NAME = "EchoService"

    def __init__(self):
        self.calls = 0

    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        self.calls += 1
        if self.calls == 1:
            time.sleep(0.3)          # first try is slow
        response.message = f"reply-to-try-{self.calls}"
        done()


def main() -> None:
    server = rpc.Server()
    svc = SlowThenFastService()
    server.add_service(svc)
    assert server.start("mem://example-backup") == 0
    try:
        ch = rpc.Channel()
        ch.init("mem://example-backup",
                options=rpc.ChannelOptions(timeout_ms=2000, max_retry=2,
                                           backup_request_ms=50))
        cntl = rpc.Controller()
        t0 = time.monotonic()
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="h"), EchoResponse)
        dt = (time.monotonic() - t0) * 1000
        assert not cntl.failed(), cntl.error_text
        print(f"got {resp.message!r} in {dt:.0f}ms "
              f"(server saw {svc.calls} tries; hedge beat the 300ms try)")
        assert dt < 280, "backup request should beat the slow first try"
    finally:
        server.stop()


if __name__ == "__main__":
    main()
