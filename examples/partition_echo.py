"""PartitionChannel over tagged naming — example/partition_echo_c++."""
from __future__ import annotations

import tempfile

from examples.common import EchoRequest, EchoResponse, start_echo_server, rpc
from brpc_tpu import channels
from examples.parallel_echo import ConcatMerger


def main() -> None:
    servers = [start_echo_server(f"mem://example-part-{i}", tag=f"part{i}")
               for i in range(3)]
    listing = tempfile.NamedTemporaryFile("w", suffix=".cluster", delete=False)
    for i in range(3):
        listing.write(f"mem://example-part-{i} 100 {i}/3\n")
    listing.close()
    try:
        pc = channels.PartitionChannel()
        assert pc.init(3, f"file://{listing.name}",
                       merger=ConcatMerger()) == 0
        assert pc.partitions_ready()
        cntl = rpc.Controller()
        resp = EchoResponse()
        pc.call_method("EchoService.Echo", cntl,
                       EchoRequest(message="pt"), resp)
        assert not cntl.failed(), cntl.error_text
        print("partition responses:", sorted(resp.message.split("|")))
    finally:
        for s in servers:
            s.stop()


if __name__ == "__main__":
    main()
