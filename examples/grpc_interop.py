"""gRPC foreign-implementation interop — the migration path for services
moving off stock gRPC: the SAME port serves this framework's clients
(tpu_std) AND unmodified grpcio clients simultaneously, and our
``rpc.Channel(protocol="grpc")`` can call an unmodified ``grpc.server()``
— so a fleet can migrate one process at a time in either direction.

Requires grpcio (skipped cleanly when absent).  Reference analogue:
example/grpc_c++ interoperating with grpc's own stacks."""
from __future__ import annotations

from examples.common import (EchoRequest, EchoResponse, EchoService,
                             rpc)


def main() -> None:
    try:
        import grpc
    except ImportError:
        print("grpc interop: grpcio not installed, skipping")
        return

    # --- direction 1: a stock grpcio client calls OUR server ----------
    server = rpc.Server()
    server.add_service(EchoService(tag="ours"))
    assert server.start("127.0.0.1:0") == 0
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{server.listen_port}")
        stub = ch.unary_unary(
            "/EchoService/Echo",
            request_serializer=EchoRequest.SerializeToString,
            response_deserializer=EchoResponse.FromString)
        resp = stub(EchoRequest(message="hello"), timeout=10)
        print(f"grpcio client -> our server: {resp.message!r}")
        ch.close()
        # the SAME port still answers our own protocol clients
        own = rpc.Channel()
        own.init(f"127.0.0.1:{server.listen_port}",
                 options=rpc.ChannelOptions(timeout_ms=2000))
        cntl = rpc.Controller()
        resp = own.call_method("EchoService.Echo", cntl,
                               EchoRequest(message="native"), EchoResponse)
        assert not cntl.failed(), cntl.error_text
        print(f"tpu_std client -> same port:  {resp.message!r}")
    finally:
        server.stop()

    # --- direction 2: OUR channel calls a stock grpc.server() ---------
    from concurrent import futures

    class Handler(grpc.GenericRpcHandler):
        def service(self, hcd):
            if hcd.method == "/EchoService/Echo":
                def unary(req, ctx):
                    out = EchoResponse()
                    out.message = "grpcio:" + req.message
                    return out
                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=EchoRequest.FromString,
                    response_serializer=EchoResponse.SerializeToString)
            return None

    gs = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    gs.add_generic_rpc_handlers((Handler(),))
    port = gs.add_insecure_port("127.0.0.1:0")
    gs.start()
    try:
        ch = rpc.Channel()
        ch.init(f"tcp://127.0.0.1:{port}",
                options=rpc.ChannelOptions(protocol="grpc",
                                           timeout_ms=5000))
        cntl = rpc.Controller()
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="out"), EchoResponse)
        assert not cntl.failed(), cntl.error_text
        print(f"our channel  -> grpc.server: {resp.message!r}")
    finally:
        gs.stop(None)


if __name__ == "__main__":
    main()
