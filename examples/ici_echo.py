"""Echo over the ici:// device fabric with an HBM-resident payload —
the TPU-native counterpart of example/rdma_performance's latency mode."""
from __future__ import annotations

import time

from examples.common import EchoRequest, EchoResponse, start_echo_server, rpc


def main() -> None:
    import jax
    import jax.numpy as jnp
    from brpc_tpu.ici.mesh import IciMesh

    mesh = IciMesh.default()
    server = start_echo_server("ici://0")
    try:
        ch = rpc.Channel()
        ch.init("ici://0", options=rpc.ChannelOptions(timeout_ms=10000))
        payload = jax.device_put(jnp.arange(65536, dtype=jnp.uint8),
                                 mesh.device(min(1, mesh.size - 1)))
        jax.block_until_ready(payload)
        lats = []
        for i in range(30):
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            t0 = time.perf_counter_ns()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="device"), EchoResponse)
            lats.append((time.perf_counter_ns() - t0) / 1000)
            assert not cntl.failed(), cntl.error_text
        lats.sort()
        from brpc_tpu.ici.transport import ici_transport_stats
        total, device_bytes = ici_transport_stats()
        print(f"ici echo with 64KB HBM payload: p50={lats[len(lats)//2]:.0f}us "
              f"p99={lats[-1]:.0f}us; fabric moved {device_bytes} "
              f"device bytes without host copies")
    finally:
        server.stop()


if __name__ == "__main__":
    main()
