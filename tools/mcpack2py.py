"""mcpack2py — the code-GENERATOR half of mcpack2pb (VERDICT r3 missing
#6; reference: src/mcpack2pb/generator.cpp, which emits per-message C++
parse/serialize from .proto).

Ours emits per-message PYTHON codecs from protobuf descriptors: straight-
line field access with names, presence checks, and nesting resolved at
GENERATION time — no runtime descriptor walk.  The emitted bytes are
guaranteed identical to the runtime bridge (`codec/mcpack.py`
pb_to_mcpack / mcpack_to_pb); tests/test_mcpack_ubrpc.py pins that with a
corpus in both FORMAT_MCPACK and FORMAT_COMPACK.

Usage:
    python tools/mcpack2py.py tests.echo_pb2:EchoRequest \
        tests.echo_pb2:TagBag -o echo_mcpack.py

Generated module surface (per message type X):
    encode_X(msg, compack=False) -> bytes
    decode_X(data, msg) -> msg        # fills and returns msg
"""
from __future__ import annotations

import argparse
import importlib
import sys
from typing import Any, List


def _is_map(fd) -> bool:
    mt = getattr(fd, "message_type", None)
    return mt is not None and mt.GetOptions().map_entry


def _is_repeated(fd) -> bool:
    rep = getattr(fd, "is_repeated", None)
    if isinstance(rep, bool):
        return rep
    from google.protobuf.descriptor import FieldDescriptor as FD
    return fd.label == FD.LABEL_REPEATED


def _has_presence(fd) -> bool:
    """Explicit field presence (proto3 `optional`, oneof member, proto2
    optional): these round-trip through HasField, not truthiness."""
    hp = getattr(fd, "has_presence", None)
    if isinstance(hp, bool):
        return hp
    return fd.containing_oneof is not None


def _collect_message_types(descs) -> List[Any]:
    """Transitive closure of message descriptors (skip map entries),
    dependency order not required — functions resolve lazily by name."""
    seen = {}
    stack = list(descs)
    while stack:
        d = stack.pop()
        if d.full_name in seen or d.GetOptions().map_entry:
            continue
        seen[d.full_name] = d
        for fd in d.fields:
            if _is_map(fd):
                vfd = fd.message_type.fields_by_name["value"]
                if vfd.message_type is not None:
                    stack.append(vfd.message_type)
            elif fd.message_type is not None:
                stack.append(fd.message_type)
    return list(seen.values())


def _fn(desc) -> str:
    return desc.full_name.replace(".", "_")


def _gen_dict_fn(desc, out: List[str]) -> None:
    from google.protobuf.descriptor import FieldDescriptor as FD
    out.append(f"def _dict_{_fn(desc)}(msg):")
    out.append("    d = {}")
    # ListFields() (the runtime bridge's walk) orders by field NUMBER —
    # matching insertion order is what makes the bytes identical
    for fd in sorted(desc.fields, key=lambda f: f.number):
        name = fd.name
        if _is_map(fd):
            vfd = fd.message_type.fields_by_name["value"]
            out.append(f"    v = msg.{name}")
            if vfd.type == FD.TYPE_MESSAGE:
                sub = _fn(vfd.message_type)
                out.append(f"    if v: d[{name!r}] = "
                           f"{{str(k): _dict_{sub}(x) "
                           f"for k, x in v.items()}}")
            else:
                out.append(f"    if v: d[{name!r}] = "
                           f"{{str(k): x for k, x in v.items()}}")
        elif _is_repeated(fd):
            out.append(f"    v = msg.{name}")
            if fd.type == FD.TYPE_MESSAGE:
                sub = _fn(fd.message_type)
                out.append(f"    if v: d[{name!r}] = "
                           f"[_dict_{sub}(x) for x in v]")
            else:
                out.append(f"    if v: d[{name!r}] = list(v)")
        elif fd.type == FD.TYPE_MESSAGE:
            sub = _fn(fd.message_type)
            out.append(f"    if msg.HasField({name!r}): "
                       f"d[{name!r}] = _dict_{sub}(msg.{name})")
        elif _has_presence(fd):
            # explicit presence (proto3 `optional`, oneof members,
            # proto2 optional): ListFields includes the field even at
            # its default value — truthiness would drop a set-to-0
            out.append(f"    if msg.HasField({name!r}): "
                       f"d[{name!r}] = msg.{name}")
        else:
            # proto3 implicit presence: emitted iff != default — exactly
            # ListFields' rule; Python truthiness matches for all scalar
            # defaults (0, 0.0, False, '', b'', enum 0)
            out.append(f"    v = msg.{name}")
            out.append(f"    if v: d[{name!r}] = v")
    out.append("    return d")
    out.append("")
    out.append("")


def _gen_fill_fn(desc, out: List[str]) -> None:
    from google.protobuf.descriptor import FieldDescriptor as FD
    out.append(f"def _fill_{_fn(desc)}(d, msg):")
    for fd in sorted(desc.fields, key=lambda f: f.number):
        name = fd.name
        out.append(f"    v = d.get({name!r})")
        out.append("    if v is not None:")
        if _is_map(fd):
            kfd = fd.message_type.fields_by_name["key"]
            vfd = fd.message_type.fields_by_name["value"]
            out.append(f"        t = msg.{name}")
            out.append("        for k, x in v.items():")
            if kfd.type != FD.TYPE_STRING:
                out.append("            k = int(k) "
                           "if isinstance(k, str) else k")
            if vfd.type == FD.TYPE_MESSAGE:
                sub = _fn(vfd.message_type)
                out.append(f"            _fill_{sub}(x, t[k])")
            else:
                out.append("            t[k] = x")
        elif _is_repeated(fd):
            out.append(f"        t = msg.{name}")
            if fd.type == FD.TYPE_MESSAGE:
                sub = _fn(fd.message_type)
                out.append("        for x in v:")
                out.append(f"            _fill_{sub}(x, t.add())")
            else:
                out.append("        t.extend(v)")
        elif fd.type == FD.TYPE_MESSAGE:
            sub = _fn(fd.message_type)
            out.append(f"        _fill_{sub}(v, msg.{name})")
        elif fd.type == FD.TYPE_BYTES:
            out.append(f"        msg.{name} = bytes(v)")
        else:
            out.append(f"        msg.{name} = v")
    out.append("    return msg")
    out.append("")
    out.append("")


def generate_module_source(message_classes) -> str:
    """Emit a self-contained module with encode_X/decode_X for every
    class (and _dict_/_fill_ helpers for every transitively reached
    message type)."""
    descs = [cls.DESCRIPTOR for cls in message_classes]
    closure = _collect_message_types(descs)
    out: List[str] = [
        '"""GENERATED by tools/mcpack2py.py — per-message mcpack codecs',
        '(mcpack2pb generated-code analogue).  Do not edit."""',
        "from brpc_tpu.codec.mcpack import mcpack_encode, mcpack_decode",
        "",
        "",
    ]
    for d in closure:
        _gen_dict_fn(d, out)
        _gen_fill_fn(d, out)
    shorts = {}
    for cls in message_classes:
        prev = shorts.setdefault(cls.DESCRIPTOR.name, cls.DESCRIPTOR)
        if prev is not cls.DESCRIPTOR:
            # encode_X names use the short name — two same-named messages
            # from different packages would silently shadow each other
            raise ValueError(
                f"duplicate short message name {cls.DESCRIPTOR.name!r}: "
                f"{prev.full_name} vs {cls.DESCRIPTOR.full_name}")
    for cls in message_classes:
        d = cls.DESCRIPTOR
        short = d.name
        out.append(f"def encode_{short}(msg, compack=False):")
        out.append(f"    return mcpack_encode(_dict_{_fn(d)}(msg), "
                   "compack=compack)")
        out.append("")
        out.append("")
        out.append(f"def decode_{short}(data, msg):")
        out.append(f"    return _fill_{_fn(d)}(mcpack_decode(data), msg)")
        out.append("")
        out.append("")
    return "\n".join(out)


def _load(spec: str):
    import os
    if os.getcwd() not in sys.path:      # script mode puts tools/ on the
        sys.path.insert(0, os.getcwd())  # path, not the invoking cwd
    mod_name, _, cls_name = spec.partition(":")
    mod = importlib.import_module(mod_name)
    return getattr(mod, cls_name)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("classes", nargs="+",
                    help="message classes as module:ClassName")
    ap.add_argument("-o", "--output", default="-",
                    help="output file (default stdout)")
    args = ap.parse_args(argv)
    src = generate_module_source([_load(s) for s in args.classes])
    if args.output == "-":
        sys.stdout.write(src)
    else:
        with open(args.output, "w") as f:
            f.write(src)
        print(f"wrote {args.output}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
