"""HTTP protocol tests: JSON RPC + builtin admin pages over the same port
(reference test/brpc_http_rpc_protocol_unittest.cpp pattern)."""
import json
import socket as pysocket
import time

import pytest

import brpc_tpu.policy
from brpc_tpu import rpc
from tests.echo_pb2 import EchoRequest, EchoResponse

_seq = [900]


def unique(p="http"):
    _seq[0] += 1
    return f"{p}-{_seq[0]}"


class EchoService(rpc.Service):
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = "http:" + request.message
        done()


def start_tcp_server():
    server = rpc.Server()
    server.add_service(EchoService())
    assert server.start("127.0.0.1:0") == 0
    return server


def raw_http(port, request: bytes) -> bytes:
    with pysocket.create_connection(("127.0.0.1", port), timeout=5) as s:
        s.sendall(request)
        data = b""
        s.settimeout(5)
        while b"\r\n\r\n" not in data or not _complete(data):
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
        return data


def _complete(data: bytes) -> bool:
    head, _, rest = data.partition(b"\r\n\r\n")
    for line in head.split(b"\r\n"):
        if line.lower().startswith(b"content-length:"):
            return len(rest) >= int(line.split(b":")[1])
    return True


class TestHttpServer:
    def test_json_rpc_post(self):
        server = start_tcp_server()
        try:
            body = json.dumps({"message": "hello"}).encode()
            req = (b"POST /EchoService/Echo HTTP/1.1\r\nHost: x\r\n"
                   b"Content-Type: application/json\r\n"
                   b"Content-Length: %d\r\n\r\n%s" % (len(body), body))
            resp = raw_http(server.listen_port, req)
            assert resp.startswith(b"HTTP/1.1 200")
            payload = json.loads(resp.split(b"\r\n\r\n", 1)[1])
            assert payload["message"] == "http:hello"
        finally:
            server.stop()

    def test_get_with_query_params(self):
        server = start_tcp_server()
        try:
            req = b"GET /EchoService/Echo?message=qs HTTP/1.1\r\nHost: x\r\n\r\n"
            resp = raw_http(server.listen_port, req)
            assert resp.startswith(b"HTTP/1.1 200")
            assert json.loads(resp.split(b"\r\n\r\n", 1)[1])["message"] == "http:qs"
        finally:
            server.stop()

    def test_404(self):
        server = start_tcp_server()
        try:
            resp = raw_http(server.listen_port,
                            b"GET /no/such/thing HTTP/1.1\r\nHost: x\r\n\r\n")
            assert resp.startswith(b"HTTP/1.1 404")
        finally:
            server.stop()

    def test_bad_json_is_400(self):
        server = start_tcp_server()
        try:
            body = b"{not json"
            req = (b"POST /EchoService/Echo HTTP/1.1\r\nHost: x\r\n"
                   b"Content-Length: %d\r\n\r\n%s" % (len(body), body))
            resp = raw_http(server.listen_port, req)
            assert resp.startswith(b"HTTP/1.1 400")
        finally:
            server.stop()

    @pytest.mark.parametrize("page,needle", [
        ("health", b"OK"),
        ("status", b"EchoService"),
        ("vars", b"rpc_socket_count"),
        ("flags", b"bthread_concurrency"),
        ("connections", b"remote"),
        ("brpc_metrics", b"# TYPE"),
        ("protobufs", b"EchoRequest"),
        ("bthreads", b"workers"),
        ("rpcz", b"spans"),
        ("version", b"brpc_tpu"),
        ("threads", b"--- thread"),
        ("list_services", b"EchoRequest"),
        ("vlog", b"min level"),
        ("dir", b"entries"),
        ("pprof/cmdline", b"python"),
        ("pprof/symbol", b"num_symbols"),
    ])
    def test_builtin_pages(self, page, needle):
        server = start_tcp_server()
        try:
            resp = raw_http(server.listen_port,
                            b"GET /%s HTTP/1.1\r\nHost: x\r\n\r\n"
                            % page.encode())
            assert resp.startswith(b"HTTP/1.1 200"), resp[:200]
            assert needle in resp
        finally:
            server.stop()

    def test_flags_set_via_http(self):
        from brpc_tpu.butil import flags as _flags
        _flags.define_flag("test_http_reload", 5, "x",
                           _flags.positive_integer)
        server = start_tcp_server()
        try:
            resp = raw_http(server.listen_port,
                            b"GET /flags?setvalue=test_http_reload&to=9 "
                            b"HTTP/1.1\r\nHost: x\r\n\r\n")
            assert b"ok" in resp
            assert _flags.get_flag("test_http_reload") == 9
        finally:
            server.stop()

    def test_protocol_coexists_with_tpu_std(self):
        """Same port serves TRPC frames and HTTP text."""
        server = start_tcp_server()
        try:
            ch = rpc.Channel()
            ch.init(f"127.0.0.1:{server.listen_port}")
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="bin"), EchoResponse)
            assert not cntl.failed() and resp.message == "http:bin"
            http_resp = raw_http(server.listen_port,
                                 b"GET /health HTTP/1.1\r\nHost: x\r\n\r\n")
            assert b"OK" in http_resp
        finally:
            server.stop()


class TestHttpClient:
    def test_channel_with_http_protocol(self):
        server = start_tcp_server()
        try:
            ch = rpc.Channel()
            ch.init(f"127.0.0.1:{server.listen_port}",
                    options=rpc.ChannelOptions(protocol="http",
                                               timeout_ms=5000))
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="cli"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "http:cli"
        finally:
            server.stop()

    def test_http_client_error_mapping(self):
        server = start_tcp_server()
        try:
            ch = rpc.Channel()
            ch.init(f"127.0.0.1:{server.listen_port}",
                    options=rpc.ChannelOptions(protocol="http",
                                               timeout_ms=5000, max_retry=0))
            cntl = rpc.Controller()
            ch.call_method("NoService.NoMethod", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert cntl.failed()
        finally:
            server.stop()


class TestChunkedTransferEncoding:
    """RFC 7230 §4.1 chunked coding, parse + emit (the last VERDICT
    Content-Length-only gap).  A chunked request is answered chunked (the
    echo rule), so one round trip exercises both directions."""

    @staticmethod
    def _chunk(body: bytes, sizes, trailer: bytes = b"") -> bytes:
        out, off = [], 0
        for n in sizes:
            piece = body[off:off + n]
            out.append(b"%x\r\n%s\r\n" % (len(piece), piece))
            off += n
        assert off == len(body)
        out.append(b"0\r\n" + trailer + b"\r\n")
        return b"".join(out)

    @staticmethod
    def _recv_chunked(port, request: bytes) -> bytes:
        with pysocket.create_connection(("127.0.0.1", port), timeout=5) as s:
            s.sendall(request)
            s.settimeout(5)
            data = b""
            while not data.endswith(b"0\r\n\r\n"):
                chunk = s.recv(65536)
                if not chunk:
                    break
                data += chunk
            return data

    def test_chunked_request_round_trip_chunked_response(self):
        server = start_tcp_server()
        try:
            body = json.dumps({"message": "chunky"}).encode()
            framed = self._chunk(body, [7, len(body) - 7],
                                 trailer=b"X-Trailer: ignored\r\n")
            req = (b"POST /EchoService/Echo HTTP/1.1\r\nHost: t\r\n"
                   b"Content-Type: application/json\r\n"
                   b"Transfer-Encoding: chunked\r\n\r\n" + framed)
            resp = self._recv_chunked(server.listen_port, req)
            head, _, rest = resp.partition(b"\r\n\r\n")
            assert b"200" in head.split(b"\r\n")[0]
            assert b"transfer-encoding: chunked" in head.lower()
            assert b"content-length" not in head.lower()
            from brpc_tpu.policy.http import _parse_chunked_body
            decoded, consumed = _parse_chunked_body(resp, len(head) + 4)
            assert decoded is not None and consumed == len(resp)
            assert json.loads(decoded)["message"] == "http:chunky"
        finally:
            server.stop()

    def test_parser_reassembles_split_chunked_delivery(self):
        """The parser must report NOT_ENOUGH_DATA for a partial chunked
        body and succeed once the tail arrives — the streamed-arrival
        path a real socket produces."""
        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.policy.http import _parse_http
        from brpc_tpu.rpc.protocol import ParseResultType
        body = b"0123456789abcdef"
        framed = self._chunk(body, [4, 12])
        wire = (b"POST /x HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n"
                + framed)
        for cut in (len(wire) - 1, len(wire) - 8, len(wire) - len(framed)):
            partial = IOBuf(wire[:cut])
            assert _parse_http(partial).type == \
                ParseResultType.NOT_ENOUGH_DATA
        buf = IOBuf(wire)
        pr = _parse_http(buf)
        assert pr.type == ParseResultType.OK
        assert pr.message.body == body
        assert len(buf) == 0

    def test_chunked_response_parsed_by_client_parser(self):
        """Client direction: a chunked RESPONSE decodes through the same
        parser (HTTP/1.1 servers stream bodies of unknown length)."""
        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.policy.http import _parse_http
        from brpc_tpu.rpc.protocol import ParseResultType
        payload = json.dumps({"message": "streamed"}).encode()
        wire = (b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n"
                + self._chunk(payload, [3, len(payload) - 3]))
        pr = _parse_http(IOBuf(wire))
        assert pr.type == ParseResultType.OK
        assert not pr.message.is_request
        assert json.loads(pr.message.body)["message"] == "streamed"

    def test_malformed_chunk_size_is_a_parse_error(self):
        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.policy.http import _parse_http
        from brpc_tpu.rpc.protocol import ParseResultType
        # int(x, 16) would accept the -2/+5/0x10/1_0 shapes — a strict
        # RFC 7230 peer disagrees about framing on them, the
        # request-smuggling setup — so only pure hex digits parse
        for bad in (b"zz", b"-2", b"+5", b"0x10", b"1_0", b""):
            wire = (b"POST /x HTTP/1.1\r\nTransfer-Encoding: chunked"
                    b"\r\n\r\n" + bad + b"\r\nbody\r\n0\r\n\r\n")
            assert _parse_http(IOBuf(wire)).type == ParseResultType.ERROR, \
                bad

    def test_transfer_encoding_must_be_a_lone_chunked_token(self):
        """'gzip, chunked' (a coding we cannot decode) and bogus tokens
        containing 'chunked' are ambiguous-framing shapes RFC 7230
        §3.3.3 says to reject — substring matching would de-chunk and
        hand garbage (or smuggled bytes) to dispatch."""
        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.policy.http import _parse_http
        from brpc_tpu.rpc.protocol import ParseResultType
        body = self._chunk(b"hello", [5])
        for te in (b"gzip, chunked", b"xchunked", b"chunked, gzip",
                   b"chunkedx"):
            wire = (b"POST /x HTTP/1.1\r\nTransfer-Encoding: " + te
                    + b"\r\n\r\n" + body)
            assert _parse_http(IOBuf(wire)).type == ParseResultType.ERROR, te
        # whitespace/case variants of the lone token still parse
        wire = (b"POST /x HTTP/1.1\r\nTransfer-Encoding:  Chunked \r\n\r\n"
                + body)
        pr = _parse_http(IOBuf(wire))
        assert pr.type == ParseResultType.OK and pr.message.body == b"hello"

    def test_chunk_extension_is_ignored(self):
        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.policy.http import _parse_http
        from brpc_tpu.rpc.protocol import ParseResultType
        wire = (b"POST /x HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n"
                b"5;ext=1\r\nhello\r\n0\r\n\r\n")
        pr = _parse_http(IOBuf(wire))
        assert pr.type == ParseResultType.OK
        assert pr.message.body == b"hello"
