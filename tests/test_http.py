"""HTTP protocol tests: JSON RPC + builtin admin pages over the same port
(reference test/brpc_http_rpc_protocol_unittest.cpp pattern)."""
import json
import socket as pysocket
import time

import pytest

import brpc_tpu.policy
from brpc_tpu import rpc
from tests.echo_pb2 import EchoRequest, EchoResponse

_seq = [900]


def unique(p="http"):
    _seq[0] += 1
    return f"{p}-{_seq[0]}"


class EchoService(rpc.Service):
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = "http:" + request.message
        done()


def start_tcp_server():
    server = rpc.Server()
    server.add_service(EchoService())
    assert server.start("127.0.0.1:0") == 0
    return server


def raw_http(port, request: bytes) -> bytes:
    with pysocket.create_connection(("127.0.0.1", port), timeout=5) as s:
        s.sendall(request)
        data = b""
        s.settimeout(5)
        while b"\r\n\r\n" not in data or not _complete(data):
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
        return data


def _complete(data: bytes) -> bool:
    head, _, rest = data.partition(b"\r\n\r\n")
    for line in head.split(b"\r\n"):
        if line.lower().startswith(b"content-length:"):
            return len(rest) >= int(line.split(b":")[1])
    return True


class TestHttpServer:
    def test_json_rpc_post(self):
        server = start_tcp_server()
        try:
            body = json.dumps({"message": "hello"}).encode()
            req = (b"POST /EchoService/Echo HTTP/1.1\r\nHost: x\r\n"
                   b"Content-Type: application/json\r\n"
                   b"Content-Length: %d\r\n\r\n%s" % (len(body), body))
            resp = raw_http(server.listen_port, req)
            assert resp.startswith(b"HTTP/1.1 200")
            payload = json.loads(resp.split(b"\r\n\r\n", 1)[1])
            assert payload["message"] == "http:hello"
        finally:
            server.stop()

    def test_get_with_query_params(self):
        server = start_tcp_server()
        try:
            req = b"GET /EchoService/Echo?message=qs HTTP/1.1\r\nHost: x\r\n\r\n"
            resp = raw_http(server.listen_port, req)
            assert resp.startswith(b"HTTP/1.1 200")
            assert json.loads(resp.split(b"\r\n\r\n", 1)[1])["message"] == "http:qs"
        finally:
            server.stop()

    def test_404(self):
        server = start_tcp_server()
        try:
            resp = raw_http(server.listen_port,
                            b"GET /no/such/thing HTTP/1.1\r\nHost: x\r\n\r\n")
            assert resp.startswith(b"HTTP/1.1 404")
        finally:
            server.stop()

    def test_bad_json_is_400(self):
        server = start_tcp_server()
        try:
            body = b"{not json"
            req = (b"POST /EchoService/Echo HTTP/1.1\r\nHost: x\r\n"
                   b"Content-Length: %d\r\n\r\n%s" % (len(body), body))
            resp = raw_http(server.listen_port, req)
            assert resp.startswith(b"HTTP/1.1 400")
        finally:
            server.stop()

    @pytest.mark.parametrize("page,needle", [
        ("health", b"OK"),
        ("status", b"EchoService"),
        ("vars", b"rpc_socket_count"),
        ("flags", b"bthread_concurrency"),
        ("connections", b"remote"),
        ("brpc_metrics", b"# TYPE"),
        ("protobufs", b"EchoRequest"),
        ("bthreads", b"workers"),
        ("rpcz", b"spans"),
        ("version", b"brpc_tpu"),
        ("threads", b"--- thread"),
        ("list_services", b"EchoRequest"),
        ("vlog", b"min level"),
        ("dir", b"entries"),
        ("pprof/cmdline", b"python"),
        ("pprof/symbol", b"num_symbols"),
    ])
    def test_builtin_pages(self, page, needle):
        server = start_tcp_server()
        try:
            resp = raw_http(server.listen_port,
                            b"GET /%s HTTP/1.1\r\nHost: x\r\n\r\n"
                            % page.encode())
            assert resp.startswith(b"HTTP/1.1 200"), resp[:200]
            assert needle in resp
        finally:
            server.stop()

    def test_flags_set_via_http(self):
        from brpc_tpu.butil import flags as _flags
        _flags.define_flag("test_http_reload", 5, "x",
                           _flags.positive_integer)
        server = start_tcp_server()
        try:
            resp = raw_http(server.listen_port,
                            b"GET /flags?setvalue=test_http_reload&to=9 "
                            b"HTTP/1.1\r\nHost: x\r\n\r\n")
            assert b"ok" in resp
            assert _flags.get_flag("test_http_reload") == 9
        finally:
            server.stop()

    def test_protocol_coexists_with_tpu_std(self):
        """Same port serves TRPC frames and HTTP text."""
        server = start_tcp_server()
        try:
            ch = rpc.Channel()
            ch.init(f"127.0.0.1:{server.listen_port}")
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="bin"), EchoResponse)
            assert not cntl.failed() and resp.message == "http:bin"
            http_resp = raw_http(server.listen_port,
                                 b"GET /health HTTP/1.1\r\nHost: x\r\n\r\n")
            assert b"OK" in http_resp
        finally:
            server.stop()


class TestHttpClient:
    def test_channel_with_http_protocol(self):
        server = start_tcp_server()
        try:
            ch = rpc.Channel()
            ch.init(f"127.0.0.1:{server.listen_port}",
                    options=rpc.ChannelOptions(protocol="http",
                                               timeout_ms=5000))
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="cli"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "http:cli"
        finally:
            server.stop()

    def test_http_client_error_mapping(self):
        server = start_tcp_server()
        try:
            ch = rpc.Channel()
            ch.init(f"127.0.0.1:{server.listen_port}",
                    options=rpc.ChannelOptions(protocol="http",
                                               timeout_ms=5000, max_retry=0))
            cntl = rpc.Controller()
            ch.call_method("NoService.NoMethod", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert cntl.failed()
        finally:
            server.stop()
