"""Tools + rpc_dump tests."""
import io
import json
import os
import threading
import time

import pytest

import brpc_tpu.policy
from brpc_tpu import rpc
from brpc_tpu.butil import flags as _flags
from brpc_tpu.rpc import rpc_dump
from tests.echo_pb2 import EchoRequest, EchoResponse

_seq = [2000]


def unique(p="tool"):
    _seq[0] += 1
    return f"{p}-{_seq[0]}"


class EchoService(rpc.Service):
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


def start_server():
    s = rpc.Server()
    s.add_service(EchoService())
    name = unique()
    assert s.start(f"mem://{name}") == 0
    return s, f"mem://{name}"


class TestRpcPress:
    def test_press_reports_qps(self):
        from brpc_tpu.tools.rpc_press import run_press
        server, target = start_server()
        try:
            result = run_press(target, "EchoService.Echo",
                               '{"message":"p"}', qps=0, duration=0.5,
                               concurrency=4,
                               proto="tests.echo_pb2:EchoRequest,EchoResponse",
                               out=io.StringIO())
            assert result["sent"] > 10
            assert result["errors"] == 0
            assert result["qps"] > 0
        finally:
            server.stop()

    def test_press_fanout_mode(self):
        """--fanout N: ONE ParallelChannel over N members, per-route
        call counts + fan-out latency in the summary; with device
        handlers registered the calls ride the compiled route."""
        import numpy as np
        from brpc_tpu.tools.rpc_press import run_press_fanout

        class FanSvc(rpc.Service):
            SERVICE_NAME = "Fan"

            @rpc.method(EchoRequest, EchoResponse)
            def Press(self, cntl, request, response, done):
                cntl.response_attachment.append(
                    cntl.request_attachment.to_bytes())
                done()

        servers = []
        for i in range(4):
            s = rpc.Server()
            s.add_service(FanSvc())
            s.register_collective("Fan.Press", lambda x: x,
                                  merge="gather", mapping="shard")
            assert s.start(f"ici://{i}") == 0
            servers.append(s)
        try:
            from brpc_tpu.channels import collective_fanout as cf
            if cf.CollectiveFanoutPlane.instance().health()["down"]:
                cf.registry().serve(99); cf.registry().withdraw(99)
            result = run_press_fanout(
                "ici://0,ici://1,ici://2,ici://3", "Fan.Press", 4,
                duration=0.5, concurrency=2, shard_bytes=64,
                out=io.StringIO())
            assert result["sent"] > 0
            assert result["errors"] == 0
            assert result["fanout_p50_us"] > 0
            assert set(result["per_route"]) == {"collective"}, result
            assert result["route_counters"].get(
                "collective_selected", 0) > 0
        finally:
            for s in servers:
                s.stop()

    def test_press_throttled(self):
        from brpc_tpu.tools.rpc_press import run_press
        server, target = start_server()
        try:
            result = run_press(target, "EchoService.Echo",
                               '{"message":"p"}', qps=50, duration=1.0,
                               concurrency=2,
                               proto="tests.echo_pb2:EchoRequest,EchoResponse",
                               out=io.StringIO())
            assert result["errors"] == 0
            assert result["qps"] < 120   # throttle held (some slack)
        finally:
            server.stop()

    def test_press_multi_endpoint_reports_per_endpoint_counts(self):
        """A comma-separated --server list drives every endpoint from
        one process and the summary carries per-endpoint sent/errors/qps
        (the pod/overload-bench shape)."""
        from brpc_tpu.tools.rpc_press import run_press
        pairs = [start_server() for _ in range(3)]
        targets = [t for _s, t in pairs]
        try:
            result = run_press(",".join(targets), "EchoService.Echo",
                               '{"message":"p"}', qps=0, duration=0.5,
                               concurrency=3,
                               proto="tests.echo_pb2:EchoRequest,"
                                     "EchoResponse",
                               out=io.StringIO())
            assert result["errors"] == 0
            per = result["per_endpoint"]
            assert sorted(per) == sorted(targets)
            assert all(c["sent"] > 0 for c in per.values()), per
            assert sum(c["sent"] for c in per.values()) == result["sent"]
            assert all(c["qps"] > 0 for c in per.values()), per
        finally:
            for s, _t in pairs:
                s.stop()

    def test_press_bulk_plane_pin_sets_flags_and_reports(self):
        """--bulk-plane pins the fabric byte-mover tier for the run:
        "uds" turns the shm ring off, "inline" turns both descriptor
        planes off, the pin is reported in the summary, and an unknown
        mode is a hard CLI error."""
        import pytest
        import brpc_tpu.ici.fabric  # noqa: F401 — defines the flags
        from brpc_tpu.butil import flags as _fl
        from brpc_tpu.tools.rpc_press import apply_bulk_plane, run_press
        saved = {k: _fl.get_flag(k) for k in ("ici_fabric_shm",
                                              "ici_fabric_bulk")}
        try:
            apply_bulk_plane("uds")
            assert _fl.get_flag("ici_fabric_shm") is False
            assert _fl.get_flag("ici_fabric_bulk") == saved[
                "ici_fabric_bulk"]
            apply_bulk_plane("inline")
            assert _fl.get_flag("ici_fabric_shm") is False
            assert _fl.get_flag("ici_fabric_bulk") is False
            with pytest.raises(SystemExit):
                apply_bulk_plane("warp-drive")
            server, target = start_server()
            try:
                result = run_press(
                    target, "EchoService.Echo", '{"message":"p"}',
                    qps=0, duration=0.2, concurrency=2,
                    proto="tests.echo_pb2:EchoRequest,EchoResponse",
                    bulk_plane="auto", out=io.StringIO())
                assert result["bulk_plane"] == "auto"
            finally:
                server.stop()
        finally:
            for k, v in saved.items():
                _fl.set_flag(k, v)

    def test_press_usercode_pool_pin_and_stats(self):
        """--usercode-pool pins the backend for in-process servers and
        the summary carries the isolation capability record + the
        server's pool stats (ISSUE 13)."""
        from brpc_tpu.rpc import usercode_pool as up
        from brpc_tpu.tools.rpc_press import (apply_usercode_pool,
                                              run_press)
        # pin BEFORE the server starts: the backend resolves when the
        # pool is created (the press re-applies the same pin)
        apply_usercode_pool("pthread")
        server = rpc.Server(rpc.ServerOptions(usercode_in_pthread=True,
                                              usercode_backup_threads=2))
        server.add_service(EchoService())
        name = unique()
        assert server.start(f"mem://{name}") == 0
        try:
            result = run_press(
                f"mem://{name}", "EchoService.Echo", '{"message":"p"}',
                qps=0, duration=0.3, concurrency=2,
                proto="tests.echo_pb2:EchoRequest,EchoResponse",
                usercode_pool="pthread", out=io.StringIO())
            assert result["usercode_pool"] == "pthread"
            stats = result["usercode_pool_stats"]
            caps = up.probe_isolation()
            assert stats["isolation"]["mode"] == caps.mode
            if not caps.scaling:
                assert stats["isolation"]["reason"]
            blk = stats["servers"][f"mem://{name}"]
            assert blk["kind"] in ("pthread", "subinterp")
            assert blk["workers"] == 2
            # the pin applied to this (auto-configured) server
            assert blk["kind"] == "pthread"
        finally:
            server.stop()
            up.set_default_kind("auto")
        import pytest as _pytest
        with _pytest.raises(SystemExit):
            apply_usercode_pool("bogus")

    def test_resolve_targets(self):
        """Endpoint lists split (single endpoints pass through); naming
        urls resolve through the naming service; an empty resolution is
        a hard error, not a silent single-channel run."""
        from brpc_tpu.tools.rpc_press import resolve_targets
        assert resolve_targets("mem://solo") == ["mem://solo"]
        assert resolve_targets("mem://a,mem://b") == ["mem://a",
                                                      "mem://b"]
        got = resolve_targets("list://mem://x,mem://y")
        assert sorted(got) == ["mem://x", "mem://y"], got
        with pytest.raises(SystemExit):
            resolve_targets("pod://never-joined")

    def test_press_sigint_stops_gracefully_with_final_summary(self):
        """^C mid-run stops ISSUING, drains in-flight calls, and still
        prints the final latency/QPS summary — run as a subprocess so
        the SIGINT handler installs in a real main thread."""
        import subprocess
        import sys as _sys
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        child = r"""
import json, os, signal, sys, threading, time
sys.path.insert(0, %(repo)r)
import brpc_tpu.policy
from brpc_tpu import rpc
from tests.echo_pb2 import EchoRequest, EchoResponse

class Echo(rpc.Service):
    SERVICE_NAME = "EchoService"
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()

server = rpc.Server()
server.add_service(Echo())
assert server.start("mem://press-sigint") == 0
threading.Timer(1.0, lambda: os.kill(os.getpid(), signal.SIGINT)).start()
from brpc_tpu.tools.rpc_press import run_press
t0 = time.monotonic()
res = run_press("mem://press-sigint", "EchoService.Echo",
                '{"message":"x"}', qps=200, duration=60, concurrency=4,
                proto="tests.echo_pb2:EchoRequest,EchoResponse",
                out=sys.stdout)
dt = time.monotonic() - t0
assert res["interrupted"] is True, res
assert res["sent"] > 0 and res["errors"] == 0, res
assert dt < 20, dt          # stopped at the ^C, not the 60s duration
server.stop()
print("SIGINT_OK", flush=True)
""" % {"repo": repo}
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run([_sys.executable, "-c", child],
                              capture_output=True, text=True, timeout=120,
                              env=env)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "SIGINT_OK" in proc.stdout
        summary = [l for l in proc.stdout.splitlines()
                   if l.startswith("{")]
        assert summary and json.loads(summary[0])["interrupted"] is True


class TestRpcDumpAndReplay:
    def test_dump_then_replay(self, tmp_path):
        from brpc_tpu.tools.rpc_replay import run_replay
        dump_dir = str(tmp_path / "dump")
        _flags.set_flag("rpc_dump_dir", dump_dir)
        _flags.set_flag("rpc_dump", True)
        server, target = start_server()
        try:
            ch = rpc.Channel(); ch.init(target)
            for i in range(5):
                cntl = rpc.Controller()
                ch.call_method("EchoService.Echo", cntl,
                               EchoRequest(message=f"d{i}"), EchoResponse)
                assert not cntl.failed()
            _flags.set_flag("rpc_dump", False)
            files = rpc_dump.list_dump_files(dump_dir)
            assert files
            frames = rpc_dump.load_dumped_frames(files[0])
            assert len(frames) == 5
            # replay against the same server
            result = run_replay(target, dump_dir, times=2, out=io.StringIO())
            assert result["sent"] == 10
            assert result["ok"] == 10
        finally:
            _flags.set_flag("rpc_dump", False)
            server.stop()


class TestRpcView:
    def test_view_mem_server(self):
        from brpc_tpu.tools.rpc_view import fetch_page
        server, target = start_server()
        try:
            body = fetch_page(target, "health")
            assert body == "OK"
            status = json.loads(fetch_page(target, "status"))
            assert "EchoService" in status["services"]
        finally:
            server.stop()

    def test_view_tcp_server(self):
        from brpc_tpu.tools.rpc_view import fetch_page
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start("127.0.0.1:0") == 0
        try:
            body = fetch_page(f"127.0.0.1:{server.listen_port}", "health")
            assert body == "OK"
        finally:
            server.stop()

    def test_view_naming_url_renders_every_member(self):
        """list:// (any naming url) resolves to every member; each gets
        its own section."""
        from brpc_tpu.tools.rpc_view import fetch_pages, main
        s1, t1 = start_server()
        s2, t2 = start_server()
        try:
            pages = fetch_pages(f"list://{t1},{t2}", "health")
            assert [p[0] for p in pages] == [t1, t2]
            assert all(body == "OK" for _, body in pages)
            # the CLI renders per-member sections
            import contextlib
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                rc = main(["--server", f"list://{t1},{t2}",
                           "--page", "health"])
            assert rc == 0
            out = buf.getvalue()
            assert f"=== {t1} ===" in out and f"=== {t2} ===" in out
        finally:
            s1.stop()
            s2.stop()

    def test_view_comma_list_and_dead_member(self):
        """A comma-separated endpoint list works like rpc_press's; a dead
        member reports its error inline instead of hiding the rest."""
        from brpc_tpu.tools.rpc_view import fetch_pages
        s1, t1 = start_server()
        try:
            pages = fetch_pages(f"{t1},mem://view-no-such", "health")
            assert pages[0] == (t1, "OK")
            assert pages[1][0] == "mem://view-no-such"
            assert "error" in pages[1][1]
        finally:
            s1.stop()

    def test_resolver_mixed_scheme_comma_list(self):
        """A comma list whose FIRST entry is a bare host:port but whose
        later entries carry schemes must split, not parse as a naming
        url ('127.0.0.1:80,mem://x' contains '://' and used to misroute
        into create_naming_service)."""
        from brpc_tpu.policy.naming import resolve_servers
        assert resolve_servers("127.0.0.1:80,mem://x") == \
            ["127.0.0.1:80", "mem://x"]
        assert resolve_servers("mem://a,mem://b") == \
            ["mem://a", "mem://b"]

    def test_view_empty_resolution_is_hard_error(self):
        from brpc_tpu.tools.rpc_view import main, resolve_servers
        with pytest.raises(ValueError):
            resolve_servers("pod://no-such-pod")
        assert main(["--server", "pod://no-such-pod",
                     "--page", "health"]) == 1


class TestParallelHttp:
    def test_fetch_many(self):
        from brpc_tpu.tools.parallel_http import fetch_all
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start("127.0.0.1:0") == 0
        try:
            base = f"http://127.0.0.1:{server.listen_port}"
            urls = [f"{base}/health", f"{base}/status", f"{base}/vars",
                    f"{base}/nope"]
            out = fetch_all(urls, concurrency=4, out=io.StringIO())
            assert out["summary"]["ok"] == 3
            assert out["summary"]["failed"] == 1
        finally:
            server.stop()
