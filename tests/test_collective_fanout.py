"""Compiled collective fan-out (channels/collective_fanout.py): the
Parallel/Partition combo-channel call as ONE SPMD program.

Legs:

  * **screen units** — ineligible shapes (unregistered method, custom
    mapper, merge mismatch, wrong shard count, non-ici target) decline
    the compiled route and ride the per-member RPC loop untouched;
  * **parity** — compiled route vs per-member RPC loop byte-exact on
    the same call, for shard/replicate mappings and gather/sum merges,
    plus the xproc program shape (zeros rows + psum broadcast — what a
    multi-controller pod enters) against the local placement leg;
  * **chaos** (the acceptance contract) — kill one pod member
    MID-FAN-OUT (FabricFaultPlan.collective_kill_device fires between
    the sequencer slot and the program entry): the call degrades
    in-call to per-member RPCs with ZERO client-visible failures, the
    route stays down (fault cleared alone is not revival), and the
    member re-advertising (epoch bump) restores the compiled route —
    N=4 in tier-1, N=8 slow-marked;
  * **once-guard** — the Collectives._cached / fan-out compile-cache
    fix: a slow builder must not block other keys' lookups (regression
    pin for the satellite bugfix);
  * **2-process** — a fan-out spanning a REAL remote pod member:
    declined cleanly off-TPU (xproc_uncompiled), and with the compiled
    leg forced on, the _F_COLL_CALL announce reaches the member, the
    member refuses entry (no multi-controller backend on CPU), and the
    client degrades in-call with zero visible failures.
"""
import os
import sys
import threading
import time

import numpy as np
import pytest

import brpc_tpu.policy  # noqa: F401
from brpc_tpu import rpc, channels
from brpc_tpu.butil import flags as fl
from brpc_tpu.channels import collective_fanout as cf
from brpc_tpu.ici import route as iroute
from brpc_tpu.rpc import fault_injection as fi
from tests.echo_pb2 import EchoRequest, EchoResponse

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.collective

SHARD = 128


class FanSvc(rpc.Service):
    """Wire fallback body: the same x*2 semantics the device handler
    compiles — scatter rows ride request attachments, result shards
    ride response attachments."""
    SERVICE_NAME = "Fan"

    @rpc.method(EchoRequest, EchoResponse)
    def Scale(self, cntl, request, response, done):
        x = np.frombuffer(cntl.request_attachment.to_bytes(), np.float32)
        cntl.response_attachment.append(
            (x * 2.0).astype(np.float32).tobytes())
        response.message = "ok"
        done()

    @rpc.method(EchoRequest, EchoResponse)
    def Accum(self, cntl, request, response, done):
        x = np.frombuffer(cntl.request_attachment.to_bytes(), np.float32)
        cntl.response_attachment.append(
            (x + 1.0).astype(np.float32).tobytes())
        done()


def _mk_server(dev: int):
    s = rpc.Server()
    s.add_service(FanSvc())
    s.register_collective("Fan.Scale", lambda x: x * 2.0,
                          merge=channels.MERGE_GATHER,
                          mapping=channels.MAP_SHARD)
    s.register_collective("Fan.Accum", lambda x: x + 1.0,
                          merge=channels.MERGE_SUM,
                          mapping=channels.MAP_REPLICATE)
    assert s.start(f"ici://{dev}") == 0
    return s


def _mk_fanout(devs, method="Fan.Scale"):
    pc = channels.ParallelChannel()
    if method == "Fan.Scale":
        mapper = channels.ShardingCallMapper()
        merger = channels.CollectiveMerger(merge=channels.MERGE_GATHER,
                                           dtype="float32",
                                           shard_shape=(SHARD,))
    else:
        mapper = channels.ReplicateFanoutMapper()
        merger = channels.CollectiveMerger(merge=channels.MERGE_SUM,
                                           dtype="float32")
    chans = []
    for d in devs:
        ch = rpc.Channel()
        ch.init(f"ici://{d}")
        pc.add_channel(ch, mapper=mapper, merger=merger)
        chans.append(ch)
    return pc


def _call(pc, op, method="Fan.Scale"):
    cntl = rpc.Controller()
    cntl.fanout_operand = op
    pc.call_method(method, cntl, EchoRequest(message="x"), EchoResponse())
    assert not cntl.failed(), (cntl.error_code_, cntl.error_text_)
    return cntl


@pytest.fixture()
def fan4():
    servers = [_mk_server(i) for i in range(4)]
    yield servers
    for s in servers:
        s.stop()


def _plane_healthy():
    """Tests must start route-up: a previous test's degrade would
    otherwise leak into this one's route assertions."""
    plane = cf.CollectiveFanoutPlane.instance()
    if plane.health()["down"]:
        # any registry transition moves the epoch
        cf.registry().serve(99)
        cf.registry().withdraw(99)
        assert plane.route_usable()


# ---------------------------------------------------------------------------
# Screen units.
# ---------------------------------------------------------------------------

class TestScreen:
    def test_plain_fanout_untouched(self, fan4):
        """No operand → the compiled plane never engages and plain
        protobuf fan-out behaves exactly as before."""
        pc = channels.ParallelChannel()
        for d in range(4):
            ch = rpc.Channel(); ch.init(f"ici://{d}")
            pc.add_channel(ch)
        cntl = rpc.Controller()
        pc.call_method("Fan.Scale", cntl, EchoRequest(message="x"),
                       EchoResponse())
        assert not cntl.failed()
        assert cntl.fanout_route == ""

    def test_unregistered_method_declines(self, fan4):
        _plane_healthy()
        pc = _mk_fanout(range(4))
        op = np.ones((4, SHARD), np.float32)
        before = iroute.collective_stats().get(
            "collective_ineligible_unregistered", 0)
        cntl = rpc.Controller()
        cntl.fanout_operand = op
        pc.call_method("Fan.Nope", cntl, EchoRequest(message="x"),
                       EchoResponse())
        assert cntl.fanout_route == "rpc"
        assert iroute.collective_stats().get(
            "collective_ineligible_unregistered", 0) == before + 1

    def test_custom_mapper_declines(self, fan4):
        """A mapper with custom semantics opts OUT of the compiled
        route (collective_mapping = None): the fan-out rides the
        per-member loop and still completes — inheritance must never
        smuggle an unknown map() into a lowering."""
        _plane_healthy()

        class MyMapper(channels.ShardingCallMapper):
            collective_mapping = None

        pc = channels.ParallelChannel()
        merger = channels.CollectiveMerger(merge=channels.MERGE_GATHER,
                                           dtype="float32",
                                           shard_shape=(SHARD,))
        for d in range(4):
            ch = rpc.Channel(); ch.init(f"ici://{d}")
            pc.add_channel(ch, mapper=MyMapper(), merger=merger)
        op = np.ones((4, SHARD), np.float32)
        cntl = _call(pc, op)
        assert cntl.fanout_route == "rpc"
        np.testing.assert_allclose(np.asarray(cntl.fanout_result),
                                   op * 2.0)

    def test_merge_mismatch_declines(self, fan4):
        _plane_healthy()
        pc = channels.ParallelChannel()
        mapper = channels.ShardingCallMapper()
        merger = channels.CollectiveMerger(merge=channels.MERGE_SUM,
                                           dtype="float32")
        for d in range(4):
            ch = rpc.Channel(); ch.init(f"ici://{d}")
            pc.add_channel(ch, mapper=mapper, merger=merger)
        cntl = rpc.Controller()
        cntl.fanout_operand = np.ones((4, SHARD), np.float32)
        pc.call_method("Fan.Scale", cntl, EchoRequest(message="x"),
                       EchoResponse())
        # Fan.Scale registered gather; client merger says sum → declined
        assert cntl.fanout_route == "rpc"

    def test_wrong_shard_count_declines(self, fan4):
        """Operand rows != fan-out width: the screen declines, and on
        the fallback loop the overflowing sub fails ITS call (EREQUEST
        through the fail_limit machinery), never the issue loop."""
        _plane_healthy()
        pc = _mk_fanout(range(4))
        pc.fail_limit = 1
        from brpc_tpu.rpc import errors
        cntl = rpc.Controller()
        cntl.fanout_operand = np.ones((3, SHARD), np.float32)
        pc.call_method("Fan.Scale", cntl, EchoRequest(message="x"),
                       EchoResponse())
        assert cntl.fanout_route == "rpc"
        assert cntl.failed() and cntl.error_code_ == errors.ETOOMANYFAILS

    def test_unserved_device_declines(self, fan4):
        _plane_healthy()
        pc = _mk_fanout([0, 1, 2, 5])        # no server on ici://5
        cntl = rpc.Controller()
        cntl.fanout_operand = np.ones((4, SHARD), np.float32)
        pc.call_method("Fan.Scale", cntl, EchoRequest(message="x"),
                       EchoResponse())
        # the per-member loop then fails on ici://5 — the SCREEN decision
        # is what this test pins
        assert cntl.fanout_route == "rpc"


# ---------------------------------------------------------------------------
# Parity: compiled vs per-member loop, byte-exact.
# ---------------------------------------------------------------------------

class TestParity:
    def test_shard_gather_parity(self, fan4):
        _plane_healthy()
        pc = _mk_fanout(range(4))
        op = np.arange(4 * SHARD, dtype=np.float32).reshape(4, SHARD)
        c1 = _call(pc, op)
        assert c1.fanout_route == "collective"
        got1 = np.asarray(c1.fanout_result)
        fl.set_flag("ici_fanout_collective", False)
        try:
            c2 = _call(pc, op)
        finally:
            fl.set_flag("ici_fanout_collective", True)
        assert c2.fanout_route == "rpc"
        got2 = np.asarray(c2.fanout_result)
        assert got1.shape == got2.shape == (4, SHARD)
        np.testing.assert_array_equal(got1, got2)
        np.testing.assert_allclose(got1, op * 2.0)

    def test_replicate_sum_parity(self, fan4):
        _plane_healthy()
        pc = _mk_fanout(range(4), method="Fan.Accum")
        op = np.linspace(0, 1, SHARD, dtype=np.float32)
        c1 = _call(pc, op, method="Fan.Accum")
        assert c1.fanout_route == "collective"
        fl.set_flag("ici_fanout_collective", False)
        try:
            c2 = _call(pc, op, method="Fan.Accum")
        finally:
            fl.set_flag("ici_fanout_collective", True)
        assert c2.fanout_route == "rpc"
        want = (op + 1.0) * 4
        np.testing.assert_allclose(np.asarray(c1.fanout_result), want,
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(c2.fanout_result), want,
                                   rtol=1e-6)

    def test_async_done_compiled(self, fan4):
        _plane_healthy()
        pc = _mk_fanout(range(4))
        op = np.ones((4, SHARD), np.float32)
        ev = threading.Event()
        out = {}

        def done(c):
            out["route"] = c.fanout_route
            out["ok"] = not c.failed()
            ev.set()

        cntl = rpc.Controller()
        cntl.fanout_operand = op
        pc.call_method("Fan.Scale", cntl, EchoRequest(message="x"),
                       EchoResponse(), done=done)
        assert ev.wait(30)
        assert out == {"route": "collective", "ok": True}

    def test_partition_channel_lowers(self, fan4, tmp_path):
        """PartitionChannel (LB-backed subs) lowers when each partition
        resolves to exactly one ici:// member."""
        _plane_healthy()
        listing = tmp_path / "parts"
        listing.write_text("".join(
            f"ici://{d} 100 {d}/4\n" for d in range(4)))
        pc = channels.PartitionChannel()
        mapper = channels.ShardingCallMapper()
        merger = channels.CollectiveMerger(merge=channels.MERGE_GATHER,
                                           dtype="float32",
                                           shard_shape=(SHARD,))
        assert pc.init(4, f"file://{listing}", mapper=mapper,
                       merger=merger) == 0
        deadline = time.time() + 10
        while not pc.partitions_ready() and time.time() < deadline:
            time.sleep(0.05)
        assert pc.partitions_ready()
        op = np.arange(4 * SHARD, dtype=np.float32).reshape(4, SHARD)
        cntl = _call(pc, op)
        assert cntl.fanout_route == "collective"
        np.testing.assert_allclose(np.asarray(cntl.fanout_result),
                                   op * 2.0)

    def test_selective_channel_propagates(self, fan4):
        """A SelectiveChannel over a ParallelChannel unit passes the
        operand through and surfaces the unit's route."""
        _plane_healthy()
        pc = _mk_fanout(range(4))
        sc = channels.SelectiveChannel()
        sc.add_channel(pc)
        cntl = rpc.Controller()
        cntl.fanout_operand = np.ones((4, SHARD), np.float32)
        sc.call_method("Fan.Scale", cntl, EchoRequest(message="x"),
                       EchoResponse)
        assert not cntl.failed(), (cntl.error_code_, cntl.error_text_)
        assert cntl.fanout_route == "collective"
        np.testing.assert_allclose(np.asarray(cntl.fanout_result), 2.0)

    def test_concurrent_fanouts_serialize_without_wedge(self, fan4):
        """Two threads issuing compiled fan-outs concurrently: the
        sequencer admits one program at a time (unsynced overlapping
        collective dispatches wedge the backend rendezvous — measured),
        and every call completes."""
        _plane_healthy()
        pc = _mk_fanout(range(4))
        op = np.arange(4 * SHARD, dtype=np.float32).reshape(4, SHARD)
        errs = []

        def worker():
            try:
                for _ in range(4):
                    c = _call(pc, op)
                    assert c.fanout_route == "collective"
            except Exception as e:      # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=worker) for _ in range(2)]
        for t in ts: t.start()
        for t in ts: t.join(120)
        assert not errs, errs
        d = cf.CollectiveFanoutPlane.instance().sequencer.describe()
        assert d["assigned"] == d["executed"]


# ---------------------------------------------------------------------------
# Chaos: kill a member mid-fan-out → in-call degrade → epoch revival.
# ---------------------------------------------------------------------------

def run_kill_revive(n: int) -> None:
    servers = {i: _mk_server(i) for i in range(n)}
    try:
        _plane_healthy()
        pc = _mk_fanout(range(n))
        op = np.arange(n * SHARD, dtype=np.float32).reshape(n, SHARD)
        want = op * 2.0

        def call():
            c = _call(pc, op)
            np.testing.assert_array_equal(np.asarray(c.fanout_result),
                                          want)
            return c.fanout_route

        base = iroute.collective_stats()
        assert call() == "collective"

        # the kill fires MID-fan-out: after the screen committed to the
        # compiled route and the sequencer assigned the slot
        victim = n // 2
        plan = fi.FabricFaultPlan(collective_kill_device=victim)
        fi.install_fabric(plan)
        try:
            assert call() == "rpc"       # degraded IN-CALL, zero failures
            assert plan.injected["collective"] == 1
            assert call() == "rpc"       # stays down; no second injection
            assert plan.injected["collective"] == 1
        finally:
            fi.install_fabric(None)
        # fault cleared but no epoch movement: still down (a dead member
        # does not resurrect by the client forgetting about it)
        assert call() == "rpc"

        # revival: the victim re-advertises (restart = withdraw + serve,
        # two epoch bumps) and the compiled route re-probes.  While the
        # victim is STOPPED the screen must still refuse (its device no
        # longer serves the method) — no parity assert: the wire member
        # itself is gone, which is exactly what the screen reports.
        servers[victim].stop()
        c = rpc.Controller()
        c.fanout_operand = op
        pc.call_method("Fan.Scale", c, EchoRequest(message="x"),
                       EchoResponse())
        assert c.fanout_route == "rpc"
        servers[victim] = _mk_server(victim)
        assert call() == "collective"

        stats = iroute.collective_stats()
        assert stats.get("collective_degraded_member_killed", 0) \
            == base.get("collective_degraded_member_killed", 0) + 1
        assert stats.get("collective_revived_member_killed", 0) \
            == base.get("collective_revived_member_killed", 0) + 1
        assert stats.get("collective_selected", 0) \
            >= base.get("collective_selected", 0) + 2
        d = cf.CollectiveFanoutPlane.instance().sequencer.describe()
        assert d["assigned"] == d["executed"], \
            "an abandoned fan-out slot must retire"
    finally:
        for s in servers.values():
            s.stop()


def test_member_kill_mid_fanout_degrades_and_revives_n4():
    run_kill_revive(4)


@pytest.mark.slow
def test_member_kill_mid_fanout_degrades_and_revives_n8():
    run_kill_revive(8)


def test_transient_exec_failure_reprobes_on_timer(fan4):
    """A route downed by a TRANSIENT reason (a program that fails to
    build/execute) re-probes after ici_fanout_reprobe_s WITHOUT an
    epoch move — one bad input must not degrade every method on the
    process forever under stable membership.  Membership reasons
    (member_killed) stay epoch-gated (see the chaos leg)."""
    _plane_healthy()

    def bad_handler(x):
        raise ValueError("bad handler body")

    cf.register_device_handler("Fan.Bad", bad_handler,
                               merge=channels.MERGE_GATHER,
                               mapping=channels.MAP_SHARD)
    pc_bad = _mk_fanout(range(4), method="Fan.Scale")
    op = np.ones((4, SHARD), np.float32)
    old = fl.get_flag("ici_fanout_reprobe_s")
    fl.set_flag("ici_fanout_reprobe_s", 0.05)
    try:
        # trip the route via the bad method (compile raises -> R_EXEC)
        cntl = rpc.Controller()
        cntl.fanout_operand = op
        pc_bad.call_method("Fan.Bad", cntl, EchoRequest(message="x"),
                           EchoResponse())
        assert cf.CollectiveFanoutPlane.instance().health()["down"]
        time.sleep(0.1)
        # no epoch movement: the timer alone revives the route
        c2 = _call(_mk_fanout(range(4)), op)
        assert c2.fanout_route == "collective"
    finally:
        fl.set_flag("ici_fanout_reprobe_s", old)


def test_screen_cache_invalidated_by_channel_reinit(fan4):
    """Re-init()ing a sub-channel to a different device must invalidate
    the per-channel screen cache — a stale device set would scatter the
    compiled program to the OLD member."""
    _plane_healthy()
    pc = channels.ParallelChannel()
    mapper = channels.ShardingCallMapper()
    merger = channels.CollectiveMerger(merge=channels.MERGE_GATHER,
                                       dtype="float32",
                                       shard_shape=(SHARD,))
    chans = []
    for d in range(4):
        ch = rpc.Channel(); ch.init(f"ici://{d}")
        pc.add_channel(ch, mapper=mapper, merger=merger)
        chans.append(ch)
    op = np.arange(4 * SHARD, dtype=np.float32).reshape(4, SHARD)
    assert _call(pc, op).fanout_route == "collective"
    # rebind sub 3 to a device with no serving member
    chans[3].init("ici://6")
    cntl = rpc.Controller()
    cntl.fanout_operand = op
    pc.call_method("Fan.Scale", cntl, EchoRequest(message="x"),
                   EchoResponse())
    assert cntl.fanout_route == "rpc"


def test_transient_exec_failures_budget(fan4):
    """collective_fail_execs: a bounded burst of execution failures
    degrades once, never fails the client call."""
    _plane_healthy()
    pc = _mk_fanout(range(4))
    op = np.ones((4, SHARD), np.float32)
    plan = fi.FabricFaultPlan(collective_fail_execs=2)
    fi.install_fabric(plan)
    try:
        c = _call(pc, op)
        assert c.fanout_route == "rpc"
        assert plan.injected["collective"] == 1   # down: no more probes
    finally:
        fi.install_fabric(None)


# ---------------------------------------------------------------------------
# xproc program shape (what a multi-controller pod enters), in-process.
# ---------------------------------------------------------------------------

class TestXprocProgram:
    def test_xproc_program_matches_local_leg(self, fan4):
        """The zeros-rows + psum-broadcast xproc program is byte-exact
        with the placement-scatter local program."""
        import jax
        _plane_healthy()
        plane = cf.CollectiveFanoutPlane.instance()
        md = cf.registry().method("Fan.Scale")
        op = np.arange(4 * SHARD, dtype=np.float32).reshape(4, SHARD)
        low_x = cf._Lowering("Fan.Scale", md, (0, 1, 2, 3), op,
                             channels.MAP_SHARD, "xproc", {})
        fn, ga = plane._prepare_xproc(low_x)
        got_x = np.asarray(jax.block_until_ready(fn(ga)))
        low_l = cf._Lowering("Fan.Scale", md, (0, 1, 2, 3), op,
                             channels.MAP_SHARD, "local", {})
        fn2, placed = plane._prepare_local(low_l)
        got_l = np.asarray(jax.block_until_ready(fn2(placed)))
        np.testing.assert_array_equal(got_x, got_l)
        np.testing.assert_allclose(got_l, op * 2.0)


# ---------------------------------------------------------------------------
# Compile-cache once-guard (the Collectives._cached satellite bugfix).
# ---------------------------------------------------------------------------

class TestCompileCacheOnceGuard:
    def test_slow_builder_does_not_block_other_keys(self):
        """Regression pin: one key's slow build (an XLA compile can take
        seconds) must not serialize every other key's cache lookup."""
        from brpc_tpu.ici.collective import Collectives
        c = Collectives.__new__(Collectives)   # no mesh needed
        c._cache = {}
        c._building = {}
        import threading as _t
        c._cache_lock = _t.Lock()
        started = threading.Event()
        release = threading.Event()

        def slow_builder():
            started.set()
            assert release.wait(30)
            return "slow"

        t = threading.Thread(
            target=lambda: c._cached(("slow",), slow_builder))
        t.start()
        assert started.wait(10)
        # the slow build holds NO lock: another key resolves immediately
        t0 = time.monotonic()
        assert c._cached(("fast",), lambda: "fast") == "fast"
        assert time.monotonic() - t0 < 5.0, \
            "fast key waited on the slow key's build"
        release.set()
        t.join(30)
        assert c._cache[("slow",)] == "slow"

    def test_concurrent_same_key_builds_once(self):
        from brpc_tpu.ici.collective import Collectives
        c = Collectives.__new__(Collectives)
        c._cache = {}
        c._building = {}
        import threading as _t
        c._cache_lock = _t.Lock()
        builds = []
        gate = threading.Event()

        def builder():
            builds.append(1)
            gate.wait(2)
            return "v"

        out = []
        ts = [threading.Thread(
            target=lambda: out.append(c._cached(("k",), builder)))
            for _ in range(4)]
        for t in ts: t.start()
        time.sleep(0.2)
        gate.set()
        for t in ts: t.join(30)
        assert out == ["v"] * 4
        assert len(builds) == 1, "same key compiled more than once"

    def test_failed_build_clears_guard_and_retries(self):
        from brpc_tpu.ici.collective import Collectives
        c = Collectives.__new__(Collectives)
        c._cache = {}
        c._building = {}
        import threading as _t
        c._cache_lock = _t.Lock()
        with pytest.raises(RuntimeError):
            c._cached(("k",), lambda: (_ for _ in ()).throw(
                RuntimeError("boom")))
        assert c._cached(("k",), lambda: "ok") == "ok"


# ---------------------------------------------------------------------------
# 2-process: a REAL remote member — clean decline off-TPU, and the
# forced-on announce path degrading in-call with zero visible failures.
# ---------------------------------------------------------------------------

_XPROC_FANOUT = r"""
import os, sys, threading, time, json
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")
_real_excepthook = sys.excepthook
def _fail_fast(tp, val, tb):
    _real_excepthook(tp, val, tb)
    sys.stdout.flush(); sys.stderr.flush()
    try:
        from brpc_tpu.butil.debug_sync import dump_report_now
        dump_report_now()
    except Exception:
        pass
    os._exit(1)
sys.excepthook = _fail_fast

pid = int(sys.argv[1]); coord = sys.argv[2]; NPROC = int(sys.argv[3])
from brpc_tpu.ici.fabric import FabricNode
node = FabricNode.initialize(coord, num_processes=NPROC, process_id=pid)
kv = node._kv
import numpy as np
import brpc_tpu.policy
from brpc_tpu import rpc, ici, channels
from brpc_tpu.butil import flags as fl
from brpc_tpu.ici import route as iroute
from brpc_tpu.ici.pod import Pod
from echo_pb2 import EchoRequest, EchoResponse
mesh = ici.IciMesh(); ici.IciMesh.set_default(mesh)
pod = Pod.join("fanout")
MYDEV = 2 * pid
SHARD = 64

class FanSvc(rpc.Service):
    SERVICE_NAME = "Fan"
    @rpc.method(EchoRequest, EchoResponse)
    def Scale(self, cntl, request, response, done):
        x = np.frombuffer(cntl.request_attachment.to_bytes(), np.float32)
        cntl.response_attachment.append((x * 2.0).astype(np.float32).tobytes())
        done()

server = rpc.Server()
server.add_service(FanSvc())
server.register_collective("Fan.Scale", lambda x: x * 2.0)
assert server.start("ici://%%d" %% MYDEV) == 0
# join x2 + advertise x2 + publish_collective x2
pod.wait_epoch(3 * NPROC, timeout=60)
members = pod.members(refresh=True)
assert all("Fan.Scale" in m.coll for m in members.values()), {
    p: m.coll for p, m in members.items()}

if pid == 0:
    pc = channels.ParallelChannel()
    mapper = channels.ShardingCallMapper()
    merger = channels.CollectiveMerger(merge=channels.MERGE_GATHER,
                                       dtype="float32", shard_shape=(SHARD,))
    for d in (0, 2):
        ch = rpc.Channel()
        ch.init("ici://%%d" %% d,
                options=rpc.ChannelOptions(timeout_ms=30000, max_retry=1))
        pc.add_channel(ch, mapper=mapper, merger=merger)
    op = np.arange(2 * SHARD, dtype=np.float32).reshape(2, SHARD)

    def call():
        cntl = rpc.Controller()
        cntl.fanout_operand = op
        pc.call_method("Fan.Scale", cntl, EchoRequest(message="x"),
                       EchoResponse())
        assert not cntl.failed(), (cntl.error_code_, cntl.error_text_)
        got = np.asarray(cntl.fanout_result)
        assert got.shape == (2, SHARD)
        assert np.allclose(got, op * 2.0), got[:, :4]
        return cntl.fanout_route

    # leg 1: default screen — remote member, no multi-controller backend
    # on CPU: decline BEFORE any announce, per-member RPCs carry the call
    assert call() == "rpc"
    s1 = iroute.collective_stats()
    assert s1.get("collective_ineligible_xproc_uncompiled", 0) >= 1, s1

    # leg 2: force the compiled xproc leg on — the announce goes out,
    # the member refuses entry (CPU), the client degrades IN-CALL with
    # zero visible failures
    fl.set_flag("ici_device_plane_xproc_compiled", "on")
    assert call() == "rpc"
    s2 = iroute.collective_stats()
    assert s2.get("collective_degraded_announce_refused", 0) >= 1, s2
    kv.key_value_set("fanout_client_done", "1")
else:
    kv.blocking_key_value_get("fanout_client_done", 120000)
    # the member SAW the announce and refused it (counter proof the
    # _F_COLL_CALL frame crossed processes and was answered)
    s = iroute.collective_stats()
    assert s.get("collective_announce_refused_xproc_uncompiled", 0) >= 1, s

kv.wait_at_barrier("fanout_done", 120000)
server.stop()
pod.leave()
print("XF%%d_OK" %% pid, flush=True)
"""


@pytest.mark.pod
def test_xproc_fanout_declines_and_forced_announce_degrades():
    from tests.test_pod import _run_pod
    outs = _run_pod(_XPROC_FANOUT % {"repo": REPO}, n=2, timeout=240,
                    tag="xproc_fanout")
    assert "XF0_OK" in outs[0]
    assert "XF1_OK" in outs[1]
