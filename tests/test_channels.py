"""Combo-channel tests: Parallel/Partition/Selective over in-process servers
(the reference's ChannelTest::ParallelChannel pattern,
test/brpc_channel_unittest.cpp:361-395) + the collective lowering."""
import threading
import time

import numpy as np
import pytest

import brpc_tpu.policy
from brpc_tpu import rpc, channels, ici
from brpc_tpu.rpc import errors
from tests.echo_pb2 import EchoRequest, EchoResponse

_seq = [100]


def unique(prefix):
    _seq[0] += 1
    return f"{prefix}-{_seq[0]}"


class TaggedEcho(rpc.Service):
    SERVICE_NAME = "EchoService"

    def __init__(self, tag):
        self.tag = tag
        self.calls = 0

    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        self.calls += 1
        response.message = f"{self.tag}:{request.message}"
        done()


def start_server(tag):
    s = rpc.Server()
    svc = TaggedEcho(tag)
    s.add_service(svc)
    name = unique(tag)
    assert s.start(f"mem://{name}") == 0
    return s, svc, f"mem://{name}"


class ConcatMerger(channels.ResponseMerger):
    def merge(self, response, sub_response):
        response.message = (response.message + "|" + sub_response.message
                            if response.message else sub_response.message)
        return self.MERGED


class TestParallelChannel:
    def test_fanout_and_merge(self):
        servers = [start_server(f"s{i}") for i in range(3)]
        try:
            pc = channels.ParallelChannel()
            for _, _, target in servers:
                ch = rpc.Channel(); ch.init(target)
                pc.add_channel(ch, merger=ConcatMerger())
            cntl = rpc.Controller()
            resp = EchoResponse()
            pc.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="x"), resp)
            assert not cntl.failed(), cntl.error_text
            parts = sorted(resp.message.split("|"))
            assert parts == ["s0:x", "s1:x", "s2:x"]
            assert all(svc.calls == 1 for _, svc, _ in servers)
        finally:
            for s, _, _ in servers:
                s.stop()

    def test_call_mapper_shards_requests(self):
        servers = [start_server(f"p{i}") for i in range(3)]
        try:
            class ShardMapper(channels.CallMapper):
                def map(self, i, method, request):
                    return channels.SubCall(
                        EchoRequest(message=f"{request.message}-part{i}"))

            pc = channels.ParallelChannel()
            for _, _, target in servers:
                ch = rpc.Channel(); ch.init(target)
                pc.add_channel(ch, mapper=ShardMapper(), merger=ConcatMerger())
            cntl = rpc.Controller()
            resp = EchoResponse()
            pc.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="q"), resp)
            assert sorted(resp.message.split("|")) == [
                "p0:q-part0", "p1:q-part1", "p2:q-part2"]
        finally:
            for s, _, _ in servers:
                s.stop()

    def test_skip_subcall(self):
        servers = [start_server(f"k{i}") for i in range(2)]
        try:
            class SkipFirst(channels.CallMapper):
                def map(self, i, method, request):
                    if i == 0:
                        return channels.SubCall.skip_call()
                    return channels.SubCall(request)

            pc = channels.ParallelChannel()
            for _, _, target in servers:
                ch = rpc.Channel(); ch.init(target)
                pc.add_channel(ch, mapper=SkipFirst(), merger=ConcatMerger())
            cntl = rpc.Controller()
            resp = EchoResponse()
            pc.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="m"), resp)
            assert not cntl.failed()
            assert resp.message == "k1:m"
            assert servers[0][1].calls == 0
        finally:
            for s, _, _ in servers:
                s.stop()

    def test_fail_limit(self):
        s0, svc0, t0 = start_server("ok")
        try:
            pc = channels.ParallelChannel(fail_limit=1)
            ch_ok = rpc.Channel(); ch_ok.init(t0)
            ch_bad = rpc.Channel()
            ch_bad.init("mem://nobody", rpc.ChannelOptions(
                timeout_ms=200, max_retry=0)
                if False else None)
            ch_bad.options.timeout_ms = 200
            ch_bad.options.max_retry = 0
            pc.add_channel(ch_ok, merger=ConcatMerger())
            pc.add_channel(ch_bad, merger=ConcatMerger())
            cntl = rpc.Controller()
            resp = EchoResponse()
            pc.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="f"), resp)
            assert cntl.failed()
            assert cntl.error_code == errors.ETOOMANYFAILS
        finally:
            s0.stop()

    def test_async_fanout(self):
        servers = [start_server(f"a{i}") for i in range(2)]
        try:
            pc = channels.ParallelChannel()
            for _, _, target in servers:
                ch = rpc.Channel(); ch.init(target)
                pc.add_channel(ch, merger=ConcatMerger())
            done = threading.Event()
            out = {}

            def cb(cntl):
                out["resp"] = cntl.response
                done.set()

            pc.call_method("EchoService.Echo", rpc.Controller(),
                           EchoRequest(message="y"), EchoResponse(), done=cb)
            assert done.wait(10)
            assert len(out["resp"].message.split("|")) == 2
        finally:
            for s, _, _ in servers:
                s.stop()


class TestPartitionChannel:
    def test_partition_fanout(self, tmp_path):
        servers = [start_server(f"part{i}") for i in range(2)]
        listing = tmp_path / "cluster"
        listing.write_text(
            f"{servers[0][2]} 100 0/2\n{servers[1][2]} 100 1/2\n")
        try:
            pc = channels.PartitionChannel()
            assert pc.init(2, f"file://{listing}",
                           merger=ConcatMerger()) == 0
            assert pc.partitions_ready()
            cntl = rpc.Controller()
            resp = EchoResponse()
            pc.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="pt"), resp)
            assert not cntl.failed(), cntl.error_text
            assert sorted(resp.message.split("|")) == [
                "part0:pt", "part1:pt"]
        finally:
            for s, _, _ in servers:
                s.stop()

    def test_partition_parser(self):
        p = channels.PartitionParser()
        assert p.parse_from_tag("0/4") == (0, 4)
        assert p.parse_from_tag("3/4") == (3, 4)
        assert p.parse_from_tag("4/4") is None
        assert p.parse_from_tag("x") is None

    def test_dynamic_partition_prefers_bigger_scheme(self, tmp_path):
        servers = [start_server(f"dp{i}") for i in range(3)]
        listing = tmp_path / "cluster"
        # scheme 1: one server covers 1/1; scheme 2: two servers 0/2,1/2
        listing.write_text(
            f"{servers[0][2]} 100 0/1\n"
            f"{servers[1][2]} 100 0/2\n{servers[2][2]} 100 1/2\n")
        try:
            dpc = channels.DynamicPartitionChannel()
            assert dpc.init([1, 2], f"file://{listing}",
                            merger=ConcatMerger()) == 0
            oks = 0
            for _ in range(20):
                cntl = rpc.Controller()
                resp = EchoResponse()
                dpc.call_method("EchoService.Echo", cntl,
                                EchoRequest(message="d"), resp)
                if not cntl.failed():
                    oks += 1
            assert oks == 20
            # scheme-2 servers (capacity 2) should see more traffic than
            # the scheme-1 server (capacity 1)
            assert (servers[1][1].calls + servers[2][1].calls
                    ) >= servers[0][1].calls
        finally:
            for s, _, _ in servers:
                s.stop()


class TestSelectiveChannel:
    def test_selects_and_retries_on_other_channel(self):
        s_ok, svc_ok, t_ok = start_server("live")
        try:
            sc = channels.SelectiveChannel()
            ch_dead = rpc.Channel()
            ch_dead.init("mem://dead-endpoint")
            ch_dead.options.timeout_ms = 200
            ch_dead.options.max_retry = 0
            ch_ok = rpc.Channel(); ch_ok.init(t_ok)
            sc.add_channel(ch_dead)
            sc.add_channel(ch_ok)
            oks = 0
            for _ in range(6):
                cntl = rpc.Controller()
                resp = sc.call_method("EchoService.Echo", cntl,
                                      EchoRequest(message="s"), EchoResponse)
                if not cntl.failed():
                    oks += 1
                    assert resp.message == "live:s"
            assert oks == 6        # every call lands via retry-on-other
        finally:
            s_ok.stop()

    def test_all_dead_fails(self):
        sc = channels.SelectiveChannel()
        for _ in range(2):
            ch = rpc.Channel(); ch.init("mem://void")
            ch.options.timeout_ms = 100
            ch.options.max_retry = 0
            sc.add_channel(ch)
        cntl = rpc.Controller()
        sc.call_method("EchoService.Echo", cntl, EchoRequest(), EchoResponse)
        assert cntl.failed()


class TestCollectiveLowering:
    @pytest.fixture(scope="class")
    def cc(self):
        import jax
        mesh = ici.IciMesh(jax.devices())
        return channels.CollectiveChannel(mesh), mesh

    def test_shard_and_sum_is_distributed_matvec(self, cc):
        """PartitionChannel semantics: shard the weight, replicate the
        activation, merge=sum → tensor-parallel matvec in ONE program."""
        import jax.numpy as jnp
        ch, mesh = cc
        n = mesh.size
        d = 8
        w = jnp.arange(n * d * d, dtype=jnp.float32).reshape(n, d, d) / 100
        x = jnp.ones((d,), jnp.float32)
        ch.register("Shard.PartialMatVec",
                    lambda w_shard, x_full: w_shard @ x_full,
                    merge=channels.MERGE_SUM, mapping=channels.MAP_SHARD)
        y = ch.call("Shard.PartialMatVec", ch.shard(w), ch.replicate(x))
        expect = np.asarray(w).sum(0) @ np.ones(d, np.float32)
        np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-5)

    def test_gather_merge_collects_all_responses(self, cc):
        import jax.numpy as jnp
        ch, mesh = cc
        n = mesh.size
        ch.register("Shard.Scale",
                    lambda idx, row: row * (idx + 1),
                    merge=channels.MERGE_GATHER, mapping=channels.MAP_SHARD,
                    takes_index=True)
        x = jnp.ones((n, 4), jnp.float32)
        y = ch.call("Shard.Scale", ch.shard(x))
        expect = np.stack([np.full((4,), i + 1) for i in range(n)])
        np.testing.assert_allclose(np.asarray(y), expect)

    def test_none_merge_keeps_sharded(self, cc):
        import jax.numpy as jnp
        ch, mesh = cc
        n = mesh.size
        ch.register("Shard.Double", lambda row: row * 2,
                    merge=channels.MERGE_NONE, mapping=channels.MAP_SHARD)
        x = jnp.arange(n * 2, dtype=jnp.float32).reshape(n, 2)
        y = ch.call("Shard.Double", ch.shard(x))
        np.testing.assert_allclose(np.asarray(y), np.asarray(x) * 2)
        assert len(y.sharding.device_set) == n   # stayed sharded

    def test_compiled_once_per_shape(self, cc):
        import jax.numpy as jnp
        ch, mesh = cc
        n = mesh.size
        ch.register("Shard.Id", lambda row: row, merge=channels.MERGE_NONE)
        x = jnp.ones((n, 3), jnp.float32)
        ch.call("Shard.Id", ch.shard(x))
        before = len(ch._compiled)
        ch.call("Shard.Id", ch.shard(x * 5))
        assert len(ch._compiled) == before       # cache hit


class TestParallelFanoutInlineIssue:
    """Fan-out issue discipline over the native ici plane (r5): sub-calls
    to INLINE-dispatch servers are issued inline on the caller's stack (a
    tasklet each bought no concurrency — the handler runs in that stack
    either way — and cost a scheduling hop); servers that park handlers
    on tasklets keep the concurrent fan-out, because there completions
    genuinely overlap."""

    def _build(self, n, usercode_inline, base, handler_sleep=0.0):
        from brpc_tpu.channels.parallel_channel import ParallelChannel

        class Svc(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                if handler_sleep:
                    time.sleep(handler_sleep)
                response.message = request.message
                done()

        servers, pc = [], ParallelChannel()
        for i in range(n):
            opts = rpc.ServerOptions()
            opts.usercode_inline = usercode_inline
            s = rpc.Server(opts)
            s.add_service(Svc())
            assert s.start(f"ici://{base + i}") == 0
            servers.append(s)
            sub = rpc.Channel()
            sub.init(f"ici://{base + i}")
            pc.add_channel(sub)
        return servers, pc

    def test_inline_servers_fanout_correct_and_inline(self):
        servers, pc = self._build(4, True, 70)
        try:
            # warm: cache the native bindings (inline eligibility needs
            # the cached binding; first call rides the generic path)
            cntl = rpc.Controller()
            pc.call_method("Svc.Echo", cntl, EchoRequest(message="w"),
                           EchoResponse())
            assert not cntl.failed(), cntl.error_text
            for chan, _, _ in pc._subs:
                assert pc._inline_eligible(
                    chan, rpc.Controller(), EchoRequest(message="x"),
                    "Svc.Echo"), "binding not cached"
            cntl = rpc.Controller()
            resp = pc.call_method("Svc.Echo", cntl,
                                  EchoRequest(message="x"), EchoResponse())
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "x"
        finally:
            for s in servers:
                s.stop()

    def test_tasklet_servers_keep_overlapping_fanout(self):
        """Blocking handlers on tasklet-dispatch servers must still
        overlap: 4 sub-calls sleeping 250ms each must complete in far
        less than the serial 1.0s (inlining them would serialize the
        sleeps; the margin allows the worker pool's compensation ramp,
        which overlaps gradually on a 1-core host)."""
        servers, pc = self._build(4, False, 76, handler_sleep=0.25)
        try:
            cntl = rpc.Controller()
            pc.call_method("Svc.Echo", cntl, EchoRequest(message="w"),
                           EchoResponse())  # warm bindings
            for chan, _, _ in pc._subs:
                assert not pc._inline_eligible(
                    chan, rpc.Controller(), EchoRequest(message="x"),
                    "Svc.Echo"), \
                    "tasklet-dispatch server wrongly marked inline"
            cntl = rpc.Controller()
            cntl.timeout_ms = 10000
            t0 = time.monotonic()
            pc.call_method("Svc.Echo", cntl, EchoRequest(message="x"),
                           EchoResponse())
            dt = time.monotonic() - t0
            assert not cntl.failed(), cntl.error_text
            # full serialization would be 4x250ms = 1.0s; the worker
            # pool's compensation ramp yields ~3x overlap-slots on a
            # 1-core host, so pin "not fully serialized" rather than
            # perfect overlap
            assert dt < 0.92, f"fan-out serialized: {dt:.2f}s for 4x250ms"
        finally:
            for s in servers:
                s.stop()
