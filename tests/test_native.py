"""Native core bindings tests (builds native/ on demand; skips when no
toolchain — the reference's hardware-gated test pattern, SURVEY.md §4)."""
import ctypes
import threading
import time

import pytest

from brpc_tpu.butil import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native core not buildable here")


class TestNativePool:
    def test_versioned_ids(self):
        lib = native.load()
        pool = lib.brpc_tpu_pool_new()
        buf = ctypes.create_string_buffer(b"x")
        addr = ctypes.cast(buf, ctypes.c_void_p)
        rid = lib.brpc_tpu_pool_get(pool, addr)
        assert lib.brpc_tpu_pool_address(pool, rid) == addr.value
        assert lib.brpc_tpu_pool_put(pool, rid) == 1
        assert lib.brpc_tpu_pool_address(pool, rid) is None
        assert lib.brpc_tpu_pool_put(pool, rid) == 0
        rid2 = lib.brpc_tpu_pool_get(pool, addr)
        assert rid2 != rid
        assert (rid2 & 0xFFFFFFFF) == (rid & 0xFFFFFFFF)   # slot reuse


class TestNativeButex:
    def test_wait_wake(self):
        lib = native.load()
        b = lib.brpc_tpu_butex_new(0)
        rc = []

        def waiter():
            rc.append(lib.brpc_tpu_butex_wait(b, 0, 5_000_000))

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        lib.brpc_tpu_butex_set_wake_all(b, 1)
        t.join(5)
        assert rc == [0]
        assert lib.brpc_tpu_butex_value(b) == 1

    def test_timeout_and_wouldblock(self):
        import errno
        lib = native.load()
        b = lib.brpc_tpu_butex_new(3)
        assert lib.brpc_tpu_butex_wait(b, 0, 1000) == errno.EWOULDBLOCK
        assert lib.brpc_tpu_butex_wait(b, 3, 20_000) == errno.ETIMEDOUT


class TestNativeScheduler:
    def test_spawn_join_many_native(self):
        sched = native.NativeScheduler(workers=2)
        assert sched.selftest(100) == 100
        assert sched.completed() >= 100
        assert sched.spawned() >= 100


class TestNativeBlockPool:
    def test_alloc_release_exhaust(self):
        lib = native.load()
        bp = lib.brpc_tpu_blockpool_new(4096, 4)
        blocks = [lib.brpc_tpu_blockpool_alloc(bp) for _ in range(4)]
        assert all(blocks)
        assert lib.brpc_tpu_blockpool_alloc(bp) is None
        for blk in blocks:
            assert lib.brpc_tpu_blockpool_release(bp, blk) == 1
        assert lib.brpc_tpu_blockpool_free_count(bp) == 4


class TestNativeTimer:
    def test_schedule_unschedule(self):
        lib = native.load()
        fired = []
        cb = native._TIMER_FN(lambda arg: fired.append(1))
        lib.brpc_tpu_timer_schedule(cb, None, 10_000)
        tid = lib.brpc_tpu_timer_schedule(cb, None, 200_000)
        assert lib.brpc_tpu_timer_unschedule(tid) == 0
        time.sleep(0.3)
        assert fired == [1]


class TestNativeEcho:
    def test_native_echo_latency(self):
        from brpc_tpu.butil.native import native_echo_p50_us
        p50 = native_echo_p50_us(iters=300, payload=1024)
        assert p50 > 0
        assert p50 < 10_000       # sanity: < 10ms
