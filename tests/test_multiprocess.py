"""Cross-process DCN test: a real server in another process, tcp transport
(the closest CI can get to multi-host; the reference's cluster tests used
multiple machines)."""
import os
import signal
import subprocess
import sys
import time

import pytest

import brpc_tpu.policy
from brpc_tpu import rpc
from tests.echo_pb2 import EchoRequest, EchoResponse

SERVER_CODE = r'''
import sys, os
sys.path.insert(0, os.getcwd())
sys.path.insert(0, "tests")
os.environ["JAX_PLATFORMS"] = "cpu"
import brpc_tpu.policy
from brpc_tpu import rpc
from tests.echo_pb2 import EchoRequest, EchoResponse

class EchoService(rpc.Service):
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = "remote:" + request.message
        done()

server = rpc.Server()
server.add_service(EchoService())
assert server.start("127.0.0.1:0") == 0
print(f"PORT={server.listen_port}", flush=True)
import time
time.sleep(60)
'''


class TestCrossProcess:
    def test_echo_to_another_process(self):
        proc = subprocess.Popen([sys.executable, "-c", SERVER_CODE],
                                stdout=subprocess.PIPE, text=True,
                                cwd=os.getcwd())
        try:
            line = ""
            deadline = time.time() + 60
            while time.time() < deadline:
                line = proc.stdout.readline()
                if line.startswith("PORT="):
                    break
            assert line.startswith("PORT="), "server did not start"
            port = int(line.strip().split("=")[1])
            ch = rpc.Channel()
            ch.init(f"127.0.0.1:{port}",
                    options=rpc.ChannelOptions(timeout_ms=10000))
            for i in range(5):
                cntl = rpc.Controller()
                resp = ch.call_method("EchoService.Echo", cntl,
                                      EchoRequest(message=f"x{i}"),
                                      EchoResponse)
                assert not cntl.failed(), cntl.error_text
                assert resp.message == f"remote:x{i}"
        finally:
            proc.send_signal(signal.SIGKILL)
            proc.wait(10)

    def test_client_survives_server_death(self):
        proc = subprocess.Popen([sys.executable, "-c", SERVER_CODE],
                                stdout=subprocess.PIPE, text=True,
                                cwd=os.getcwd())
        try:
            line = proc.stdout.readline()
            while not line.startswith("PORT="):
                line = proc.stdout.readline()
            port = int(line.strip().split("=")[1])
            ch = rpc.Channel()
            ch.init(f"127.0.0.1:{port}",
                    options=rpc.ChannelOptions(timeout_ms=3000, max_retry=0))
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="a"), EchoResponse)
            assert not cntl.failed()
            proc.send_signal(signal.SIGKILL)
            proc.wait(10)
            time.sleep(0.2)
            cntl2 = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl2,
                           EchoRequest(message="b"), EchoResponse)
            assert cntl2.failed()      # clean failure, not a hang
        finally:
            if proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
                proc.wait(10)
