"""Native sanitizer builds: `make tsan` / `make asan` (slow-marked).

Each target rebuilds libbrpc_tpu_core.so + core_test + fabric_smoke +
ici_smoke (the PR-8 batched one-struct upcall ABI under concurrent
callers, steal-mode drainers, a cross-thread responder, and an
unlisten-mid-traffic drain) under the sanitizer (into native/build-tsan / build-asan — the
production .so is never clobbered) and runs both with halt_on_error=1,
so ANY report is a nonzero exit.  The sweep that landed this wiring
fixed four real native findings instead of suppressing them:

  * ResourcePool's flat slot vector reallocated under wait-free
    address() — a use-after-free window (now chunked, stable storage);
  * PoolSlot.payload raced put()'s revoke (now atomic — the sanctioned
    stale read, without the UB);
  * TimerThread was a function-local static whose destructor tore down
    its mutex under the detached run() thread (now a leaked singleton,
    the Scheduler lifetime model);
  * a yielded fiber was silently RESTARTED from its trampoline on
    redispatch (makecontext re-run on every pop).

TSan notes: core.cpp routes timed cv waits through system_clock under
-fsanitize=thread (GCC-10 libtsan lacks the pthread_cond_clockwait
interceptor) and runs fibers inline on their worker (its swapcontext
interceptor SEGVs on non-main-thread ucontext switches, probed) — see
the comments in native/core.cpp and native/tsan_compat.h.
"""
import os
import shutil
import subprocess

import pytest

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")

pytestmark = pytest.mark.slow


def _toolchain_ok(flag: str) -> bool:
    gxx = shutil.which(os.environ.get("CXX", "g++"))
    if gxx is None:
        return False
    probe = subprocess.run(
        [gxx, flag, "-x", "c++", "-", "-o", "/dev/null", "-pthread"],
        input=b"int main(){return 0;}", capture_output=True)
    return probe.returncode == 0


def _run_make(target: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        ["make", "-C", NATIVE, target], capture_output=True, text=True,
        timeout=600)


@pytest.mark.parametrize("target,flag", [
    ("tsan", "-fsanitize=thread"),
    ("asan", "-fsanitize=address"),
])
def test_sanitizer_build_and_smoke(target, flag):
    if not _toolchain_ok(flag):
        pytest.skip(f"toolchain lacks {flag}")
    res = _run_make(target)
    tail = (res.stdout + res.stderr)[-4000:]
    assert res.returncode == 0, f"make {target} failed:\n{tail}"
    assert "ALL NATIVE TESTS PASSED" in res.stdout, tail
    assert "ALL FABRIC SMOKE PASSED" in res.stdout, tail
    assert "ALL ICI SMOKE PASSED" in res.stdout, tail
    # halt_on_error=1 makes any report fatal, but belt-and-braces:
    assert "WARNING: ThreadSanitizer" not in res.stdout + res.stderr, tail
    assert "ERROR: AddressSanitizer" not in res.stdout + res.stderr, tail
    assert "LeakSanitizer" not in res.stdout + res.stderr, tail
