"""fablint: the concurrency static analyzer (tools/fablint.py).

Two halves:

  * fixture coverage — each of the four analyzer passes catches its
    seeded-violation fixture at the exact file:line, and the clean
    fixture is silent;
  * the tier-1 ZERO-FINDINGS GATE — `python -m brpc_tpu.tools.fablint
    brpc_tpu/` (and the deadcode subcommand) must exit 0 over the
    shipped tree.  Suppressions live in-line as `# fablint:
    ignore[rule] <reason>`; a reason-less ignore is itself a finding,
    so the accepted baseline stays explicit and reviewed.
"""
import json
import os
import subprocess
import sys

from brpc_tpu.tools import fablint

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "fixtures", "fablint")
PKG = os.path.join(REPO, "brpc_tpu")


def _findings(path, rules):
    return fablint.run([os.path.join(FIXTURES, path)], rules)


class TestFixtureViolations:
    def test_guarded_state_violation_reported_with_line(self):
        out = _findings("bad_guarded.py", fablint.CONCURRENCY_RULES)
        assert [(f.rule, f.line) for f in out] == [("guarded-state", 17)]
        assert "_count" in out[0].message and "_lock" in out[0].message
        assert out[0].path.endswith("bad_guarded.py")

    def test_lock_order_cycle_reported(self):
        out = _findings("bad_cycle.py", fablint.CONCURRENCY_RULES)
        assert len(out) == 1 and out[0].rule == "lock-order"
        assert "a_lock" in out[0].message and "b_lock" in out[0].message
        # the report anchors on one closing edge of the cycle
        assert out[0].line in (10, 16)
        assert ":10" in out[0].message and ":16" in out[0].message

    def test_sleep_under_lock_reported_with_line(self):
        out = _findings("bad_sleep.py", fablint.CONCURRENCY_RULES)
        assert [(f.rule, f.line) for f in out] == \
            [("blocking-under-lock", 10)]
        assert "sleep" in out[0].message and "_lock" in out[0].message

    def test_unjoined_thread_reported_with_line(self):
        out = _findings("bad_thread.py", fablint.CONCURRENCY_RULES)
        rules = {(f.rule, f.line) for f in out}
        # both hygiene defects fire: non-daemon AND no quiesce path
        assert all(r == "thread-hygiene" and ln == 6 for r, ln in rules)
        msgs = " | ".join(f.message for f in out)
        assert "daemon" in msgs and "quiesce" in msgs

    def test_unguarded_admission_queue_mutation_reported_with_line(self):
        """The admission-control state class (ISSUE 9): a band-queue
        append outside the controller lock is caught at the exact
        file:line."""
        out = _findings("bad_admission_queue.py",
                        fablint.CONCURRENCY_RULES)
        assert [(f.rule, f.line) for f in out] == [("guarded-state", 22)]
        assert "_bands" in out[0].message and "_lock" in out[0].message
        assert out[0].path.endswith("bad_admission_queue.py")

    def test_unguarded_batch_queue_access_reported_with_line(self):
        """The batched-delivery state class (PR 8): an append to the
        response collector's batch queue outside its lock is caught at
        the exact file:line."""
        out = _findings("bad_batch_queue.py", fablint.CONCURRENCY_RULES)
        assert [(f.rule, f.line) for f in out] == [("guarded-state", 24)]
        assert "_items" in out[0].message and "_lock" in out[0].message
        assert out[0].path.endswith("bad_batch_queue.py")

    def test_unguarded_shm_handle_swap_reported_with_line(self):
        """The shm ring-plane state class (ISSUE 10): a ring-handle
        swap outside the plane lock is caught at the exact file:line —
        the FabricSocket._shm degrade/re-attach shape."""
        out = _findings("bad_shm_route.py", fablint.CONCURRENCY_RULES)
        assert [(f.rule, f.line) for f in out] == [("guarded-state", 22)]
        assert "_shm" in out[0].message and "_plane_lock" in out[0].message
        assert out[0].path.endswith("bad_shm_route.py")

    def test_unguarded_stripe_health_swap_reported_with_line(self):
        """The STRIPED shm plane's state class (ISSUE 12): resetting the
        stripe geometry outside the plane lock is caught at the exact
        file:line — _shm_stripes must move ATOMICALLY with the handle
        swap on degrade, or a claimer decodes descriptors onto the
        wrong ring."""
        out = _findings("bad_shm_stripe.py", fablint.CONCURRENCY_RULES)
        assert [(f.rule, f.line) for f in out] == [("guarded-state", 26)]
        assert "_shm_stripes" in out[0].message \
            and "_plane_lock" in out[0].message
        assert out[0].path.endswith("bad_shm_stripe.py")

    def test_unguarded_compile_cache_insert_reported_with_line(self):
        """The compiled fan-out plane's state class (ISSUE 11): a
        compile-cache insert outside the plane lock is caught at the
        exact file:line — the once-guard's publish step must stay
        under _lock even though the BUILD runs outside it."""
        out = _findings("bad_collective_cache.py",
                        fablint.CONCURRENCY_RULES)
        assert [(f.rule, f.line) for f in out] == [("guarded-state", 24)]
        assert "_programs" in out[0].message and "_lock" in out[0].message
        assert out[0].path.endswith("bad_collective_cache.py")

    def test_unguarded_worker_table_swap_reported_with_line(self):
        """The usercode pool's worker table (ISSUE 13): clearing
        _iso_workers outside the pool lock is caught at the exact
        file:line — the table must move atomically with the shutdown
        flag or a death-handler resurrects a worker the sentinel loop
        never stops."""
        out = _findings("bad_usercode_pool.py", fablint.CONCURRENCY_RULES)
        assert [(f.rule, f.line) for f in out] == [("guarded-state", 26)]
        assert "_iso_workers" in out[0].message \
            and "_lock" in out[0].message
        assert out[0].path.endswith("bad_usercode_pool.py")

    def test_unguarded_kv_free_list_swap_reported_with_line(self):
        """The serving KV pool's state class (ISSUE 14): swapping the
        block free list outside the pool lock is caught at the exact
        file:line — _free must move atomically with the session tables
        or two sessions can share a block (cross-tenant KV leak)."""
        out = _findings("bad_kv_pool.py", fablint.CONCURRENCY_RULES)
        assert [(f.rule, f.line) for f in out] == [("guarded-state", 27)]
        assert "_free" in out[0].message and "_lock" in out[0].message
        assert out[0].path.endswith("bad_kv_pool.py")

    def test_unguarded_kv_adopt_publish_reported_with_line(self):
        """The zero-copy KV adoption path (ISSUE 15): reserving blocks
        under the pool lock but filling + publishing the session table
        outside it is caught at the exact file:line — between the
        dropped lock and the publish an eviction can hand a reserved
        block to another loader (two sessions scattering into one
        arena row)."""
        out = _findings("bad_kv_adopt.py", fablint.CONCURRENCY_RULES)
        assert [(f.rule, f.line) for f in out] == [("guarded-state", 26)]
        assert "_tables" in out[0].message and "_lock" in out[0].message
        assert out[0].path.endswith("bad_kv_adopt.py")

    def test_unchecked_cow_commit_reported_with_line(self):
        """The CoW prefix-sharing pool (ISSUE 16): an outside-the-lock
        fill is FINE (reserved blocks are invisible to every other pool
        operation), but the commit must re-acquire the lock for the
        re-check — a lock-free table publish is caught at the exact
        file:line (it races close()'s free-list rebuild and concurrent
        same-session loaders)."""
        out = _findings("bad_kv_cow.py", fablint.CONCURRENCY_RULES)
        assert [(f.rule, f.line) for f in out] == [("guarded-state", 28)]
        assert "_tables" in out[0].message and "_lock" in out[0].message
        assert out[0].path.endswith("bad_kv_cow.py")

    def test_unlocked_spill_publish_reported_with_lines(self):
        """The tiered KV pool (ISSUE 19): demoting a session to the
        host arena must publish the spilled record AND bump the host
        refcount under the lock — a lock-free publish races a
        concurrent release/restore (the refcount the restore
        decrements may not exist yet, leaking the host block), caught
        at both exact file:lines."""
        out = _findings("bad_kv_spill.py", fablint.CONCURRENCY_RULES)
        assert [(f.rule, f.line) for f in out] == [
            ("guarded-state", 29), ("guarded-state", 30)]
        assert "_host_refs" in out[0].message
        assert "_spilled" in out[1].message
        assert all("_lock" in f.message for f in out)
        assert out[0].path.endswith("bad_kv_spill.py")

    def test_rogue_plane_state_machine_reported_with_lines(self):
        """ISSUE 17: a plane growing its own down/reestablish machine —
        private state fields plus a hand-rolled revival thread — is
        caught at every declaration site; the fix the message names is
        plane_health.register_plane()."""
        out = _findings("bad_plane_state.py", fablint.CONCURRENCY_RULES)
        assert [(f.rule, f.line) for f in out] == [
            ("plane-state", 14), ("plane-state", 15),
            ("plane-state", 19), ("plane-state", 20),
            ("plane-state", 28)]
        msgs = " | ".join(f.message for f in out)
        assert "register_plane" in msgs and "revival loop" in msgs
        assert out[0].path.endswith("bad_plane_state.py")

    def test_clean_fixture_is_silent(self):
        out = _findings(
            "clean_module.py",
            fablint.CONCURRENCY_RULES + fablint.DEADCODE_RULES)
        assert out == [], [str(f) for f in out]


class TestCustodyFixtures:
    """The ISSUE-20 custody family: path-sensitive acquire/release plus
    refcount balance, each seeded violation pinned at exact file:line."""

    def test_exception_edge_leak_reported_with_line(self):
        out = _findings("bad_custody_exc.py", fablint.CUSTODY_RULES)
        assert [(f.rule, f.line) for f in out] == [("custody", 32)]
        assert "'pin'" in out[0].message and "raise" in out[0].message
        assert out[0].path.endswith("bad_custody_exc.py")

    def test_unguarded_refcount_increment_reported_with_line(self):
        out = _findings("bad_custody_refcount.py", fablint.CUSTODY_RULES)
        assert [(f.rule, f.line) for f in out] == \
            [("refcount-balance", 23)]
        assert "_refs" in out[0].message and "_lock" in out[0].message

    def test_decrement_without_zero_check_reported_with_line(self):
        out = _findings("bad_custody_zerocheck.py",
                        fablint.CUSTODY_RULES)
        assert [(f.rule, f.line) for f in out] == \
            [("refcount-balance", 24)]
        assert "zero-check" in out[0].message
        assert "strands" in out[0].message

    def test_reasonless_custody_moved_marker_is_a_finding(self):
        out = _findings("bad_custody_marker.py",
                        fablint.CUSTODY_RULES + ("bad-suppression",))
        assert [(f.rule, f.line) for f in out] == \
            [("bad-suppression", 28)]
        assert "custody-moved" in out[0].message

    def test_pr16_cow_split_shape_reported_with_line(self):
        # the PR-16 CoW-split refcount leak, re-expressed: the freshly
        # acquired private-block ref leaks on the copy's exception edge
        out = _findings("bad_custody_cow_split.py",
                        fablint.CUSTODY_RULES)
        assert [(f.rule, f.line) for f in out] == [("custody", 35)]
        assert "'_refs'" in out[0].message

    def test_pr6_parked_transfer_drop_reported_with_line(self):
        # the PR-6 parked-transfer drop, re-expressed: the refusal
        # branch returns without untracking and without a marker
        out = _findings("bad_custody_parked_drop.py",
                        fablint.CUSTODY_RULES)
        assert [(f.rule, f.line) for f in out] == [("custody", 27)]
        assert "'_track'" in out[0].message
        assert "returns without releasing" in out[0].message

    def test_clean_custody_fixture_is_silent(self):
        # the accepted idioms: reasoned transfer marker, owning-return,
        # try/finally + broad-handler release, `> 1` guard, zero-check
        out = _findings("clean_custody.py",
                        fablint.ALL_RULES + ("bad-suppression",))
        assert out == [], [str(f) for f in out]

    def test_large_copy_under_lock_reported(self, tmp_path):
        # satellite: blocking-under-lock knows block-sized copy calls
        mod = tmp_path / "m.py"
        mod.write_text(
            "import threading\nimport numpy as np\n"
            "_lock = threading.Lock()\n"
            "def f(a, b):\n"
            "    with _lock:\n"
            "        return a.tobytes() and np.array_equal(a, b)\n")
        out = fablint.run([str(mod)], fablint.CONCURRENCY_RULES)
        assert {(f.rule, f.line) for f in out} == \
            {("blocking-under-lock", 6)}
        msgs = " | ".join(f.message for f in out)
        assert "large copy" in msgs

    def test_custody_maps_on_all_six_modules(self):
        # the ISSUE-20 annotation contract: every custody-carrying
        # module declares its acquire/release protocol
        six = ["serving/kv_pool.py", "ici/device_plane.py",
               "ici/native_plane.py", "rpc/controller.py",
               "rpc/stream.py", "serving/migration.py"]
        for rel in six:
            src = open(os.path.join(PKG, rel)).read()
            assert "_CUSTODY" in src, f"{rel} lost its custody map"


class TestAnalyzerMechanics:
    def test_reasonless_suppression_is_a_finding(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text(
            "import threading\n_lk = threading.Lock()\n"
            "def f():\n"
            "    with _lk:\n"
            "        import time\n"
            "        time.sleep(1)  # fablint: ignore[blocking-under-lock]\n")
        out = fablint.run([str(mod)], fablint.CONCURRENCY_RULES)
        assert [f.rule for f in out] == ["bad-suppression"]

    def test_reasoned_suppression_silences(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text(
            "import threading\n_lk = threading.Lock()\n"
            "def f():\n"
            "    with _lk:\n"
            "        import time\n"
            "        time.sleep(1)  # fablint: ignore[blocking-under-lock] "
            "the sleep is the point\n")
        out = fablint.run([str(mod)], fablint.CONCURRENCY_RULES)
        assert out == [], [str(f) for f in out]

    def test_nested_def_resets_held_locks(self, tmp_path):
        # a closure defined under a with-lock runs LATER: accesses in it
        # must not count as protected
        mod = tmp_path / "m.py"
        mod.write_text(
            "import threading\n"
            "class C:\n"
            "    _GUARDED_BY = {'_x': '_lock'}\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._x = 0\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            def cb():\n"
            "                self._x += 1\n"
            "            return cb\n")
        out = fablint.run([str(mod)], fablint.CONCURRENCY_RULES)
        assert [(f.rule, f.line) for f in out] == [("guarded-state", 10)]

    def test_str_join_not_flagged(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text(
            "import threading\n_lk = threading.Lock()\n"
            "def f(parts):\n"
            "    with _lk:\n"
            "        return ', '.join(parts) + ''.join(p for p in parts)\n")
        out = fablint.run([str(mod)], fablint.CONCURRENCY_RULES)
        assert out == [], [str(f) for f in out]


class TestZeroFindingsGate:
    """The shipped tree is lint-clean — the regression gate."""

    def test_package_concurrency_clean(self):
        out = fablint.run([PKG], fablint.CONCURRENCY_RULES)
        assert out == [], "\n".join(str(f) for f in out)

    def test_package_deadcode_clean(self):
        out = fablint.run([PKG], fablint.DEADCODE_RULES)
        assert out == [], "\n".join(str(f) for f in out)

    def test_package_custody_clean(self):
        out = fablint.run([PKG],
                          fablint.CUSTODY_RULES + ("bad-suppression",))
        assert out == [], "\n".join(str(f) for f in out)

    def test_cli_custody_subcommand_exits_zero(self):
        res = subprocess.run(
            [sys.executable, "-m", "brpc_tpu.tools.fablint", "custody",
             "--json", PKG],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert res.returncode == 0, res.stdout + res.stderr
        assert json.loads(res.stdout) == []

    def test_cli_all_subcommand_exits_zero(self):
        res = subprocess.run(
            [sys.executable, "-m", "brpc_tpu.tools.fablint", "all", PKG],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert res.returncode == 0, res.stdout + res.stderr

    def test_cli_rules_selection_bisects(self):
        # --rules narrows the family: only refcount-balance findings
        # from a fixture that trips both custody rules
        res = subprocess.run(
            [sys.executable, "-m", "brpc_tpu.tools.fablint", "custody",
             "--rules", "refcount-balance", "--json",
             os.path.join(FIXTURES, "bad_custody_cow_split.py")],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert res.returncode == 0, res.stdout + res.stderr
        assert json.loads(res.stdout) == []
        res = subprocess.run(
            [sys.executable, "-m", "brpc_tpu.tools.fablint", "custody",
             "--rules=custody", "--json",
             os.path.join(FIXTURES, "bad_custody_cow_split.py")],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert res.returncode == 1, res.stdout + res.stderr
        data = json.loads(res.stdout)
        assert [d["rule"] for d in data] == ["custody"]

    def test_cli_rules_unknown_name_exits_two(self):
        res = subprocess.run(
            [sys.executable, "-m", "brpc_tpu.tools.fablint",
             "--rules", "no-such-rule", PKG],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert res.returncode == 2
        assert "unknown rule" in res.stderr

    def test_cli_exits_zero_and_emits_json(self):
        res = subprocess.run(
            [sys.executable, "-m", "brpc_tpu.tools.fablint", "--json", PKG],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert res.returncode == 0, res.stdout + res.stderr
        assert json.loads(res.stdout) == []

    def test_cli_exits_one_on_findings(self):
        res = subprocess.run(
            [sys.executable, "-m", "brpc_tpu.tools.fablint", "--json",
             os.path.join(FIXTURES, "bad_sleep.py")],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert res.returncode == 1
        data = json.loads(res.stdout)
        assert data and data[0]["rule"] == "blocking-under-lock"

    def test_hot_modules_declare_guard_maps(self):
        # the annotation contract the issue names: every hot module
        # carries a guard map the analyzer enforces
        hot = ["rpc/socket.py", "rpc/stream.py", "rpc/health_check.py",
               "ici/fabric.py", "ici/transport.py", "ici/device_plane.py",
               "ici/plane_health.py",
               "policy/load_balancers.py", "butil/resource_pool.py",
               "bthread/scheduler.py", "serving/kv_pool.py",
               "serving/kv_source.py", "serving/scheduler.py",
               "serving/autoscaler.py", "serving/router.py",
               "serving/migration.py"]
        for rel in hot:
            src = open(os.path.join(PKG, rel)).read()
            assert "_GUARDED_BY" in src, f"{rel} lost its guard map"

    def test_lock_order_graph_is_extractable(self):
        edges = fablint.lock_order_edges([PKG])
        # the graph exists and is acyclic (the gate above already
        # proves acyclicity; this pins the docs/CONCURRENCY.md source)
        assert isinstance(edges, dict)
