"""Naming-service coverage the reference holds us to:

* golden-payload parser tests for the consul / nacos / discovery JSON
  formats (fixtures under tests/fixtures/ mirror real registry
  responses — the mocked-payload coverage of
  test/brpc_naming_service_unittest.cpp), and
* the consul BLOCKING long-poll watch (index=/wait= round trip against
  a mocked consul that actually holds the poll open), asserting
  sub-second membership propagation that periodic polling could not
  explain.
"""
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from brpc_tpu.butil import flags as _flags
from brpc_tpu.policy import naming

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")


def _fixture(name: str) -> bytes:
    with open(os.path.join(FIXTURES, name), "rb") as f:
        return f.read()


class _Resp:
    """Stand-in for urllib's addinfourl: context manager + read() +
    headers."""

    def __init__(self, body: bytes, headers=None):
        self._body = body
        self.headers = headers or {}

    def read(self):
        return self._body

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class TestGoldenPayloads:
    def test_consul_health_service(self, monkeypatch):
        body = _fixture("consul_health_service.json")
        seen = {}

        def fake_urlopen(url, timeout=None):
            seen["url"] = url
            return _Resp(body, {"X-Consul-Index": "1042"})

        monkeypatch.setattr("urllib.request.urlopen", fake_urlopen)
        ns = naming.ConsulNamingService("127.0.0.1:8500/web")
        entries = ns.get_servers()
        assert seen["url"] == \
            "http://127.0.0.1:8500/v1/health/service/web"
        assert [str(e.endpoint) for e in entries] == \
            ["10.1.10.12:8000", "10.1.10.13:8001"]
        assert entries[0].tag == "primary,v1"
        assert entries[1].tag == ""
        assert ns.last_index == "1042"       # header primed the index

    def test_nacos_instance_list(self, monkeypatch):
        body = _fixture("nacos_instance_list.json")
        seen = {}

        def fake_urlopen(url, timeout=None):
            seen["url"] = url
            return _Resp(body)

        monkeypatch.setattr("urllib.request.urlopen", fake_urlopen)
        ns = naming.NacosNamingService("127.0.0.1:8848/demo.service")
        entries = ns.get_servers()
        assert "serviceName=demo.service" in seen["url"]
        # unhealthy (10.2.0.7) and disabled (10.2.0.8) are filtered out
        assert [str(e.endpoint) for e in entries] == \
            ["10.2.0.5:8848", "10.2.0.6:8848"]
        # nacos float weights scale the default 100
        assert [e.weight for e in entries] == [100, 250]
        assert entries[0].tag == "DEFAULT"

    def test_discovery_fetchs(self, monkeypatch):
        body = _fixture("discovery_fetchs.json")

        def fake_urlopen(url, timeout=None):
            return _Resp(body)

        monkeypatch.setattr("urllib.request.urlopen", fake_urlopen)
        ns = naming.DiscoveryNamingService("127.0.0.1:7171/demo.service")
        entries = ns.get_servers()
        # status!=1 (host-2) is filtered; every addr of a live instance
        # is an entry, zone rides the tag
        assert [str(e.endpoint) for e in entries] == \
            ["10.3.1.1:9000", "10.3.1.1:8080",
             "10.3.1.3:9000"]
        assert [e.tag for e in entries] == ["sh001", "sh001", "sh003"]


# ---------------------------------------------------------------------------
# The blocking watch against a mocked consul.
# ---------------------------------------------------------------------------

class _MockConsulHandler(BaseHTTPRequestHandler):
    def do_GET(self):
        srv = self.server
        parsed = urlparse(self.path)
        idx = parse_qs(parsed.query).get("index", [None])[0]
        with srv.state_lock:
            srv.queries.append((parsed.path, idx))
            gen_event = srv.change
            current = str(srv.index)
        if idx == current:
            # a real consul HOLDS the poll open until membership moves
            # past the presented index (or the wait elapses)
            gen_event.wait(5.0)
        self._respond()

    def _respond(self):
        srv = self.server
        with srv.state_lock:
            body = json.dumps(srv.payload).encode()
            index = str(srv.index)
        self.send_response(200)
        self.send_header("X-Consul-Index", index)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


def _consul_item(addr: str, port: int):
    return {"Service": {"Service": "web", "Tags": [], "Address": addr,
                        "Port": port}}


class TestConsulBlockingWatch:
    def test_index_round_trip_and_subsecond_propagation(self):
        srv = ThreadingHTTPServer(("127.0.0.1", 0), _MockConsulHandler)
        srv.daemon_threads = True
        srv.state_lock = threading.Lock()
        srv.index = 7
        srv.payload = [_consul_item("10.9.0.1", 80)]
        srv.change = threading.Event()
        srv.queries = []
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()

        # a 30s polling period: any sub-second propagation below must
        # come from the long poll, not from a lucky poll tick
        old_poll = _flags.get_flag("ns_poll_interval_s")
        _flags.set_flag("ns_poll_interval_s", 30.0)
        got = []

        class Watcher:
            def reset_servers(self, entries):
                got.append((time.monotonic(), [str(e.endpoint)
                                               for e in entries]))

        t = None
        try:
            t = naming.NamingServiceThread(
                f"consul://127.0.0.1:{port}/web")
            t.add_watcher(Watcher())
            assert got and got[-1][1] == ["10.9.0.1:80"]
            # the watch loop must be PARKED in a blocking poll carrying
            # the primed index before we flip membership
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                with srv.state_lock:
                    if any(q[1] == "7" for q in srv.queries):
                        break
                time.sleep(0.01)
            with srv.state_lock:
                assert any(q[1] == "7" for q in srv.queries), srv.queries
                # membership flips: bump the index and release the poll
                srv.payload = [_consul_item("10.9.0.1", 80),
                               _consul_item("10.9.0.2", 81)]
                srv.index = 8
                released, srv.change = srv.change, threading.Event()
                t0 = time.monotonic()
                released.set()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if got and len(got[-1][1]) == 2:
                    break
                time.sleep(0.01)
            assert got[-1][1] == ["10.9.0.1:80",
                                  "10.9.0.2:81"]
            dt = got[-1][0] - t0
            assert dt < 1.0, \
                f"long poll should propagate sub-second, took {dt:.2f}s"
            # and the next round re-issued with the NEW index
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                with srv.state_lock:
                    if any(q[1] == "8" for q in srv.queries):
                        break
                time.sleep(0.01)
            with srv.state_lock:
                assert any(q[1] == "8" for q in srv.queries), srv.queries
        finally:
            _flags.set_flag("ns_poll_interval_s", old_poll)
            if t is not None:
                t.stop()
            srv.shutdown()
