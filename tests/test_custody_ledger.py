"""Runtime custody ledger (ISSUE 20, butil/custody_ledger.py).

The static custody pass proves the lexical shape; this ledger is the
runtime complement — every declared acquire/release point records a
stack-tagged entry, so a leak names the ACQUIRING file:line.  Tier-1
runs entirely under ``BRPC_TPU_DEBUG_CUSTODY=1`` (conftest), so these
tests drive the same instrumentation the census asserts on.
"""
import inspect

import numpy as np
import pytest

from brpc_tpu.butil import custody_ledger

from test_serving import _mk_pool, _rows


def _acquire_here(resource, key):
    # one helper frame so the default depth lands on OUR caller line,
    # mirroring the instrumented-method shape (pool.pin -> acquire)
    custody_ledger.acquire(resource, key)


def _release_strict_here(resource, key):
    custody_ledger.release(resource, key, strict=True)


class TestLedgerCore:
    def test_enabled_under_tier1(self):
        # conftest exports BRPC_TPU_DEBUG_CUSTODY=1 before any import
        assert custody_ledger.enabled()

    def test_acquires_nest_and_release_drops_one(self):
        key = ("nest-test",)
        def mine():
            return [r for r in custody_ledger.outstanding()
                    if r["resource"] == "t.nest"]
        assert mine() == []
        _acquire_here("t.nest", key)
        _acquire_here("t.nest", key)
        assert len(mine()) == 2
        custody_ledger.release("t.nest", key)
        assert len(mine()) == 1
        custody_ledger.release("t.nest", key)
        assert mine() == []

    def test_nonstrict_release_of_unknown_key_is_ignored(self):
        rep0 = custody_ledger.report()
        custody_ledger.release("t.unknown", ("nobody",))
        rep = custody_ledger.report()
        assert len(rep["unmatched_releases"]) == \
            len(rep0["unmatched_releases"])

    def test_strict_unmatched_release_recorded_with_site(self):
        n0 = len(custody_ledger.report()["unmatched_releases"])
        line = inspect.currentframe().f_lineno + 1
        _release_strict_here("t.strict", ("nobody",))
        um = custody_ledger.report()["unmatched_releases"]
        assert len(um) == n0 + 1
        assert um[-1]["resource"] == "t.strict"
        assert um[-1]["site"] == f"test_custody_ledger.py:{line}"

    def test_drop_prefix_forgets_one_owner_scope(self):
        _acquire_here("t.pfx", (1, "a"))
        _acquire_here("t.pfx", (1, "b"))
        _acquire_here("t.pfx", (2, "c"))
        assert custody_ledger.drop_prefix("t.pfx", 1) == 2
        left = [r for r in custody_ledger.outstanding()
                if r["resource"] == "t.pfx"]
        assert [r["key"] for r in left] == [[2, "c"]]
        custody_ledger.release("t.pfx", (2, "c"))

    def test_disabled_hooks_are_noops(self, monkeypatch):
        monkeypatch.setattr(custody_ledger, "enabled", lambda: False)
        custody_ledger.acquire("t.off", ("x",))
        custody_ledger.release("t.off", ("x",))
        assert custody_ledger.drop_prefix("t.off", "x") == 0
        monkeypatch.undo()
        assert all(r["resource"] != "t.off"
                   for r in custody_ledger.outstanding())


class TestLeakAttribution:
    """The ISSUE-20 acceptance criterion: a deliberately-injected leak
    is attributed to its acquiring file:line, through the REAL pool."""

    def test_deliberate_pin_leak_names_this_files_line(self):
        pool = _mk_pool(num_blocks=4, block_tokens=8)
        try:
            toks = [3] * 16
            pool.load("s1", _rows(toks), last_token=3)

            def pins():
                return [r for r in custody_ledger.outstanding()
                        if r["resource"] == "kv.pin"
                        and r["key"][1] == "s1"]

            assert pins() == []
            # the deliberate leak: pin and walk away
            leak_line = inspect.currentframe().f_lineno + 1
            assert pool.pin("s1")
            out = pins()
            assert len(out) == 1
            assert out[0]["site"] == \
                f"test_custody_ledger.py:{leak_line}"
            # the report carries the same attribution the chaos
            # parent asserts on
            rep = custody_ledger.report()
            assert not rep["ok"]
            # balance it so the census (and this very ledger) stay
            # clean — the leak above was the injected one
            pool.unpin("s1")
            assert pins() == []
        finally:
            pool.close()

    def test_pool_close_ends_custody_of_everything_it_owned(self):
        pool = _mk_pool(num_blocks=4, block_tokens=8)
        pool.load("s1", _rows([3] * 16), last_token=3)
        assert pool.pin("s1")      # deliberately leaked across close
        pool.close()
        assert all(r["key"][0] != id(pool)
                   for r in custody_ledger.outstanding()
                   if r["resource"] in ("kv.pin", "kv.reserve"))


class TestEchoBenchRegression:
    def test_device_index_failure_leaks_no_registry_key(self,
                                                        monkeypatch):
        """Sweep true positive (native_plane echo bench): _device_index
        raising between put() and the try/finally leaked the registry
        key pre-fix; the descriptor is now computed before put."""
        from brpc_tpu.ici import native_plane as npl
        if npl.native.load() is None or not npl.ensure_hooks():
            pytest.skip("native ici lib unavailable")
        import jax.numpy as jnp
        arr = jnp.zeros((16,), dtype=jnp.uint8)
        base = npl.registry().live()

        def boom(a):
            raise RuntimeError("stale mesh generation")

        monkeypatch.setattr(npl, "_device_index", boom)
        with pytest.raises(RuntimeError):
            npl.native_ici_echo_p50_us(iters=1, payload=8,
                                       device_array=arr)
        assert npl.registry().live() == base
