"""Pod fabric: N-process membership, the sequenced xproc device plane,
and the N=4 all-to-all chaos contract.

Three legs:

  * **Sequencer units** (single process): the direction-spanning total
    order — master assignment, client parking, identical execution
    order on both ends of a simulated pair, teardown failing parked
    transfers (pins release).
  * **2-process bidirectional xproc** — the shape that broke the old
    per-direction executors: concurrent device payloads BOTH WAYS on one
    socket pair, byte-exact, with both ends' sequencers executing the
    IDENTICAL uuid order (published through the coordination KV and
    compared cross-process).
  * **N=4 chaos** (the acceptance contract): all-to-all traffic over a
    ``pod://`` LB while one member's serving endpoint is KILLED (listener
    torn down + every server-side control conn severed — process-death-
    equivalent at the fabric layer; the OS process is kept alive only
    because it hosts a quarter of the shared jax coordination service)
    and another member DRAINS gracefully mid-traffic; zero
    client-visible failures on surviving pairs throughout; the killed
    member revives under a NEW socket id and rejoins the pod epoch
    (gen bump observed by every member, epoch converging to the same
    value everywhere).
"""
import json
import os
import subprocess
import sys
import threading
import time

import pytest

import brpc_tpu.policy  # noqa: F401
from brpc_tpu import rpc  # noqa: F401  (re-exported helpers used below)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.pod


def _run_pod(script: str, n: int, timeout: int = 300,
             expect_rc=None, tag: str = "pod"):
    """Run an n-process pod scenario under the debug_sync runtime
    lock-order layer (the chaos harness discipline): every child runs
    with instrumented locks and dumps its acquisition graph; the parent
    asserts each surviving child's graph stayed acyclic with zero long
    holds."""
    import tempfile
    from netalloc import alloc_port
    if expect_rc is None:
        expect_rc = tuple(0 for _ in range(n))
    coord = f"127.0.0.1:{alloc_port(tag)}"
    tmpdir = tempfile.mkdtemp(prefix="pod_debug_sync_")
    procs, report_paths = [], []
    for i in range(n):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env.pop("JAX_NUM_PROCESSES", None)
        env["BRPC_TPU_DEBUG_LOCK_ORDER"] = "1"
        report = os.path.join(tmpdir, f"debug_sync_{i}.json")
        env["BRPC_TPU_DEBUG_SYNC_REPORT"] = report
        report_paths.append(report)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script, str(i), coord, str(n)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env))
    outs, rcs = [], []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
        rcs.append(p.returncode)
    assert list(rcs) == list(expect_rc), (
        f"rcs={rcs} want={expect_rc}\n" + "\n".join(
            f"--- child{i} ---\n{o}" for i, o in enumerate(outs)))
    for i, (path, want_rc) in enumerate(zip(report_paths, expect_rc)):
        if want_rc != 0:
            continue
        assert os.path.exists(path), (
            f"child {i} exited 0 but wrote no debug_sync report")
        with open(path) as f:
            rep = json.load(f)
        assert not rep["cycles"], (
            f"child {i}: runtime lock-order cycle:\n"
            + json.dumps(rep["cycles"], indent=2))
        assert not rep["long_holds"], (
            f"child {i}: long lock holds:\n"
            + json.dumps(rep["long_holds"], indent=2))
    return outs


# ---------------------------------------------------------------------------
# Pod membership units (single process, no fabric).
# ---------------------------------------------------------------------------

class TestPodUnits:
    def test_epoch_is_sum_of_gens_and_strictly_monotone(self):
        from brpc_tpu.ici.pod import PodMember, epoch_of, UP, DOWN
        m = {0: PodMember(0, 1, UP, [0, 1], [0], []),
             1: PodMember(1, 2, UP, [2, 3], [2], [])}
        assert epoch_of(m) == 3
        # every transition bumps exactly one gen: epoch strictly grows
        m[1] = PodMember(1, 3, DOWN, [2, 3], [], [])
        assert epoch_of(m) == 4
        m[2] = PodMember(2, 1, UP, [4, 5], [4], [])
        assert epoch_of(m) == 5

    def test_member_record_roundtrip(self):
        from brpc_tpu.ici.pod import PodMember, DRAINING
        m = PodMember(3, 7, DRAINING, [6, 7], [6], [6], ctrl="h:1")
        m2 = PodMember.from_json(m.to_json())
        assert (m2.pid, m2.gen, m2.state, m2.devices, m2.serving,
                m2.draining, m2.ctrl) == (3, 7, DRAINING, [6, 7], [6],
                                          [6], "h:1")

    def test_join_requires_fabric_node(self):
        from brpc_tpu.ici.fabric import FabricNode
        from brpc_tpu.ici.pod import Pod
        if FabricNode.instance() is not None:
            pytest.skip("fabric initialized in this process")
        with pytest.raises(RuntimeError):
            Pod.join("nope")

    def test_pod_naming_empty_without_join(self):
        from brpc_tpu.policy.naming import create_naming_service
        ns = create_naming_service("pod://unjoined")
        assert ns.get_servers() == []


# ---------------------------------------------------------------------------
# CollectiveSequencer units: the total order on a simulated pair.
# ---------------------------------------------------------------------------

class _SeqSock:
    """Just enough socket for a CollectiveSequencer: executions recorded,
    assignments forwarded to the peer sequencer (the control channel)."""

    failed = False
    is_server_side = False
    remote_dev = 99
    remote_side = "fake"

    def __init__(self):
        self.executed = []
        self.peer_seq = None
        self.downs = []

    def _peer_gone(self):
        return False

    def _device_plane_down(self, reason):
        self.downs.append(reason)

    def _ctrl_send(self, ftype, body):
        import struct
        from brpc_tpu.ici import fabric as F
        assert ftype == F._F_DPLANE_SEQ
        u, s = struct.unpack("<Qq", body)
        if self.peer_seq is not None:
            self.peer_seq.on_assignment(u, s)

    def _dplane_execute_bulk(self, t):
        from brpc_tpu.ici import device_plane as dp
        self.executed.append(t.uuid)
        dp.plane().finish_remote(t, None)


@pytest.fixture()
def _bulk_leg():
    """Force the bulk-carried execution leg (routes through the fake
    socket's _dplane_execute_bulk)."""
    from brpc_tpu.butil import flags as fl
    old = fl.get_flag("ici_device_plane_xproc_compiled")
    fl.set_flag("ici_device_plane_xproc_compiled", "off")
    yield
    fl.set_flag("ici_device_plane_xproc_compiled", old)


class TestCollectiveSequencer:
    def _pair(self):
        from brpc_tpu.ici.fabric import CollectiveSequencer
        a, b = _SeqSock(), _SeqSock()
        sa = CollectiveSequencer(a, master=True)
        sb = CollectiveSequencer(b, master=False)
        a.peer_seq, b.peer_seq = sb, sa
        return a, b, sa, sb

    @staticmethod
    def _transfer(uuid):
        from brpc_tpu.ici.device_plane import DeviceTransfer
        return DeviceTransfer(uuid, 0, 1, 64)

    def _wait_executed(self, *socks, n, timeout=10.0):
        deadline = time.monotonic() + timeout
        while any(len(s.executed) < n for s in socks) \
                and time.monotonic() < deadline:
            time.sleep(0.01)

    def test_interleaved_bidirectional_total_order(self, _bulk_leg):
        a, b, sa, sb = self._pair()
        try:
            # master sends 1,2; client sends 11,12 — descriptors cross in
            # a scrambled arrival order, as concurrent directions do
            t1, t2 = self._transfer(1), self._transfer(2)
            t11, t12 = self._transfer(11), self._transfer(12)
            s1 = sa.submit_local(t1)            # master assigns 0
            s11 = sb.submit_local(t11)          # client parks (-1)
            # client's descriptor reaches the master BEFORE the master's
            # own second send; master's first descriptor reaches the
            # client last
            sa.submit_remote(self._recv(11), s11)   # master assigns 1
            s2 = sa.submit_local(t2)                # master assigns 2
            s12 = sb.submit_local(t12)              # parks
            sa.submit_remote(self._recv(12), s12)   # assigns 3
            sb.submit_remote(self._recv(2), s2)
            sb.submit_remote(self._recv(1), s1)
            self._wait_executed(a, b, n=4)
            assert a.executed == b.executed == [1, 11, 2, 12]
            assert list(sa.executed) == list(sb.executed)
            assert not a.downs and not b.downs
        finally:
            sa.close()
            sb.close()

    def _recv(self, uuid):
        from brpc_tpu.ici.device_plane import plane
        return plane().post_recv_remote(uuid, 64, src_dev=0, dst_dev=1)

    def test_close_fails_parked_and_queued_transfers(self, _bulk_leg):
        from brpc_tpu.ici.device_plane import FAILED
        a, b, sa, sb = self._pair()
        # a parked client send (no assignment yet) and an out-of-order
        # queued transfer (seq 5 with 0..4 missing: never executable)
        parked = self._transfer(21)
        assert sb.submit_local(parked) == -1
        gapped = self._recv(22)
        sb.submit_remote(gapped, 5)
        sa.close()
        sb.close()
        deadline = time.monotonic() + 5
        while (parked.state != FAILED or gapped.state != FAILED) \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert parked.state == FAILED      # completion fired, pin released
        assert gapped.state == FAILED
        assert parked.completion.poll() and gapped.completion.poll()

    def test_submit_after_close_is_refused(self, _bulk_leg):
        a, b, sa, sb = self._pair()
        sb.close()
        sa.close()
        assert sb.submit_local(self._transfer(31)) is None
        t = self._recv(32)
        sa.submit_remote(t, -1)
        from brpc_tpu.ici.device_plane import FAILED
        deadline = time.monotonic() + 5
        while t.state != FAILED and time.monotonic() < deadline:
            time.sleep(0.01)
        assert t.state == FAILED


# ---------------------------------------------------------------------------
# 2-process bidirectional xproc: identical total order on both ends.
# ---------------------------------------------------------------------------

_POD_PRELUDE = r"""
import os, sys, threading, time, json
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")

# Fail FAST and HARD: an uncaught exception must not reach the normal
# interpreter exit — the coordination-service leader's atexit shutdown
# waits for every task to disconnect while the other children sit in
# multi-minute barriers, wedging the whole scenario until the parent's
# timeout obscures the real traceback.  Print, dump the debug_sync
# report (the atexit hook won't run), and _exit(1) so peers abort
# quickly on leader death instead.
_real_excepthook = sys.excepthook
def _fail_fast(tp, val, tb):
    _real_excepthook(tp, val, tb)
    sys.stdout.flush(); sys.stderr.flush()
    try:
        from brpc_tpu.butil.debug_sync import dump_report_now
        dump_report_now()
    except Exception:
        pass
    os._exit(1)
sys.excepthook = _fail_fast

pid = int(sys.argv[1]); coord = sys.argv[2]; NPROC = int(sys.argv[3])
from brpc_tpu.ici.fabric import FabricNode, FabricSocket
node = FabricNode.initialize(coord, num_processes=NPROC, process_id=pid)
kv = node._kv
import brpc_tpu.policy
from brpc_tpu import rpc, ici
from brpc_tpu.rpc.socket import list_sockets, Socket
from brpc_tpu.butil.iobuf import IOBuf
from echo_pb2 import EchoRequest, EchoResponse
mesh = ici.IciMesh(); ici.IciMesh.set_default(mesh)

def fabric_socks():
    return [s for s in list_sockets() if isinstance(s, FabricSocket)]
"""

_XPROC_BIDIR = _POD_PRELUDE + r"""
import numpy as np
import jax.numpy as jnp
from brpc_tpu.butil import flags as _fl
_fl.set_flag("ici_device_plane_host_mesh", True)
_fl.set_flag("ici_device_plane_threshold", 4096)

N = 128 * 1024
K = 6
MYDEV = 2 * pid
PEERDEV = 2 * (1 - pid)

class Echo(rpc.Service):
    SERVICE_NAME = "Echo"
    @rpc.method(EchoRequest, EchoResponse)
    def Bounce(self, cntl, request, response, done):
        data = np.frombuffer(cntl.request_attachment.to_bytes(), np.uint8)
        back = jax.device_put(jnp.asarray((data.astype(np.int64) + 1) %% 251,
                                          dtype=jnp.uint8),
                              jax.devices()[MYDEV])
        jax.block_until_ready(back)
        # device-resident response attachment: the RESPONSE rides kind-4
        # too — both directions sequenced on ONE socket pair
        cntl.response_attachment.append_device_array(back)
        response.message = "ok"
        done()

server = rpc.Server(); server.add_service(Echo())
assert server.start("ici://%%d" %% MYDEV) == 0
kv.key_value_set("xb_up_%%d" %% pid, "1")
kv.blocking_key_value_get("xb_up_%%d" %% (1 - pid), 60000)

ch = rpc.Channel()
ch.init("ici://%%d" %% PEERDEV,
        options=rpc.ChannelOptions(timeout_ms=60000, max_retry=0))
errs = []

def fire(i):
    val = (i * 7 + pid * 3 + 1) %% 251
    payload = jax.device_put(jnp.full((N,), val, jnp.uint8),
                             jax.devices()[MYDEV])
    jax.block_until_ready(payload)
    cntl = rpc.Controller()
    cntl.request_attachment.append_device_array(payload)
    resp = ch.call_method("Echo.Bounce", cntl,
                          EchoRequest(message=str(i)), EchoResponse)
    if cntl.failed():
        errs.append((i, cntl.error_code_, cntl.error_text_))
        return
    got = np.frombuffer(cntl.response_attachment.to_bytes(), np.uint8)
    if not (got == (val + 1) %% 251).all():
        errs.append((i, "corrupt", int(got[0])))

# both directions concurrently: two threads of K calls on each process
threads = [threading.Thread(target=lambda lo=lo: [fire(i) for i in
                                                  range(lo, lo + K)])
           for lo in (0, K)]
for t in threads: t.start()
for t in threads: t.join()
assert not errs, errs[:5]

clients = [s for s in fabric_socks() if not s.is_server_side]
servers = [s for s in fabric_socks() if s.is_server_side]
assert len(clients) == 1 and len(servers) == 1, (clients, servers)
c, s = clients[0], servers[0]
# every call's request AND response crossed kind-4 (2K transfers per
# socket: K send halves + K recv halves)
deadline = time.time() + 30
while (len(c._dplane_seq.executed) < 4 * K
       or len(s._dplane_seq.executed) < 4 * K) and time.time() < deadline:
    time.sleep(0.02)
assert len(c._dplane_seq.executed) == 4 * K, len(c._dplane_seq.executed)
assert len(s._dplane_seq.executed) == 4 * K, len(s._dplane_seq.executed)
assert c._dplane_seq.master is False and s._dplane_seq.master is True
assert c.dplane_bytes_sent >= 2 * K * N, c.dplane_bytes_sent
assert c.dplane_bytes_recv >= 2 * K * N, c.dplane_bytes_recv
# the bulk-carried leg moved the bytes (no compiled collectives on CPU)
assert c.bulk_bytes_sent >= 2 * K * N, c.bulk_bytes_sent
kv.key_value_set("xb_order_c_%%d" %% pid,
                 json.dumps(list(c._dplane_seq.executed)))
kv.key_value_set("xb_order_s_%%d" %% pid,
                 json.dumps(list(s._dplane_seq.executed)))
# pair A = my client socket <-> peer's server socket: IDENTICAL order
peer_s = json.loads(kv.blocking_key_value_get(
    "xb_order_s_%%d" %% (1 - pid), 60000))
assert list(c._dplane_seq.executed) == peer_s, (
    "total order diverged", list(c._dplane_seq.executed)[:8], peer_s[:8])
kv.wait_at_barrier("xb_done", 120000)
server.stop()
print("XB%%d_OK" %% pid, flush=True)
"""


def test_xproc_bidirectional_total_order_and_byte_exactness():
    """Concurrent device payloads both ways on one socket pair — the
    per-direction-executor failure shape — must execute in ONE identical
    total order on both processes, byte-exact."""
    outs = _run_pod(_XPROC_BIDIR % {"repo": REPO}, n=2, timeout=240,
                    tag="xproc_bidir")
    assert "XB0_OK" in outs[0]
    assert "XB1_OK" in outs[1]


# ---------------------------------------------------------------------------
# N=4 membership (no faults): join/advertise/resolve/drain/restart/leave.
# Also the dryrun_multichip membership leg (__graft_entry__).
# ---------------------------------------------------------------------------

_POD_MEMBERSHIP = _POD_PRELUDE + r"""
from brpc_tpu.ici.pod import Pod

MYDEV = 2 * pid
pod = Pod.join("dryrun")

class Svc(rpc.Service):
    SERVICE_NAME = "EchoService"
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = "m%%d" %% pid
        done()

server = rpc.Server(); server.add_service(Svc())
assert server.start("ici://%%d" %% MYDEV) == 0
pod.wait_epoch(2 * NPROC, timeout=60)        # join xN + advertise xN

# pod:// naming resolves every member's serving device, identically
from brpc_tpu.policy.naming import create_naming_service
eps = sorted(str(e.endpoint)
             for e in create_naming_service("pod://dryrun").get_servers())
want = sorted("ici://%%d" %% (2 * p) for p in range(NPROC))
assert eps == want, (eps, want)

# an LB channel over the pod reaches every member
ch = rpc.Channel()
ch.init("pod://dryrun", "rr",
        options=rpc.ChannelOptions(timeout_ms=30000, max_retry=2))
seen = set()
deadline = time.time() + 60
while len(seen) < NPROC and time.time() < deadline:
    cntl = rpc.Controller()
    resp = ch.call_method("EchoService.Echo", cntl,
                          EchoRequest(message="x"), EchoResponse)
    assert not cntl.failed(), (cntl.error_code_, cntl.error_text_)
    seen.add(resp.message)
assert seen == {"m%%d" %% p for p in range(NPROC)}, seen

# one member drains gracefully and restarts: everyone observes the
# membership move through the epoch, and pod:// follows
kv.wait_at_barrier("pm_resolved", 120000)
if pid == NPROC - 1:
    server.stop(2.0)                         # drain mark + withdraw
    server2 = rpc.Server(); server2.add_service(Svc())
    assert server2.start("ici://%%d" %% MYDEV) == 0
    live_server = server2
else:
    live_server = server
# drain mark + withdraw + restart advertise = 3 bumps
FINAL = 2 * NPROC + 3
pod.wait_epoch(FINAL, timeout=60)
final = pod.members(refresh=True)
from brpc_tpu.ici.pod import epoch_of
assert epoch_of(final) == FINAL, (epoch_of(final), FINAL)
assert all(final[p].serving == [2 * p] for p in range(NPROC)), {
    p: final[p].serving for p in final}
kv.wait_at_barrier("pm_done", 120000)
live_server.stop()
pod.leave()
print("PM%%d_OK" %% pid, flush=True)
"""


def run_membership_n4(n: int = 4, timeout: int = 240) -> None:
    """The N=4 membership leg, importable by __graft_entry__'s
    dryrun_multichip: join/advertise/pod-naming/LB/drain/restart/epoch
    convergence across 4 real processes, under the debug_sync runtime
    lock-order layer."""
    outs = _run_pod(_POD_MEMBERSHIP % {"repo": REPO}, n=n,
                    timeout=timeout, tag="pod_membership")
    for i in range(n):
        assert f"PM{i}_OK" in outs[i], outs[i][-2000:]


def test_pod_membership_join_resolve_drain_restart_n4():
    """4 processes join the pod, pod:// resolves every serving member
    identically everywhere, an LB channel reaches all four, a graceful
    drain + restart moves the epoch on every member, and the final
    membership converges."""
    run_membership_n4()


# ---------------------------------------------------------------------------
# N=4 chaos: kill + drain under all-to-all traffic, revival, epoch rejoin.
# ---------------------------------------------------------------------------

_POD_CHAOS = _POD_PRELUDE + r"""
from brpc_tpu.ici.pod import Pod

MYDEV = 2 * pid
pod = Pod.join("chaos")

class Svc(rpc.Service):
    SERVICE_NAME = "EchoService"
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = "t%%d:%%s" %% (pid, request.message)
        done()

server = rpc.Server(); server.add_service(Svc())
assert server.start("ici://%%d" %% MYDEV) == 0
# join x4 + advertise x4
pod.wait_epoch(2 * NPROC, timeout=60)
members = pod.members(refresh=True)
assert sorted(members) == list(range(NPROC)), members
assert all(members[p].serving == [2 * p] for p in range(NPROC)), {
    p: members[p].serving for p in members}

opts = rpc.ChannelOptions(timeout_ms=15000, max_retry=3)
ch = rpc.Channel()
ch.init("pod://chaos", "rr", options=opts)

failures = []
seen = set()
seen_lock = threading.Lock()

def fire(i):
    cntl = rpc.Controller()
    resp = ch.call_method("EchoService.Echo", cntl,
                          EchoRequest(message=str(i)), EchoResponse)
    if cntl.failed():
        failures.append((i, cntl.error_code_, cntl.error_text_))
    else:
        with seen_lock:
            seen.add(resp.message.split(":")[0])

# ---- phase 1: all-to-all warmup — every member sees every tag --------
deadline = time.time() + 60
i = 0
while time.time() < deadline:
    fire(i); i += 1
    with seen_lock:
        if len(seen) == NPROC:
            break
assert len(seen) == NPROC, seen
assert not failures, failures[:5]
print("PHASE warm %%d" %% pid, flush=True)
kv.wait_at_barrier("pc_warm", 120000)

if pid not in (2, 3):
    # ---- survivors (every member but the kill/drain targets, N-generic):
    # continuous traffic, ZERO visible failures ----
    stop_traffic = threading.Event()
    def traffic():
        j = 100000 * (pid + 1)
        while not stop_traffic.is_set():
            fire(j); j += 1
            time.sleep(0.01)
    # daemon: an assertion failure on the main thread must exit the
    # child with its traceback, not hang behind the traffic loop
    th = threading.Thread(target=traffic, daemon=True)
    th.start()
    old_sid = None
    if pid == 0:
        # direct channel to the kill target: record the pre-kill socket id
        dch = rpc.Channel()
        dch.init("ici://4", options=rpc.ChannelOptions(timeout_ms=15000,
                                                       max_retry=0))
        cntl = rpc.Controller()
        dch.call_method("EchoService.Echo", cntl, EchoRequest(message="d"),
                        EchoResponse)
        assert not cntl.failed(), cntl.error_text
        socks = [s for s in fabric_socks() if s.remote_dev == 4]
        assert socks, "no fabric socket to the kill target before the kill"
        old_sid = socks[0].id
        kv.key_value_set("pc_presock", "1")
    kv.key_value_set("pc_traffic_on_%%d" %% pid, "1")
    print("PHASE traffic_on %%d" %% pid, flush=True)
    kv.blocking_key_value_get("pc_revived", 180000)
    print("PHASE saw_revived %%d" %% pid, flush=True)
    # ---- post-revival: both transitioned members serve again ----------
    with seen_lock:
        seen.clear()
    deadline = time.time() + 60
    while time.time() < deadline:
        with seen_lock:
            if "t2" in seen and "t3" in seen:
                break
        time.sleep(0.05)
    stop_traffic.set()
    th.join(30)
    print("PHASE post_revival_seen %%d %%s" %% (pid, sorted(seen)), flush=True)
    with seen_lock:
        assert "t2" in seen, ("killed member never revived into LB", seen)
        assert "t3" in seen, ("drained member never restarted into LB",
                              seen)
    # THE contract: kill + drain under continuous all-to-all traffic was
    # client-invisible on surviving pairs
    assert not failures, failures[:5]
    if pid == 0:
        # revived under a NEW socket id; the old id is revoked
        cntl = rpc.Controller()
        cntl.timeout_ms = 20000
        cntl.max_retry = 40
        cntl.retry_backoff_ms = 50
        resp = dch.call_method("EchoService.Echo", cntl,
                               EchoRequest(message="post"), EchoResponse)
        assert not cntl.failed(), (cntl.error_code_, cntl.error_text_)
        assert resp.message == "t2:post", (
            "direct post-revival call answered by the wrong server",
            resp.message)
        # The pre-kill socket saw a GRACEFUL EOF (the kill's
        # shutdown(SHUT_RDWR) is a plain FIN, no error code), and a
        # graceful EOF deliberately rides the ORDERED delivery queue —
        # the zombie (peer-gone, unfailed) lingers in the pool until the
        # messenger drains end-of-stream, which under the instrumented
        # debug_sync locks can trail this assert.  The contract is
        # EVENTUAL revocation: wait for it, then require every usable
        # socket to carry a NEW id.
        eod = time.time() + 60
        while Socket.address(old_sid) is not None and time.time() < eod:
            time.sleep(0.05)
        assert Socket.address(old_sid) is None, \
            "stale pre-kill socket id must not resolve"
        new_socks = [s for s in fabric_socks()
                     if s.remote_dev == 4 and not s.failed
                     and not s._peer_gone()]
        assert new_socks, "no live socket to the revived member"
        assert all(s.id != old_sid for s in new_socks), (
            "revived member reached through the PRE-KILL socket id",
            old_sid, [s.id for s in new_socks])
elif pid == 2:
    # ---- the KILL: process-death-equivalent for the serving endpoint.
    # No GOODBYE, no pod withdraw — the record still claims "serving",
    # exactly like a crashed process; liveness is the health checker's
    # job, membership only moves again at REVIVAL (the gen bump).
    kv.blocking_key_value_get("pc_traffic_on_0", 60000)
    kv.blocking_key_value_get("pc_traffic_on_1", 60000)
    kv.blocking_key_value_get("pc_presock", 60000)
    import socket as pysock
    from brpc_tpu.ici.transport import ici_unlisten
    ici_unlisten(MYDEV)
    nb = getattr(server, "_native_ici", None)
    if nb is not None:
        nb.stop()
    for s in fabric_socks():
        if s.is_server_side:
            try:
                s._conn.shutdown(pysock.SHUT_RDWR)
            except OSError:
                pass
    kv.key_value_set("pc_killed", "1")
    # the kill itself moved no membership: OUR record (only this process
    # writes it) still claims serving with the join+advertise gen — the
    # crashed-process shape; the gen moves again only at revival
    time.sleep(1.0)
    assert pod.members(refresh=True)[pid].gen == 2, (
        "the kill must not move membership",
        pod.members(refresh=True)[pid].describe())
    kv.blocking_key_value_get("pc_drained", 180000)
    time.sleep(0.5)
    server2 = rpc.Server(); server2.add_service(Svc())
    assert server2.start("ici://%%d" %% MYDEV) == 0   # the revival
    kv.key_value_set("pc_revived", "1")
    live_server = server2
    kv.wait_at_barrier("pc_done", 300000)
else:
    # ---- pid 3: graceful lame-duck drain mid-traffic, then restart ----
    kv.blocking_key_value_get("pc_killed", 60000)
    time.sleep(1.0)              # surviving traffic rides the outage
    t0 = time.monotonic()
    server.stop(5.0)             # drain: GOODBYE + pod draining mark
    dt = time.monotonic() - t0
    assert dt < 4.0, ("drain should converge well before grace", dt)
    time.sleep(0.3)
    server_b = rpc.Server(); server_b.add_service(Svc())
    assert server_b.start("ici://%%d" %% MYDEV) == 0
    kv.key_value_set("pc_drained", "1")
    live_server = server_b
    kv.blocking_key_value_get("pc_revived", 180000)
    kv.wait_at_barrier("pc_done", 300000)

if pid not in (2, 3):
    live_server = server
    kv.wait_at_barrier("pc_done", 300000)

# ---- epoch convergence: every member computes the same final epoch ----
# join x4 (4) + advertise x4 (4) + drain mark (1) + drain withdraw (1)
# + restart advertise (1) + revival advertise (1) = 12
print("PHASE pre_epoch %%d" %% pid, flush=True)
FINAL = 2 * NPROC + 4
pod.wait_epoch(FINAL, timeout=60)
final_members = pod.members(refresh=True)
assert Pod.current() is pod, "pod singleton changed mid-scenario"
from brpc_tpu.ici.pod import epoch_of
assert epoch_of(final_members) == FINAL, (epoch_of(final_members), FINAL)
assert all(final_members[p].state == "up" for p in range(NPROC))
assert all(final_members[p].serving == [2 * p] for p in range(NPROC)), {
    p: final_members[p].serving for p in final_members}
kv.wait_at_barrier("pc_exit", 300000)
live_server.stop()
pod.leave()
print("PC%%d_OK" %% pid, flush=True)
"""


def run_chaos(n: int = 4, timeout: int = 300) -> None:
    """The kill/drain/revive chaos scenario, parameterized over pod
    size: pids 0..n-1 join; pid 2 is killed, pid 3 drains, every OTHER
    member keeps firing all-to-all traffic with zero visible failures;
    both transitioned members revive and the epoch converges to
    2n + 4 identically everywhere."""
    outs = _run_pod(_POD_CHAOS % {"repo": REPO}, n=n, timeout=timeout,
                    tag=f"pod_chaos_n{n}")
    for i in range(n):
        assert f"PC{i}_OK" in outs[i], outs[i][-2000:]


def test_pod_chaos_kill_and_drain_under_all_to_all_n4():
    """The acceptance contract: N=4 all-to-all traffic; one member's
    serving endpoint killed, another drained mid-traffic; zero
    client-visible failures on surviving pairs; the killed member
    revives under a new socket id and rejoins the pod epoch, which
    converges to the same value on every member."""
    run_chaos(n=4)


@pytest.mark.slow
def test_pod_chaos_kill_and_drain_under_all_to_all_n6():
    """ROADMAP item 2 follow-on: the same kill/drain/revive contract at
    N=6 — four surviving members (not two) carry the traffic while the
    same one kill + one drain land, proving the harness and the epoch
    algebra scale past the acceptance shape."""
    run_chaos(n=6, timeout=420)
