"""ns_filter, EOVERCROWDED, restful mappings, pooled/short connections."""
import json
import socket as pysocket
import time

import pytest

import brpc_tpu.policy
from brpc_tpu import rpc
from brpc_tpu.butil import flags as _flags
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import errors
from tests.echo_pb2 import EchoRequest, EchoResponse

_seq = [9000]


def unique(p):
    _seq[0] += 1
    return f"{p}-{_seq[0]}"


class EchoService(rpc.Service):
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


class TestNsFilter:
    def test_filter_excludes_tagged_servers(self, tmp_path):
        names = [unique("nsf") for _ in range(2)]
        servers = []
        for i, name in enumerate(names):
            s = rpc.Server()
            s.add_service(EchoService())
            assert s.start(f"mem://{name}") == 0
            servers.append(s)
        listing = tmp_path / "servers"
        listing.write_text(f"mem://{names[0]} 100 keep\n"
                           f"mem://{names[1]} 100 drop\n")
        opts = rpc.ChannelOptions(timeout_ms=1000)
        opts.ns_filter = lambda e: e.tag != "drop"
        ch = rpc.Channel()
        assert ch.init(f"file://{listing}", "rr", opts) == 0
        assert ch._lb.server_count() == 1
        for _ in range(5):
            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="f"), EchoResponse)
            assert not cntl.failed()
        for s in servers:
            s.stop()


class TestOvercrowded:
    def test_write_backlog_rejected(self):
        from brpc_tpu.rpc.mem_transport import new_mem_pair
        a, b = new_mem_pair()
        _flags.set_flag("socket_max_unwritten_bytes", 1024)
        try:
            # block the drain by failing the peer reference AFTER hooking:
            # simulate stuck transport by monkeypatching _do_write to EAGAIN
            a._do_write = lambda data: -1
            rc1 = a.write(IOBuf(b"x" * 800))
            rc2 = a.write(IOBuf(b"y" * 800))
            rc3 = a.write(IOBuf(b"z" * 800))
            assert rc1 == 0
            assert errors.EOVERCROWDED in (rc2, rc3)
        finally:
            _flags.set_flag("socket_max_unwritten_bytes", 64 * 1024 * 1024)
            a.set_failed()
            b.set_failed()


class TestRestful:
    def test_restful_mapping(self):
        opts = rpc.ServerOptions()
        opts.restful_mappings = {"/v1/echo": "EchoService.Echo"}
        server = rpc.Server(opts)
        server.add_service(EchoService())
        assert server.start("127.0.0.1:0") == 0
        try:
            import urllib.request
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.listen_port}/v1/echo",
                data=json.dumps({"message": "restful"}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as r:
                body = json.loads(r.read())
            assert body["message"] == "restful"
        finally:
            server.stop()


class TestConnectionTypes:
    @pytest.mark.parametrize("ctype", ["pooled", "short"])
    def test_connection_type_works(self, ctype):
        name = unique("conn")
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start(f"mem://{name}") == 0
        try:
            ch = rpc.Channel()
            ch.init(f"mem://{name}",
                    options=rpc.ChannelOptions(connection_type=ctype,
                                               timeout_ms=2000))
            for i in range(5):
                cntl = rpc.Controller()
                resp = ch.call_method("EchoService.Echo", cntl,
                                      EchoRequest(message=f"c{i}"),
                                      EchoResponse)
                assert not cntl.failed(), cntl.error_text
                assert resp.message == f"c{i}"
        finally:
            server.stop()

    def test_pooled_reuses_connections(self):
        name = unique("pool")
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start(f"mem://{name}") == 0
        try:
            ch = rpc.Channel()
            ch.init(f"mem://{name}",
                    options=rpc.ChannelOptions(connection_type="pooled",
                                               timeout_ms=2000))
            for _ in range(10):
                cntl = rpc.Controller()
                ch.call_method("EchoService.Echo", cntl,
                               EchoRequest(message="p"), EchoResponse)
            # sequential pooled calls reuse one connection
            from brpc_tpu.butil.endpoint import parse_endpoint
            from brpc_tpu.rpc.socket_map import SocketMap
            stats = SocketMap.instance().stats()
            ep = parse_endpoint(f"mem://{name}")
            assert stats.get(ep, 0) <= 2
        finally:
            server.stop()
