"""ns_filter, EOVERCROWDED, restful mappings, pooled/short connections."""
import json
import socket as pysocket
import time

import pytest

import brpc_tpu.policy
from brpc_tpu import rpc
from brpc_tpu.butil import flags as _flags
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import errors
from tests.echo_pb2 import EchoRequest, EchoResponse

_seq = [9000]


def unique(p):
    _seq[0] += 1
    return f"{p}-{_seq[0]}"


class EchoService(rpc.Service):
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


class TestNsFilter:
    def test_filter_excludes_tagged_servers(self, tmp_path):
        names = [unique("nsf") for _ in range(2)]
        servers = []
        for i, name in enumerate(names):
            s = rpc.Server()
            s.add_service(EchoService())
            assert s.start(f"mem://{name}") == 0
            servers.append(s)
        listing = tmp_path / "servers"
        listing.write_text(f"mem://{names[0]} 100 keep\n"
                           f"mem://{names[1]} 100 drop\n")
        opts = rpc.ChannelOptions(timeout_ms=1000)
        opts.ns_filter = lambda e: e.tag != "drop"
        ch = rpc.Channel()
        assert ch.init(f"file://{listing}", "rr", opts) == 0
        assert ch._lb.server_count() == 1
        for _ in range(5):
            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="f"), EchoResponse)
            assert not cntl.failed()
        for s in servers:
            s.stop()


class TestOvercrowded:
    def test_write_backlog_rejected(self):
        from brpc_tpu.rpc.mem_transport import new_mem_pair
        a, b = new_mem_pair()
        _flags.set_flag("socket_max_unwritten_bytes", 1024)
        try:
            # block the drain by failing the peer reference AFTER hooking:
            # simulate stuck transport by monkeypatching _do_write to EAGAIN
            a._do_write = lambda data: -1
            rc1 = a.write(IOBuf(b"x" * 800))
            rc2 = a.write(IOBuf(b"y" * 800))
            rc3 = a.write(IOBuf(b"z" * 800))
            assert rc1 == 0
            assert errors.EOVERCROWDED in (rc2, rc3)
        finally:
            _flags.set_flag("socket_max_unwritten_bytes", 64 * 1024 * 1024)
            a.set_failed()
            b.set_failed()


class TestRestful:
    def test_restful_mapping(self):
        opts = rpc.ServerOptions()
        opts.restful_mappings = {"/v1/echo": "EchoService.Echo"}
        server = rpc.Server(opts)
        server.add_service(EchoService())
        assert server.start("127.0.0.1:0") == 0
        try:
            import urllib.request
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.listen_port}/v1/echo",
                data=json.dumps({"message": "restful"}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as r:
                body = json.loads(r.read())
            assert body["message"] == "restful"
        finally:
            server.stop()


class TestConnectionTypes:
    @pytest.mark.parametrize("ctype", ["pooled", "short"])
    def test_connection_type_works(self, ctype):
        name = unique("conn")
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start(f"mem://{name}") == 0
        try:
            ch = rpc.Channel()
            ch.init(f"mem://{name}",
                    options=rpc.ChannelOptions(connection_type=ctype,
                                               timeout_ms=2000))
            for i in range(5):
                cntl = rpc.Controller()
                resp = ch.call_method("EchoService.Echo", cntl,
                                      EchoRequest(message=f"c{i}"),
                                      EchoResponse)
                assert not cntl.failed(), cntl.error_text
                assert resp.message == f"c{i}"
        finally:
            server.stop()

    def test_pooled_reuses_connections(self):
        name = unique("pool")
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start(f"mem://{name}") == 0
        try:
            ch = rpc.Channel()
            ch.init(f"mem://{name}",
                    options=rpc.ChannelOptions(connection_type="pooled",
                                               timeout_ms=2000))
            for _ in range(10):
                cntl = rpc.Controller()
                ch.call_method("EchoService.Echo", cntl,
                               EchoRequest(message="p"), EchoResponse)
            # sequential pooled calls reuse one connection
            from brpc_tpu.butil.endpoint import parse_endpoint
            from brpc_tpu.rpc.socket_map import SocketMap
            stats = SocketMap.instance().stats()
            ep = parse_endpoint(f"mem://{name}")
            assert stats.get(ep, 0) <= 2
        finally:
            server.stop()


class TestServerOptionsLifecycle:
    """idle_timeout_s / internal_port / server_info_name (server.h parity:
    these options must DO something, not just exist)."""

    def test_idle_timeout_reaps_stale_connections(self):
        import time
        from tests.echo_pb2 import EchoRequest, EchoResponse

        class Echo(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                response.message = request.message
                done()

        opts = rpc.ServerOptions()
        opts.idle_timeout_s = 1
        server = rpc.Server(opts)
        server.add_service(Echo())
        assert server.start("127.0.0.1:0") == 0
        try:
            ch = rpc.Channel()
            ch.init(f"127.0.0.1:{server.listen_port}",
                    options=rpc.ChannelOptions(timeout_ms=5000))
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="a"), EchoResponse)
            assert not cntl.failed() and resp.message == "a"
            assert len(server.connections()) == 1
            deadline = time.monotonic() + 6
            while server.connections() and time.monotonic() < deadline:
                time.sleep(0.2)
            assert not server.connections(), "idle connection not reaped"
            # a fresh call reconnects and succeeds
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="b"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "b"
        finally:
            server.stop()

    def test_internal_port_separates_admin_pages(self):
        import json
        import urllib.request
        from tests.echo_pb2 import EchoRequest, EchoResponse

        class Echo(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                response.message = "ok"
                done()

        opts = rpc.ServerOptions()
        opts.internal_port = 0          # ephemeral
        opts.server_info_name = "unit-fixture"
        server = rpc.Server(opts)
        server.add_service(Echo())
        assert server.start("127.0.0.1:0") == 0
        try:
            pub, adm = server.listen_port, server.internal_port
            assert adm > 0 and adm != pub
            # admin page on the internal port, with the display name
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{adm}/status", timeout=10).read()
            assert json.loads(body)["name"] == "unit-fixture"
            # admin page REFUSED on the public port
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{pub}/status", timeout=10)
                assert False, "public port served an admin page"
            except urllib.error.HTTPError as e:
                assert e.code == 403
            # user method REFUSED on the internal port
            req = urllib.request.Request(
                f"http://127.0.0.1:{adm}/EchoService/Echo",
                data=b'{"message":"x"}',
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=10)
                assert False, "internal port served a user method"
            except urllib.error.HTTPError as e:
                assert e.code == 403
            # user method SERVED on the public port
            req = urllib.request.Request(
                f"http://127.0.0.1:{pub}/EchoService/Echo",
                data=b'{"message":"x"}',
                headers={"Content-Type": "application/json"})
            body = urllib.request.urlopen(req, timeout=10).read()
            assert json.loads(body)["message"] == "ok"
        finally:
            server.stop()

    def test_internal_port_refuses_non_http_protocols(self):
        """The admin/service separation must hold for EVERY protocol: a
        tpu_std client speaking to the internal port is refused at the
        dispatch point, not served."""
        from tests.echo_pb2 import EchoRequest, EchoResponse

        class Echo(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                response.message = "leak!"
                done()

        opts = rpc.ServerOptions()
        opts.internal_port = 0
        server = rpc.Server(opts)
        server.add_service(Echo())
        assert server.start("127.0.0.1:0") == 0
        try:
            ch = rpc.Channel()
            ch.init(f"127.0.0.1:{server.internal_port}",
                    options=rpc.ChannelOptions(timeout_ms=3000,
                                               max_retry=0))
            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert cntl.failed(), "tpu_std served on the internal port"
        finally:
            server.stop()

    def test_connect_timeout_ms_reaches_tcp_connect(self, monkeypatch):
        """ChannelOptions.connect_timeout_ms must flow into the TCP
        connect (it was declared but hardcoded to 5s)."""
        from brpc_tpu.rpc import socket_map as smod
        from brpc_tpu.rpc import tcp_transport as tmod
        from tests.echo_pb2 import EchoRequest, EchoResponse

        class Echo(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                response.message = "ok"
                done()

        server = rpc.Server()
        server.add_service(Echo())
        assert server.start("127.0.0.1:0") == 0
        seen = {}
        real = tmod.tcp_connect

        def spy(ep, timeout=5.0, ssl_context=None):
            seen["timeout"] = timeout
            return real(ep, timeout=timeout, ssl_context=ssl_context)

        monkeypatch.setattr(tmod, "tcp_connect", spy)
        try:
            ch = rpc.Channel()
            ch.init(f"127.0.0.1:{server.listen_port}",
                    options=rpc.ChannelOptions(timeout_ms=5000,
                                               connect_timeout_ms=1234))
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="x"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "ok"
            assert abs(seen["timeout"] - 1.234) < 1e-9
        finally:
            server.stop()

    def test_internal_port_with_mem_listener_stays_loopback(self):
        """internal_port on a non-TCP main listener must neither crash
        (mem:// host is not a network name) nor bind 0.0.0.0."""
        opts = rpc.ServerOptions()
        opts.internal_port = 0
        server = rpc.Server(opts)
        assert server.start("mem://internal-port-probe") == 0
        try:
            import json
            import urllib.request
            adm = server.internal_port
            assert adm > 0
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{adm}/health", timeout=10).read()
            assert body
        finally:
            server.stop()

    def test_server_restart_keeps_idle_reaper_alive(self):
        import time
        from tests.echo_pb2 import EchoRequest, EchoResponse

        class Echo(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                response.message = "ok"
                done()

        opts = rpc.ServerOptions()
        opts.idle_timeout_s = 1
        server = rpc.Server(opts)
        server.add_service(Echo())
        assert server.start("127.0.0.1:0") == 0
        server.stop()
        # second run: the stopped-event must have been cleared, or the
        # reaper exits instantly and idle conns are never collected
        assert server.start("127.0.0.1:0") == 0
        try:
            assert server.is_running()
            ch = rpc.Channel()
            ch.init(f"127.0.0.1:{server.listen_port}",
                    options=rpc.ChannelOptions(timeout_ms=5000))
            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            deadline = time.monotonic() + 6
            while server.connections() and time.monotonic() < deadline:
                time.sleep(0.2)
            assert not server.connections(), \
                "reaper dead after server restart"
        finally:
            server.stop()
