"""Admission control (ISSUE 9): priority/deadline-aware shed-before-queue
with per-tenant weighted fair queueing — rpc/admission.py plus its
integration on all three call planes (tpu_std wire, mem:// loopback,
native-ici), the client-side retry_after_ms honoring, and the
shed-exclusion bugfix in MethodStatus.

The deterministic mini-overload test (TestMiniOverload, `overload`
marker) drives the whole shed logic with a SIMULATED clock and an
injectable service rate, so tier-1 exercises it without the full
`bench.py --sub overload` adversary.
"""
from __future__ import annotations

import threading
import time

import pytest

import brpc_tpu.policy  # noqa: F401 — registers protocols
from brpc_tpu import rpc
from brpc_tpu.ici import IciMesh
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.admission import (AdmissionController, AdmissionOptions,
                                    SHED_DEADLINE_TEXT,
                                    SHED_QUEUE_TIMEOUT_TEXT,
                                    server_method_gate)
from brpc_tpu.rpc.method_status import MethodStatus

from echo_pb2 import EchoRequest, EchoResponse


# ---------------------------------------------------------------------
# controller-level units (simulated clock, fake gate)
# ---------------------------------------------------------------------

class _Gate:
    """A fake concurrency gate with explicit capacity."""

    def __init__(self, slots: int):
        self.slots = slots
        self.lock = threading.Lock()

    def try_enter(self) -> bool:
        with self.lock:
            if self.slots > 0:
                self.slots -= 1
                return True
            return False

    def release(self) -> None:
        with self.lock:
            self.slots += 1


def _mk_controller(gate, clock, *, dispatch_log=None, **opt_kw):
    opts = AdmissionOptions(use_timers=False, **opt_kw)
    runs = dispatch_log if dispatch_log is not None else []
    return AdmissionController(
        None, opts, now_us=lambda: clock[0],
        dispatch=lambda run, waited_us: (runs.append(waited_us),
                                         run(waited_us)))


def _submit(adm, gate, order, tag, pri, tenant, clock, deadline_ms=5000):
    adm.submit(priority=pri, tenant=tenant, deadline_left_ms=deadline_ms,
               recv_us=clock[0], try_enter=gate.try_enter,
               run=lambda w, t=tag: order.append(t),
               shed=lambda c, txt, ra, t=tag: order.append(
                   ("SHED", t, c, ra, txt)))


class TestAdmissionQueueUnits:
    def test_strict_priority_and_drr_fairness(self):
        clock = [1_000_000]
        gate = _Gate(0)
        adm = _mk_controller(gate, clock, service_rate_override=100.0,
                             queue_capacity=64,
                             tenant_weights={"a": 3, "b": 1},
                             queueable_priority_max=1)
        order = []
        for i in range(6):
            for t in ("a", "b"):
                _submit(adm, gate, order, f"{t}{i}", 0, t, clock)
        for i in range(2):
            _submit(adm, gate, order, f"p1-{i}", 1, "a", clock)
        assert adm.queued() == 14
        gate.slots = 100
        n = adm.pump()
        assert n == 14
        # strict priority: every band-0 entry before any band-1 entry
        assert order.index("p1-0") > max(order.index(f"a{i}")
                                         for i in range(6))
        # DRR 3:1 — among the first 4 served, tenant a gets 3
        a_first4 = sum(1 for x in order[:4]
                       if isinstance(x, str) and x.startswith("a"))
        assert a_first4 == 3, order[:4]

    def test_shed_before_queue_for_sheddable_band(self):
        clock = [1_000_000]
        gate = _Gate(0)
        adm = _mk_controller(gate, clock, service_rate_override=100.0)
        order = []
        _submit(adm, gate, order, "low", 3, "t", clock)
        assert order and order[0][0] == "SHED"
        _, _, code, retry_after, _ = order[0]
        assert code == errors.ELIMIT and retry_after > 0
        assert adm.queued() == 0          # never queued: shed BEFORE queue

    def test_fair_share_shed(self):
        clock = [1_000_000]
        gate = _Gate(0)
        adm = _mk_controller(gate, clock, service_rate_override=100.0,
                             queue_capacity=8,
                             tenant_weights={"a": 3, "b": 1})
        order = []
        # alone, a tenant may use the whole queue; once a competes,
        # b's share is capacity * 1/(3+1) = 2
        _submit(adm, gate, order, "a0", 0, "a", clock)
        for i in range(3):
            _submit(adm, gate, order, f"b{i}", 0, "b", clock)
        sheds = [x for x in order if isinstance(x, tuple)]
        assert len(sheds) == 1 and sheds[0][1] == "b2"
        assert "fair share" in sheds[0][4]
        assert adm.queued() == 3

    def test_deadline_expired_shed_before_any_work(self):
        clock = [10_000_000]
        gate = _Gate(10)                  # capacity available — deadline
        adm = _mk_controller(gate, clock)  # check still rejects first
        order = []
        adm.submit(priority=0, tenant="t", deadline_left_ms=100,
                   recv_us=clock[0] - 200_000,   # 200ms ago
                   try_enter=gate.try_enter,
                   run=lambda w: order.append("RAN"),
                   shed=lambda c, txt, ra: order.append((c, txt, ra)))
        assert order == [(errors.ERPCTIMEDOUT, SHED_DEADLINE_TEXT, 0)]
        assert gate.slots == 10           # no gate entered, no work done

    def test_queue_timeout_shed_with_retry_after(self):
        clock = [1_000_000]
        gate = _Gate(0)
        adm = _mk_controller(gate, clock, service_rate_override=50.0,
                             max_queue_ms=30.0)
        order = []
        _submit(adm, gate, order, "q", 0, "t", clock)
        assert adm.queued() == 1
        clock[0] += 31_000                # past the 30ms bound
        assert adm.expire_queued() == 1
        assert order and order[0][0] == "SHED"
        _, _, code, ra, txt = order[0]
        assert code == errors.ELIMIT and ra > 0
        assert txt == SHED_QUEUE_TIMEOUT_TEXT

    def test_retry_after_tracks_backlog_and_rate(self):
        clock = [1_000_000]
        gate = _Gate(0)
        adm = _mk_controller(gate, clock, service_rate_override=100.0,
                             queue_capacity=64)
        # empty queue: backlog 1 @ 100 rps -> 10ms
        assert adm.retry_after_ms() == 10
        order = []
        for i in range(9):
            _submit(adm, gate, order, f"q{i}", 0, "t", clock)
        # backlog 10 @ 100 rps -> 100ms
        assert adm.retry_after_ms() == 100
        adm.fail_all(errors.ELOGOFF, "cleanup")

    def test_service_rate_ema_from_release_events(self):
        clock = [1_000_000]
        gate = _Gate(0)
        adm = _mk_controller(gate, clock)
        # releases every 10ms -> ~100 rps observed
        for _ in range(20):
            clock[0] += 10_000
            adm.on_release()
        assert 80.0 <= adm.service_rate() <= 120.0

    def test_fail_all_bounces_queued_and_refuses_later(self):
        clock = [1_000_000]
        gate = _Gate(0)
        adm = _mk_controller(gate, clock, service_rate_override=100.0)
        order = []
        _submit(adm, gate, order, "q0", 0, "t", clock)
        n = adm.fail_all(errors.ELOGOFF, "server stopping")
        assert n == 1
        assert order[0][0] == "SHED" and order[0][2] == errors.ELOGOFF
        # later enqueues bounce with the stop reason
        _submit(adm, gate, order, "q1", 0, "t", clock)
        assert order[1][0] == "SHED" and order[1][2] == errors.ELOGOFF
        # reset lifts the refusal
        adm.reset()
        gate.slots = 1
        _submit(adm, gate, order, "q2", 0, "t", clock)
        assert order[2] == "q2"

    def test_queue_bound_capped_by_residual_deadline(self):
        """Review fix: the queue stay is bounded by what's LEFT of the
        propagated deadline (deadline_left_ms minus time already burned
        since receive), not the raw deadline_left_ms — a request that
        spent 45 of its 50ms in the dispatch backlog may queue at most
        ~5ms more."""
        clock = [10_000_000]
        gate = _Gate(0)
        adm = _mk_controller(gate, clock, service_rate_override=100.0,
                             max_queue_ms=50.0)
        order = []
        adm.submit(priority=0, tenant="t", deadline_left_ms=50,
                   recv_us=clock[0] - 45_000,     # 45ms already burned
                   try_enter=gate.try_enter,
                   run=lambda w: order.append("RAN"),
                   shed=lambda c, txt, ra: order.append((c, txt)))
        assert adm.queued() == 1
        clock[0] += 6_000                          # 6ms later: residual
        assert adm.expire_queued() == 1            # (5ms) elapsed
        assert order == [(errors.ELIMIT, SHED_QUEUE_TIMEOUT_TEXT)]

    def test_method_gate_rollback_does_not_pump_or_poison_rate(self):
        """Review fix: a method-gate refusal after the server gate
        passed must roll back via on_request_rollback — NOT
        on_request_out, whose admission release-pump would recurse
        (pump → gate → rollback → pump) and whose phantom 'releases'
        would inflate the service-rate EMA."""
        calls = {"out": 0, "rollback": 0}

        class _SpyServer:
            def on_request_in(self):
                return True

            def on_request_out(self):
                calls["out"] += 1

            def on_request_rollback(self):
                calls["rollback"] += 1

        class _RefusingStatus:
            def on_requested(self):
                return False

        gate = server_method_gate(_SpyServer(), _RefusingStatus())
        assert gate() is False
        assert calls == {"out": 0, "rollback": 1}

    def test_method_limited_server_release_does_not_recurse(self):
        """End-to-end shape of the rollback recursion: a method-level
        limiter keeps refusing while the admission queue holds many
        entries; a completing request's release pump must terminate
        (restore-at-head) instead of recursing once per queued entry."""
        gate_evt = threading.Event()
        entered = []

        class Echo(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                if request.message == "block":
                    entered.append(1)
                    gate_evt.wait(10)
                response.message = "ok"
                done()

        opts = rpc.ServerOptions()
        opts.method_max_concurrency = {"Echo.Echo": 1}
        opts.admission = AdmissionOptions(max_queue_ms=3000.0,
                                          service_rate_override=50.0)
        server = rpc.Server(opts)
        server.add_service(Echo())
        assert server.start("mem://adm-mlimit") == 0
        ch = rpc.Channel()
        ch.init("mem://adm-mlimit",
                options=rpc.ChannelOptions(timeout_ms=4000, max_retry=0))
        threads = []
        try:
            threads = _saturate(ch, entered, n=1)
            results = []
            lock = threading.Lock()

            def hp(i):
                c = rpc.Controller()
                c.priority = 0
                r = ch.call_method("Echo.Echo", c,
                                   EchoRequest(message=f"q{i}"),
                                   EchoResponse)
                with lock:
                    results.append(c.error_code_)
            qthreads = [threading.Thread(target=hp, args=(i,))
                        for i in range(8)]
            for t in qthreads:
                t.start()
            deadline = time.monotonic() + 3
            while server.admission.queued() < 8 \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert server.admission.queued() == 8
            gate_evt.set()
            for t in qthreads:
                t.join(10)
            # every queued request completed, one at a time, without a
            # RecursionError blowing up the release path
            assert results == [0] * 8, results
            # the rate EMA reflects real completions, not the phantom
            # rollback releases (which would read in the tens of
            # thousands of rps)
            assert server.admission.service_rate() == 50.0
        finally:
            gate_evt.set()
            for t in threads:
                t.join(5)
            ch.close()
            server.stop()

    def test_tenant_counter_cardinality_is_capped(self):
        """Review fix: the per-tenant counters are fed by untrusted wire
        input — distinct non-configured tenants beyond the cap fold
        into '~other' instead of registering unbounded bvar Adders."""
        clock = [1_000_000]
        gate = _Gate(1_000_000)
        adm = _mk_controller(gate, clock)
        for i in range(AdmissionController.MAX_TRACKED_TENANTS + 40):
            adm.submit(priority=0, tenant=f"uuid-{i}",
                       deadline_left_ms=None, recv_us=clock[0],
                       try_enter=gate.try_enter,
                       run=lambda w: None,
                       shed=lambda c, t, r: None)
        assert len(adm._tenant_labels) == \
            AdmissionController.MAX_TRACKED_TENANTS
        per = adm.describe()["by_tenant_band"]
        assert per.get("admitted[~other][b0]") == 40

    def test_gate_refusal_restores_entry_at_queue_head(self):
        clock = [1_000_000]
        gate = _Gate(0)
        adm = _mk_controller(gate, clock, service_rate_override=100.0)
        order = []
        _submit(adm, gate, order, "first", 0, "t", clock)
        _submit(adm, gate, order, "second", 0, "t", clock)
        assert adm.pump() == 0            # gate still closed: nothing ran
        assert adm.queued() == 2          # both restored, none lost
        gate.slots = 2
        adm.pump()
        assert order == ["first", "second"]   # FIFO preserved


# ---------------------------------------------------------------------
# satellite bugfix: shed responses must not poison the limiter
# ---------------------------------------------------------------------

class _SpyLimiter:
    def __init__(self):
        self.samples = []

    def on_requested(self, conc):
        return True

    def on_responded(self, code, latency_us):
        self.samples.append((code, latency_us))

    def max_concurrency(self):
        return 1 << 30


class TestShedExclusionFromLimiter:
    def test_shed_codes_skip_limiter_and_error_count(self):
        lim = _SpyLimiter()
        ms = MethodStatus("Svc.M", limiter=lim)
        assert ms.on_requested()
        ms.on_responded(errors.ELIMIT, 5000)
        assert ms.on_requested()
        ms.on_responded(errors.ELOGOFF, 5000)
        # shed traffic: no limiter samples, no error_count — only shed
        assert lim.samples == []
        assert ms.error_count.get_value() == 0
        assert ms.shed_count.get_value() == 2
        # real outcomes still feed both
        assert ms.on_requested()
        ms.on_responded(0, 1000)
        assert ms.on_requested()
        ms.on_responded(errors.EINTERNAL, 1000)
        assert lim.samples == [(0, 1000), (errors.EINTERNAL, 1000)]
        assert ms.error_count.get_value() == 1
        assert ms.concurrency == 0

    def test_wire_gate_reject_does_not_skew_method_status(self):
        """Regression pin: a server-max_concurrency ELIMIT used to call
        status.on_responded WITHOUT a matching on_requested — method
        concurrency went negative and the limiter ate a failure sample
        (the learned-floor poisoning of ISSUE 9's bugfix satellite)."""
        gate = threading.Event()
        entered = threading.Event()

        class Echo(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                entered.set()
                gate.wait(5)
                response.message = "ok"
                done()

        opts = rpc.ServerOptions()
        opts.max_concurrency = 1          # NO admission layer: gate path
        server = rpc.Server(opts)
        server.add_service(Echo())
        assert server.start(0) == 0       # tcp: the wire plane
        status = server.method_status("Echo.Echo")
        spy = _SpyLimiter()
        status.limiter = spy
        ch = rpc.Channel()
        ch.init(f"127.0.0.1:{server.listen_port}",
                options=rpc.ChannelOptions(timeout_ms=3000, max_retry=0))
        try:
            blocked = []
            t = threading.Thread(
                target=lambda: blocked.append(ch.call_method(
                    "Echo.Echo", rpc.Controller(),
                    EchoRequest(message="b"), EchoResponse)))
            t.start()
            assert entered.wait(3)
            cntl = rpc.Controller()
            ch.call_method("Echo.Echo", cntl, EchoRequest(message="x"),
                           EchoResponse)
            assert cntl.error_code_ == errors.ELIMIT
            gate.set()
            t.join(5)
            deadline = time.monotonic() + 3
            while status.concurrency != 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            # the reject left NO trace: concurrency balanced (not -1),
            # no error counted, no limiter sample for the shed
            assert status.concurrency == 0
            assert status.error_count.get_value() == 0
            assert all(code == 0 for code, _ in spy.samples), spy.samples
        finally:
            ch.close()
            server.stop()


# ---------------------------------------------------------------------
# plane-level shed semantics (wire / loopback / native-ici)
# ---------------------------------------------------------------------

def _overloadable_server(addr, *, rate=50.0, queue_ms=2000.0):
    gate = threading.Event()
    entered = []

    class Echo(rpc.Service):
        @rpc.method(EchoRequest, EchoResponse)
        def Echo(self, cntl, request, response, done):
            if request.message == "block":
                entered.append(1)
                gate.wait(10)
            response.message = f"{cntl.priority}/{cntl.tenant}"
            done()

    opts = rpc.ServerOptions()
    opts.max_concurrency = 2
    opts.admission = AdmissionOptions(max_queue_ms=queue_ms,
                                      service_rate_override=rate)
    server = rpc.Server(opts)
    server.add_service(Echo())
    assert server.start(addr) == 0
    return server, gate, entered


def _saturate(ch, entered, n=2):
    """Fill the server's 2 slots with blocking calls on real threads."""
    threads = []
    for _ in range(n):
        def blocker():
            c = rpc.Controller()
            c.priority = 0
            ch.call_method("Echo.Echo", c, EchoRequest(message="block"),
                           EchoResponse)
        t = threading.Thread(target=blocker)
        t.start()
        threads.append(t)
    deadline = time.monotonic() + 5
    while len(entered) < n and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(entered) == n, "server slots did not fill"
    return threads


@pytest.fixture
def mesh():
    import jax
    m = IciMesh(jax.devices())
    IciMesh.set_default(m)
    return m


class TestPlaneShedSemantics:
    """The same three assertions on every call plane: a sheddable-band
    request sheds immediately with retryable ELIMIT + nonzero
    retry_after_ms; a high-priority request queues and completes when a
    slot frees; priority/tenant propagate to the handler's controller."""

    def _drive(self, server, gate, entered, target, copts=None):
        ch = rpc.Channel()
        ch.init(target, options=copts or rpc.ChannelOptions(
            timeout_ms=4000, max_retry=0))
        threads = []
        try:
            threads = _saturate(ch, entered)
            # sheddable band: immediate ELIMIT + retry hint
            c = rpc.Controller()
            c.priority = 3
            c.tenant = "bulk"
            r = ch.call_method("Echo.Echo", c,
                               EchoRequest(message="x"), EchoResponse)
            assert r is None and c.error_code_ == errors.ELIMIT
            assert c.retry_after_ms > 0
            assert "shed" in c.error_text_
            # high priority queues, admitted on release, sees metadata
            res = {}

            def hp():
                c2 = rpc.Controller()
                c2.priority = 0
                c2.tenant = "svc"
                r2 = ch.call_method("Echo.Echo", c2,
                                    EchoRequest(message="hi"),
                                    EchoResponse)
                res["code"] = c2.error_code_
                res["msg"] = r2.message if r2 else c2.error_text_
            t = threading.Thread(target=hp)
            t.start()
            deadline = time.monotonic() + 3
            while server.admission.queued() != 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert server.admission.queued() == 1
            gate.set()
            t.join(5)
            assert res == {"code": 0, "msg": "0/svc"}
            d = server.admission.describe()
            assert d["by_tenant_band"].get("shed_band[bulk][b3]") == 1
            assert d["by_tenant_band"].get("admitted[svc][b0]") == 1
        finally:
            gate.set()
            for t in threads:
                t.join(5)
            ch.close()

    def test_wire_plane(self):
        server, gate, entered = _overloadable_server(0)
        try:
            self._drive(server, gate, entered,
                        f"127.0.0.1:{server.listen_port}")
        finally:
            server.stop()

    def test_loopback_plane(self):
        server, gate, entered = _overloadable_server("mem://adm-loopback")
        try:
            self._drive(server, gate, entered, "mem://adm-loopback")
            # loopback really engaged: no wire connections were opened
            assert server.connections() == []
        finally:
            server.stop()

    def test_native_ici_plane(self, mesh):
        from brpc_tpu.ici import native_plane
        if not native_plane.available():
            pytest.skip("native plane unavailable")
        server, gate, entered = _overloadable_server("ici://71")
        try:
            assert native_plane.has_listener(71)
            self._drive(server, gate, entered, "ici://71")
        finally:
            server.stop()

    def test_draining_bounces_queued_entries_with_elogoff(self):
        server, gate, entered = _overloadable_server("mem://adm-drain")
        ch = rpc.Channel()
        ch.init("mem://adm-drain",
                options=rpc.ChannelOptions(timeout_ms=4000, max_retry=0))
        threads = []
        try:
            threads = _saturate(ch, entered)
            res = {}

            def hp():
                c2 = rpc.Controller()
                c2.priority = 0
                ch.call_method("Echo.Echo", c2,
                               EchoRequest(message="hi"), EchoResponse)
                res["code"] = c2.error_code_
            t = threading.Thread(target=hp)
            t.start()
            deadline = time.monotonic() + 3
            while server.admission.queued() != 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert server.admission.queued() == 1
            # graceful stop: the queued-not-started entry bounces with
            # retryable ELOGOFF at drain start; the executing blockers
            # complete inside the grace window
            stopper = threading.Thread(target=lambda: server.stop(3.0))
            stopper.start()
            t.join(5)
            assert res["code"] == errors.ELOGOFF
            gate.set()
            stopper.join(10)
        finally:
            gate.set()
            for t in threads:
                t.join(5)
            ch.close()
            server.stop()


class TestDeadlineExpiredShedOnWire:
    def test_stale_request_shed_before_parse(self):
        """A wire request whose deadline budget was spent while it sat
        in the dispatch queue (stale recv stamp) is rejected before any
        work, with the distinct deadline-shed error text."""
        from brpc_tpu.policy import tpu_std
        from brpc_tpu.proto import rpc_meta_pb2 as meta_pb

        class Echo(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                response.message = "ran"
                done()

        opts = rpc.ServerOptions()
        opts.admission = AdmissionOptions()
        server = rpc.Server(opts)
        server.add_service(Echo())
        assert server.start("mem://adm-deadline") == 0
        try:
            meta = meta_pb.RpcMeta()
            meta.correlation_id = 7
            meta.request.service_name = "Echo"
            meta.request.method_name = "Echo"
            meta.request.deadline_left_ms = 50
            from brpc_tpu.butil.iobuf import IOBuf
            body = IOBuf()
            body.append(EchoRequest(message="x").SerializeToString())
            msg = tpu_std.StdMessage(meta, body)
            # the frame was cut 200ms ago — budget (50ms) long spent
            msg.recv_ns = time.monotonic_ns() - 200_000_000

            writes = []

            class _Sock:
                remote_side = None

                def write(self, frame, notify_cid=None):
                    writes.append(bytes(frame.to_bytes()))
                    return 0

            tpu_std.process_request(msg, _Sock(), server)
            deadline = time.monotonic() + 2
            while not writes and time.monotonic() < deadline:
                time.sleep(0.01)
            assert writes, "no response written"
            raw = writes[0]
            meta_size = int.from_bytes(raw[4:8], "big")
            rmeta = meta_pb.RpcMeta()
            rmeta.ParseFromString(raw[12:12 + meta_size])
            assert rmeta.response.error_code == errors.ERPCTIMEDOUT
            assert rmeta.response.error_text == SHED_DEADLINE_TEXT
        finally:
            server.stop()


# ---------------------------------------------------------------------
# client leg (satellite): honoring retry_after_ms
# ---------------------------------------------------------------------

class TestClientRetryAfter:
    def test_retry_waits_for_hint_then_succeeds(self):
        """A shed call must not re-dispatch before the server's hint
        (jitter only ABOVE it): the retry lands >= retry_after_ms after
        the shed, and succeeds once capacity freed."""
        # service_rate_override=10 -> retry_after = 1000*(0+1)/10 = 100ms
        server, gate, entered = _overloadable_server(0, rate=10.0)
        ch = rpc.Channel()
        ch.init(f"127.0.0.1:{server.listen_port}",
                options=rpc.ChannelOptions(timeout_ms=4000, max_retry=3))
        threads = []
        try:
            # warm the channel (connect + first-dispatch costs) BEFORE
            # saturating: the probe below must reach the still-full
            # server ahead of the free timer, and a cold first dispatch
            # under full-suite load can eat tens of ms (observed flake:
            # the probe arrived after the slots freed, was never shed,
            # and retried_count stayed 0)
            warm = rpc.Controller()
            ch.call_method("Echo.Echo", warm, EchoRequest(message="w"),
                           EchoResponse)
            assert not warm.failed(), warm.error_text
            threads = _saturate(ch, entered)
            # free the slots well BEFORE the 100ms hint elapses: any
            # early re-dispatch would succeed too soon
            t_free = threading.Timer(0.05, gate.set)
            t_free.start()
            c = rpc.Controller()
            c.priority = 3
            t0 = time.monotonic()
            r = ch.call_method("Echo.Echo", c, EchoRequest(message="x"),
                               EchoResponse)
            dt = time.monotonic() - t0
            assert c.error_code_ == 0 and r is not None
            assert c.retried_count >= 1
            # the hint was 100ms; jitter adds up to +25% — the success
            # can only have landed after the full hint
            assert dt >= 0.1, dt
            t_free.cancel()
        finally:
            gate.set()
            for t in threads:
                t.join(5)
            ch.close()
            server.stop()

    def test_retry_bounded_by_overall_deadline(self):
        """A hint longer than the remaining budget loses to
        ERPCTIMEDOUT — the deadline, not the hint, bounds the call."""
        # rate 0.5 rps -> hint = 2000ms (the cap), way past the deadline
        server, gate, entered = _overloadable_server(0, rate=0.5)
        ch = rpc.Channel()
        ch.init(f"127.0.0.1:{server.listen_port}",
                options=rpc.ChannelOptions(timeout_ms=300, max_retry=3))
        threads = []
        try:
            threads = _saturate(ch, entered)
            c = rpc.Controller()
            c.priority = 3
            t0 = time.monotonic()
            ch.call_method("Echo.Echo", c, EchoRequest(message="x"),
                           EchoResponse)
            dt = time.monotonic() - t0
            assert c.error_code_ == errors.ERPCTIMEDOUT
            assert dt < 1.5, dt          # not the 2s hint: the deadline
        finally:
            gate.set()
            for t in threads:
                t.join(5)
            ch.close()
            server.stop()

    def test_sheds_do_not_trip_the_client_circuit_breaker(self):
        """Review fix: an admission shed is an overloaded-but-HEALTHY
        endpoint — a burst of sheds must not isolate it via the client
        breaker (which would block the critical-band traffic the server
        is still serving)."""
        from brpc_tpu.rpc.circuit_breaker import BreakerRegistry
        server, gate, entered = _overloadable_server(0, rate=50.0)
        ch = rpc.Channel()
        ch.init(f"127.0.0.1:{server.listen_port}",
                options=rpc.ChannelOptions(timeout_ms=2000, max_retry=0))
        threads = []
        try:
            threads = _saturate(ch, entered)
            for _ in range(60):           # a shed burst well past any
                c = rpc.Controller()      # breaker error-rate window
                c.priority = 3
                ch.call_method("Echo.Echo", c,
                               EchoRequest(message="x"), EchoResponse)
                assert c.error_code_ == errors.ELIMIT
            breaker = BreakerRegistry.instance().breaker(
                ch._endpoint)
            assert not breaker.is_isolated()
            # the endpoint still serves: a high-priority call completes
            gate.set()
            for t in threads:
                t.join(5)
            threads = []
            c = rpc.Controller()
            c.priority = 0
            r = ch.call_method("Echo.Echo", c,
                               EchoRequest(message="after"), EchoResponse)
            assert c.error_code_ == 0 and r is not None
        finally:
            gate.set()
            for t in threads:
                t.join(5)
            ch.close()
            server.stop()

    def test_hedging_does_not_amplify_into_retry_storm(self):
        """backup-request hedging against a shedding server: the shed
        hint still gates every re-dispatch, so one logical call lands at
        most max_retry+1 tries on the server — never a storm."""
        server, gate, entered = _overloadable_server(0, rate=10.0)
        ch = rpc.Channel()
        ch.init(f"127.0.0.1:{server.listen_port}",
                options=rpc.ChannelOptions(timeout_ms=600, max_retry=2,
                                           backup_request_ms=20))
        threads = []
        try:
            threads = _saturate(ch, entered)
            shed_before = server.admission.shed_total.get_value()
            c = rpc.Controller()
            c.priority = 3
            ch.call_method("Echo.Echo", c, EchoRequest(message="x"),
                           EchoResponse)
            assert c.failed()
            # settle: any straggler re-issues land within the deadline
            time.sleep(0.3)
            shed_delta = server.admission.shed_total.get_value() \
                - shed_before
            # max_retry+1 tries (+1 tolerance for a stale straggler
            # issue) — a storm would be dozens within the 600ms window
            assert 1 <= shed_delta <= 4, shed_delta
        finally:
            gate.set()
            for t in threads:
                t.join(5)
            ch.close()
            server.stop()


# ---------------------------------------------------------------------
# the deterministic mini-overload (tier-1; simulated clock + rate)
# ---------------------------------------------------------------------

@pytest.mark.overload
class TestMiniOverload:
    """The shed logic under a simulated 10x overload, fully
    deterministic: a fake gate of capacity 2, a simulated clock, an
    injected 100 rps service rate, 4 tenants offering 3:1 low:high."""

    def test_shed_absorbs_excess_high_priority_survives(self):
        clock = [1_000_000]
        gate = _Gate(2)
        adm = _mk_controller(gate, clock, service_rate_override=100.0,
                             queue_capacity=16, max_queue_ms=20.0)
        tenants = [f"t{i}" for i in range(4)]
        outcomes = {"hi_ok": {t: 0 for t in tenants}, "lo_ok": 0,
                    "shed": 0, "hints": []}
        inflight = []

        def submit(pri, tenant):
            def shed(code, txt, ra):
                outcomes["shed"] += 1
                if code == errors.ELIMIT:
                    outcomes["hints"].append(ra)
                assert code in (errors.ELIMIT, errors.ERPCTIMEDOUT)
            adm.submit(priority=pri, tenant=tenant, deadline_left_ms=500,
                       recv_us=clock[0], try_enter=gate.try_enter,
                       run=(lambda w, p=pri, t=tenant:
                            inflight.append((p, t))),
                       shed=shed)

        def complete_one():
            if inflight:
                pri, t = inflight.pop(0)
                if pri == 0:
                    outcomes["hi_ok"][t] += 1
                else:
                    outcomes["lo_ok"] += 1
                gate.release()
                adm.on_release()

        # 40 ticks of 10ms: each tick offers 1 request per tenant
        # alternating 3 low : 1 high (10x the 2-slot capacity), and the
        # "server" completes at the injected service rate (1 per tick)
        for tick in range(40):
            clock[0] += 10_000
            for ti, t in enumerate(tenants):
                pri = 0 if (tick + ti) % 4 == 0 else 3
                submit(pri, t)
            complete_one()
            adm.expire_queued()
        for _ in range(30):               # drain the queue
            clock[0] += 10_000
            complete_one()
            adm.expire_queued()
        # the excess was absorbed by SHED, not by queueing: the queue
        # never exceeded its bound and ended empty
        assert adm.queued() == 0
        assert outcomes["shed"] > 80          # ~10x excess was shed
        # every ELIMIT shed carried a nonzero, rate-derived hint
        assert outcomes["hints"] and all(h > 0 for h in outcomes["hints"])
        # zero tenant starvation: every tenant's high-priority stream
        # got service
        assert all(n > 0 for n in outcomes["hi_ok"].values()), \
            outcomes["hi_ok"]
        # high-priority goodput dominates low (strict bands)
        assert sum(outcomes["hi_ok"].values()) > outcomes["lo_ok"]


# ---------------------------------------------------------------------
# observability: admission wait feeds the queue-stage decomposition
# ---------------------------------------------------------------------

class TestQueueStageDecomposition:
    def test_admission_wait_recorded_in_queue_stage(self):
        from brpc_tpu.butil import flags as _flags
        from brpc_tpu.policy import tpu_std
        server, gate, entered = _overloadable_server(0, rate=50.0)
        ch = rpc.Channel()
        ch.init(f"127.0.0.1:{server.listen_port}",
                options=rpc.ChannelOptions(timeout_ms=4000, max_retry=0))
        threads = []
        _flags.set_flag("tpu_std_stage_metrics", "on")
        try:
            before = tpu_std._stage_recorders["queue"].count()
            threads = _saturate(ch, entered)
            res = {}

            def hp():
                c2 = rpc.Controller()
                c2.priority = 0
                ch.call_method("Echo.Echo", c2,
                               EchoRequest(message="hi"), EchoResponse)
                res["code"] = c2.error_code_
            t = threading.Thread(target=hp)
            t.start()
            deadline = time.monotonic() + 3
            while server.admission.queued() != 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            time.sleep(0.05)              # accrue measurable queue wait
            gate.set()
            t.join(5)
            assert res["code"] == 0
            # the admitted-from-queue request contributed queue-stage
            # samples (arrival dispatch + admission wait)
            assert tpu_std._stage_recorders["queue"].count() > before
        finally:
            _flags.set_flag("tpu_std_stage_metrics", "sampled")
            gate.set()
            for t in threads:
                t.join(5)
            ch.close()
            server.stop()
