"""Graceful drain & zero-downtime restart: lame-duck mode end to end.

Reference: Server::Stop(closewait_ms)/Join + -graceful_quit_on_sigterm
(src/brpc/server.cpp, docs/cn/server.md "优雅退出").  Covered here:

  * stop(grace_s) flips the server to draining: /health reports it, new
    requests on still-open connections bounce with retryable ELOGOFF,
    in-flight handlers complete inside the grace window, and stop
    returns as soon as the drain converges (not at grace expiry).
  * GOODBYE pulls the endpoint from a peer's load balancers BEFORE the
    first health-check probe would have run (probe-counter assertion
    under an injected 30s first-probe delay).
  * mesh:// naming drops a draining member and re-lists it on restart.
  * The drain gate waits on posted device-plane transfers (pins release
    at completion), and a grace expiry fails stragglers so a pin is
    NEVER leaked.
  * Lifecycle hygiene: stop→start→stop cycles rebind the same port with
    no thread leak, the idle reaper is generation-bound (a fast
    stop→start cycle cannot leave two reapers), join() waits for
    in-flight handlers, and a drained+restarted endpoint is revived by
    the PR-2 health checker.
  * graceful_quit_on_sigterm drains registered servers on TERM
    (subprocess).
"""
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

import brpc_tpu.policy  # noqa: F401 — registers protocols
from brpc_tpu import ici, rpc
from brpc_tpu.butil import flags as _fl
from brpc_tpu.butil.endpoint import parse_endpoint
from brpc_tpu.rpc import errors, health_check, lameduck
from tests.echo_pb2 import EchoRequest, EchoResponse

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _echo_service(tag="srv", slow_messages=(), slow_s=0.0, finished=None):
    class Echo(rpc.Service):
        SERVICE_NAME = "EchoService"

        @rpc.method(EchoRequest, EchoResponse)
        def Echo(self, cntl, request, response, done):
            if request.message in slow_messages:
                time.sleep(slow_s)
                if finished is not None:
                    finished.set()
            response.message = f"{tag}:{request.message}"
            done()

    return Echo()


def _call(ch, msg, **cntl_attrs):
    cntl = rpc.Controller()
    for k, v in cntl_attrs.items():
        setattr(cntl, k, v)
    resp = ch.call_method("EchoService.Echo", cntl,
                          EchoRequest(message=msg), EchoResponse)
    return cntl, resp


class TestDrain:
    def test_drain_completes_inflight_and_rejects_new_with_elogoff(self):
        finished = threading.Event()
        server = rpc.Server()
        server.add_service(_echo_service(slow_messages=("slow",),
                                         slow_s=0.8, finished=finished))
        assert server.start("mem://drain-basic") == 0
        ch = rpc.Channel()
        ch.init("mem://drain-basic",
                options=rpc.ChannelOptions(timeout_ms=5000, max_retry=0))
        results = {}
        c1 = rpc.Controller()
        ch.call_method("EchoService.Echo", c1, EchoRequest(message="slow"),
                       EchoResponse,
                       done=lambda c: results.update(slow=(
                           c.error_code_,
                           getattr(c.response, "message", None))))
        time.sleep(0.1)

        stop_dt = {}

        def stopper():
            t0 = time.monotonic()
            server.stop(5.0)
            stop_dt["dt"] = time.monotonic() - t0

        t = threading.Thread(target=stopper)
        t.start()
        deadline = time.monotonic() + 2
        while not server.is_draining() and time.monotonic() < deadline:
            time.sleep(0.005)
        assert server.is_draining()
        # /health flips while draining — 503 + body, so both
        # status-code-keyed and body-reading checkers pull the endpoint
        assert server._builtin.dispatch("health", {}) == \
            (503, "text/plain", "draining")
        # new request on the still-open connection: retryable ELOGOFF
        c2, _ = _call(ch, "new")
        assert c2.error_code_ == errors.ELOGOFF, (c2.error_code_,
                                                  c2.error_text_)
        t.join(10)
        assert finished.is_set(), "in-flight handler must complete"
        time.sleep(0.2)
        assert results["slow"] == (0, "srv:slow"), results
        # stop returned when the drain converged, not at grace expiry
        assert stop_dt["dt"] < 3.0, stop_dt
        assert server._builtin.dispatch("health", {}) == \
            ("text/plain", "OK") or not server.is_running()

    def test_post_grace_straggler_fails_elogoff(self):
        finished = threading.Event()
        server = rpc.Server()
        server.add_service(_echo_service(slow_messages=("veryslow",),
                                         slow_s=2.0, finished=finished))
        assert server.start("mem://drain-straggler") == 0
        ch = rpc.Channel()
        ch.init("mem://drain-straggler",
                options=rpc.ChannelOptions(timeout_ms=8000, max_retry=0))
        results = {}
        done_evt = threading.Event()
        c1 = rpc.Controller()

        def adone(c):
            results["code"] = c.error_code_
            done_evt.set()

        ch.call_method("EchoService.Echo", c1,
                       EchoRequest(message="veryslow"), EchoResponse,
                       done=adone)
        time.sleep(0.1)
        t0 = time.monotonic()
        server.stop(0.3)
        dt = time.monotonic() - t0
        assert 0.25 <= dt < 1.5, dt
        assert done_evt.wait(5), "straggler call never completed"
        # the handler outlived the grace: its connection failed ELOGOFF
        assert results["code"] == errors.ELOGOFF, results
        server.join(5.0)
        assert finished.is_set()

    def test_health_returns_503_on_keepalive_connection_while_draining(self):
        """A status-code-keyed checker (k8s readiness, LB HTTP check)
        holding a keep-alive connection must see the drain as 503, not a
        200 with a body it never reads."""
        import socket as pysock
        finished = threading.Event()
        server = rpc.Server()
        server.add_service(_echo_service(slow_messages=("slow",),
                                         slow_s=0.6, finished=finished))
        assert server.start("tcp://127.0.0.1:0") == 0
        port = server.listen_port
        hc = pysock.create_connection(("127.0.0.1", port), timeout=5)
        try:
            hc.sendall(b"GET /health HTTP/1.1\r\nHost: x\r\n\r\n")
            resp = hc.recv(65536)
            assert b"200" in resp.split(b"\r\n")[0] and \
                resp.endswith(b"OK"), resp
            ch = rpc.Channel()
            ch.init(f"tcp://127.0.0.1:{port}",
                    options=rpc.ChannelOptions(timeout_ms=8000, max_retry=0))
            c = rpc.Controller()
            ch.call_method("EchoService.Echo", c,
                           EchoRequest(message="slow"), EchoResponse,
                           done=lambda _c: None)
            time.sleep(0.1)
            stopper = threading.Thread(target=lambda: server.stop(5.0))
            stopper.start()
            deadline = time.monotonic() + 2
            while not server.is_draining() and time.monotonic() < deadline:
                time.sleep(0.005)
            assert server.is_draining()
            hc.sendall(b"GET /health HTTP/1.1\r\nHost: x\r\n\r\n")
            resp = hc.recv(65536)
            assert b"503" in resp.split(b"\r\n")[0], resp
            assert resp.endswith(b"draining"), resp
            stopper.join(10)
            assert finished.is_set()
        finally:
            hc.close()
            server.stop()

    def test_http_json_rpc_rejected_with_elogoff_while_draining(self):
        from brpc_tpu.policy import http as http_mod
        server = rpc.Server()
        server.add_service(_echo_service())
        assert server.start("mem://drain-http") == 0
        server._draining = True          # flip without tearing down
        try:
            sent = []
            msg = http_mod.HttpMessage()
            msg.method = "POST"
            msg.path = "/EchoService/Echo"
            msg.body = b'{"message":"x"}'

            class Sock:
                internal_only = False
                remote_side = None

                def write(self, buf):
                    sent.append(buf.to_bytes())

            http_mod.process_request(msg, Sock(), server)
            assert sent and b"503" in sent[0].split(b"\r\n")[0]
            assert str(errors.ELOGOFF).encode() in sent[0]
        finally:
            server._draining = False
            server.stop()

    def test_drain_waits_for_usercode_pool_backlog(self):
        """A request QUEUED on the usercode_in_pthread backup pool (not
        yet admitted) must still hold the drain gate."""
        release = threading.Event()
        done_msgs = []

        class Echo(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                release.wait(5)
                done_msgs.append(request.message)
                response.message = "srv:" + request.message
                done()

        server = rpc.Server(rpc.ServerOptions(usercode_in_pthread=True,
                                              usercode_backup_threads=1))
        server.add_service(Echo())
        assert server.start("mem://drain-pool") == 0
        ch = rpc.Channel()
        ch.init("mem://drain-pool",
                options=rpc.ChannelOptions(timeout_ms=8000, max_retry=0))
        codes = []
        evts = [threading.Event() for _ in range(2)]
        for i, evt in enumerate(evts):
            c = rpc.Controller()
            ch.call_method(
                "EchoService.Echo", c, EchoRequest(message=f"m{i}"),
                EchoResponse,
                done=lambda c, e=evt: (codes.append(c.error_code_), e.set()))
        deadline = time.monotonic() + 2
        while server._usercode_queued < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert server._usercode_queued >= 2
        threading.Timer(0.3, release.set).start()
        server.stop(5.0)
        for evt in evts:
            assert evt.wait(5)
        # the STARTED request (m0, holding the single backup thread)
        # completes; the queued-not-yet-started one is answered with
        # retryable ELOGOFF — either way the drain gate held the stop
        # until both had their response, instead of failing the
        # connection under them
        assert sorted(codes) == [0, errors.ELOGOFF], codes
        assert done_msgs == ["m0"], done_msgs


class TestGoodbye:
    def test_goodbye_pulls_endpoint_before_first_probe(self):
        """GOODBYE removes the endpoint from a peer's LB while the first
        health-check probe is still 30 injected seconds away — the
        probe counter stays at zero."""
        mesh = ici.IciMesh()
        ici.IciMesh.set_default(mesh)
        old = _fl.get_flag("health_check_interval_s")
        _fl.set_flag("health_check_interval_s", 30.0)
        servers = []
        try:
            for dev, tag in ((4, "a"), (5, "b")):
                s = rpc.Server(rpc.ServerOptions(native_ici=False))
                s.add_service(_echo_service(tag=tag))
                assert s.start(f"ici://{dev}") == 0
                servers.append(s)
            ch = rpc.Channel()
            ch.init("list://ici://4,ici://5", "rr",
                    options=rpc.ChannelOptions(timeout_ms=5000, max_retry=2))
            got = set()
            for i in range(8):
                c, r = _call(ch, str(i))
                assert not c.failed(), (c.error_code_, c.error_text_)
                got.add(r.message.split(":")[0])
            assert got == {"a", "b"}, got

            servers[0].stop(1.0)
            ep4 = mesh.endpoint(4)
            deadline = time.monotonic() + 3
            while not lameduck.is_draining(ep4) \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            assert lameduck.is_draining(ep4), "GOODBYE never registered"
            task = health_check._tasks.get(ep4)
            assert task is not None, "drained peer must be under check"
            assert task.probe_count == 0, \
                "LB pull must beat the first probe (GOODBYE, not timeout)"
            for _ in range(50):
                assert ch._lb.select_server() != ep4
            # traffic continues, zero failures, all on the survivor
            for i in range(10):
                c, r = _call(ch, str(i))
                assert not c.failed(), (c.error_code_, c.error_text_)
                assert r.message.startswith("b:"), r.message
        finally:
            _fl.set_flag("health_check_interval_s", old)
            for ep in (mesh.endpoint(4), mesh.endpoint(5)):
                t = health_check._tasks.get(ep)
                if t is not None:
                    t.cancel()
                lameduck.clear_peer_draining(ep)
            for s in servers:
                s.stop()

    def test_drained_restart_revived_by_health_checker(self):
        """The PR-2 revival loop closes the lame-duck cycle: drain →
        GOODBYE → health check → restart → probe succeeds → endpoint
        re-admitted (peer-drain mark cleared)."""
        mesh = ici.IciMesh()
        ici.IciMesh.set_default(mesh)
        ep = mesh.endpoint(6)
        server = rpc.Server(rpc.ServerOptions(native_ici=False))
        server.add_service(_echo_service(tag="v1"))
        assert server.start("ici://6") == 0
        ch = rpc.Channel()
        ch.init("ici://6",
                options=rpc.ChannelOptions(timeout_ms=5000, max_retry=1))
        c, r = _call(ch, "one")
        assert not c.failed() and r.message == "v1:one"
        server.stop(0.5)
        deadline = time.monotonic() + 3
        while not lameduck.is_draining(ep) and time.monotonic() < deadline:
            time.sleep(0.005)
        assert lameduck.is_draining(ep)
        assert health_check.checking(ep)
        # restart on the same endpoint: the checker's probe revives it
        server2 = rpc.Server(rpc.ServerOptions(native_ici=False))
        server2.add_service(_echo_service(tag="v2"))
        assert server2.start("ici://6") == 0
        try:
            deadline = time.monotonic() + 10
            while health_check.checking(ep) and time.monotonic() < deadline:
                time.sleep(0.02)
            assert not health_check.checking(ep), "revival never fired"
            assert not lameduck.is_draining(ep), \
                "revival must clear the peer-drain mark"
            c, r = _call(ch, "two")
            assert not c.failed(), (c.error_code_, c.error_text_)
            assert r.message == "v2:two"
        finally:
            server2.stop()

    def test_mesh_naming_drops_draining_member(self):
        """mesh:// membership excludes a member WHILE it drains; once
        the stop completes, liveness is the health checker's concern
        again (and the GOODBYE peer mark keeps protecting clients), so
        topology-derived membership returns to the full mesh."""
        from brpc_tpu.policy.naming import MeshNamingService
        mesh = ici.IciMesh()
        ici.IciMesh.set_default(mesh)
        ns = MeshNamingService()
        ep3 = mesh.endpoint(3)
        assert ep3 in [e.endpoint for e in ns.get_servers()]
        finished = threading.Event()
        server = rpc.Server(rpc.ServerOptions(native_ici=False))
        server.add_service(_echo_service(slow_messages=("slow",),
                                         slow_s=0.6, finished=finished))
        assert server.start("ici://3") == 0
        ch = rpc.Channel()
        ch.init("ici://3",
                options=rpc.ChannelOptions(timeout_ms=5000, max_retry=0))
        c = rpc.Controller()
        ch.call_method("EchoService.Echo", c, EchoRequest(message="slow"),
                       EchoResponse, done=lambda _c: None)
        time.sleep(0.1)
        stopper = threading.Thread(target=lambda: server.stop(5.0))
        stopper.start()
        deadline = time.monotonic() + 2
        while not server.is_draining() and time.monotonic() < deadline:
            time.sleep(0.005)
        assert server.is_draining()
        assert ep3 not in [e.endpoint for e in ns.get_servers()], \
            "draining member must leave mesh:// membership"
        stopper.join(10)
        assert finished.is_set()
        # the GOODBYE peer mark outlives the stop: clients keep the dead
        # endpoint excluded until revival re-admits it
        assert lameduck.is_draining(ep3)
        assert ep3 not in [e.endpoint for e in ns.get_servers()]
        server2 = rpc.Server(rpc.ServerOptions(native_ici=False))
        server2.add_service(_echo_service())
        assert server2.start("ici://3") == 0
        try:
            deadline = time.monotonic() + 10
            while lameduck.is_draining(ep3) and time.monotonic() < deadline:
                time.sleep(0.02)
            assert not lameduck.is_draining(ep3), "revival never fired"
            assert ep3 in [e.endpoint for e in ns.get_servers()], \
                "restart must re-list the member"
        finally:
            server2.stop()
            hc = health_check._tasks.get(ep3)
            if hc is not None:
                hc.cancel()
            lameduck.clear_peer_draining(ep3)


class TestDevicePlaneDrainBarrier:
    @pytest.fixture(autouse=True)
    def _host_mesh(self):
        mesh = ici.IciMesh()
        ici.IciMesh.set_default(mesh)
        old = (_fl.get_flag("ici_device_plane_host_mesh"),
               _fl.get_flag("ici_device_plane_threshold"))
        _fl.set_flag("ici_device_plane_host_mesh", True)
        _fl.set_flag("ici_device_plane_threshold", 4096)
        yield mesh
        _fl.set_flag("ici_device_plane_host_mesh", old[0])
        _fl.set_flag("ici_device_plane_threshold", old[1])

    def _posted(self, mesh):
        import jax
        import jax.numpy as jnp
        from brpc_tpu.ici import device_plane as dp
        plane = dp.DevicePlane.instance()
        arr = jax.device_put(jnp.zeros(65536, jnp.uint8), mesh.device(0))
        jax.block_until_ready(arr)
        released = []
        t = plane.post_send(arr, 0, 1)
        t.add_source_release(lambda: released.append(1))
        return plane, t, released

    def test_drain_waits_for_posted_transfer(self, _host_mesh):
        from brpc_tpu.ici import device_plane as dp
        plane, t, released = self._posted(_host_mesh)
        assert plane.active_transfers() >= 1
        threading.Timer(0.4, lambda: plane.post_recv(t.uuid)).start()
        server = rpc.Server(rpc.ServerOptions(native_ici=False))
        server.add_service(_echo_service())
        assert server.start("mem://dplane-drain") == 0
        t0 = time.monotonic()
        server.stop(5.0)
        dt = time.monotonic() - t0
        assert 0.3 <= dt < 3.0, dt
        assert t.state == dp.COMPLETE
        assert released == [1], "source pin must release at completion"
        assert plane.active_transfers() == 0
        assert plane.pending_sends() == 0

    def test_grace_expiry_fails_unmatched_send_releasing_pin(self, _host_mesh):
        from brpc_tpu.ici import device_plane as dp
        plane, t, released = self._posted(_host_mesh)
        server = rpc.Server(rpc.ServerOptions(native_ici=False))
        server.add_service(_echo_service())
        assert server.start("mem://dplane-straggle") == 0
        server.stop(0.3)
        assert t.state == dp.FAILED, t.state
        assert released == [1], "a lame-duck stop must never leak a pin"
        assert plane.pending_sends() == 0


class TestLifecycleHygiene:
    def test_stop_start_cycles_rebind_port_no_thread_leak(self):
        def census():
            return {t for t in threading.enumerate() if t.is_alive()}

        server = rpc.Server(rpc.ServerOptions(idle_timeout_s=30))
        server.add_service(_echo_service())
        # warmup cycle WITH a call: spawns the process singletons (timer
        # thread, scheduler workers, the tcp event dispatcher) that a
        # naive census would misread as leaks
        assert server.start("tcp://127.0.0.1:0") == 0
        port = server.listen_port
        assert port > 0
        ch0 = rpc.Channel()
        ch0.init(f"tcp://127.0.0.1:{port}",
                 options=rpc.ChannelOptions(timeout_ms=5000, max_retry=0,
                                            connection_type="short"))
        c0, _ = _call(ch0, "warmup")
        assert not c0.failed(), (c0.error_code_, c0.error_text_)
        server.stop()
        server.join(2.0)
        time.sleep(0.2)
        before = census()
        for i in range(3):
            assert server.start(f"tcp://127.0.0.1:{port}") == 0, i
            assert server.listen_port == port
            ch = rpc.Channel()
            ch.init(f"tcp://127.0.0.1:{port}",
                    options=rpc.ChannelOptions(timeout_ms=5000, max_retry=0,
                                               connection_type="short"))
            c, r = _call(ch, f"cycle{i}")
            assert not c.failed(), (c.error_code_, c.error_text_)
            assert r.message == f"srv:cycle{i}"
            server.stop()
            server.join(2.0)
        deadline = time.monotonic() + 5
        while len(census() - before) > 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        leaked = [t.name for t in census() - before]
        assert not leaked, f"threads leaked across cycles: {leaked}"

    def test_idle_reaper_is_generation_bound(self):
        server = rpc.Server(rpc.ServerOptions(idle_timeout_s=5))
        server.add_service(_echo_service())
        assert server.start("mem://reaper-gen") == 0
        # fast stop -> start: the old reaper must observe ITS OWN stop
        # event (set) and exit even though a new run is already up
        server.stop()
        assert server.start("mem://reaper-gen") == 0
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline:
            reapers = [t for t in threading.enumerate()
                       if t.name == "idle_reaper" and t.is_alive()]
            if len(reapers) == 1:
                break
            time.sleep(0.02)
        assert len(reapers) == 1, f"{len(reapers)} reapers alive"
        server.stop()
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline:
            if not any(t.name == "idle_reaper" and t.is_alive()
                       for t in threading.enumerate()):
                break
            time.sleep(0.02)
        assert not any(t.name == "idle_reaper" and t.is_alive()
                       for t in threading.enumerate())

    def test_join_waits_for_inflight_handlers(self):
        finished = threading.Event()
        server = rpc.Server()
        server.add_service(_echo_service(slow_messages=("slow",),
                                         slow_s=0.6, finished=finished))
        assert server.start("mem://join-inflight") == 0
        ch = rpc.Channel()
        ch.init("mem://join-inflight",
                options=rpc.ChannelOptions(timeout_ms=5000, max_retry=0))
        c = rpc.Controller()
        ch.call_method("EchoService.Echo", c, EchoRequest(message="slow"),
                       EchoResponse, done=lambda _c: None)
        time.sleep(0.1)
        server.stop()        # immediate stop: handler still running
        server.join(5.0)
        assert finished.is_set(), \
            "join() must wait for in-flight handlers, not just the flag"
        assert server.inflight_requests() == 0

    def test_status_page_reports_lifecycle(self):
        import json as _json
        server = rpc.Server()
        server.add_service(_echo_service())
        assert server.start("mem://status-lifecycle") == 0
        body = _json.loads(server._builtin.dispatch("status", {})[1])
        assert body["state"] == "running"
        server._draining = True
        body = _json.loads(server._builtin.dispatch("status", {})[1])
        assert body["state"] == "draining"
        server._draining = False
        server.stop()
        body = _json.loads(server._builtin.dispatch("status", {})[1])
        assert body["state"] == "stopped"


_SIGTERM_CHILD = r"""
import os, sys, threading, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import brpc_tpu.policy
from brpc_tpu import rpc
from echo_pb2 import EchoRequest, EchoResponse

finished = []

class Echo(rpc.Service):
    SERVICE_NAME = "EchoService"
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        time.sleep(0.5)
        finished.append(request.message)
        response.message = "srv:" + request.message
        done()

server = rpc.Server(rpc.ServerOptions(graceful_shutdown_s=5.0,
                                      graceful_quit_on_sigterm=True))
server.add_service(Echo())
assert server.start("mem://gq-child") == 0
ch = rpc.Channel()
ch.init("mem://gq-child", options=rpc.ChannelOptions(timeout_ms=8000,
                                                     max_retry=0))
results = {}
evt = threading.Event()
c = rpc.Controller()
ch.call_method("EchoService.Echo", c, EchoRequest(message="inflight"),
               EchoResponse,
               done=lambda c: (results.update(code=c.error_code_), evt.set()))
time.sleep(0.1)
print("UP", flush=True)
server.join()                      # unblocks when the TERM drain finishes
assert evt.wait(5), "in-flight call never completed"
assert results["code"] == 0, results
assert finished == ["inflight"], finished
print("DRAINED", flush=True)
"""


class TestGracefulQuitOnSigterm:
    def test_sigterm_drains_inflight_then_process_exits_cleanly(self):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.Popen(
            [sys.executable, "-c", _SIGTERM_CHILD % {"repo": REPO}],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        try:
            line = proc.stdout.readline()
            deadline = time.monotonic() + 60
            while "UP" not in line and time.monotonic() < deadline and line:
                line = proc.stdout.readline()
            assert "UP" in line, line
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=60)
        except Exception:
            proc.kill()
            raise
        assert proc.returncode == 0, out
        assert "DRAINED" in out, out


class TestDrainUnderBatchedDelivery:
    def test_drain_counts_and_rejects_queued_requests_in_batches(self):
        """usercode_in_pthread accounting under the batched ici upcall
        ABI: requests delivered in a batch but queued-not-started on the
        backup pool must (a) be counted INDIVIDUALLY by the drain gate
        (batch contents, not batches) and (b) be answered retryable
        ELOGOFF once the lame-duck drain flips — while the one request
        already executing completes inside the grace window."""
        from brpc_tpu.ici import native_plane
        if not native_plane.available():
            pytest.skip("native core unavailable")
        gate = threading.Event()
        entered = threading.Event()

        class Blocky(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                if request.message == "block":
                    entered.set()
                    gate.wait(20)
                response.message = request.message
                done()

        opts = rpc.ServerOptions()
        opts.usercode_in_pthread = True
        opts.usercode_backup_threads = 1      # serializes: 1 running, rest queued
        server = rpc.Server(opts)
        server.add_service(Blocky())
        assert server.start("ici://9") == 0
        binding = server._native_ici
        assert binding is not None
        try:
            results = {}
            lock = threading.Lock()

            def caller(i, msg):
                ch = rpc.Channel()
                ch.init("ici://9",
                        options=rpc.ChannelOptions(timeout_ms=20000,
                                                   max_retry=0))
                cntl = rpc.Controller()
                ch.call_method("EchoService.Echo", cntl,
                               EchoRequest(message=msg), EchoResponse)
                with lock:
                    results[i] = (cntl.error_code_, cntl.error_text_)
                ch.close()

            ts = [threading.Thread(target=caller, args=(0, "block"))]
            ts[0].start()
            assert entered.wait(10), "blocking request never started"
            # these pile up behind the single busy pool worker: delivered
            # by the batch upcall, counted queued, not yet started
            for i in range(1, 4):
                ts.append(threading.Thread(target=caller, args=(i, f"q{i}")))
                ts[-1].start()
            deadline = time.monotonic() + 10
            while server.inflight_requests() < 4 and \
                    time.monotonic() < deadline:
                time.sleep(0.01)
            # the drain gate SEES every queued-not-started request: the
            # executing one plus the three parked in the batch/pool
            assert server.inflight_requests() >= 4, \
                server.inflight_requests()
            # the batched ABI delivered them (snapshot before stop()
            # tears the native listener down and zeroes the handle)
            upcalls, delivered, _max = binding.batch_stats()
            assert delivered >= 4, (upcalls, delivered)
            stopper = threading.Thread(target=lambda: server.stop(8.0))
            t0 = time.monotonic()
            stopper.start()
            time.sleep(0.4)
            gate.set()                       # in-flight request completes
            stopper.join(20)
            dt = time.monotonic() - t0
            assert not stopper.is_alive(), "stop() wedged"
            assert dt < 8.0, ("drain should converge before grace "
                              "expiry once the queue drains", dt)
            for t in ts:
                t.join(20)
            # the blocked-but-executing request completed successfully...
            assert results[0][0] == 0, results[0]
            # ...and every queued-not-started one was answered ELOGOFF
            # (retryable go-elsewhere), not dropped and not executed
            for i in range(1, 4):
                assert results[i][0] == errors.ELOGOFF, (i, results[i])
            assert server.inflight_requests() == 0
        finally:
            gate.set()
            server.stop()
