"""Legacy protocol family: nshead, nova_pbrpc, public_pbrpc, hulu_pbrpc,
sofa_pbrpc, esp — golden-buffer framing checks + in-process server round
trips (the reference covers these in test/brpc_*_protocol_unittest.cpp
with the same two patterns)."""
import struct
import threading

import pytest

import brpc_tpu.policy  # noqa: F401  (registers protocols)
from brpc_tpu import rpc
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import errors
from brpc_tpu.policy.nshead import (NSHEAD_MAGIC, HEAD_SIZE, NsheadHead,
                                    NsheadMessage, NsheadService)
from brpc_tpu.policy.nova import NovaServiceAdaptor
from brpc_tpu.policy.public_pbrpc import PublicPbrpcServiceAdaptor
from brpc_tpu.policy import legacy_pbrpc
from brpc_tpu.policy.esp import EspHead, EspMessage, EspService
from brpc_tpu.proto import legacy_meta_pb2 as legacy_pb
from tests.echo_pb2 import EchoRequest, EchoResponse

_seq = [0]


def unique_name(prefix):
    _seq[0] += 1
    return f"{prefix}-{_seq[0]}"


class EchoService(rpc.Service):
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()

    @rpc.method(EchoRequest, EchoResponse)
    def Fail(self, cntl, request, response, done):
        cntl.set_failed(errors.EINTERNAL, "deliberate failure")
        done()


def make_channel(target, protocol, **opts):
    ch = rpc.Channel()
    options = rpc.ChannelOptions(protocol=protocol, **opts)
    assert ch.init(target, options=options) == 0
    return ch


# ======================================================================
# nshead head codec + raw service
# ======================================================================

class TestNsheadCodec:
    def test_head_roundtrip(self):
        h = NsheadHead(id=7, version=3, log_id=99, provider=b"tester",
                       reserved=5, body_len=123)
        h2 = NsheadHead.unpack(h.pack())
        assert (h2.id, h2.version, h2.log_id, h2.provider, h2.magic_num,
                h2.reserved, h2.body_len) == (7, 3, 99, b"tester",
                                              NSHEAD_MAGIC, 5, 123)

    def test_golden_layout(self):
        # the magic must sit at offset 24, little-endian (nshead.h layout)
        raw = NsheadHead(body_len=4).pack()
        assert len(raw) == HEAD_SIZE == 36
        assert raw[24:28] == struct.pack("<I", 0xFB709394)
        assert raw[32:36] == struct.pack("<I", 4)


class UpperService(NsheadService):
    def process_nshead_request(self, server, cntl, request, response, done):
        response.body.append(request.body.to_bytes().upper())
        done()


class TestNsheadService:
    def test_raw_roundtrip_mem(self):
        server = rpc.Server()
        server.add_service(UpperService())
        target = f"mem://{unique_name('nshead')}"
        assert server.start(target) == 0
        try:
            ch = make_channel(target, "nshead")
            cntl = rpc.Controller()
            req = NsheadMessage()
            req.head.log_id = 42
            req.body.append(b"hello nshead")
            resp = ch.call_method("", cntl, req)
            assert not cntl.failed(), cntl.error_text
            assert resp.body.to_bytes() == b"HELLO NSHEAD"
            assert resp.head.log_id == 42
        finally:
            server.stop()

    def test_raw_roundtrip_tcp(self):
        server = rpc.Server()
        server.add_service(UpperService())
        assert server.start("127.0.0.1:0") == 0
        try:
            ch = make_channel(f"127.0.0.1:{server.listen_port}", "nshead")
            cntl = rpc.Controller()
            req = NsheadMessage()
            req.body.append(b"over tcp")
            resp = ch.call_method("", cntl, req)
            assert not cntl.failed(), cntl.error_text
            assert resp.body.to_bytes() == b"OVER TCP"
        finally:
            server.stop()

    def test_concurrent_pooled_calls(self):
        server = rpc.Server()
        server.add_service(UpperService())
        target = f"mem://{unique_name('nshead')}"
        assert server.start(target) == 0
        try:
            ch = make_channel(target, "nshead")
            results = {}

            def call(i):
                cntl = rpc.Controller()
                req = NsheadMessage()
                req.body.append(f"msg-{i}".encode())
                resp = ch.call_method("", cntl, req)
                results[i] = (cntl.failed(), resp.body.to_bytes())

            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for i in range(8):
                failed, body = results[i]
                assert not failed
                assert body == f"MSG-{i}".upper().encode()
        finally:
            server.stop()


# ======================================================================
# nova_pbrpc (nshead + method index in `reserved`)
# ======================================================================

class TestNova:
    @pytest.fixture()
    def nova_server(self):
        server = rpc.Server()
        server.add_service(EchoService())
        server.add_service(NovaServiceAdaptor("EchoService"))
        target = f"mem://{unique_name('nova')}"
        assert server.start(target) == 0
        yield target
        server.stop()

    def test_echo(self, nova_server):
        ch = make_channel(nova_server, "nova_pbrpc")
        cntl = rpc.Controller()
        cntl.method_index = 0          # name-sorted: Echo=0, Fail=1
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="nova!"), EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "nova!"

    def test_bad_index(self, nova_server):
        ch = make_channel(nova_server, "nova_pbrpc",
                          max_retry=0, timeout_ms=2000)
        cntl = rpc.Controller()
        cntl.method_index = 99
        ch.call_method("EchoService.Echo", cntl,
                       EchoRequest(message="x"), EchoResponse)
        # nova has no error channel on the wire: the pb body fails to
        # parse (empty response) — the call must not hang or crash
        assert cntl.response is None or not cntl.response.message


# ======================================================================
# public_pbrpc (nshead v1000 + PublicRequest envelope)
# ======================================================================

class TestPublicPbrpc:
    @pytest.fixture()
    def public_server(self):
        server = rpc.Server()
        server.add_service(EchoService())
        server.add_service(PublicPbrpcServiceAdaptor())
        target = f"mem://{unique_name('public')}"
        assert server.start(target) == 0
        yield target
        server.stop()

    def test_echo(self, public_server):
        ch = make_channel(public_server, "public_pbrpc")
        cntl = rpc.Controller()
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="public!"), EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "public!"

    def test_error_propagates(self, public_server):
        ch = make_channel(public_server, "public_pbrpc", max_retry=0)
        cntl = rpc.Controller()
        ch.call_method("EchoService.Fail", cntl,
                       EchoRequest(message="x"), EchoResponse)
        assert cntl.failed()
        assert cntl.error_code == errors.EINTERNAL
        assert "deliberate" in cntl.error_text

    def test_unknown_method_is_enomethod(self, public_server):
        # a typo'd method name must NOT silently dispatch to index 0
        ch = make_channel(public_server, "public_pbrpc", max_retry=0)
        cntl = rpc.Controller()
        ch.call_method("EchoService.Nope", cntl,
                       EchoRequest(message="x"), EchoResponse)
        assert cntl.failed()
        assert cntl.error_code == errors.ENOMETHOD

    def test_unknown_service_is_error(self, public_server):
        ch = make_channel(public_server, "public_pbrpc", max_retry=0)
        cntl = rpc.Controller()
        ch.call_method("NoSvc.Echo", cntl,
                       EchoRequest(message="x"), EchoResponse)
        assert cntl.failed()
        assert cntl.error_code == errors.ENOSERVICE

    def test_envelope_golden(self):
        # the whole nshead body is ONE PublicRequest message
        env = legacy_pb.PublicRequest()
        env.requestHead.log_id = 5
        body = env.requestBody.add()
        body.service = "S"
        body.method_id = 0
        body.id = 77
        env2 = legacy_pb.PublicRequest()
        env2.ParseFromString(env.SerializeToString())
        assert env2.requestBody[0].id == 77


# ======================================================================
# hulu_pbrpc
# ======================================================================

class TestHulu:
    @pytest.fixture()
    def hulu_server(self):
        server = rpc.Server()
        server.add_service(EchoService())
        target = f"mem://{unique_name('hulu')}"
        assert server.start(target) == 0
        yield target
        server.stop()

    def test_echo(self, hulu_server):
        ch = make_channel(hulu_server, "hulu_pbrpc")
        cntl = rpc.Controller()
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="hulu!"), EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "hulu!"

    def test_error_propagates(self, hulu_server):
        ch = make_channel(hulu_server, "hulu_pbrpc", max_retry=0)
        cntl = rpc.Controller()
        ch.call_method("EchoService.Fail", cntl,
                       EchoRequest(message="x"), EchoResponse)
        assert cntl.failed()
        assert cntl.error_code == errors.EINTERNAL

    def test_compress(self, hulu_server):
        from brpc_tpu.rpc.compress import COMPRESS_TYPE_GZIP
        ch = make_channel(hulu_server, "hulu_pbrpc")
        cntl = rpc.Controller()
        cntl.compress_type = COMPRESS_TYPE_GZIP
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="zipped " * 100),
                              EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "zipped " * 100

    def test_method_index_dispatch(self, hulu_server):
        # craft a frame addressing Echo positionally (index 0, name unset)
        meta = legacy_pb.HuluRequestMeta()
        meta.service_name = "EchoService"
        meta.method_index = 0
        meta.correlation_id = 1
        payload = IOBuf(EchoRequest(message="by-index").SerializeToString())
        frame = legacy_pbrpc._pack_hulu(meta, payload)
        raw = frame.to_bytes()
        assert raw[:4] == b"HULU"
        body_size = int.from_bytes(raw[4:8], "little")
        meta_size = int.from_bytes(raw[8:12], "little")
        assert body_size == len(raw) - 12
        assert meta_size == len(meta.SerializeToString())

    def test_parse_golden(self):
        meta = legacy_pb.HuluResponseMeta()
        meta.correlation_id = 9
        buf = legacy_pbrpc._pack_hulu(meta, IOBuf(b"PAYLOAD"))
        res = legacy_pbrpc.hulu_parse(buf, None, False, None)
        from brpc_tpu.rpc.protocol import ParseResultType
        assert res.type == ParseResultType.OK
        assert res.message.body.to_bytes() == b"PAYLOAD"

    def test_parse_incremental(self):
        meta = legacy_pb.HuluResponseMeta()
        meta.correlation_id = 9
        raw = legacy_pbrpc._pack_hulu(meta, IOBuf(b"xyz")).to_bytes()
        from brpc_tpu.rpc.protocol import ParseResultType
        buf = IOBuf(raw[:7])
        assert legacy_pbrpc.hulu_parse(buf, None, False, None).type == \
            ParseResultType.NOT_ENOUGH_DATA
        buf.append(raw[7:])
        assert legacy_pbrpc.hulu_parse(buf, None, False, None).type == \
            ParseResultType.OK

    def test_parse_rejects_foreign_magic(self):
        from brpc_tpu.rpc.protocol import ParseResultType
        buf = IOBuf(b"PRPCxxxxxxxxxxxxxxxx")
        assert legacy_pbrpc.hulu_parse(buf, None, False, None).type == \
            ParseResultType.TRY_OTHERS


# ======================================================================
# sofa_pbrpc
# ======================================================================

class TestSofa:
    @pytest.fixture()
    def sofa_server(self):
        server = rpc.Server()
        server.add_service(EchoService())
        target = f"mem://{unique_name('sofa')}"
        assert server.start(target) == 0
        yield target
        server.stop()

    def test_echo(self, sofa_server):
        ch = make_channel(sofa_server, "sofa_pbrpc")
        cntl = rpc.Controller()
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="sofa!"), EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "sofa!"

    def test_error_propagates(self, sofa_server):
        ch = make_channel(sofa_server, "sofa_pbrpc", max_retry=0)
        cntl = rpc.Controller()
        ch.call_method("EchoService.Fail", cntl,
                       EchoRequest(message="x"), EchoResponse)
        assert cntl.failed()
        assert cntl.error_code == errors.EINTERNAL

    def test_frame_golden(self):
        meta = legacy_pb.SofaRpcMeta()
        meta.type = legacy_pb.SofaRpcMeta.REQUEST
        meta.sequence_id = 3
        raw = legacy_pbrpc._pack_sofa(meta, IOBuf(b"BODY")).to_bytes()
        assert raw[:4] == b"SOFA"
        meta_size = int.from_bytes(raw[4:8], "little")
        body_size = int.from_bytes(raw[8:16], "little")
        total = int.from_bytes(raw[16:24], "little")
        assert body_size == 4
        assert total == meta_size + body_size
        assert raw[24 + meta_size:] == b"BODY"

    def test_parse_rejects_inconsistent_sizes(self):
        from brpc_tpu.rpc.protocol import ParseResultType
        raw = b"SOFA" + (1).to_bytes(4, "little") + \
            (2).to_bytes(8, "little") + (99).to_bytes(8, "little") + b"xxx"
        assert legacy_pbrpc.sofa_parse(IOBuf(raw), None, False, None).type \
            == ParseResultType.TRY_OTHERS

    def test_concurrent_single_connection(self, sofa_server):
        # sofa carries the correlation id on the wire → single connection
        # multiplexes concurrent calls
        ch = make_channel(sofa_server, "sofa_pbrpc")
        results = {}

        def call(i):
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message=f"c{i}"), EchoResponse)
            results[i] = (cntl.failed(), resp and resp.message)

        threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(8):
            assert results[i] == (False, f"c{i}")


# ======================================================================
# esp
# ======================================================================

class DoublerEspService(EspService):
    def process_esp_request(self, server, cntl, request, response, done):
        response.body.append(request.body.to_bytes() * 2)
        done()


class TestEsp:
    def test_head_golden(self):
        h = EspHead(from_addr=1, to_addr=2, msg=3, msg_id=4, body_len=5)
        raw = h.pack()
        assert len(raw) == 32
        h2 = EspHead.unpack(raw)
        assert (h2.from_addr, h2.to_addr, h2.msg, h2.msg_id, h2.body_len) \
            == (1, 2, 3, 4, 5)

    def test_roundtrip(self):
        server = rpc.Server()
        server.add_service(DoublerEspService())
        target = f"mem://{unique_name('esp')}"
        assert server.start(target) == 0
        try:
            ch = make_channel(target, "esp")
            cntl = rpc.Controller()
            req = EspMessage()
            req.head.msg = 17
            req.head.msg_id = 112233
            req.body.append(b"ab")
            resp = ch.call_method("", cntl, req)
            assert not cntl.failed(), cntl.error_text
            assert resp.body.to_bytes() == b"abab"
            assert resp.head.msg_id == 112233
            assert resp.head.msg == 17
        finally:
            server.stop()


# ======================================================================
# cross-cutting: protocol registry grew the family
# ======================================================================

def test_second_nshead_adaptor_rejected():
    server = rpc.Server()
    server.add_service(EchoService())
    assert server.add_service(NovaServiceAdaptor("EchoService")) == 0
    assert server.add_service(PublicPbrpcServiceAdaptor()) == errors.EINVAL


def test_explicit_single_rejected_for_cidless_protocol():
    ch = rpc.Channel()
    with pytest.raises(ValueError):
        ch.init("mem://x", options=rpc.ChannelOptions(
            protocol="nshead", connection_type="single"))


def test_registry_has_legacy_family():
    from brpc_tpu.rpc.protocol import find_protocol
    for name in ("nshead", "nova_pbrpc", "public_pbrpc", "hulu_pbrpc",
                 "sofa_pbrpc", "esp"):
        assert find_protocol(name) is not None, name
