"""Scheduler-layer tests (mirrors reference test/bthread_*_unittest.cpp)."""
import threading
import time

import pytest

from brpc_tpu import bthread
from brpc_tpu.bthread import bthread_id


class TestScheduler:
    def test_start_and_join(self):
        tid = bthread.start_background(lambda: 42)
        assert bthread.join(tid) in (42, None)   # None iff joined after reclaim

    def test_exception_propagates(self):
        def boom():
            raise ValueError("x")
        tid = bthread.start_background(boom)
        with pytest.raises(ValueError):
            time.sleep(0.05)  # let it run
            r = bthread.join(tid)
            if r is None:     # reclaimed before join observed it
                raise ValueError("x")

    def test_many_tasklets(self):
        counter = []
        lock = threading.Lock()
        done = bthread.CountdownEvent(100)

        def work(i):
            with lock:
                counter.append(i)
            done.signal()

        for i in range(100):
            bthread.start_background(work, i)
        assert done.wait(10) == 0
        assert sorted(counter) == list(range(100))

    def test_urgent_from_worker_runs_soon(self):
        order = []
        done = bthread.CountdownEvent(1)

        def outer():
            bthread.start_urgent(lambda: order.append("urgent"))
            order.append("outer-done")
            done.signal()

        bthread.start_background(outer)
        done.wait(5)
        time.sleep(0.2)
        assert "urgent" in order and "outer-done" in order

    def test_nested_spawn_and_join(self):
        results = []
        done = bthread.CountdownEvent(1)

        def child(x):
            return x * 2

        def parent():
            tids = [bthread.start_background(child, i) for i in range(10)]
            for t in tids:
                r = bthread.join(t)
                if r is not None:
                    results.append(r)
            done.signal()

        bthread.start_background(parent)
        assert done.wait(10) == 0

    def test_local_storage(self):
        seen = {}
        done = bthread.CountdownEvent(2)

        def task(name):
            bthread.local_set("session", name)
            time.sleep(0.01)
            seen[name] = bthread.local_get("session")
            done.signal()

        bthread.start_background(task, "a")
        bthread.start_background(task, "b")
        done.wait(5)
        assert seen == {"a": "a", "b": "b"}


class TestButex:
    def test_wait_wake(self):
        b = bthread.Butex(0)
        woke = []

        def waiter():
            rc = b.wait(0, timeout=5)
            woke.append(rc)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        b.set_value(1)
        b.wake_all()
        t.join(5)
        assert woke == [0]

    def test_wait_value_changed(self):
        b = bthread.Butex(7)
        assert b.wait(3) == bthread.EWOULDBLOCK

    def test_wait_timeout(self):
        b = bthread.Butex(0)
        t0 = time.monotonic()
        assert b.wait(0, timeout=0.05) == bthread.ETIMEDOUT
        assert time.monotonic() - t0 < 1.0

    def test_fetch_add_compare_exchange(self):
        b = bthread.Butex(5)
        assert b.fetch_add(3) == 5
        assert b.value == 8
        assert b.compare_exchange(8, 1)
        assert not b.compare_exchange(8, 2)


class TestBthreadId:
    def test_basic_lock_cycle(self):
        cid = bthread_id.create(data={"x": 1})
        rc, data = bthread_id.lock(cid)
        assert rc == 0 and data == {"x": 1}
        assert bthread_id.unlock(cid) == 0
        assert bthread_id.unlock_and_destroy(cid) == 0
        rc, _ = bthread_id.lock(cid)
        assert rc == bthread_id.EINVAL

    def test_stale_version_ignored(self):
        """The retry-race mechanism: after starting try 1, a response
        carrying try 0's version must fail to lock."""
        cid = bthread_id.create_ranged({"rpc": True}, None, version_range=4)
        v0 = bthread_id.with_version(cid, 0)
        v1 = bthread_id.with_version(cid, 1)
        rc, _ = bthread_id.lock(v0)
        assert rc == 0
        bthread_id.reset_version(cid, 1)     # retry #1 issued
        bthread_id.unlock(v0)
        rc, _ = bthread_id.lock(v0)          # late response of try 0
        assert rc == bthread_id.EINVAL
        rc, _ = bthread_id.lock(v1)
        assert rc == 0
        bthread_id.unlock_and_destroy(v1)

    def test_error_callback(self):
        events = []

        def on_error(data, cid, code):
            events.append((data, code))
            bthread_id.unlock_and_destroy(cid)

        cid = bthread_id.create("payload", on_error)
        assert bthread_id.error(cid, 1008) == 0
        assert events == [("payload", 1008)]
        assert bthread_id.error(cid, 1) == bthread_id.EINVAL  # destroyed

    def test_error_while_locked_queues(self):
        events = []

        def on_error(data, cid, code):
            events.append(code)
            bthread_id.unlock(cid)

        cid = bthread_id.create("d", on_error)
        rc, _ = bthread_id.lock(cid)
        assert rc == 0
        bthread_id.error(cid, 7)
        assert events == []                  # queued, not run
        bthread_id.unlock(cid)               # drains pending error
        assert events == [7]
        bthread_id.unlock_and_destroy(cid)

    def test_join_waits_for_destroy(self):
        cid = bthread_id.create()
        results = []

        def joiner():
            results.append(bthread_id.join(cid, timeout=5))

        t = threading.Thread(target=joiner)
        t.start()
        time.sleep(0.05)
        rc, _ = bthread_id.lock(cid)
        bthread_id.unlock_and_destroy(cid)
        t.join(5)
        assert results == [0]


class TestExecutionQueue:
    def test_serialized_in_order(self):
        out = []

        def handler(it):
            for task in it:
                out.append(task)

        q = bthread.execution_queue_start(handler)
        for i in range(50):
            q.execute(i)
        q.stop()
        assert q.join(5)
        assert out == list(range(50))

    def test_multi_producer(self):
        out = []

        def handler(it):
            for task in it:
                out.append(task)

        q = bthread.execution_queue_start(handler)

        def produce(base):
            for i in range(100):
                q.execute(base + i)

        ts = [threading.Thread(target=produce, args=(k * 1000,)) for k in range(4)]
        for t in ts: t.start()
        for t in ts: t.join()
        q.stop()
        assert q.join(5)
        assert len(out) == 400
        # per-producer order preserved (MPSC guarantees total order of submits)
        for k in range(4):
            sub = [x for x in out if k * 1000 <= x < k * 1000 + 1000]
            assert sub == sorted(sub)

    def test_execute_after_stop_fails(self):
        q = bthread.execution_queue_start(lambda it: [x for x in it])
        q.stop()
        assert q.execute(1) != 0


class TestTimerThread:
    def test_fires_in_order(self):
        fired = []
        done = bthread.CountdownEvent(2)
        tt = bthread.TimerThread.instance()
        tt.schedule_after(lambda: (fired.append("b"), done.signal()), 0.10)
        tt.schedule_after(lambda: (fired.append("a"), done.signal()), 0.02)
        assert done.wait(5) == 0
        assert fired == ["a", "b"]

    def test_unschedule_prevents(self):
        fired = []
        tt = bthread.TimerThread.instance()
        tid = tt.schedule_after(lambda: fired.append(1), 0.2)
        assert tt.unschedule(tid) == 0
        time.sleep(0.35)
        assert fired == []

    def test_unschedule_after_fire(self):
        done = bthread.CountdownEvent(1)
        tt = bthread.TimerThread.instance()
        tid = tt.schedule_after(lambda: done.signal(), 0.01)
        assert done.wait(5) == 0
        time.sleep(0.02)
        assert tt.unschedule(tid) == 1


class TestCountdown:
    def test_countdown(self):
        ev = bthread.CountdownEvent(3)
        for _ in range(3):
            assert ev.wait(0.01) == bthread.ETIMEDOUT or True
            ev.signal()
        assert ev.wait(1) == 0

    def test_timeout(self):
        ev = bthread.CountdownEvent(1)
        assert ev.wait(0.05) == bthread.ETIMEDOUT


class TestDeviceWaiter:
    def test_wait_on_computation(self):
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return (x @ x).sum()

        x = jnp.ones((128, 128))
        y = f(x)
        assert bthread.device_wait(y, timeout=30) == 0
        assert float(y) == 128 * 128 * 128

    def test_on_ready_callback_order(self):
        import jax.numpy as jnp
        order = []
        done = bthread.CountdownEvent(3)
        for i in range(3):
            arr = jnp.full((4,), i)
            bthread.device_on_ready(
                arr, lambda i=i: (order.append(i), done.signal()))
        assert done.wait(30) == 0
        assert order == [0, 1, 2]   # stream completion order is FIFO

    def test_wait_from_tasklet(self):
        import jax.numpy as jnp
        results = []
        done = bthread.CountdownEvent(1)

        def task():
            arr = jnp.arange(10) * 2
            rc = bthread.device_wait(arr, timeout=30)
            results.append((rc, int(arr.sum())))
            done.signal()

        bthread.start_background(task)
        assert done.wait(30) == 0
        assert results == [(0, 90)]
