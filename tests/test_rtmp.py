"""RTMP / AMF0 / FLV / MPEG-TS tests.

Reference test strategy: in-process loopback server + real client over
localhost TCP (SURVEY.md §4), plus codec golden-byte checks (the pattern
of brpc_http_rpc_protocol_unittest etc. for wire formats).
"""
import os
import struct
import threading
import time

import pytest

import brpc_tpu.policy  # noqa: F401  (registers protocols)
from brpc_tpu import rpc
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.policy import amf, flv, rtmp, ts
from brpc_tpu.policy.rtmp import (
    CSID_AUDIO, MSG_AUDIO, MSG_COMMAND_AMF0, MSG_SET_CHUNK_SIZE,
    RtmpClient, RtmpClientOptions, RtmpClientStream, RtmpConnection,
    RtmpServerStream, RtmpService)


def _wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


# ---------------------------------------------------------------- AMF0 --

class TestAmf0:
    def test_golden_number_string_null(self):
        # independently computed AMF0 bytes
        assert amf.encode(5.0) == b"\x00\x40\x14\x00\x00\x00\x00\x00\x00"
        assert amf.encode("abc") == b"\x02\x00\x03abc"
        assert amf.encode(None) == b"\x05"
        assert amf.encode(True) == b"\x01\x01"

    def test_golden_object(self):
        data = amf.encode({"app": "live"})
        assert data == (b"\x03" + b"\x00\x03app" + b"\x02\x00\x04live"
                        + b"\x00\x00\x09")

    def test_roundtrip_nested(self):
        value = {
            "num": 1.5, "flag": False, "s": "x" * 10, "none": None,
            "arr": [1.0, "two", None],
            "ecma": amf.EcmaArray({"k": "v"}),
            "obj": {"inner": 2.0},
            "long": "y" * 70000,
            "date": amf.AmfDate(123456.0, 60),
            "undef": amf.UNDEFINED,
        }
        out = amf.decode_all(amf.encode("cmd", 1.0, value))
        assert out[0] == "cmd" and out[1] == 1.0
        got = out[2]
        assert got["num"] == 1.5 and got["flag"] is False
        assert got["arr"] == [1.0, "two", None]
        assert isinstance(got["ecma"], amf.EcmaArray)
        assert got["ecma"]["k"] == "v"
        assert got["obj"]["inner"] == 2.0
        assert got["long"] == "y" * 70000
        assert got["date"] == amf.AmfDate(123456.0, 60)
        assert got["undef"] is amf.UNDEFINED

    def test_truncated_raises(self):
        good = amf.encode({"a": 1.0})
        with pytest.raises(amf.AmfError):
            amf.decode(good[:-1])


# ------------------------------------------------- chunk state machine --

class _FakeSocket:
    """Just enough Socket surface for RtmpConnection unit tests."""

    def __init__(self):
        self.sent = []
        self.failed = False
        self.on_failed_callbacks = []
        self.remote_side = None

    def write(self, data, **kw):
        self.sent.append(data.to_bytes())
        return 0


def _chunk(fmt, csid, ts, mlen, mtype, msid, payload):
    out = bytes([(fmt << 6) | csid])
    if fmt == 0:
        out += ts.to_bytes(3, "big") + mlen.to_bytes(3, "big") \
            + bytes([mtype]) + struct.pack("<I", msid)
    elif fmt == 1:
        out += ts.to_bytes(3, "big") + mlen.to_bytes(3, "big") \
            + bytes([mtype])
    elif fmt == 2:
        out += ts.to_bytes(3, "big")
    return out + payload


class TestChunkCodec:
    def _server_conn(self):
        sock = _FakeSocket()
        conn = RtmpConnection(sock, is_server=True)
        conn.state = 3  # _ESTABLISHED: skip handshake for codec tests
        got = []
        conn._dispatch = lambda m: got.append(m)
        return conn, got

    def test_fmt0_fmt2_delta_and_fmt3_repeat(self):
        conn, got = self._server_conn()
        from brpc_tpu.butil.iobuf import IOBuf
        buf = IOBuf()
        # fmt0 absolute ts=100, then fmt2 delta=10, then bare fmt3
        buf.append(_chunk(0, 6, 100, 4, MSG_AUDIO, 1, b"aaaa"))
        buf.append(_chunk(2, 6, 10, 0, 0, 0, b"bbbb"))
        buf.append(_chunk(3, 6, 0, 0, 0, 0, b"cccc"))
        assert conn.consume(buf)
        assert [m.timestamp for m in got] == [100, 110, 120]
        assert [m.body for m in got] == [b"aaaa", b"bbbb", b"cccc"]
        assert all(m.msid == 1 and m.type == MSG_AUDIO for m in got)

    def test_fragmented_message_reassembly(self):
        conn, got = self._server_conn()
        from brpc_tpu.butil.iobuf import IOBuf
        body = bytes(range(256)) * 2          # 512 bytes > 128 chunk size
        buf = IOBuf()
        buf.append(_chunk(0, 3, 7, len(body), MSG_AUDIO, 9, body[:128]))
        for i in range(1, 4):
            buf.append(_chunk(3, 3, 0, 0, 0, 0, body[128 * i:128 * (i + 1)]))
        # feed byte-by-byte boundaries: split across two consume calls
        raw = buf.to_bytes()
        b1, b2 = IOBuf(raw[:200]), IOBuf(raw[200:])
        assert conn.consume(b1) and len(got) == 0
        rest = IOBuf(b1.to_bytes() + b2.to_bytes())
        assert conn.consume(rest)
        assert len(got) == 1 and got[0].body == body and got[0].msid == 9

    def test_set_chunk_size_applies(self):
        conn, got = self._server_conn()
        real_dispatch = RtmpConnection._dispatch
        conn._dispatch = lambda m: (real_dispatch(conn, m),
                                    got.append(m))
        from brpc_tpu.butil.iobuf import IOBuf
        buf = IOBuf()
        buf.append(_chunk(0, 2, 0, 4, MSG_SET_CHUNK_SIZE, 0,
                          struct.pack(">I", 256)))
        body = b"z" * 256
        buf.append(_chunk(0, 6, 1, len(body), MSG_AUDIO, 1, body))
        assert conn.consume(buf)
        assert conn.in_chunk_size == 256
        assert got[-1].body == body

    def test_extended_timestamp_roundtrip(self):
        """Sender emits ext timestamps; a second connection reads them."""
        send_sock = _FakeSocket()
        sender = RtmpConnection(send_sock, is_server=False)
        big_ts = 0x1000000 + 5
        sender.send_message(6, 1, MSG_AUDIO, big_ts, b"x" * 300)
        recv, got = self._server_conn()
        from brpc_tpu.butil.iobuf import IOBuf
        buf = IOBuf()
        for frame in send_sock.sent:
            buf.append(frame)
        assert recv.consume(buf)
        assert len(got) == 1
        assert got[0].timestamp == big_ts and got[0].body == b"x" * 300

    def test_garbage_is_protocol_error(self):
        conn, _ = self._server_conn()
        from brpc_tpu.butil.iobuf import IOBuf
        # valid-looking header with fmt1 while no message is in progress
        # is tolerated, but a non-3 fmt inside a partial message is fatal
        buf = IOBuf()
        buf.append(_chunk(0, 3, 0, 300, MSG_COMMAND_AMF0, 1, b"q" * 128))
        buf.append(_chunk(0, 3, 0, 300, MSG_COMMAND_AMF0, 1, b"q" * 128))
        assert not conn.consume(buf)


# -------------------------------------------------- loopback end-to-end --

class _RecordingServerStream(RtmpServerStream):
    def __init__(self, hub):
        super().__init__()
        self.hub = hub

    def on_publish(self, name, publish_type="live"):
        if name == "forbidden":
            return 1
        self.hub.publishers[name] = self
        return 0

    def on_play(self, name):
        self.hub.players.setdefault(name, []).append(self)
        return 0

    def on_meta_data(self, meta, name="onMetaData"):
        self.hub.meta.append((name, meta))

    def on_audio_message(self, timestamp, data):
        self.hub.audio.append((timestamp, data))

    def on_video_message(self, timestamp, data):
        self.hub.video.append((timestamp, data))

    def on_stop(self):
        self.hub.stopped.append(self)


class _Hub(RtmpService):
    def __init__(self):
        self.publishers = {}
        self.players = {}
        self.meta = []
        self.audio = []
        self.video = []
        self.stopped = []
        self.connect_infos = []

    def new_stream(self, remote_side, connect_info):
        self.connect_infos.append(dict(connect_info))
        return _RecordingServerStream(self)


@pytest.fixture()
def rtmp_server():
    hub = _Hub()
    server = rpc.Server()
    assert server.add_service(hub) == 0
    assert server.start("127.0.0.1:0") == 0
    yield server, hub
    server.stop()


class TestRtmpEndToEnd:
    def test_connect_reports_app(self, rtmp_server):
        server, hub = rtmp_server
        client = RtmpClient(f"127.0.0.1:{server.listen_port}",
                            RtmpClientOptions(app="myapp"))
        try:
            stream = client.create_stream()
            assert stream.stream_id >= 1
            assert stream.publish("s") == 0
            # the connect command's object reached the server's stream
            # factory (rtmp.h RtmpService::NewStream gets connect info)
            assert _wait_for(lambda: hub.connect_infos)
            assert hub.connect_infos[0]["app"] == "myapp"
            assert hub.connect_infos[0]["tcUrl"].endswith("/myapp")
        finally:
            client.stop()

    def test_publish_meta_audio_video(self, rtmp_server):
        server, hub = rtmp_server
        client = RtmpClient(f"127.0.0.1:{server.listen_port}")
        try:
            stream = client.create_stream()
            assert stream.publish("cam0") == 0
            assert _wait_for(lambda: "cam0" in hub.publishers)
            assert hub.publishers["cam0"].publish_name == "cam0"
            stream.send_meta_data({"width": 640.0, "height": 480.0})
            stream.send_audio_message(b"\xaf\x01" + b"A" * 100,
                                      timestamp=10)
            # a video frame larger than both chunk sizes
            big = b"\x17\x01" + bytes(range(256)) * 300
            stream.send_video_message(big, timestamp=20)
            assert _wait_for(lambda: hub.video)
            assert hub.meta[0][1]["width"] == 640.0
            assert hub.audio[0] == (10, b"\xaf\x01" + b"A" * 100)
            assert hub.video[0] == (20, big)
        finally:
            client.stop()

    def test_publish_rejected(self, rtmp_server):
        server, hub = rtmp_server
        client = RtmpClient(f"127.0.0.1:{server.listen_port}")
        try:
            stream = client.create_stream()
            assert stream.publish("forbidden") != 0
        finally:
            client.stop()

    def test_play_receives_server_media(self, rtmp_server):
        server, hub = rtmp_server

        class Player(RtmpClientStream):
            def __init__(self):
                super().__init__()
                self.meta = []
                self.audio = []
                self.video = []

            def on_meta_data(self, meta, name="onMetaData"):
                self.meta.append(meta)

            def on_audio_message(self, timestamp, data):
                self.audio.append((timestamp, data))

            def on_video_message(self, timestamp, data):
                self.video.append((timestamp, data))

        client = RtmpClient(f"127.0.0.1:{server.listen_port}")
        try:
            player = Player()
            client.create_stream(player)
            assert player.play("feed") == 0
            assert _wait_for(lambda: hub.players.get("feed"))
            sstream = hub.players["feed"][0]
            sstream.send_meta_data({"fps": 30.0})
            sstream.send_audio_message(b"\xaf\x00cfg", timestamp=0)
            sstream.send_video_message(b"\x17\x00sps", timestamp=0)
            assert _wait_for(lambda: player.video)
            assert player.meta[0]["fps"] == 30.0
            assert player.audio[0] == (0, b"\xaf\x00cfg")
            assert player.video[0] == (0, b"\x17\x00sps")
        finally:
            client.stop()

    def test_delete_stream_stops_server_stream(self, rtmp_server):
        server, hub = rtmp_server
        client = RtmpClient(f"127.0.0.1:{server.listen_port}")
        try:
            stream = client.create_stream()
            assert stream.publish("tmp") == 0
            assert _wait_for(lambda: "tmp" in hub.publishers)
            stream.close()
            assert _wait_for(lambda: hub.stopped)
        finally:
            client.stop()

    def test_rpc_and_rtmp_share_port(self, rtmp_server):
        """Protocol detection: the same port serves tpu_std RPC and RTMP."""
        server, hub = rtmp_server
        from tests.echo_pb2 import EchoRequest, EchoResponse

        class EchoService(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                response.message = "hi:" + request.message
                done()

        # services cannot be added after start; run a second server with
        # both services to prove coexistence
        both = rpc.Server()
        assert both.add_service(_Hub()) == 0
        assert both.add_service(EchoService()) == 0
        assert both.start("127.0.0.1:0") == 0
        try:
            ch = rpc.Channel()
            ch.init(f"127.0.0.1:{both.listen_port}")
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="x"), EchoResponse)
            assert not cntl.failed() and resp.message == "hi:x"
            client = RtmpClient(f"127.0.0.1:{both.listen_port}")
            stream = client.create_stream()
            assert stream.publish("s") == 0
            client.stop()
        finally:
            both.stop()


# ------------------------------------------------------------------ FLV --

class TestFlv:
    def test_roundtrip(self):
        w = flv.FlvWriter()
        w.write_meta_data({"duration": 0.0})
        w.write_audio(0, b"\xaf\x00audiocfg")
        w.write_video(40, b"\x17\x00videocfg")
        w.write_video(0x1234567, b"frame")
        r = flv.FlvReader(w.buf.to_bytes())
        tags = list(r)
        assert [t[0] for t in tags] == [flv.FLV_TAG_SCRIPT_DATA,
                                        flv.FLV_TAG_AUDIO,
                                        flv.FLV_TAG_VIDEO,
                                        flv.FLV_TAG_VIDEO]
        name, meta = r.read_meta_data(tags[0][2])
        assert name == "onMetaData" and meta["duration"] == 0.0
        assert tags[1][1:] == (0, b"\xaf\x00audiocfg")
        assert tags[3][1] == 0x1234567 and tags[3][2] == b"frame"

    def test_incremental_feed(self):
        w = flv.FlvWriter()
        w.write_audio(1, b"a" * 1000)
        raw = w.buf.to_bytes()
        r = flv.FlvReader()
        r.feed(raw[:500])
        assert r.read_tag() is None
        r.feed(raw[500:])
        assert r.read_tag() == (flv.FLV_TAG_AUDIO, 1, b"a" * 1000)


# ------------------------------------------------------------------- TS --

class TestTsMuxer:
    def _packets(self, data):
        assert len(data) % ts.TS_PACKET_SIZE == 0
        return [data[i:i + ts.TS_PACKET_SIZE]
                for i in range(0, len(data), ts.TS_PACKET_SIZE)]

    def test_mux_structure(self):
        m = ts.TsMuxer()
        m.write_video(90000, b"\x00\x00\x00\x01\x65" + b"V" * 400)
        m.write_audio(90000, b"\xff\xf1" + b"A" * 100)
        pkts = self._packets(m.buf.to_bytes())
        assert all(p[0] == 0x47 for p in pkts)
        pids = [((p[1] & 0x1F) << 8) | p[2] for p in pkts]
        assert ts.PID_PAT in pids and ts.PID_PMT in pids
        assert ts.PID_VIDEO in pids and ts.PID_AUDIO in pids

    def test_psi_crc_valid(self):
        m = ts.TsMuxer()
        m.write_pat_pmt()
        pkts = self._packets(m.buf.to_bytes())
        for p in pkts:
            # pointer_field then section
            sec_off = 4 + 1 + p[4]
            length = ((p[sec_off + 1] & 0x0F) << 8) | p[sec_off + 2]
            section = p[sec_off:sec_off + 3 + length]
            assert ts.crc32_mpeg(section[:-4]) == \
                struct.unpack(">I", section[-4:])[0]

    def test_pes_start_and_continuity(self):
        m = ts.TsMuxer()
        for i in range(5):
            m.write_video(90000 * i,
                          b"\x00\x00\x00\x01\x65" + bytes(200) * (i + 1))
        pkts = self._packets(m.buf.to_bytes())
        ccs = []
        for p in pkts:
            pid = ((p[1] & 0x1F) << 8) | p[2]
            if pid != ts.PID_VIDEO:
                continue
            if p[1] & 0x40:               # PUSI: PES header must follow
                afc = (p[3] >> 4) & 0x3
                off = 4 + (1 + p[4] if afc & 0x2 else 0)
                assert p[off:off + 3] == b"\x00\x00\x01"
            ccs.append(p[3] & 0xF)
        for a, b in zip(ccs, ccs[1:]):
            assert b == (a + 1) & 0xF


class TestDigestHandshake:
    """The digest ("complex") handshake (rtmp_protocol.cpp's
    complex-handshake path): HMAC-SHA256 digests embedded in C1/S1 at
    scheme-derived offsets, proof-of-read S2/C2 keyed on the peer's
    digest, server-side auto-detection, and a recorded digest-mode C1
    fixture pinning the byte layout."""

    FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                           "rtmp_digest_c1.bin")

    def _c1_fixture(self):
        with open(self.FIXTURE, "rb") as f:
            c1 = f.read()
        assert len(c1) == rtmp.HANDSHAKE_SIZE
        return c1

    def test_recorded_c1_fixture_digest_validates(self):
        c1 = self._c1_fixture()
        digest = rtmp.find_handshake_digest(c1)
        assert digest is not None
        # pinned layout: scheme-0 offset field → digest at a known spot,
        # regenerating the HMAC over the joined remainder reproduces it
        off = rtmp._digest_offset(c1, 0)
        assert off == 365
        assert c1[off:off + 32] == digest
        assert digest == rtmp._hmac_sha256(
            rtmp._FP_KEY[:30], c1[:off] + c1[off + 32:])
        # a corrupted byte anywhere under the HMAC kills validation
        bad = bytearray(c1)
        bad[100] ^= 0xFF
        assert rtmp.find_handshake_digest(bytes(bad)) is None

    def test_server_answers_digest_c1_with_digest_s1_and_keyed_s2(self):
        sock = _FakeSocket()
        conn = rtmp.RtmpConnection(sock, is_server=True)
        c1 = self._c1_fixture()
        src = IOBuf(bytes([rtmp.RTMP_VERSION]) + c1)
        assert conn.consume(src)
        assert conn.state == rtmp._HS_WAIT_C2
        out = sock.sent[0]
        assert out[0] == rtmp.RTMP_VERSION
        s1 = out[1:1 + rtmp.HANDSHAKE_SIZE]
        s2 = out[1 + rtmp.HANDSHAKE_SIZE:]
        # S1 carries a VALID digest under the FMS key (not an echo)
        assert rtmp.find_handshake_digest(s1, rtmp._FMS_KEY[:36]) \
            is not None
        # S2 proves the server READ our C1 digest: HMAC keyed on it
        c1_digest = rtmp.find_handshake_digest(c1)
        assert rtmp.validate_handshake_response2(s2, c1_digest,
                                                 rtmp._FMS_KEY)
        # ...and is NOT keyed on anything else
        assert not rtmp.validate_handshake_response2(s2, b"\0" * 32,
                                                     rtmp._FMS_KEY)

    def test_server_still_answers_simple_c1_with_echo(self):
        sock = _FakeSocket()
        conn = rtmp.RtmpConnection(sock, is_server=True)
        c1 = struct.pack(">II", 7, 0) + bytes(rtmp.HANDSHAKE_SIZE - 8)
        assert conn.consume(IOBuf(bytes([rtmp.RTMP_VERSION]) + c1))
        out = sock.sent[0]
        assert out[1 + rtmp.HANDSHAKE_SIZE:] == c1    # S2 echoes C1

    def test_digest_client_against_digest_server_end_to_end(self):
        """Two RtmpConnections wired back to back complete the digest
        handshake: client validates S2, server's C2 arrives, both sides
        reach ESTABLISHED's handshake edge."""
        from brpc_tpu.butil import flags as fl
        csock, ssock = _FakeSocket(), _FakeSocket()
        saved = fl.get_flag("rtmp_client_digest")
        fl.set_flag("rtmp_client_digest", True)
        try:
            client = rtmp.RtmpConnection(csock, is_server=False)
            server = rtmp.RtmpConnection(ssock, is_server=True)
            client._on_client_established = lambda: None
            client._start_client_handshake()
            assert client._c1_digest is not None
            # server consumes C0+C1, emits S0S1S2
            assert server.consume(IOBuf(csock.sent[0]))
            # client consumes S0S1S2, emits digest-mode C2
            assert client.consume(IOBuf(b"".join(ssock.sent)))
            assert client.state == rtmp._ESTABLISHED
            c2 = csock.sent[1]
            s1 = ssock.sent[0][1:1 + rtmp.HANDSHAKE_SIZE]
            s1_digest = rtmp.find_handshake_digest(s1, rtmp._FMS_KEY[:36])
            assert rtmp.validate_handshake_response2(c2, s1_digest,
                                                     rtmp._FP_KEY)
            # server consumes C2 → established
            assert server.consume(IOBuf(c2))
            assert server.state == rtmp._ESTABLISHED
        finally:
            fl.set_flag("rtmp_client_digest", saved)

    def test_corrupt_s2_is_a_protocol_error_for_digest_client(self):
        from brpc_tpu.butil import flags as fl
        csock = _FakeSocket()
        saved = fl.get_flag("rtmp_client_digest")
        fl.set_flag("rtmp_client_digest", True)
        try:
            client = rtmp.RtmpConnection(csock, is_server=False)
            client._start_client_handshake()
            c1_digest = client._c1_digest
            s1 = rtmp.make_digest_block(rtmp._S1_VERSION,
                                        rtmp._FMS_KEY[:36])
            s2 = bytearray(rtmp.make_handshake_response2(
                c1_digest, rtmp._FMS_KEY))
            s2[-1] ^= 0xFF                          # break the proof
            ok = client.consume(IOBuf(bytes([rtmp.RTMP_VERSION]) + s1
                                      + bytes(s2)))
            assert ok is False                      # protocol error
        finally:
            fl.set_flag("rtmp_client_digest", saved)
