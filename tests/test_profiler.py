"""Profiler tests (hotspots + contention, reference §5.1 machinery)."""
import threading
import time

from brpc_tpu.rpc import profiler


class TestCpuProfile:
    def test_profile_call(self):
        def busy():
            return sum(i * i for i in range(50000))

        result, report = profiler.profile_call(busy)
        assert result == sum(i * i for i in range(50000))
        assert "cumulative" in report

    def test_profile_for(self):
        report = profiler.profile_for(0.05, top=5)
        assert "function calls" in report


class TestContention:
    def test_contended_lock_sampled(self):
        profiler.enable_contention_profiler(True)
        try:
            m = profiler.ContentionMutex()

            def holder():
                with m:
                    time.sleep(0.15)

            t = threading.Thread(target=holder)
            t.start()
            time.sleep(0.02)
            with m:          # will wait ~130ms → sampled
                pass
            t.join()
            rows = profiler.contention_profile()
            assert rows
            total_wait = sum(r[2] for r in rows)
            assert total_wait > 0.05
        finally:
            profiler.enable_contention_profiler(False)

    def test_uncontended_not_sampled(self):
        profiler.enable_contention_profiler(True)
        try:
            m = profiler.ContentionMutex()
            for _ in range(100):
                with m:
                    pass
            assert profiler.contention_profile() == []
        finally:
            profiler.enable_contention_profiler(False)


class TestBuiltinPages:
    def test_contention_page(self):
        import brpc_tpu.policy
        from brpc_tpu import rpc
        server = rpc.Server()
        from brpc_tpu.rpc.builtin import register_builtin_services
        register_builtin_services(server)
        ctype, body = server._builtin.dispatch("contention", {"enable": "1"})
        assert "enabled" in body
        ctype, body = server._builtin.dispatch("contention", {})
        assert "total_wait_s" in body
        server._builtin.dispatch("contention", {"enable": "0"})
