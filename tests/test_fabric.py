"""Multi-controller ici://: 2-process echo over the fabric (VERDICT #4).

The reference tests distributed behavior with multiple in-process servers
on localhost TCP (SURVEY.md §4); the multi-CONTROLLER equivalent needs real
process isolation — each child owns its slice of the global device list,
jax.distributed is the out-of-band handshake channel, and device payloads
cross process boundaries through the transfer server (the RDMA-READ pull
model of src/brpc/rdma/rdma_endpoint.cpp translated to XLA).
"""
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import os, sys, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

pid = int(sys.argv[1])
coord = sys.argv[2]

from brpc_tpu.ici.fabric import FabricNode
node = FabricNode.initialize(coord, num_processes=2, process_id=pid)
kv = node._kv

import brpc_tpu.policy
from brpc_tpu import rpc, ici
from echo_pb2 import EchoRequest, EchoResponse

mesh = ici.IciMesh()          # global devices, identical in both processes
ici.IciMesh.set_default(mesh)
assert mesh.size == 4, mesh.size

if pid == 0:
    class EchoService(rpc.Service):
        @rpc.method(EchoRequest, EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = "srv0:" + request.message
            if len(cntl.request_attachment):
                # bounce the device payload straight back
                cntl.response_attachment.append(cntl.request_attachment)
            done()

    server = rpc.Server()
    server.add_service(EchoService())
    assert server.start("ici://0") == 0
    kv.key_value_set("srv_up", "1")
    kv.wait_at_barrier("fabric_echo_done", 120000)
    server.stop()
    print("CHILD0_OK", flush=True)
else:
    kv.blocking_key_value_get("srv_up", 60000)
    ch = rpc.Channel()
    ch.init("ici://0", options=rpc.ChannelOptions(timeout_ms=60000,
                                                  max_retry=0))
    # plain echo
    cntl = rpc.Controller()
    resp = ch.call_method("EchoService.Echo", cntl,
                          EchoRequest(message="hello"), EchoResponse)
    assert not cntl.failed(), cntl.error_text
    assert resp.message == "srv0:hello", resp.message

    # echo with a device attachment living on THIS process's device —
    # crosses the process boundary via transfer-server pull both ways
    local_dev_idx = next(i for i, d in enumerate(jax.devices())
                         if d.process_index == pid)
    payload = jax.device_put(jnp.arange(4096, dtype=jnp.uint8),
                             jax.devices()[local_dev_idx])
    jax.block_until_ready(payload)
    cntl = rpc.Controller()
    cntl.request_attachment.append_device_array(payload)
    resp = ch.call_method("EchoService.Echo", cntl,
                          EchoRequest(message="att"), EchoResponse)
    assert not cntl.failed(), cntl.error_text
    assert resp.message == "srv0:att"
    got = cntl.response_attachment.to_bytes()
    np.testing.assert_array_equal(
        np.frombuffer(got, dtype=np.uint8),
        np.arange(4096, dtype=np.uint8))
    kv.wait_at_barrier("fabric_echo_done", 120000)
    print("CHILD1_OK", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


STRESS_CHILD = r"""
import os, sys, threading, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

pid = int(sys.argv[1])
coord = sys.argv[2]

from brpc_tpu.ici.fabric import FabricNode
node = FabricNode.initialize(coord, num_processes=2, process_id=pid)
kv = node._kv

import brpc_tpu.policy
from brpc_tpu import rpc, ici
from echo_pb2 import EchoRequest, EchoResponse

mesh = ici.IciMesh()
ici.IciMesh.set_default(mesh)

CHUNK = 2 * 1024 * 1024      # 2MB payloads vs the 4MB window: 3 threads
THREADS, CALLS = 3, 3        # saturate it (9 x 2MB each way)

if pid == 0:
    total = [0]
    lock = threading.Lock()

    class Sink(rpc.Service):
        @rpc.method(EchoRequest, EchoResponse)
        def Push(self, cntl, request, response, done):
            n = len(cntl.request_attachment)
            with lock:
                total[0] += n
            # bounce it back: the response direction saturates too
            cntl.response_attachment.append(cntl.request_attachment)
            response.message = str(total[0])
            done()

    server = rpc.Server()
    server.add_service(Sink())
    assert server.start("ici://0") == 0
    kv.key_value_set("stress_srv_up", "1")
    kv.wait_at_barrier("stress_done", 300000)
    expect = THREADS * CALLS * CHUNK
    assert total[0] == expect, (total[0], expect)
    server.stop()
    print("STRESS0_OK", flush=True)
else:
    kv.blocking_key_value_get("stress_srv_up", 60000)
    local_dev = next(i for i, d in enumerate(jax.devices())
                     if d.process_index == pid)
    payload = jax.device_put(jnp.arange(CHUNK, dtype=jnp.uint8),
                             jax.devices()[local_dev])
    jax.block_until_ready(payload)
    expect_bytes = bytes(np.asarray(payload))
    errs = []

    def worker():
        try:
            ch = rpc.Channel()
            ch.init("ici://0", options=rpc.ChannelOptions(
                timeout_ms=240000, max_retry=0))
            for _ in range(CALLS):
                cntl = rpc.Controller()
                cntl.request_attachment.append_device_array(payload)
                resp = ch.call_method("Sink.Push", cntl,
                                      EchoRequest(message="p"),
                                      EchoResponse)
                assert not cntl.failed(), cntl.error_text
                got = cntl.response_attachment.to_bytes()
                assert got == expect_bytes, "bounced payload corrupted"
        except Exception as e:
            errs.append(repr(e))

    threads = [threading.Thread(target=worker) for _ in range(THREADS)]
    for t in threads: t.start()
    for t in threads: t.join()
    assert not errs, errs
    kv.wait_at_barrier("stress_done", 300000)
    print("STRESS1_OK", flush=True)
"""


def _run_pair(script: str, timeout: int = 240):
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("JAX_NUM_PROCESSES", None)
    procs = [subprocess.Popen(
        [sys.executable, "-c", script, str(i), coord],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        for i in range(2)]
    outs = []
    rcs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
        rcs.append(p.returncode)
    assert rcs == [0, 0], (
        f"--- child0 ---\n{outs[0]}\n--- child1 ---\n{outs[1]}")
    return outs


def test_two_process_echo_over_ici_fabric():
    outs = _run_pair(CHILD % {"repo": REPO})
    assert "CHILD0_OK" in outs[0]
    assert "CHILD1_OK" in outs[1]


def test_two_process_window_saturation_stress():
    """Concurrent bulk device transfers past the send window, both
    directions, with byte-exact verification (VERDICT r3 #6: the fabric
    must survive window saturation, and a graceful close must not drop
    the in-flight tail)."""
    outs = _run_pair(STRESS_CHILD % {"repo": REPO}, timeout=300)
    assert "STRESS0_OK" in outs[0]
    assert "STRESS1_OK" in outs[1]


_XFER_FLAG = '''
from brpc_tpu.butil import flags as _xfl
_xfl.set_flag("ici_fabric_bulk", False)
'''

# pin the same-host shm ring tier off for tests that assert the socket
# bulk plane's engagement byte-exactly (shm outranks it in the route
# table; its own coverage lives in tests/test_shm.py)
_SHM_OFF_FLAG = '''
from brpc_tpu.butil import flags as _sfl
_sfl.set_flag("ici_fabric_shm", False)
'''


def test_two_process_stress_over_transfer_server():
    """The flagged pod-DMA alternative (ici_fabric_bulk=False: device
    payloads ride jax transfer-server pulls with staged-until-PULLED
    custody) must keep passing the same byte-exact saturation stress —
    the bulk plane's default would otherwise silently orphan this
    path's coverage."""
    child = STRESS_CHILD % {"repo": REPO}
    marker = "from brpc_tpu.ici.fabric import FabricNode"
    assert marker in child    # a silent no-op here would re-test the
    # bulk plane and leave the pod-DMA path uncovered again
    # the flag is defined at fabric-module import: inject AFTER it
    child = child.replace(marker, marker + _XFER_FLAG)
    outs = _run_pair(child, timeout=300)
    assert "STRESS0_OK" in outs[0]
    assert "STRESS1_OK" in outs[1]


def test_uds_failure_falls_back_to_tcp_bulk():
    """A same-host peer whose advertised abstract-unix name cannot be
    dialed (stale info, netns boundary) must fall back to the TCP bulk
    plane transparently — bulk still engaged, bytes still exact."""
    child = CHILD % {"repo": REPO}
    inject = '''
    info = node.peer_info(0)
    # preconditions: the UDS branch must actually be reachable, or this
    # test passes vacuously on plain TCP (review finding)
    assert info.get("bulk_uds"), "peer advertised no UDS plane"
    assert info.get("host") == node.host_ip, (info, node.host_ip)
    info["bulk_uds"] = "brpc_tpu_fab.nonexistent.0"   # poison the cache
'''
    marker = '    kv.blocking_key_value_get("srv_up", 60000)\n'
    assert marker in child
    child = child.replace(marker, marker + inject)
    check = '''
    from brpc_tpu.ici.fabric import FabricSocket
    from brpc_tpu.rpc.socket import list_sockets
    fabs = [s for s in list_sockets() if isinstance(s, FabricSocket)]
    assert fabs and all(s._bulk for s in fabs), "tcp bulk fallback failed"
'''
    tail = '    kv.wait_at_barrier("fabric_echo_done", 120000)\n'
    assert child.count(tail) == 2     # server branch + client branch
    head, client_part = child.rsplit(tail, 1)
    child = head + check + tail + client_part   # client-side only: the
    # server's barrier runs before any client has connected
    outs = _run_pair(child)
    assert "CHILD0_OK" in outs[0]
    assert "CHILD1_OK" in outs[1]


class TestFabricUnits:
    def test_derive_host_ip(self):
        from brpc_tpu.ici.fabric import FabricNode
        # loopback coordinator → loopback self (route resolution)
        assert FabricNode._derive_host_ip("127.0.0.1:1234") == "127.0.0.1"
        # no coordinator → safe default, never an exception
        assert FabricNode._derive_host_ip(None) == "127.0.0.1"
        assert FabricNode._derive_host_ip("") == "127.0.0.1"
        # unroutable/garbage host falls back instead of raising
        assert isinstance(
            FabricNode._derive_host_ip("nonexistent.invalid:1"), str)
        # port-less address: rpartition used to yield host='' and
        # port=<hostname>, so int(port) raised ValueError straight
        # through initialize() — must fall back/resolve, never raise
        assert FabricNode._derive_host_ip("127.0.0.1") == "127.0.0.1"
        assert FabricNode._derive_host_ip("somehost.invalid") == "127.0.0.1"
        # IPv6 forms misparse under AF_INET → clean fallback
        assert FabricNode._derive_host_ip("[::1]:1234") == "127.0.0.1"
        assert FabricNode._derive_host_ip("[::]") == "127.0.0.1"

    def test_graceful_fin_waits_for_inflight_device_frame(self, monkeypatch):
        """EOF rides the ordered delivery queue: a FIN arriving while a
        device frame still awaits its pull must not surface EOF first
        (the stream tail would be dropped)."""
        from brpc_tpu.ici import transport as T
        from brpc_tpu.ici.fabric import FabricSocket

        sock = FabricSocket.__new__(FabricSocket)
        import threading as _threading
        from brpc_tpu.butil.iobuf import IOBuf
        sock._inbox = IOBuf()
        sock._inbox_lock = _threading.Lock()
        sock._peer_closed = False
        sock._conn_dead = False
        sock._fin_code = 0
        sock._staged = {}
        sock._staged_lock = _threading.Lock()
        sock._bulk = 0
        sock._blib = None
        sock._bulk_lock = _threading.Lock()
        sock._reestab_pending = None
        sock._reestab_evt = _threading.Event()
        sock._shm = 0
        sock._shm_dead = 0
        sock._shmlib = None
        sock._shm_reestab_pending = None
        sock._shm_reestab_evt = _threading.Event()
        sock._dplane_lock = _threading.Lock()
        sock._dplane_seq = None
        sock._dplane_closed = False
        sock._init_delivery()
        events = []
        sock.start_input_event = lambda *a, **k: events.append("input")
        sock._wake_window = lambda: None
        sock._flush_staged = lambda: None

        pending = []

        class FakeDisp:
            def on_ready(self, arrays, cb):
                pending.append(cb)

        monkeypatch.setattr(T, "_all_ready", lambda arrays: False)
        monkeypatch.setattr(T.DeviceEventDispatcher, "instance",
                            classmethod(lambda cls: FakeDisp()))
        # a device-bearing frame is in flight...
        committed = []
        sock._enqueue_delivery([object()], lambda: committed.append(1))
        # ...when the connection ends
        sock._on_connection_over()
        assert sock._conn_dead is True       # writers fail immediately
        assert sock._peer_closed is False    # but EOF has NOT jumped ahead
        pending[0]()                         # the pull completes
        assert committed == [1]
        assert sock._peer_closed is True     # now EOF commits, in order
        assert "input" in events


class TestNativeBulkPlane:
    """The native bulk data plane alone (native/fabric.cpp): uuid-tagged
    frames over a dedicated connection, exercised single-process over
    both transports.  The 2-process tests above exercise it end-to-end
    under the RPC stack; these pin the ABI contract."""

    @pytest.fixture()
    def lib(self):
        from brpc_tpu.butil import native
        lib = native.load()
        if lib is None:
            pytest.skip("native core unavailable")
        return lib

    def _pair(self, lib, key=b"t", uds=False):
        import ctypes
        port = ctypes.c_int()
        uds_out = ctypes.create_string_buffer(108)
        lh = lib.brpc_tpu_fab_listen(b"127.0.0.1", ctypes.byref(port),
                                     uds_out, 108)
        assert lh
        if uds:
            assert uds_out.value, "abstract unix listener did not bind"
            ch = lib.brpc_tpu_fab_connect_uds(uds_out.value, key)
        else:
            ch = lib.brpc_tpu_fab_connect(b"127.0.0.1", port.value, key)
        assert ch
        sh = lib.brpc_tpu_fab_accept(lh, key, 10_000_000)
        assert sh
        return lh, ch, sh

    @pytest.mark.parametrize("uds", [False, True])
    def test_out_of_order_claim_both_transports(self, lib, uds):
        """Frames are claimed BY UUID, not arrival order — the control
        descriptor and the bulk bytes ride different connections, so the
        receiver must tolerate either order."""
        import ctypes
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lh, ch, sh = self._pair(lib, b"ooo", uds=uds)
        try:
            for uuid, fill in ((7, 0x11), (8, 0x22), (9, 0x33)):
                data = (ctypes.c_uint8 * 1000)(*([fill] * 1000))
                assert lib.brpc_tpu_fab_send(ch, uuid, data, 1000) == 0
            for uuid, fill in ((9, 0x33), (7, 0x11), (8, 0x22)):
                out, olen = u8p(), ctypes.c_uint64()
                rc = lib.brpc_tpu_fab_recv(sh, uuid, 10_000_000,
                                           ctypes.byref(out),
                                           ctypes.byref(olen))
                assert rc == 0 and olen.value == 1000
                assert out[0] == fill and out[999] == fill
                lib.brpc_tpu_fab_buf_release(sh, out, olen.value)
        finally:
            lib.brpc_tpu_fab_conn_close(ch)
            lib.brpc_tpu_fab_conn_close(sh)
            lib.brpc_tpu_fab_listener_close(lh)

    def test_claim_timeout_and_dead_conn(self, lib):
        import ctypes
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lh, ch, sh = self._pair(lib, b"to")
        try:
            out, olen = u8p(), ctypes.c_uint64()
            # absent uuid: bounded timeout, rc -1
            rc = lib.brpc_tpu_fab_recv(sh, 404, 50_000, ctypes.byref(out),
                                       ctypes.byref(olen))
            assert rc == -1
            # a frame sent BEFORE the peer closes is claimable AFTER the
            # close (control descriptor may lag the bulk bytes)
            data = (ctypes.c_uint8 * 16)(*([5] * 16))
            assert lib.brpc_tpu_fab_send(ch, 42, data, 16) == 0
            import time
            time.sleep(0.2)              # let the reader park the frame
            lib.brpc_tpu_fab_conn_close(ch)
            rc = lib.brpc_tpu_fab_recv(sh, 42, 5_000_000,
                                       ctypes.byref(out),
                                       ctypes.byref(olen))
            assert rc == 0 and olen.value == 16 and out[3] == 5
            lib.brpc_tpu_fab_buf_release(sh, out, olen.value)
            # now the conn is dead and drained: missing uuids fail fast
            rc = lib.brpc_tpu_fab_recv(sh, 505, 10_000_000,
                                       ctypes.byref(out),
                                       ctypes.byref(olen))
            assert rc == -2
            # send on the closed side fails cleanly
            assert lib.brpc_tpu_fab_send(ch, 1, data, 16) == -1
        finally:
            lib.brpc_tpu_fab_conn_close(sh)
            lib.brpc_tpu_fab_listener_close(lh)

    def test_buffer_pool_reuses_exact_size(self, lib):
        """Released buffers recycle for same-size frames (the page-fault
        economy the pool exists for): the second claim of an equal-size
        frame returns the SAME address."""
        import ctypes
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lh, ch, sh = self._pair(lib, b"pool")
        try:
            data = (ctypes.c_uint8 * 4096)(*([1] * 4096))
            assert lib.brpc_tpu_fab_send(ch, 1, data, 4096) == 0
            out, olen = u8p(), ctypes.c_uint64()
            assert lib.brpc_tpu_fab_recv(sh, 1, 5_000_000,
                                         ctypes.byref(out),
                                         ctypes.byref(olen)) == 0
            first_addr = ctypes.addressof(out.contents)
            lib.brpc_tpu_fab_buf_release(sh, out, olen.value)
            assert lib.brpc_tpu_fab_send(ch, 2, data, 4096) == 0
            out2, olen2 = u8p(), ctypes.c_uint64()
            assert lib.brpc_tpu_fab_recv(sh, 2, 5_000_000,
                                         ctypes.byref(out2),
                                         ctypes.byref(olen2)) == 0
            assert ctypes.addressof(out2.contents) == first_addr
            lib.brpc_tpu_fab_buf_release(sh, out2, olen2.value)
        finally:
            lib.brpc_tpu_fab_conn_close(ch)
            lib.brpc_tpu_fab_conn_close(sh)
            lib.brpc_tpu_fab_listener_close(lh)

    def test_accept_key_mismatch_times_out(self, lib):
        import ctypes
        port = ctypes.c_int()
        uds_out = ctypes.create_string_buffer(108)
        lh = lib.brpc_tpu_fab_listen(b"127.0.0.1", ctypes.byref(port),
                                     uds_out, 108)
        try:
            ch = lib.brpc_tpu_fab_connect(b"127.0.0.1", port.value, b"A")
            assert ch
            assert lib.brpc_tpu_fab_accept(lh, b"B", 100_000) == 0
            sh = lib.brpc_tpu_fab_accept(lh, b"A", 5_000_000)
            assert sh
            lib.brpc_tpu_fab_conn_close(ch)
            lib.brpc_tpu_fab_conn_close(sh)
        finally:
            lib.brpc_tpu_fab_listener_close(lh)

    def test_concurrent_send_recv_close_hammer(self, lib):
        """Teardown vs traffic: concurrent senders, claimers, and an
        asynchronous close must end in clean failures (rc -1/-2), never
        a hang, crash, or double free.  Pins the close_join/wmu
        exclusion (a closing fd must not be recycled under a writer)."""
        import ctypes
        import threading
        import time
        u8p = ctypes.POINTER(ctypes.c_uint8)
        for round_ in range(6):
            lh, ch, sh = self._pair(lib, b"hammer%d" % round_)
            stop = threading.Event()
            errs = []

            def sender():
                # stop is only a wedge-breaker: the sender may be
                # descheduled across the close+stop window and exit via
                # the flag without ever observing a failed send — that
                # is a scheduling outcome, not a product failure
                data = (ctypes.c_uint8 * 8192)(*([3] * 8192))
                uuid = round_ * 1_000_000
                while not stop.is_set():
                    uuid += 1
                    if lib.brpc_tpu_fab_send(ch, uuid, data, 8192) != 0:
                        return      # conn died under us: expected

            def claimer():
                out, olen = u8p(), ctypes.c_uint64()
                uuid = round_ * 1_000_000
                while True:
                    uuid += 1
                    rc = lib.brpc_tpu_fab_recv(sh, uuid, 2_000_000,
                                               ctypes.byref(out),
                                               ctypes.byref(olen))
                    if rc == 0:
                        lib.brpc_tpu_fab_buf_release(sh, out, olen.value)
                    else:
                        return      # timeout (-1) or dead (-2): expected

            ts = [threading.Thread(target=sender, daemon=True),
                  threading.Thread(target=claimer, daemon=True)]
            for t in ts:
                t.start()
            time.sleep(0.05)
            # close BOTH ends while traffic is in flight
            lib.brpc_tpu_fab_conn_close(ch)
            lib.brpc_tpu_fab_conn_close(sh)
            stop.set()
            for t in ts:
                t.join(timeout=10)
                assert not t.is_alive(), "hammer thread wedged"
            assert not errs, errs
            lib.brpc_tpu_fab_listener_close(lh)


STREAM_CHILD = r"""
import os, sys, threading, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1]); coord = sys.argv[2]
from brpc_tpu.ici.fabric import FabricNode
node = FabricNode.initialize(coord, num_processes=2, process_id=pid)
kv = node._kv
import brpc_tpu.policy
from brpc_tpu import rpc, ici
from brpc_tpu.butil.iobuf import IOBuf
from echo_pb2 import EchoRequest, EchoResponse
mesh = ici.IciMesh(); ici.IciMesh.set_default(mesh)

CHUNK = 256 * 1024   # >= ici_stream_bulk_threshold: DATA rides the bulk plane
N = %(n)d            # chunks per pass
PASSES = %(passes)d  # peak-of-passes: the two processes share one core
                     # with the OS, a single pass can eat a scheduling
                     # artifact (same methodology as the bulk tier)

def body_for(seq):
    return b"%%08d" %% seq + bytes([seq %% 251]) * (CHUNK - 8)

# chunk bodies are precomputed OUTSIDE the timed region on both ends:
# constructing a 256KB pattern per chunk costs ~50us of the one shared
# core per frame — harness work that would be billed to the transport
EXPECT = [body_for(s) for s in range(PASSES * N)]

if pid == 0:
    got = {"n": 0, "bytes": 0, "bad": 0}
    done_evt = threading.Event()

    class Sink:
        def on_received_messages(self, sid, msgs):
            for m in msgs:
                b = m.to_bytes()
                # byte-exact AND order-exact: memcmp against the
                # precomputed body for the next expected seq
                if got["n"] >= len(EXPECT) or b != EXPECT[got["n"]]:
                    got["bad"] += 1
                # bytes BEFORE n: the main loop publishes the ack on
                # byte volume, and a preemption between the two writes
                # would ack short of the final chunk (review finding)
                got["bytes"] += len(b)
                got["n"] += 1

        def on_closed(self, sid):
            done_evt.set()

    class StreamSvc(rpc.Service):
        @rpc.method(EchoRequest, EchoResponse)
        def Start(self, cntl, request, response, done):
            rpc.stream_accept(cntl, rpc.StreamOptions(handler=Sink()))
            response.message = "ok"
            done()

    server = rpc.Server(); server.add_service(StreamSvc())
    assert server.start("ici://0") == 0
    kv.key_value_set("st_srv_up", "1")
    deadline = time.time() + 240
    for p in range(PASSES):
        want = (p + 1) * N * CHUNK
        while got["bytes"] < want and time.time() < deadline:
            time.sleep(0.001)
        # per-pass consumption ack BEFORE any assertion: the client's
        # clock stops on this, so it must reflect delivered-and-verified
        # volume (not bytes still in flight)
        kv.key_value_set("st_acked_%%d" %% p, str(got["bytes"]))
    assert done_evt.wait(120), "stream never closed"
    assert got["n"] == PASSES * N, got
    assert got["bytes"] == PASSES * N * CHUNK, got
    assert got["bad"] == 0, got
    kv.wait_at_barrier("st_done", 120000)
    server.stop()
    print("ST0_OK", flush=True)
else:
    kv.blocking_key_value_get("st_srv_up", 60000)
    ch = rpc.Channel()
    ch.init("ici://0", options=rpc.ChannelOptions(timeout_ms=60000,
                                                  max_retry=0))
    cntl = rpc.Controller()
    stream = rpc.stream_create(cntl, rpc.StreamOptions(max_buf_size=8 << 20))
    resp = ch.call_method("StreamSvc.Start", cntl,
                          EchoRequest(message="s"), EchoResponse)
    assert not cntl.failed(), cntl.error_text
    assert stream.wait_connected(10)
    best = 0.0
    seq = 0
    for p in range(PASSES):
        t0 = time.perf_counter()
        for _ in range(N):
            assert stream.write(IOBuf(EXPECT[seq]), timeout=30) == 0
            seq += 1
        # clock stops on the server's consumed-and-verified ack, not on
        # the last write returning — up to max_buf_size of the volume is
        # still in flight at that point and would inflate the number
        acked = int(kv.blocking_key_value_get("st_acked_%%d" %% p, 120000))
        dt = time.perf_counter() - t0
        assert acked >= (p + 1) * N * CHUNK, acked
        best = max(best, N * CHUNK / dt / 1e6)
    stream.close()
    print("FABRIC_STREAM_MBPS %%.1f best_of=%%d" %% (best, PASSES),
          flush=True)
    # which fast plane carried the DATA payloads (bench route assertion)
    from brpc_tpu.ici.fabric import FabricSocket
    from brpc_tpu.rpc.socket import list_sockets
    shm_b = sum(s.shm_bytes_sent for s in list_sockets()
                if isinstance(s, FabricSocket))
    bulk_b = sum(s.bulk_bytes_sent for s in list_sockets()
                 if isinstance(s, FabricSocket))
    print("ST_ROUTE shm=%%d bulk=%%d" %% (shm_b, bulk_b), flush=True)
    kv.wait_at_barrier("st_done", 120000)
    print("ST1_OK", flush=True)
"""


# Correctness child for streaming-over-bulk: frames alternate below and
# above ici_stream_bulk_threshold, the server asserts byte-exact payloads
# IN SEQ ORDER, both ends assert the credit/feedback loop moved, and the
# client asserts the large frames actually rode the bulk plane.
MIXED_STREAM_CHILD = r"""
import os, sys, threading, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1]); coord = sys.argv[2]
from brpc_tpu.ici.fabric import FabricNode
node = FabricNode.initialize(coord, num_processes=2, process_id=pid)
kv = node._kv
import brpc_tpu.policy
from brpc_tpu import rpc, ici
from brpc_tpu.butil.iobuf import IOBuf
from echo_pb2 import EchoRequest, EchoResponse
mesh = ici.IciMesh(); ici.IciMesh.set_default(mesh)

BIG = 256 * 1024     # >= threshold: descriptor on control, bytes on bulk
SMALL = 1024         # < threshold: inline control frame (latency path)
N = %(n)d            # alternating big/small, starting big
WINDOW = 2 * 1024 * 1024

def body_for(seq):
    size = BIG if seq %% 2 == 0 else SMALL
    return b"%%08d" %% seq + bytes([(seq * 7 + 3) %% 251]) * (size - 8)

TOTAL = sum(len(body_for(s)) for s in range(N))

if pid == 0:
    state = {"next": 0, "bad": []}
    streams = []
    done_evt = threading.Event()

    class Sink:
        def on_received_messages(self, sid, msgs):
            for m in msgs:
                # byte-exact AND in seq order: a reordered or corrupted
                # frame fails here, whichever plane carried it
                if m.to_bytes() != body_for(state["next"]):
                    state["bad"].append(state["next"])
                state["next"] += 1

        def on_closed(self, sid):
            done_evt.set()

    class StreamSvc(rpc.Service):
        @rpc.method(EchoRequest, EchoResponse)
        def Start(self, cntl, request, response, done):
            streams.append(rpc.stream_accept(
                cntl, rpc.StreamOptions(handler=Sink())))
            response.message = "ok"
            done()

    server = rpc.Server(); server.add_service(StreamSvc())
    assert server.start("ici://0") == 0
    kv.key_value_set("mx_srv_up", "1")
    assert done_evt.wait(180), ("stream never closed", state["next"])
    assert state["next"] == N, state
    assert not state["bad"], state["bad"][:5]
    # credit accounting unchanged by the bulk route: every byte passed
    # through the consumption/feedback machinery
    assert streams[0]._local_consumed == TOTAL, (
        streams[0]._local_consumed, TOTAL)
    kv.wait_at_barrier("mx_done", 120000)
    server.stop()
    print("MX0_OK", flush=True)
else:
    kv.blocking_key_value_get("mx_srv_up", 60000)
    ch = rpc.Channel()
    ch.init("ici://0", options=rpc.ChannelOptions(timeout_ms=60000,
                                                  max_retry=0))
    cntl = rpc.Controller()
    stream = rpc.stream_create(
        cntl, rpc.StreamOptions(max_buf_size=WINDOW))
    resp = ch.call_method("StreamSvc.Start", cntl,
                          EchoRequest(message="s"), EchoResponse)
    assert not cntl.failed(), cntl.error_text
    assert stream.wait_connected(10)
    assert TOTAL > 2 * WINDOW   # the writer MUST block on the window at
    # least once, so the assertions below prove feedback actually flowed
    for seq in range(N):
        assert stream.write(IOBuf(body_for(seq)), timeout=60) == 0
    # sender-side credit accounting: produced == total, and feedback
    # advanced the remote-consumed watermark (the final write could not
    # have been admitted otherwise)
    assert stream._produced == TOTAL, (stream._produced, TOTAL)
    assert stream._remote_consumed >= TOTAL - WINDOW, (
        stream._remote_consumed, TOTAL, WINDOW)
    from brpc_tpu.ici.fabric import FabricSocket
    from brpc_tpu.rpc.socket import list_sockets
    fabs = [s for s in list_sockets() if isinstance(s, FabricSocket)]
    assert fabs, "no fabric socket"
    big_total = sum(len(body_for(s)) for s in range(N) if s %% 2 == 0)
    bulk_out = sum(s._blib.brpc_tpu_fab_bytes(s._bulk, 1)
                   for s in fabs if s._bulk)
    %(bulk_assert)s
    stream.close()
    kv.wait_at_barrier("mx_done", 120000)
    print("MX1_OK", flush=True)
"""

# with the bulk plane bound, every big frame's payload must have ridden
# it — and ONLY the big frames (small ones keep the inline latency path)
_BULK_ON_ASSERT = ("assert bulk_out == big_total, (bulk_out, big_total)")
# with the bulk plane disabled end-to-end, the stream must fall back to
# the inline path transparently: no bulk conn, no bulk bytes
_BULK_OFF_ASSERT = (
    "assert all(not s._bulk for s in fabs), 'bulk conn unexpectedly bound'\n"
    "    assert bulk_out == 0, bulk_out")


def test_streaming_over_cross_process_fabric():
    """Streaming RPC across a real process boundary rides the bulk fast
    plane: DATA frames >= ici_stream_bulk_threshold put only a 16-byte
    descriptor on the control channel while the payload gather-sends on
    the native bulk connection; smaller frames keep the inline path.
    Byte-exact seq-order verification server-side, credit accounting
    asserted on both ends, bulk engagement asserted byte-exactly.

    The same-host shm ring tier is pinned OFF here: it outranks the
    socket bulk conn in the route table, and this test exists to keep
    the UDS/TCP leg honest (tests/test_shm.py owns the shm leg)."""
    child = MIXED_STREAM_CHILD % {"repo": REPO, "n": 80,
                                  "bulk_assert": _BULK_ON_ASSERT}
    marker = "from brpc_tpu.ici.fabric import FabricNode"
    assert marker in child
    child = child.replace(marker, marker + _SHM_OFF_FLAG)
    outs = _run_pair(child, timeout=240)
    assert "MX0_OK" in outs[0]
    assert "MX1_OK" in outs[1]


def test_streaming_falls_back_inline_without_bulk_plane():
    """With the native bulk plane disabled (ici_fabric_bulk=False — the
    pod-DMA configuration), stream DATA frames of every size must fall
    back to the inline control-channel path transparently: same bytes,
    same order, same credit loop."""
    child = MIXED_STREAM_CHILD % {"repo": REPO, "n": 40,
                                  "bulk_assert": _BULK_OFF_ASSERT}
    marker = "from brpc_tpu.ici.fabric import FabricNode"
    assert marker in child
    child = child.replace(marker, marker + _XFER_FLAG)
    outs = _run_pair(child, timeout=240)
    assert "MX0_OK" in outs[0]
    assert "MX1_OK" in outs[1]


def test_streaming_perf_child_smoke():
    """The bench harness's measured child (STREAM_CHILD) stays runnable:
    a short 2-pass run with per-pass consumed acks."""
    outs = _run_pair(STREAM_CHILD % {"repo": REPO, "n": 8, "passes": 2},
                     timeout=240)
    assert "ST0_OK" in outs[0]
    assert "ST1_OK" in outs[1]
    assert any(line.startswith("FABRIC_STREAM_MBPS")
               for line in outs[1].splitlines())
