"""Multi-controller ici://: 2-process echo over the fabric (VERDICT #4).

The reference tests distributed behavior with multiple in-process servers
on localhost TCP (SURVEY.md §4); the multi-CONTROLLER equivalent needs real
process isolation — each child owns its slice of the global device list,
jax.distributed is the out-of-band handshake channel, and device payloads
cross process boundaries through the transfer server (the RDMA-READ pull
model of src/brpc/rdma/rdma_endpoint.cpp translated to XLA).
"""
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import os, sys, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

pid = int(sys.argv[1])
coord = sys.argv[2]

from brpc_tpu.ici.fabric import FabricNode
node = FabricNode.initialize(coord, num_processes=2, process_id=pid)
kv = node._kv

import brpc_tpu.policy
from brpc_tpu import rpc, ici
from echo_pb2 import EchoRequest, EchoResponse

mesh = ici.IciMesh()          # global devices, identical in both processes
ici.IciMesh.set_default(mesh)
assert mesh.size == 4, mesh.size

if pid == 0:
    class EchoService(rpc.Service):
        @rpc.method(EchoRequest, EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = "srv0:" + request.message
            if len(cntl.request_attachment):
                # bounce the device payload straight back
                cntl.response_attachment.append(cntl.request_attachment)
            done()

    server = rpc.Server()
    server.add_service(EchoService())
    assert server.start("ici://0") == 0
    kv.key_value_set("srv_up", "1")
    kv.wait_at_barrier("fabric_echo_done", 120000)
    server.stop()
    print("CHILD0_OK", flush=True)
else:
    kv.blocking_key_value_get("srv_up", 60000)
    ch = rpc.Channel()
    ch.init("ici://0", options=rpc.ChannelOptions(timeout_ms=60000,
                                                  max_retry=0))
    # plain echo
    cntl = rpc.Controller()
    resp = ch.call_method("EchoService.Echo", cntl,
                          EchoRequest(message="hello"), EchoResponse)
    assert not cntl.failed(), cntl.error_text
    assert resp.message == "srv0:hello", resp.message

    # echo with a device attachment living on THIS process's device —
    # crosses the process boundary via transfer-server pull both ways
    local_dev_idx = next(i for i, d in enumerate(jax.devices())
                         if d.process_index == pid)
    payload = jax.device_put(jnp.arange(4096, dtype=jnp.uint8),
                             jax.devices()[local_dev_idx])
    jax.block_until_ready(payload)
    cntl = rpc.Controller()
    cntl.request_attachment.append_device_array(payload)
    resp = ch.call_method("EchoService.Echo", cntl,
                          EchoRequest(message="att"), EchoResponse)
    assert not cntl.failed(), cntl.error_text
    assert resp.message == "srv0:att"
    got = cntl.response_attachment.to_bytes()
    np.testing.assert_array_equal(
        np.frombuffer(got, dtype=np.uint8),
        np.arange(4096, dtype=np.uint8))
    kv.wait_at_barrier("fabric_echo_done", 120000)
    print("CHILD1_OK", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_echo_over_ici_fabric():
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("JAX_NUM_PROCESSES", None)
    script = CHILD % {"repo": REPO}
    procs = [subprocess.Popen(
        [sys.executable, "-c", script, str(i), coord],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        for i in range(2)]
    outs = []
    rcs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
        rcs.append(p.returncode)
    assert rcs == [0, 0], (
        f"--- child0 ---\n{outs[0]}\n--- child1 ---\n{outs[1]}")
    assert "CHILD0_OK" in outs[0]
    assert "CHILD1_OK" in outs[1]
