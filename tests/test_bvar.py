"""bvar tests (mirrors reference test/bvar_*_unittest.cpp patterns)."""
import threading
import time

from brpc_tpu import bvar


class TestAdder:
    def test_basic(self):
        a = bvar.Adder()
        a << 5
        a << 3
        assert a.get_value() == 8
        a.increment(); a.decrement()
        assert a.get_value() == 8
        assert a.reset() == 8
        assert a.get_value() == 0

    def test_multithreaded_writes(self):
        a = bvar.Adder()

        def work():
            for _ in range(1000):
                a << 1

        ts = [threading.Thread(target=work) for _ in range(8)]
        for t in ts: t.start()
        for t in ts: t.join()
        assert a.get_value() == 8000

    def test_maxer_miner(self):
        mx, mn = bvar.Maxer(), bvar.Miner()
        for v in (3, 9, 1):
            mx << v
            mn << v
        assert mx.get_value() == 9
        assert mn.get_value() == 1


class TestRegistry:
    def test_expose_dump(self):
        a = bvar.Adder("test_counter_one")
        a << 7
        assert "test_counter_one" in bvar.list_exposed()
        assert bvar.find_exposed("test_counter_one") is a
        dump = dict(bvar.dump_exposed("test_counter*"))
        assert dump["test_counter_one"] == "7"
        a.hide()
        assert bvar.find_exposed("test_counter_one") is None

    def test_name_normalization(self):
        assert bvar.to_underscored_name("Foo Bar-baz::Qux") == "foo_bar_baz_qux"

    def test_duplicate_name_rejected(self):
        a = bvar.Adder("test_dup_name")
        b = bvar.Adder()
        assert not b.expose("test_dup_name")
        a.hide()

    def test_status_and_passive(self):
        s = bvar.Status(value=41)
        s.set_value(42)
        assert s.get_value() == 42
        p = bvar.PassiveStatus(lambda: 7)
        assert p.get_value() == 7


class TestWindow:
    def test_window_delta(self):
        a = bvar.Adder()
        w = bvar.Window(a, window_size=10)
        a << 100
        bvar.SamplerCollector.instance().sample_once()
        assert w.get_value() == 100
        a << 50
        bvar.SamplerCollector.instance().sample_once()
        assert w.get_value() == 150

    def test_per_second(self):
        a = bvar.Adder()
        q = bvar.PerSecond(a, window_size=10)
        time.sleep(0.05)
        a << 500
        bvar.SamplerCollector.instance().sample_once()
        assert q.get_value() > 0

    def test_window_over_maxer(self):
        m = bvar.Maxer()
        w = bvar.Window(m, window_size=10)
        m << 3
        bvar.SamplerCollector.instance().sample_once()
        m << 9
        bvar.SamplerCollector.instance().sample_once()
        assert w.get_value() == 9


class TestLatencyRecorder:
    def test_record_and_read(self):
        rec = bvar.LatencyRecorder()
        for us in (100, 200, 300, 400, 500):
            rec << us
        assert rec.count() == 5
        assert rec.latency() == 300
        assert rec.max_latency() == 500
        p50 = rec._percentile.get_value().get_number(0.5)
        assert 100 <= p50 <= 500

    def test_windowed_percentile(self):
        rec = bvar.LatencyRecorder(window_size=10)
        for us in range(1, 101):
            rec << us
        bvar.SamplerCollector.instance().sample_once()
        p99 = rec.latency_percentile(0.99)
        assert 50 <= p99 <= 100

    def test_exposed_family(self):
        rec = bvar.LatencyRecorder("test_method_a")
        rec << 100
        names = bvar.list_exposed("test_method_a*")
        assert "test_method_a_latency" in names
        assert "test_method_a_qps" in names
        assert "test_method_a_latency_99" in names

    def test_int_recorder(self):
        r = bvar.IntRecorder()
        r << 10
        r << 20
        assert r.average() == 15
        assert r.sum() == 30 and r.count() == 2

    def test_batched_record_shares_one_lock(self):
        """Single-lock batched recording (ISSUE 15): under the default
        flag a thread's five agents share ONE lock object (a record is
        one acquisition), reads stay correct across threads, and the
        windowed percentile still samples."""
        from brpc_tpu.butil import flags as _fl
        assert _fl.get_flag("bvar_batched_record") is True
        rec = bvar.LatencyRecorder(window_size=10)
        rec << 100
        lock, s, c, m, n, p, _ident = rec._tls_fast.agents
        assert lock is not None
        assert s.lock is lock and c.lock is lock and m.lock is lock
        assert n.lock is lock and p.lock is lock

        def w(v):
            for _ in range(2000):
                rec << v

        ts = [threading.Thread(target=w, args=(v,)) for v in (10, 30)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert rec.count() == 4001
        assert rec.max_latency() == 100
        bvar.SamplerCollector.instance().sample_once()
        assert rec.latency_percentile(0.5) > 0

    def test_unbatched_flag_restores_per_agent_locks(self):
        from brpc_tpu.butil import flags as _fl
        prev = _fl.get_flag("bvar_batched_record")
        _fl.set_flag("bvar_batched_record", False)
        try:
            rec = bvar.LatencyRecorder()
            rec << 50
            lock, s, c, *_rest = rec._tls_fast.agents
            assert lock is None
            assert s.lock is not c.lock
            assert rec.count() == 1 and rec.latency() == 50.0
        finally:
            _fl.set_flag("bvar_batched_record", prev)


class TestMultiDimension:
    def test_labelled_stats(self):
        md = bvar.MultiDimension("test_md_requests", ["method", "status"],
                                 bvar.Adder)
        md.get_stats(["echo", "ok"]) << 3
        md.get_stats(["echo", "err"]) << 1
        md.get_stats(["echo", "ok"]) << 2
        assert md.count_stats() == 2
        assert md.get_stats(["echo", "ok"]).get_value() == 5
        assert 'method="echo"' in md.describe()
        md.delete_stats(["echo", "err"])
        assert md.count_stats() == 1


class TestCollector:
    def test_speed_limit(self):
        limit = bvar.CollectorSpeedLimit(max_samples_per_second=5)
        accepted = sum(1 for _ in range(100) if limit.is_sampled())
        assert accepted == 5
        assert limit.submitted == 100

    def test_submit_and_process(self):
        class Sample(bvar.Collected):
            def __init__(self, v): self.v = v

        got = []
        c = bvar.Collector.instance()
        c.register_processor(Sample, lambda batch: got.extend(s.v for s in batch))
        c.submit(Sample(1))
        c.submit(Sample(2))
        c.flush_for_test()
        deadline = time.time() + 2
        while len(got) < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert sorted(got) == [1, 2]


class TestDefaultVariables:
    def test_process_vars(self):
        bvar.expose_default_variables()
        dump = dict(bvar.dump_exposed("process_*"))
        assert int(dump["process_pid"]) > 0
        assert int(dump["process_thread_count"]) >= 1
        assert "tpu_device_count" in dict(bvar.dump_exposed("tpu_*"))
