"""ici:// transport + collectives tests on the 8-device virtual CPU mesh."""
import threading
import time

import numpy as np
import pytest

import brpc_tpu.policy  # registers protocols
from brpc_tpu import rpc, ici
from tests.echo_pb2 import EchoRequest, EchoResponse


@pytest.fixture(scope="module")
def mesh():
    import jax
    m = ici.IciMesh(jax.devices())
    ici.IciMesh.set_default(m)
    return m


class DeviceEchoService(rpc.Service):
    SERVICE_NAME = "EchoService"

    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        if len(cntl.request_attachment):
            cntl.response_attachment.append(cntl.request_attachment)
        done()


class TestIciTransport:
    def test_echo_over_ici(self, mesh):
        server = rpc.Server()
        server.add_service(DeviceEchoService())
        assert server.start("ici://0") == 0
        try:
            ch = rpc.Channel()
            ch.init("ici://0")
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="chip-to-chip"),
                                  EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "chip-to-chip"
        finally:
            server.stop()

    def test_device_payload_stays_in_hbm(self, mesh):
        """Attachment carried as a DEVICE block must arrive as a DEVICE
        block resident on the server's chip."""
        import jax
        import jax.numpy as jnp
        seen = {}

        class AttachmentService(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Probe(self, cntl, request, response, done):
                refs = cntl.request_attachment.device_refs()
                seen["n_device_refs"] = len(refs)
                if refs:
                    seen["devices"] = {str(d) for d in refs[0].block.data.devices()}
                seen["bytes"] = cntl.request_attachment.to_bytes()
                response.message = "ok"
                done()

        server = rpc.Server()
        server.add_service(AttachmentService())
        assert server.start("ici://1") == 0
        try:
            payload = jnp.arange(4096, dtype=jnp.uint8)
            payload = jax.device_put(payload, mesh.device(2))
            ch = rpc.Channel()
            ch.init("ici://1")
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            ch.call_method("AttachmentService.Probe", cntl,
                           EchoRequest(message="m"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert seen["n_device_refs"] == 1
            assert seen["devices"] == {str(mesh.device(1))}   # relocated
            assert seen["bytes"] == bytes(np.arange(4096, dtype=np.uint8) & 0xFF)
        finally:
            server.stop()

    def test_transport_stats_count_device_bytes(self, mesh):
        before_total, before_dev = ici.ici_transport_stats()
        # covered by previous tests having moved traffic
        assert before_total > 0
        assert before_dev >= 4096


class TestIciWindow:
    """Transport-level sliding window (VERDICT #3; reference
    rdma_endpoint.cpp:771 window check, :926 completion-driven free)."""

    def _pair(self, mesh, window):
        from brpc_tpu.ici.transport import IciSocket
        a = IciSocket(0, 0, mesh, window_bytes=window)
        b = IciSocket(0, 0, mesh, window_bytes=window)
        a.peer, b.peer = b, a
        return a, b

    def test_slow_reader_bounds_memory_and_stalls_writer(self, mesh):
        from brpc_tpu.butil.iobuf import IOBuf, IOPortal
        win = 8 * 1024
        a, b = self._pair(mesh, win)
        chunk = 4 * 1024
        total = 10 * chunk
        done_codes = []
        for _ in range(total // chunk):
            rc = a.write(IOBuf(b"x" * chunk),
                         on_done=lambda ec: done_codes.append(ec))
            assert rc == 0
        # nobody reads: the peer inbox must stay bounded by the window
        deadline = time.monotonic() + 2
        while time.monotonic() < deadline and len(b._inbox) < win:
            time.sleep(0.01)
        assert len(b._inbox) <= win
        assert a.send_window_left() == 0
        stalled_unacked = a.unacked_send_bytes()
        assert stalled_unacked == win
        # reader drains: writer must resume and deliver everything
        portal = IOPortal()
        got = 0
        deadline = time.monotonic() + 10
        while got < total and time.monotonic() < deadline:
            n = b._do_read(portal, 1 << 20)
            if n <= 0:
                time.sleep(0.005)
                continue
            got += n
        assert got == total, f"delivered {got}/{total}"
        # all writes completed OK once the window reopened
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(done_codes) < total // chunk:
            time.sleep(0.01)
        assert done_codes == [0] * (total // chunk)
        a.set_failed()
        b.set_failed()

    def test_window_replenishes_exactly_consumed_bytes(self, mesh):
        from brpc_tpu.butil.iobuf import IOBuf, IOPortal
        win = 4096
        a, b = self._pair(mesh, win)
        assert a.write(IOBuf(b"y" * 3000)) == 0
        assert a.send_window_left() == win - 3000
        portal = IOPortal()
        n = b._do_read(portal, 1000)
        assert n == 1000
        assert a.send_window_left() == win - 2000
        assert b._do_read(portal, 1 << 20) == 2000
        assert a.send_window_left() == win
        a.set_failed()
        b.set_failed()

    def test_device_blocks_pinned_until_transfer_complete(self, mesh):
        """A cross-device write pins the SOURCE block until the moved
        array is ready (completion-driven reuse, rdma_endpoint.cpp:926)."""
        import jax
        import jax.numpy as jnp
        from brpc_tpu.butil.iobuf import IOBuf, IOPortal
        if mesh.size < 2:
            pytest.skip("needs 2 devices")
        from brpc_tpu.ici.transport import IciSocket
        a = IciSocket(0, 1, mesh, window_bytes=1 << 20)
        b = IciSocket(1, 0, mesh, window_bytes=1 << 20)
        a.peer, b.peer = b, a
        freed = []
        arr = jax.device_put(jnp.arange(1024, dtype=jnp.uint8),
                             mesh.device(0))
        jax.block_until_ready(arr)
        buf = IOBuf()
        buf.append_device_array(arr)
        ref_block = buf.backing_block(0).block
        ref_block.on_send_complete = lambda: freed.append(1)
        assert a.write(buf) == 0
        portal = IOPortal()
        deadline = time.monotonic() + 5
        got = 0
        while got < 1024 and time.monotonic() < deadline:
            n = b._do_read(portal, 1 << 20)
            got += max(0, n)
            if n <= 0:
                time.sleep(0.005)
        assert got == 1024
        deadline = time.monotonic() + 5
        while not freed and time.monotonic() < deadline:
            time.sleep(0.005)
        assert freed, "source block completion hook never fired"
        assert a.inflight_send_blocks() == 0
        a.set_failed()
        b.set_failed()


class TestOrderedDelivery:
    def test_host_frame_cannot_jump_pending_device_frame(self, monkeypatch):
        """Byte-stream ordering: a host-only frame arriving after a
        device-bearing frame whose transfer is still in flight must wait
        for it (the parsers rely on transport ordering)."""
        from brpc_tpu.ici import transport as T

        class Host(T.OrderedDelivery):
            def __init__(self):
                self._init_delivery()

        h = Host()
        order = []
        pending = []

        class FakeDisp:
            def on_ready(self, arrays, cb):
                pending.append(cb)

        monkeypatch.setattr(T, "_all_ready", lambda arrays: False)
        monkeypatch.setattr(T.DeviceEventDispatcher, "instance",
                            classmethod(lambda cls: FakeDisp()))
        h._enqueue_delivery([object()], lambda: order.append(1))
        h._enqueue_delivery([], lambda: order.append(2))
        assert order == []          # 2 must not jump ahead of pending 1
        pending[0]()                # device payload lands
        assert order == [1, 2]


class TestCollectives:
    def test_all_reduce(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        x = coll.shard(jnp.arange(n * 4, dtype=jnp.float32).reshape(n, 4))
        out = coll.all_reduce(x)
        expect = np.arange(n * 4, dtype=np.float32).reshape(n, 4).sum(0)
        np.testing.assert_allclose(np.asarray(out), expect)

    def test_all_gather(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        x = coll.shard(jnp.arange(n, dtype=jnp.float32).reshape(n, 1) * 10)
        out = coll.all_gather(x)
        np.testing.assert_allclose(
            np.asarray(out).ravel(), np.arange(n) * 10)

    def test_broadcast(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        rows = jnp.stack([jnp.full((3,), i, jnp.float32) for i in range(n)])
        out = coll.broadcast(coll.shard(rows), root=2)
        np.testing.assert_allclose(np.asarray(out), np.full((3,), 2.0))

    def test_ppermute_ring(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        x = coll.shard(jnp.arange(n, dtype=jnp.float32).reshape(n, 1))
        out = coll.ppermute(x, shift=1)
        np.testing.assert_allclose(
            np.asarray(out).ravel(),
            np.roll(np.arange(n, dtype=np.float32), 1))

    def test_all_to_all(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        x = jnp.arange(n * n, dtype=jnp.float32).reshape(n, n, 1)
        out = coll.all_to_all(coll.shard(x))
        np.testing.assert_allclose(np.asarray(out)[:, :, 0],
                                   np.arange(n * n).reshape(n, n).T)

    def test_reduce_scatter(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        x = jnp.ones((n, n, 2), jnp.float32)
        out = coll.reduce_scatter(coll.shard(x))
        np.testing.assert_allclose(np.asarray(out), np.full((n, 1, 2), n))


class TestRing:
    def test_ring_all_reduce_matches_psum(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        x = coll.shard(jnp.arange(n * 8, dtype=jnp.float32).reshape(n, 8))
        ring_out = ici.ring_all_reduce(x, mesh)
        expect = np.arange(n * 8, dtype=np.float32).reshape(n, 8).sum(0)
        for row in np.asarray(ring_out):
            np.testing.assert_allclose(row, expect)

    def test_two_writers_never_overshoot_window(self, monkeypatch):
        """Concurrent writers racing the window check must not both pass
        before either reserves its credit (VERDICT r3 #2: the reference's
        AppendIfNotFull is check-and-reserve atomically, stream.cpp:274).
        The pre-fix code reserved AFTER dispatch, so two writers could
        dispatch with window=1."""
        import brpc_tpu.ici.ring as ring_mod

        lock = threading.Lock()
        state = {"active": 0, "peak": 0}
        pending = []

        class FakeColl:
            def ppermute(self, x, shift):
                with lock:
                    state["active"] += 1
                    state["peak"] = max(state["peak"], state["active"])
                time.sleep(0.03)         # widen the race window
                with lock:
                    state["active"] -= 1
                return x

        class FakeDisp:
            def on_ready(self, arrays, cb):
                # consume asynchronously, like the device poller
                t = threading.Timer(0.01, cb)
                t.daemon = True
                t.start()
                pending.append(t)

        monkeypatch.setattr(ring_mod.DeviceEventDispatcher, "instance",
                            classmethod(lambda cls: FakeDisp()))
        stream = ring_mod.RingStream.__new__(ring_mod.RingStream)
        stream.mesh = None
        stream.coll = FakeColl()
        stream.hops = 1
        stream.window = 1
        stream.on_chunk = None
        stream._cv = threading.Condition()
        stream._produced = 0
        stream._consumed = 0

        errs = []

        def writer():
            try:
                for _ in range(5):
                    assert stream.write(object(), timeout=10)
            except Exception as e:       # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=writer) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        assert stream.flush(10)
        # with window=1, at most ONE chunk may ever be mid-dispatch
        assert state["peak"] == 1, \
            f"window overshoot: {state['peak']} concurrent dispatches"
        assert stream.in_flight == 0

    def test_failed_dispatch_returns_reserved_credit(self, monkeypatch):
        """A raising ppermute must roll back its reservation so later
        writes and flush() are not wedged by a phantom in-flight chunk."""
        import brpc_tpu.ici.ring as ring_mod

        class BoomColl:
            def __init__(self):
                self.calls = 0

            def ppermute(self, x, shift):
                self.calls += 1
                if self.calls == 1:
                    raise RuntimeError("transfer failed")
                return x

        class FakeDisp:
            def on_ready(self, arrays, cb):
                cb()

        monkeypatch.setattr(ring_mod.DeviceEventDispatcher, "instance",
                            classmethod(lambda cls: FakeDisp()))
        stream = ring_mod.RingStream.__new__(ring_mod.RingStream)
        stream.mesh = None
        stream.coll = BoomColl()
        stream.hops = 1
        stream.window = 1
        stream.on_chunk = None
        stream._cv = threading.Condition()
        stream._produced = 0
        stream._consumed = 0

        with pytest.raises(RuntimeError):
            stream.write(object(), timeout=1)
        assert stream.in_flight == 0     # credit rolled back
        assert stream.write(object(), timeout=1)   # window not wedged
        assert stream.flush(5)

    def test_ring_stream_window_and_order(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        got = []
        stream = ici.RingStream(hops=1, window=2, mesh=mesh,
                                on_chunk=lambda c: got.append(np.asarray(c)))
        for i in range(6):
            ok = stream.write(coll.shard(
                jnp.full((n, 4), i, jnp.float32)))
            assert ok
        assert stream.flush(60)
        assert len(got) == 6
        for i, chunk in enumerate(got):
            np.testing.assert_allclose(chunk, np.full((n, 4), i))
        assert stream.in_flight == 0
