"""ici:// transport + collectives tests on the 8-device virtual CPU mesh."""
import threading
import time

import numpy as np
import pytest

import brpc_tpu.policy  # registers protocols
from brpc_tpu import rpc, ici
from tests.echo_pb2 import EchoRequest, EchoResponse


@pytest.fixture(scope="module")
def mesh():
    import jax
    m = ici.IciMesh(jax.devices())
    ici.IciMesh.set_default(m)
    return m


class DeviceEchoService(rpc.Service):
    SERVICE_NAME = "EchoService"

    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        if len(cntl.request_attachment):
            cntl.response_attachment.append(cntl.request_attachment)
        done()


class TestIciTransport:
    def test_echo_over_ici(self, mesh):
        server = rpc.Server()
        server.add_service(DeviceEchoService())
        assert server.start("ici://0") == 0
        try:
            ch = rpc.Channel()
            ch.init("ici://0")
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="chip-to-chip"),
                                  EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "chip-to-chip"
        finally:
            server.stop()

    def test_device_payload_stays_in_hbm(self, mesh):
        """Attachment carried as a DEVICE block must arrive as a DEVICE
        block resident on the server's chip."""
        import jax
        import jax.numpy as jnp
        seen = {}

        class AttachmentService(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Probe(self, cntl, request, response, done):
                refs = cntl.request_attachment.device_refs()
                seen["n_device_refs"] = len(refs)
                if refs:
                    seen["devices"] = {str(d) for d in refs[0].block.data.devices()}
                seen["bytes"] = cntl.request_attachment.to_bytes()
                response.message = "ok"
                done()

        server = rpc.Server()
        server.add_service(AttachmentService())
        assert server.start("ici://1") == 0
        try:
            payload = jnp.arange(4096, dtype=jnp.uint8)
            payload = jax.device_put(payload, mesh.device(2))
            ch = rpc.Channel()
            ch.init("ici://1")
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            ch.call_method("AttachmentService.Probe", cntl,
                           EchoRequest(message="m"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert seen["n_device_refs"] == 1
            assert seen["devices"] == {str(mesh.device(1))}   # relocated
            assert seen["bytes"] == bytes(np.arange(4096, dtype=np.uint8) & 0xFF)
        finally:
            server.stop()

    def test_transport_stats_count_device_bytes(self, mesh):
        before_total, before_dev = ici.ici_transport_stats()
        # covered by previous tests having moved traffic
        assert before_total > 0
        assert before_dev >= 4096


class TestCollectives:
    def test_all_reduce(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        x = coll.shard(jnp.arange(n * 4, dtype=jnp.float32).reshape(n, 4))
        out = coll.all_reduce(x)
        expect = np.arange(n * 4, dtype=np.float32).reshape(n, 4).sum(0)
        np.testing.assert_allclose(np.asarray(out), expect)

    def test_all_gather(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        x = coll.shard(jnp.arange(n, dtype=jnp.float32).reshape(n, 1) * 10)
        out = coll.all_gather(x)
        np.testing.assert_allclose(
            np.asarray(out).ravel(), np.arange(n) * 10)

    def test_broadcast(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        rows = jnp.stack([jnp.full((3,), i, jnp.float32) for i in range(n)])
        out = coll.broadcast(coll.shard(rows), root=2)
        np.testing.assert_allclose(np.asarray(out), np.full((3,), 2.0))

    def test_ppermute_ring(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        x = coll.shard(jnp.arange(n, dtype=jnp.float32).reshape(n, 1))
        out = coll.ppermute(x, shift=1)
        np.testing.assert_allclose(
            np.asarray(out).ravel(),
            np.roll(np.arange(n, dtype=np.float32), 1))

    def test_all_to_all(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        x = jnp.arange(n * n, dtype=jnp.float32).reshape(n, n, 1)
        out = coll.all_to_all(coll.shard(x))
        np.testing.assert_allclose(np.asarray(out)[:, :, 0],
                                   np.arange(n * n).reshape(n, n).T)

    def test_reduce_scatter(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        x = jnp.ones((n, n, 2), jnp.float32)
        out = coll.reduce_scatter(coll.shard(x))
        np.testing.assert_allclose(np.asarray(out), np.full((n, 1, 2), n))


class TestRing:
    def test_ring_all_reduce_matches_psum(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        x = coll.shard(jnp.arange(n * 8, dtype=jnp.float32).reshape(n, 8))
        ring_out = ici.ring_all_reduce(x, mesh)
        expect = np.arange(n * 8, dtype=np.float32).reshape(n, 8).sum(0)
        for row in np.asarray(ring_out):
            np.testing.assert_allclose(row, expect)

    def test_ring_stream_window_and_order(self, mesh):
        import jax.numpy as jnp
        coll = ici.Collectives(mesh)
        n = mesh.size
        got = []
        stream = ici.RingStream(hops=1, window=2, mesh=mesh,
                                on_chunk=lambda c: got.append(np.asarray(c)))
        for i in range(6):
            ok = stream.write(coll.shard(
                jnp.full((n, 4), i, jnp.float32)))
            assert ok
        assert stream.flush(60)
        assert len(got) == 6
        for i, chunk in enumerate(got):
            np.testing.assert_allclose(chunk, np.full((n, 4), i))
        assert stream.in_flight == 0
