"""Adversarial GIL-stall coverage (VERDICT Weak #6): the Python
scheduler compensates for workers that BLOCK in butexes, but a CPU-bound
handler holds a worker (and mostly the GIL) without ever parking — with
enough of them, every scheduler worker spins usercode and unrelated
sockets' reads starve.  ``ServerOptions.usercode_in_pthread`` (the
reference's usercode_in_pthread analogue) routes handler invocation to a
dedicated backup thread pool so scheduler workers only parse/dispatch.
"""
import threading
import time

import pytest

import brpc_tpu.policy  # noqa: F401
from brpc_tpu import rpc
from brpc_tpu.bthread.scheduler import TaskControl
from tests.echo_pb2 import EchoRequest, EchoResponse

_seq = [41000]


def unique(p):
    _seq[0] += 1
    return f"{p}-{_seq[0]}"


class SpinService(rpc.Service):
    """A hostile handler: pure-Python compute until the deadline — never
    parks in a butex, never releases its carrying thread."""

    SPIN_S = 0.8

    def __init__(self):
        self.entered = threading.Semaphore(0)

    @rpc.method(EchoRequest, EchoResponse)
    def Spin(self, cntl, request, response, done):
        self.entered.release()
        deadline = time.monotonic() + self.SPIN_S
        x = 1
        while time.monotonic() < deadline:
            x = (x * 1103515245 + 12345) & 0x7FFFFFFF
        response.message = str(x)
        done()


class EchoService(rpc.Service):
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = "echo:" + request.message
        done()


def test_cpu_bound_handlers_do_not_starve_other_sockets():
    """Saturate MORE CPU-bound handlers than there are scheduler workers
    on server A (usercode_in_pthread=True); a fast RPC to server B on a
    DIFFERENT socket must still complete promptly while every spin is
    known to be executing."""
    nworkers = TaskControl.instance().worker_count()
    nspin = nworkers + 2

    spin_svc = SpinService()
    srv_a = rpc.Server(rpc.ServerOptions(
        usercode_in_pthread=True,
        usercode_backup_threads=nspin + 2))
    srv_a.add_service(spin_svc)
    target_a = f"mem://{unique('spin')}"
    assert srv_a.start(target_a) == 0

    srv_b = rpc.Server()
    srv_b.add_service(EchoService())
    target_b = f"mem://{unique('fast')}"
    assert srv_b.start(target_b) == 0
    try:
        ch_a = rpc.Channel()
        ch_a.init(target_a, options=rpc.ChannelOptions(timeout_ms=30000,
                                                       max_retry=0))
        pending = []
        for i in range(nspin):
            cntl = rpc.Controller()
            ch_a.call_method("SpinService.Spin", cntl,
                             EchoRequest(message=str(i)), EchoResponse,
                             done=lambda c: None)
            pending.append(cntl)
        # every spin handler is EXECUTING (not queued) before we probe
        for _ in range(nspin):
            assert spin_svc.entered.acquire(timeout=10), \
                "spin handlers never all started — dispatch starved"
        ch_b = rpc.Channel()
        ch_b.init(target_b, options=rpc.ChannelOptions(timeout_ms=10000,
                                                       max_retry=0))
        t0 = time.monotonic()
        cntl = rpc.Controller()
        resp = ch_b.call_method("EchoService.Echo", cntl,
                                EchoRequest(message="through"),
                                EchoResponse)
        dt = time.monotonic() - t0
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "echo:through"
        # well under SPIN_S: the echo did not wait for any spinner's
        # worker to free up (GIL switching costs some ms, not 800)
        assert dt < 0.5, f"fast RPC starved behind CPU-bound usercode: " \
                         f"{dt:.3f}s"
        for cntl in pending:
            cntl.join(30)
            assert not cntl.failed(), cntl.error_text
    finally:
        srv_a.stop()
        srv_b.stop()


def test_usercode_pool_lifecycle_and_results():
    """The pool serves correct responses and shuts down with the
    server."""
    srv = rpc.Server(rpc.ServerOptions(usercode_in_pthread=True,
                                       usercode_backup_threads=2))
    srv.add_service(EchoService())
    target = f"mem://{unique('pool')}"
    assert srv.start(target) == 0
    assert srv.usercode_pool is not None
    try:
        ch = rpc.Channel()
        ch.init(target)
        for i in range(8):
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message=str(i)), EchoResponse)
            assert not cntl.failed() and resp.message == f"echo:{i}"
    finally:
        srv.stop()
    assert srv.usercode_pool is None


# ---------------------------------------------------------------------
# ISSUE 13 (ROADMAP 4c): the free-threading/subinterpreter pool behind
# the same seam.  The plain surface above stays byte-identical; these
# cover the isolation backend: probe/capability fallback, the
# share-nothing contract, per-worker registration, worker-death chaos,
# and the native-plane isolated dispatch end to end.
# ---------------------------------------------------------------------

from brpc_tpu.rpc.usercode_pool import (IsolationCaps, UsercodePool,  # noqa: E402
                                        probe_isolation)


class TestIsolationProbe:
    def test_probe_is_cached_and_shaped(self):
        caps = probe_isolation()
        assert caps is probe_isolation()        # once per process
        assert caps.mode in ("free-threading", "subinterp",
                             "subinterp-shared-gil", "none")
        if not caps.scaling:
            assert caps.reason, "a non-scaling probe must say why"

    def test_pool_kind_resolution(self):
        caps = probe_isolation()
        p = UsercodePool(kind="auto", workers=1)
        try:
            if caps.mode == "free-threading":
                # plain threads already scale: the backup pool IS the
                # scaling backend
                assert p.kind == "pthread"
            elif caps.functional:
                assert p.kind == "subinterp"
            else:
                assert p.kind == "pthread"
        finally:
            p.shutdown()
        with pytest.raises(ValueError):
            UsercodePool(kind="nope")


class TestShareNothingContract:
    def test_non_bytes_payload_refused(self):
        p = UsercodePool(kind="pthread", workers=1)
        try:
            p.register("M.h", "def handle(payload):\n    return payload\n")
            with pytest.raises(TypeError, match="share-nothing"):
                p.call_isolated("M.h", {"an": "object"})
            with pytest.raises(TypeError, match="share-nothing"):
                p.call_isolated("M.h", object())
            assert p.contract_rejections == 2
            # bytes-like all cross
            assert p.call_isolated("M.h", b"x") == b"x"
            assert p.call_isolated("M.h", bytearray(b"y")) == b"y"
            assert p.call_isolated("M.h", memoryview(b"z")) == b"z"
        finally:
            p.shutdown()

    def test_non_source_registration_refused(self):
        p = UsercodePool(kind="pthread", workers=1)
        try:
            with pytest.raises(TypeError, match="share-nothing"):
                p.register("M.h", lambda payload: payload)
        finally:
            p.shutdown()


class TestIsolationBackend:
    def test_isolated_call_roundtrip(self):
        caps = probe_isolation()
        if not caps.functional:
            pytest.skip(f"no isolation support: {caps.reason}")
        p = UsercodePool(kind="subinterp", workers=2)
        try:
            p.register("M.h",
                       "def handle(payload):\n    return b'ok:' + payload\n")
            assert p.call_isolated("M.h", b"abc") == b"ok:abc"
            assert p.isolation_active
            d = p.describe()
            assert d["isolation_workers"] == 2
            assert d["registered_isolated"] == ["M.h"]
        finally:
            p.shutdown()

    def test_handler_error_surfaces_not_worker_death(self):
        caps = probe_isolation()
        if not caps.functional:
            pytest.skip(f"no isolation support: {caps.reason}")
        p = UsercodePool(kind="subinterp", workers=1)
        try:
            p.register("M.boom",
                       "def handle(payload):\n"
                       "    raise ValueError('boom')\n")
            with pytest.raises(RuntimeError, match="boom"):
                p.call_isolated("M.boom", b"x")
            assert p.worker_deaths == 0
            # the worker survived: a later call still works
            p.register("M.ok", "def handle(payload):\n    return payload\n")
            assert p.call_isolated("M.ok", b"y") == b"y"
        finally:
            p.shutdown()

    def test_worker_death_requeues_with_zero_visible_failures(self):
        caps = probe_isolation()
        if not caps.functional:
            pytest.skip(f"no isolation support: {caps.reason}")
        p = UsercodePool(kind="subinterp", workers=2)
        try:
            p.register("M.h", "def handle(payload):\n    return payload\n")
            assert p.call_isolated("M.h", b"warm") == b"warm"
            p.chaos_kill_next = True
            assert p.call_isolated("M.h", b"survives") == b"survives"
            assert p.worker_deaths == 1
            assert p.requeues == 1
            # the replacement keeps the pool at strength
            assert p.describe()["isolation_workers"] == 2
        finally:
            p.shutdown()

    def test_capability_fallback_runs_same_source(self):
        """kind='pthread' executes the registered SOURCE on the backup
        thread — functional parity when isolation is unsupported."""
        p = UsercodePool(kind="pthread", workers=1)
        try:
            assert not p.isolation_active
            p.register("M.h",
                       "def handle(payload):\n    return b'fb:' + payload\n")
            assert p.call_isolated("M.h", b"x") == b"fb:x"
        finally:
            p.shutdown()


class TestIsolatedRpcDispatch:
    """End to end over the native-ici plane: Server.register_isolated
    routes the method's payload bytes to a pool worker; the parked
    attachment handle passes through to the response (the zero-copy
    echo shape); a worker dying mid-RPC is invisible to the client."""

    ISO_SRC = """
import sys
sys.path.insert(0, %r)
from echo_pb2 import EchoRequest, EchoResponse
def handle(payload):
    req = EchoRequest(); req.ParseFromString(payload)
    resp = EchoResponse(); resp.message = "iso:" + req.message
    return resp.SerializeToString()
""" % __file__.rsplit("/", 1)[0]

    def _mesh(self):
        import jax
        from brpc_tpu import ici
        m = ici.IciMesh(jax.devices())
        ici.IciMesh.set_default(m)
        return m

    def _serve(self, dev=5):
        from brpc_tpu.ici import native_plane
        if not native_plane.available():
            pytest.skip("native core unavailable")
        mesh = self._mesh()
        srv = rpc.Server(rpc.ServerOptions(usercode_in_pthread=True,
                                           usercode_backup_threads=2))
        srv.register_isolated("IsoService.Echo", self.ISO_SRC)
        assert srv.start(f"ici://{dev}") == 0
        ch = rpc.Channel()
        ch.init(f"ici://{dev}",
                options=rpc.ChannelOptions(timeout_ms=20000, max_retry=0,
                                           ici_local_device=dev))
        return mesh, srv, ch

    def test_isolated_method_end_to_end(self):
        import jax
        import jax.numpy as jnp
        from brpc_tpu.ici import native_plane
        mesh, srv, ch = self._serve()
        try:
            payload = jax.device_put(jnp.arange(256, dtype=jnp.uint8),
                                     mesh.device(5))
            jax.block_until_ready(payload)
            for i in range(4):
                cntl = rpc.Controller()
                cntl.request_attachment.append_device_array(payload)
                resp = ch.call_method("IsoService.Echo", cntl,
                                      EchoRequest(message=f"m{i}"),
                                      EchoResponse)
                assert not cntl.failed(), cntl.error_text
                assert resp.message == f"iso:m{i}"
                # attachment handle passed through (the echo shape)
                assert len(cntl.response_attachment) == 256
            del cntl, resp
            import gc
            gc.collect()
            assert native_plane.registry().live() == 0
            assert native_plane.att_table_live() == 0
        finally:
            srv.stop()

    def test_worker_death_mid_rpc_invisible_to_client(self):
        caps = probe_isolation()
        if not caps.functional:
            pytest.skip(f"no isolation support: {caps.reason}")
        mesh, srv, ch = self._serve(dev=6)
        try:
            cntl = rpc.Controller()
            resp = ch.call_method("IsoService.Echo", cntl,
                                  EchoRequest(message="warm"),
                                  EchoResponse)
            assert not cntl.failed(), cntl.error_text
            srv.usercode_pool.chaos_kill_next = True
            cntl = rpc.Controller()
            resp = ch.call_method("IsoService.Echo", cntl,
                                  EchoRequest(message="chaos"),
                                  EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "iso:chaos"
            assert srv.usercode_pool.worker_deaths == 1
        finally:
            srv.stop()

    def test_status_page_records_capability(self):
        srv = rpc.Server(rpc.ServerOptions(usercode_in_pthread=True,
                                           usercode_backup_threads=1))
        srv.add_service(EchoService())
        target = f"mem://{unique('caps')}"
        assert srv.start(target) == 0
        try:
            import json
            from brpc_tpu.rpc.builtin.services import _status
            _ctype, body = _status(srv, {})
            block = json.loads(body)["usercode_pool"]
            caps = probe_isolation()
            assert block["isolation"]["mode"] == caps.mode
            assert block["isolation"]["scaling"] == caps.scaling
            if not caps.scaling:
                assert block["isolation"]["reason"]
        finally:
            srv.stop()

    def test_drain_semantics_preserved_with_new_pool(self):
        """The queued-counter / drain-bounce discipline is unchanged:
        a draining server bounces isolated methods with retryable
        ELOGOFF like any other."""
        mesh, srv, ch = self._serve(dev=7)
        try:
            cntl = rpc.Controller()
            ch.call_method("IsoService.Echo", cntl,
                           EchoRequest(message="ok"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            srv._draining = True
            cntl = rpc.Controller()
            ch.call_method("IsoService.Echo", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert cntl.error_code == rpc.errors.ELOGOFF
        finally:
            srv._draining = False
            srv.stop()


class TestReviewFixes:
    """Regression pins for the PR-13 review findings."""

    def test_process_exit_after_shutdown_does_not_abort(self):
        """shutdown() joins the isolation workers so their
        subinterpreters are destroyed BEFORE process finalization — a
        live subinterpreter at exit is a hard CPython abort
        ('PyInterpreterState_Delete: remaining subinterpreters')."""
        caps = probe_isolation()
        if not caps.functional:
            pytest.skip(f"no isolation support: {caps.reason}")
        import subprocess
        import sys as _sys
        code = (
            "import sys; sys.path.insert(0, %r)\n"
            "from brpc_tpu.rpc.usercode_pool import UsercodePool\n"
            "p = UsercodePool(kind='subinterp', workers=2)\n"
            "p.register('M.h', 'def handle(payload):\\n    return payload\\n')\n"
            "assert p.call_isolated('M.h', b'x') == b'x'\n"
            "p.shutdown()\n"
            "print('CLEAN')\n"
        ) % __file__.rsplit("/", 2)[0]
        r = subprocess.run([_sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, (r.returncode, r.stderr[-500:])
        assert "CLEAN" in r.stdout

    def test_call_isolated_after_shutdown_fails_fast(self):
        p = UsercodePool(kind="pthread", workers=1)
        p.register("M.h", "def handle(payload):\n    return payload\n")
        p.shutdown()
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="stopped"):
            p.call_isolated("M.h", b"x")
        assert time.monotonic() - t0 < 1.0, "caller parked on a dead pool"

    def test_fallback_namespace_cached_across_calls(self):
        """The pthread fallback compiles the handler source once per
        registration, not once per call."""
        p = UsercodePool(kind="pthread", workers=1)
        try:
            p.register("M.h",
                       "import itertools\n"
                       "_c = itertools.count()\n"
                       "def handle(payload):\n"
                       "    return str(next(_c)).encode()\n")
            # module-level state persists across calls = one exec
            assert p.call_isolated("M.h", b"") == b"0"
            assert p.call_isolated("M.h", b"") == b"1"
            # re-registration recompiles
            p.register("M.h", "def handle(payload):\n    return b'v2'\n")
            assert p.call_isolated("M.h", b"") == b"v2"
        finally:
            p.shutdown()

    def test_isolated_method_rides_admission(self):
        """An admission-enabled server runs isolated methods through
        the SAME decision tree as every other plane (the review found
        them bypassing it): the admission counters move."""
        from brpc_tpu.ici import native_plane
        if not native_plane.available():
            pytest.skip("native core unavailable")
        import jax
        from brpc_tpu import ici
        m = ici.IciMesh(jax.devices())
        ici.IciMesh.set_default(m)
        src = ("def handle(payload):\n"
               "    return b''\n")
        srv = rpc.Server(rpc.ServerOptions(usercode_in_pthread=True,
                                           usercode_backup_threads=2,
                                           admission=True))
        srv.register_isolated("Iso.Adm", src, att="drop")
        assert srv.start("ici://4") == 0
        try:
            ch = rpc.Channel()
            ch.init("ici://4",
                    options=rpc.ChannelOptions(timeout_ms=20000,
                                               max_retry=0,
                                               ici_local_device=4))
            before = srv.admission.describe()["admitted"]
            cntl = rpc.Controller()
            ch.call_method("Iso.Adm", cntl,
                           EchoRequest(message="a"), None)
            assert not cntl.failed(), cntl.error_text
            after = srv.admission.describe()["admitted"]
            assert after == before + 1, (before, after)
        finally:
            srv.stop()

    def test_reregistration_reaches_subinterp_workers(self):
        """Re-registering a handler recompiles on the SUBINTERP backend
        too (the per-worker memoization is version-keyed, review
        finding): both backends serve the new source."""
        caps = probe_isolation()
        if not caps.functional:
            pytest.skip(f"no isolation support: {caps.reason}")
        p = UsercodePool(kind="subinterp", workers=1)
        try:
            p.register("M.h", "def handle(payload):\n    return b'v1'\n")
            assert p.call_isolated("M.h", b"") == b"v1"
            p.register("M.h", "def handle(payload):\n    return b'v2'\n")
            assert p.call_isolated("M.h", b"") == b"v2"
        finally:
            p.shutdown()

    def test_shutdown_sweeps_stranded_tasks(self):
        """A task enqueued just before shutdown (racing the sentinels)
        is failed by the leftover sweep, not parked to its timeout."""
        caps = probe_isolation()
        if not caps.functional:
            pytest.skip(f"no isolation support: {caps.reason}")
        # NO registration → no workers spawned: a task planted in the
        # queue is exactly the lost-race shape (enqueued with nobody
        # left to drain it) and only the shutdown sweep can answer it
        p = UsercodePool(kind="subinterp", workers=1)
        from brpc_tpu.rpc.usercode_pool import _IsoTask
        stale = _IsoTask("M.h", b"y")
        p._iso_queue.put(stale)
        t0 = time.monotonic()
        p.shutdown()
        assert stale.event.wait(5), "stranded task never answered"
        assert stale.error == "usercode pool stopped"
        assert time.monotonic() - t0 < 6.0

    def test_register_isolated_requires_pool(self):
        """Starting a server with isolated methods but no usercode pool
        is a configuration error, not a latent ENOMETHOD."""
        srv = rpc.Server()     # usercode_in_pthread defaults False
        srv.register_isolated("M.h", "def handle(p):\n    return p\n")
        with pytest.raises(ValueError, match="usercode_in_pthread"):
            srv.start(f"mem://{unique('iso-misconfig')}")

    def test_isolated_deadline_maps_to_rpc_timeout(self):
        """A spent deadline waiting on the isolation worker reports
        ERPCTIMEDOUT like every other plane, and the abandoned task
        does not burn a worker later."""
        from brpc_tpu.ici import native_plane
        if not native_plane.available():
            pytest.skip("native core unavailable")
        caps = probe_isolation()
        if not caps.functional:
            pytest.skip(f"no isolation support: {caps.reason}")
        import jax
        from brpc_tpu import ici
        m = ici.IciMesh(jax.devices())
        ici.IciMesh.set_default(m)
        # ONE worker, wedged by a slow handler; the probe call then
        # waits out its own (short) deadline behind it
        slow = ("import time\n"
                "def handle(payload):\n"
                "    time.sleep(0.8 if payload == b'' else 0)\n"
                "    return payload\n")
        srv = rpc.Server(rpc.ServerOptions(usercode_in_pthread=True,
                                           usercode_backup_threads=2))
        srv.register_isolated("Iso.Slow", slow, att="drop")
        assert srv.start("ici://3") == 0
        try:
            ch = rpc.Channel()
            ch.init("ici://3",
                    options=rpc.ChannelOptions(timeout_ms=10000,
                                               max_retry=0,
                                               ici_local_device=3))
            # force the single isolation worker: shrink after spawn
            pool = srv.usercode_pool
            cntl0 = rpc.Controller()
            ch.call_method("Iso.Slow", cntl0,
                           EchoRequest(message="warm"), None)
            assert not cntl0.failed(), cntl0.error_text
            # retire all but one isolation worker (each sentinel ends
            # exactly one), so the wedge below is exclusive
            for _ in range(len(pool._iso_workers) - 1):
                pool._iso_queue.put(None)
            time.sleep(0.1)
            # wedge: an async empty-payload call sleeps 0.8s on the
            # remaining worker
            wedge = rpc.Controller()
            wedge_done = threading.Event()
            ch.call_method("Iso.Slow", wedge, b"", None,
                           done=lambda c: wedge_done.set())
            time.sleep(0.05)
            cntl = rpc.Controller()
            cntl.timeout_ms = 200
            ch.call_method("Iso.Slow", cntl,
                           EchoRequest(message="x"), None)
            # the client's native deadline and the server's pool-wait
            # deadline carry the same 200 ms budget and race; BOTH
            # sides now report the timeout code (pre-fix the server
            # side answered EINTERNAL)
            assert cntl.error_code == rpc.errors.ERPCTIMEDOUT, \
                (cntl.error_code, cntl.error_text)
            assert wedge_done.wait(10), "wedge call never completed"
        finally:
            srv.stop()

    def test_abandoned_task_not_executed_after_timeout(self):
        """A call that timed out waiting marks its task abandoned; a
        worker that later dequeues it drops it instead of burning a
        slot on an unread result."""
        caps = probe_isolation()
        if not caps.functional:
            pytest.skip(f"no isolation support: {caps.reason}")
        p = UsercodePool(kind="subinterp", workers=1)
        try:
            p.register(
                "M.count",
                "import time\n"
                "_n = [0]\n"
                "def handle(payload):\n"
                "    if payload == b'slow':\n"
                "        time.sleep(0.5)\n"
                "    elif payload == b'count':\n"
                "        return str(_n[0]).encode()\n"
                "    _n[0] += 1\n"
                "    return b'ok'\n")
            assert p.call_isolated("M.count", b"x") == b"ok"   # _n=1
            import threading as _th
            wedge = _th.Thread(
                target=lambda: p.call_isolated("M.count", b"slow"))
            wedge.start()
            time.sleep(0.05)
            with pytest.raises(TimeoutError):
                p.call_isolated("M.count", b"y", timeout=0.1)  # abandoned
            wedge.join(5)
            # the abandoned b'y' task must have been DROPPED: the
            # counter saw only x and slow (2), never y
            assert p.call_isolated("M.count", b"count") == b"2"
        finally:
            p.shutdown()
