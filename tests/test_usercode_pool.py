"""Adversarial GIL-stall coverage (VERDICT Weak #6): the Python
scheduler compensates for workers that BLOCK in butexes, but a CPU-bound
handler holds a worker (and mostly the GIL) without ever parking — with
enough of them, every scheduler worker spins usercode and unrelated
sockets' reads starve.  ``ServerOptions.usercode_in_pthread`` (the
reference's usercode_in_pthread analogue) routes handler invocation to a
dedicated backup thread pool so scheduler workers only parse/dispatch.
"""
import threading
import time

import pytest

import brpc_tpu.policy  # noqa: F401
from brpc_tpu import rpc
from brpc_tpu.bthread.scheduler import TaskControl
from tests.echo_pb2 import EchoRequest, EchoResponse

_seq = [41000]


def unique(p):
    _seq[0] += 1
    return f"{p}-{_seq[0]}"


class SpinService(rpc.Service):
    """A hostile handler: pure-Python compute until the deadline — never
    parks in a butex, never releases its carrying thread."""

    SPIN_S = 0.8

    def __init__(self):
        self.entered = threading.Semaphore(0)

    @rpc.method(EchoRequest, EchoResponse)
    def Spin(self, cntl, request, response, done):
        self.entered.release()
        deadline = time.monotonic() + self.SPIN_S
        x = 1
        while time.monotonic() < deadline:
            x = (x * 1103515245 + 12345) & 0x7FFFFFFF
        response.message = str(x)
        done()


class EchoService(rpc.Service):
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = "echo:" + request.message
        done()


def test_cpu_bound_handlers_do_not_starve_other_sockets():
    """Saturate MORE CPU-bound handlers than there are scheduler workers
    on server A (usercode_in_pthread=True); a fast RPC to server B on a
    DIFFERENT socket must still complete promptly while every spin is
    known to be executing."""
    nworkers = TaskControl.instance().worker_count()
    nspin = nworkers + 2

    spin_svc = SpinService()
    srv_a = rpc.Server(rpc.ServerOptions(
        usercode_in_pthread=True,
        usercode_backup_threads=nspin + 2))
    srv_a.add_service(spin_svc)
    target_a = f"mem://{unique('spin')}"
    assert srv_a.start(target_a) == 0

    srv_b = rpc.Server()
    srv_b.add_service(EchoService())
    target_b = f"mem://{unique('fast')}"
    assert srv_b.start(target_b) == 0
    try:
        ch_a = rpc.Channel()
        ch_a.init(target_a, options=rpc.ChannelOptions(timeout_ms=30000,
                                                       max_retry=0))
        pending = []
        for i in range(nspin):
            cntl = rpc.Controller()
            ch_a.call_method("SpinService.Spin", cntl,
                             EchoRequest(message=str(i)), EchoResponse,
                             done=lambda c: None)
            pending.append(cntl)
        # every spin handler is EXECUTING (not queued) before we probe
        for _ in range(nspin):
            assert spin_svc.entered.acquire(timeout=10), \
                "spin handlers never all started — dispatch starved"
        ch_b = rpc.Channel()
        ch_b.init(target_b, options=rpc.ChannelOptions(timeout_ms=10000,
                                                       max_retry=0))
        t0 = time.monotonic()
        cntl = rpc.Controller()
        resp = ch_b.call_method("EchoService.Echo", cntl,
                                EchoRequest(message="through"),
                                EchoResponse)
        dt = time.monotonic() - t0
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "echo:through"
        # well under SPIN_S: the echo did not wait for any spinner's
        # worker to free up (GIL switching costs some ms, not 800)
        assert dt < 0.5, f"fast RPC starved behind CPU-bound usercode: " \
                         f"{dt:.3f}s"
        for cntl in pending:
            cntl.join(30)
            assert not cntl.failed(), cntl.error_text
    finally:
        srv_a.stop()
        srv_b.stop()


def test_usercode_pool_lifecycle_and_results():
    """The pool serves correct responses and shuts down with the
    server."""
    srv = rpc.Server(rpc.ServerOptions(usercode_in_pthread=True,
                                       usercode_backup_threads=2))
    srv.add_service(EchoService())
    target = f"mem://{unique('pool')}"
    assert srv.start(target) == 0
    assert srv.usercode_pool is not None
    try:
        ch = rpc.Channel()
        ch.init(target)
        for i in range(8):
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message=str(i)), EchoResponse)
            assert not cntl.failed() and resp.message == f"echo:{i}"
    finally:
        srv.stop()
    assert srv.usercode_pool is None
