"""Pod-scope rpcz: trace stitching, clock alignment, traced data planes,
and the server-path latency decomposition.

Four legs:

  * **Units** — span wall anchors / transfer spans, the per-peer clock
    table (min-bound keep, local-wall mapping), stitch_tree ordering.
  * **In-process** — the satellite-1 regression (client-side device-plane
    annotations land on the CLIENT span via the channel-write local), the
    tpu_std stage decomposition (queue/parse/handler/encode/write
    annotations + recorders), and the builtin RPC services
    (brpc_tpu.Trace / brpc_tpu.Builtin over an ordinary channel).
  * **2-process** — trace continuity over the fabric: client span (proc
    A) and server span (proc B) share trace_id and parent linkage, the
    fabric clock exchange bounds the peer offset, and the stitched tree
    orders A-send < B-recv < B-send < A-recv within the bound.
  * **N=3 disagg** (the acceptance contract) — ONE /rpcz?trace_id= query
    on the router member returns the complete router→prefill→decode
    trace: client+server spans from all three processes PLUS the
    device-plane KV-handoff transfer events (posted / seq-admit /
    complete / pin hold), as one causally-ordered tree.
"""
import json
import time

import pytest

import brpc_tpu.policy  # noqa: F401
from brpc_tpu import rpc
from brpc_tpu.butil import flags as _flags

from echo_pb2 import EchoRequest, EchoResponse
from test_pod import _run_pod, _POD_PRELUDE, REPO


@pytest.fixture()
def rpcz_on():
    old = _flags.get_flag("rpcz_enabled")
    _flags.set_flag("rpcz_enabled", True)
    yield
    _flags.set_flag("rpcz_enabled", old)


@pytest.fixture()
def dplane_host():
    olds = {f: _flags.get_flag(f) for f in
            ("ici_device_plane_host_mesh", "ici_device_plane_threshold")}
    _flags.set_flag("ici_device_plane_host_mesh", True)
    _flags.set_flag("ici_device_plane_threshold", 4096)
    yield
    for f, v in olds.items():
        _flags.set_flag(f, v)


class _Echo(rpc.Service):
    SERVICE_NAME = "EchoService"

    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


# ---------------------------------------------------------------------------
# Units.
# ---------------------------------------------------------------------------

class TestSpanUnits:
    def test_wall_anchor_and_kind(self):
        from brpc_tpu.rpc.span import Span, start_transfer_span
        s = Span("m", True)
        assert abs(s.wall_us - time.time_ns() // 1000) < 5_000_000
        assert s.describe()["side"] == "client"
        assert s.describe()["start_real_us"] == s.wall_us
        t = start_transfer_span("device_plane x", s.trace_id, s.span_id)
        assert t.describe()["side"] == "transfer"
        assert t.trace_id == s.trace_id
        assert t.parent_span_id == s.span_id

    def test_clock_table_keeps_tightest_bound(self):
        from brpc_tpu.ici import clock
        clock.reset_for_test()
        try:
            clock.record(7, 1000.0, 500.0)
            clock.record(7, 2000.0, 900.0)     # looser: ignored
            off, bound = clock.offset(7)
            # the bound carries an age-proportional drift allowance;
            # freshly recorded it is within a whisker of the sample's
            assert off == 1000.0 and 500.0 <= bound < 501.0
            clock.record(7, 1500.0, 100.0)     # tighter: replaces
            off, bound = clock.offset(7)
            assert off == 1500.0 and 100.0 <= bound < 101.0
            aligned, bound = clock.to_local_wall_us(7, 10_000.0)
            assert aligned == 10_000.0 - 1500.0
            assert 100.0 <= bound < 101.0
            # unknown peer: passthrough with the unbounded marker
            aligned, bound = clock.to_local_wall_us(99, 123.0)
            assert aligned == 123.0 and bound == -1.0
        finally:
            clock.reset_for_test()

    def test_stitch_tree_orders_by_aligned_start(self):
        from brpc_tpu.rpc.builtin.pod_scope import stitch_tree
        spans = [
            {"span_id": "a", "parent": "0", "aligned_start_us": 100},
            {"span_id": "b", "parent": "a", "aligned_start_us": 300},
            {"span_id": "c", "parent": "a", "aligned_start_us": 200},
            {"span_id": "d", "parent": "missing", "aligned_start_us": 50},
        ]
        tree = stitch_tree(spans)
        assert [n["span_id"] for n in tree] == ["d", "a"]
        assert [n["span_id"] for n in tree[1]["children"]] == ["c", "b"]


# ---------------------------------------------------------------------------
# In-process: client-span data-plane annotations (satellite-1 regression).
# ---------------------------------------------------------------------------

class TestClientSpanAnnotations:
    def test_client_side_device_plane_events_land_on_client_span(
            self, rpcz_on, dplane_host):
        """A client-side RPC whose request attachment relocates through
        the device plane: the posted/matched/complete lifecycle must
        reach the CLIENT span's trace (it used to be lost — only the
        bthread-local server span was consulted)."""
        import jax
        import jax.numpy as jnp
        from brpc_tpu.ici.mesh import IciMesh
        from brpc_tpu.rpc.span import find_trace
        mesh = IciMesh.default()
        opts = rpc.ServerOptions()
        opts.native_ici = False          # the Python ici plane relocates
        server = rpc.Server(opts)
        server.add_service(_Echo())
        assert server.start("ici://0") == 0
        ch = rpc.Channel()
        ch.init("ici://0", options=rpc.ChannelOptions(timeout_ms=15000,
                                                      max_retry=0))
        try:
            payload = jax.device_put(jnp.arange(65536, dtype=jnp.uint8),
                                     mesh.device(1))
            jax.block_until_ready(payload)
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            deadline = time.monotonic() + 10
            xfer = []
            while time.monotonic() < deadline:
                spans = find_trace(cntl.trace_id)
                xfer = [s for s in spans if s.kind == "transfer"
                        and s.end_us]
                if xfer:
                    break
                time.sleep(0.05)
            assert xfer, "no transfer span joined the client's trace"
            client = [s for s in find_trace(cntl.trace_id)
                      if s.kind == "client"]
            assert client, "client span missing"
            assert all(x.parent_span_id == client[0].span_id
                       for x in xfer)
            ann = " | ".join(a for x in xfer for _, a in x.annotations)
            assert "posted" in ann
            assert "complete" in ann and "pin_held_us=" in ann
        finally:
            server.stop()
            ch.close()


# ---------------------------------------------------------------------------
# In-process: tpu_std stage decomposition.
# ---------------------------------------------------------------------------

class TestStageDecomposition:
    def test_sampled_request_gets_stage_annotations(self, rpcz_on):
        from brpc_tpu.rpc.span import find_trace
        server = rpc.Server()
        server.add_service(_Echo())
        assert server.start("mem://stage_decomp") == 0
        ch = rpc.Channel()
        ch.init("mem://stage_decomp",
                options=rpc.ChannelOptions(timeout_ms=5000))
        try:
            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="d"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            deadline = time.monotonic() + 5
            srv = []
            while time.monotonic() < deadline:
                srv = [s for s in find_trace(cntl.trace_id)
                       if s.kind == "server"]
                if srv:
                    break
                time.sleep(0.02)
            assert srv, "server span missing"
            ann = " | ".join(a for _, a in srv[0].annotations)
            for stage in ("queue", "parse", "handler", "encode", "write"):
                assert f"{stage}_us=" in ann, (stage, ann)
        finally:
            server.stop()
            ch.close()

    def test_on_mode_feeds_stage_recorders_for_every_request(self):
        """mode 'on': the tpu_std_server_* recorders see every request,
        span or no span (the /vars-distribution measurement mode)."""
        from brpc_tpu.policy.tpu_std import _stage_recorders
        old = _flags.get_flag("tpu_std_stage_metrics")
        _flags.set_flag("tpu_std_stage_metrics", "on")
        before = {s: r.count() for s, r in _stage_recorders.items()}
        server = rpc.Server()
        server.add_service(_Echo())
        assert server.start("mem://stage_on") == 0
        ch = rpc.Channel()
        ch.init("mem://stage_on",
                options=rpc.ChannelOptions(timeout_ms=5000))
        try:
            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="d"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            for stage, n in before.items():
                assert _stage_recorders[stage].count() > n, stage
        finally:
            _flags.set_flag("tpu_std_stage_metrics", old)
            server.stop()
            ch.close()

    def test_inline_completion_does_not_leak_client_span_local(
            self, rpcz_on):
        """usercode_inline completes the whole RPC INSIDE the channel's
        sock.write, clearing cntl.span before the finally runs — the
        restore must key on whether the span was PUBLISHED, or the
        finished span leaks into the thread-local and parents every
        later transfer on this thread into a dead trace."""
        from brpc_tpu.bthread import scheduler
        opts = rpc.ServerOptions()
        opts.usercode_inline = True
        server = rpc.Server(opts)
        server.add_service(_Echo())
        assert server.start("mem://span_leak") == 0
        ch = rpc.Channel()
        ch.init("mem://span_leak",
                options=rpc.ChannelOptions(timeout_ms=5000))
        try:
            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="i"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert cntl.span is None          # completed inline
            assert scheduler.local_get("rpcz_client_span") is None, \
                "finished client span leaked into the thread-local"
        finally:
            server.stop()
            ch.close()

    def test_stage_metrics_off_mode(self, rpcz_on):
        from brpc_tpu.policy.tpu_std import _stage_recorders
        from brpc_tpu.rpc.span import find_trace
        old = _flags.get_flag("tpu_std_stage_metrics")
        _flags.set_flag("tpu_std_stage_metrics", "off")
        before = _stage_recorders["handler"].count()
        server = rpc.Server()
        server.add_service(_Echo())
        assert server.start("mem://stage_off") == 0
        ch = rpc.Channel()
        ch.init("mem://stage_off",
                options=rpc.ChannelOptions(timeout_ms=5000))
        try:
            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="d"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert _stage_recorders["handler"].count() == before
        finally:
            _flags.set_flag("tpu_std_stage_metrics", old)
            server.stop()
            ch.close()


# ---------------------------------------------------------------------------
# In-process: the builtin RPC services.
# ---------------------------------------------------------------------------

class TestBuiltinRpc:
    def test_trace_service_and_builtin_call_over_rpc(self, rpcz_on):
        from brpc_tpu.rpc.builtin.rpc_service import JsonMsg
        server = rpc.Server()
        server.add_service(_Echo())
        assert server.start("mem://builtin_rpc") == 0
        ch = rpc.Channel()
        ch.init("mem://builtin_rpc",
                options=rpc.ChannelOptions(timeout_ms=5000))
        try:
            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="t"), EchoResponse)
            assert not cntl.failed()
            tid = cntl.trace_id
            deadline = time.monotonic() + 5
            got = {}
            while time.monotonic() < deadline:
                c2 = rpc.Controller()
                r2 = ch.call_method("brpc_tpu.Trace.FindTrace", c2,
                                    JsonMsg(trace_id=f"{tid:x}"), JsonMsg)
                assert not c2.failed(), c2.error_text
                got = r2.fields
                if len(got.get("spans", [])) >= 2:
                    break
                time.sleep(0.02)
            sides = {s["side"] for s in got["spans"]}
            assert {"client", "server"} <= sides, got
            assert "wall_us" in got and "pid" in got
            # ListRecent
            c3 = rpc.Controller()
            r3 = ch.call_method("brpc_tpu.Trace.ListRecent", c3,
                                JsonMsg(limit=10), JsonMsg)
            assert not c3.failed() and r3.fields["spans"]
            # Builtin.Call: any page over RPC
            c4 = rpc.Controller()
            r4 = ch.call_method("brpc_tpu.Builtin.Call", c4,
                                JsonMsg(page="health"), JsonMsg)
            assert not c4.failed()
            assert r4.fields["status"] == 200 and r4.fields["body"] == "OK"
            # unknown page: a 404 payload, not a failed RPC
            c5 = rpc.Controller()
            r5 = ch.call_method("brpc_tpu.Builtin.Call", c5,
                                JsonMsg(page="nope"), JsonMsg)
            assert not c5.failed() and r5.fields["status"] == 404
        finally:
            server.stop()
            ch.close()

    def test_builtin_call_refused_when_admin_moved_to_internal_port(self):
        from brpc_tpu.rpc.builtin.rpc_service import JsonMsg
        opts = rpc.ServerOptions()
        opts.internal_port = 0           # any free port
        server = rpc.Server(opts)
        server.add_service(_Echo())
        assert server.start("mem://builtin_internal") == 0
        ch = rpc.Channel()
        ch.init("mem://builtin_internal",
                options=rpc.ChannelOptions(timeout_ms=5000, max_retry=0))
        try:
            c = rpc.Controller()
            ch.call_method("brpc_tpu.Builtin.Call", c,
                           JsonMsg(page="flags"), JsonMsg)
            assert c.failed() and c.error_code == rpc.errors.EPERM
            # the SpanDB query surface is admin data too
            c2 = rpc.Controller()
            ch.call_method("brpc_tpu.Trace.ListRecent", c2,
                           JsonMsg(limit=5), JsonMsg)
            assert c2.failed() and c2.error_code == rpc.errors.EPERM
        finally:
            server.stop()
            ch.close()

    def test_rpcz_page_scope_pod_without_pod_reports_error(self):
        server = rpc.Server()
        server.add_service(_Echo())
        assert server.start("mem://rpcz_nopod") == 0
        try:
            ctype, body = server._builtin.dispatch(
                "rpcz", {"scope": "pod"})
            assert "requires a joined pod" in body
            # no pod joined: a trace_id query stays single-process
            ctype, body = server._builtin.dispatch(
                "rpcz", {"trace_id": "ab"})
            assert "spans" in json.loads(body)
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# 2-process fabric: trace continuity + clock-bounded ordering.
# ---------------------------------------------------------------------------

pytestmark_pod = pytest.mark.pod

_TRACE_2PROC = _POD_PRELUDE + r"""
from brpc_tpu.butil import flags as _fl
_fl.set_flag("rpcz_enabled", True)
from brpc_tpu.ici.pod import Pod

MYDEV = 2 * pid
pod = Pod.join("trace2")

class Svc(rpc.Service):
    SERVICE_NAME = "EchoService"
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        time.sleep(0.02)          # a visible server-side dwell
        response.message = "p%%d" %% pid
        done()

server = rpc.Server(); server.add_service(Svc())
assert server.start("ici://%%d" %% MYDEV) == 0
pod.wait_epoch(2 * NPROC, timeout=60)

if pid == 0:
    ch = rpc.Channel()
    ch.init("ici://2", options=rpc.ChannelOptions(timeout_ms=30000,
                                                  max_retry=0))
    cntl = rpc.Controller()
    resp = ch.call_method("EchoService.Echo", cntl,
                          EchoRequest(message="x"), EchoResponse)
    assert not cntl.failed(), (cntl.error_code_, cntl.error_text_)
    tid = cntl.trace_id
    assert tid, "client span was not sampled"
    # the fabric clock exchange bounded the peer offset
    from brpc_tpu.ici import clock
    off = clock.offset(1)
    assert off is not None, "no clock sample for peer 1"
    assert 0 < off[1] < 5_000_000, off
    # pod-scope stitch from THIS member
    deadline = time.time() + 30
    tree = None
    while time.time() < deadline:
        ctype, body = server._builtin.dispatch(
            "rpcz", {"trace_id": "%%x" %% tid})
        out = json.loads(body)
        tree = out.get("tree") or []
        if out.get("span_count", 0) >= 2:
            break
        time.sleep(0.1)
    assert out["scope"] == "pod", out
    assert len(tree) == 1, json.dumps(tree, indent=1)[:2000]
    root = tree[0]
    assert root["side"] == "client" and root["process"] == 0
    kids = root["children"]
    assert len(kids) == 1, kids
    srv = kids[0]
    assert srv["side"] == "server" and srv["process"] == 1
    assert srv["method"] == "EchoService.Echo"
    # causal ordering under the clock bound:
    #   A-send < B-recv < B-send < A-recv
    bound = srv["clock_bound_us"]
    assert bound >= 0, "stitcher lost the clock bound"
    a_send = root["aligned_start_us"]
    a_recv = a_send + root["latency_us"]
    b_recv = srv["aligned_start_us"]
    b_send = b_recv + srv["latency_us"]
    assert b_recv >= a_send - bound, (a_send, b_recv, bound)
    assert b_send <= a_recv + bound, (b_send, a_recv, bound)
    assert b_recv < b_send
    # pod-aggregated /vars: every member's variables, per-process
    ctype, vbody = server._builtin.dispatch("vars", {"scope": "pod"})
    assert "== process 0 ==" in vbody and "== process 1 ==" in vbody, \
        vbody[:500]
    assert "<unreachable" not in vbody, vbody[:2000]
    # pod-aggregated /brpc_metrics: process-labelled Prometheus
    ctype, mbody = server._builtin.dispatch("brpc_metrics",
                                            {"scope": "pod"})
    assert 'process="0"' in mbody and 'process="1"' in mbody, mbody[:500]
    assert "# TYPE" in mbody
    kv.key_value_set("tr_done", "1")
else:
    kv.blocking_key_value_get("tr_done", 120000)
kv.wait_at_barrier("tr_exit", 120000)
server.stop()
pod.leave()
print("TR%%d_OK" %% pid, flush=True)
"""


@pytest.mark.pod
def test_cross_process_trace_continuity_and_clock_bound():
    """Client span (proc A) and server span (proc B) share trace_id and
    parent linkage; one /rpcz?trace_id= on A returns the stitched tree
    ordering A-send < B-recv < B-send < A-recv under the fabric's
    ±RTT/2 clock bound."""
    outs = _run_pod(_TRACE_2PROC % {"repo": REPO}, n=2, timeout=240,
                    tag="trace2")
    assert "TR0_OK" in outs[0], outs[0][-2000:]
    assert "TR1_OK" in outs[1], outs[1][-2000:]


# ---------------------------------------------------------------------------
# N=3 disagg acceptance: the complete router→prefill→decode trace from
# one query, device-plane KV-handoff events included.
# ---------------------------------------------------------------------------

_TRACE_DISAGG = _POD_PRELUDE + r"""
from brpc_tpu.butil import flags as _fl
_fl.set_flag("rpcz_enabled", True)
_fl.set_flag("ici_device_plane_host_mesh", True)
_fl.set_flag("ici_device_plane_threshold", 4096)
from brpc_tpu.ici.pod import Pod
from examples.disagg_serving.workers import (PrefillService, DecodeService,
                                             RouterService)
from examples.disagg_serving.model import kv_nbytes, reference_generate

MYDEV = 2 * pid
pod = Pod.join("dtrace")
TOKENS = list(range(5, 101))          # 96 tokens -> 96KB KV block
STEPS = 4

opts = rpc.ServerOptions(); opts.native_ici = False
server = rpc.Server(opts)
if pid == 1:
    svc = PrefillService(device=jax.devices()[2])
    server.add_service(svc)
elif pid == 2:
    svc = DecodeService(device=jax.devices()[4])
    server.add_service(svc)
else:
    svc = RouterService("ici://2", {"ici://4": "ici://4"})
    server.add_service(svc)
assert server.start("ici://%%d" %% MYDEV) == 0
pod.wait_epoch(2 * NPROC, timeout=60)

if pid == 0:
    ch = rpc.Channel()
    ch.init("ici://0", options=rpc.ChannelOptions(timeout_ms=60000,
                                                  max_retry=0))
    cntl = rpc.Controller()
    resp = ch.call_method("Router.Generate", cntl,
                          EchoRequest(message=json.dumps(
                              {"tokens": TOKENS, "steps": STEPS})),
                          EchoResponse)
    assert not cntl.failed(), (cntl.error_code_, cntl.error_text_)
    out = json.loads(resp.message)
    assert out["tokens"] == reference_generate(TOKENS, STEPS), out
    tid = cntl.trace_id
    assert tid, "client span was not sampled"

    # ONE query on THIS member returns the whole pod's trace
    deadline = time.time() + 60
    stitched = {}
    want_methods = {
        (0, "client", "Router.Generate"),
        (0, "server", "Router.Generate"),
        (0, "client", "Prefill.Prefill"),
        (1, "server", "Prefill.Prefill"),
        (1, "client", "Decode.LoadKv"),
        (2, "server", "Decode.LoadKv"),
        (0, "client", "Decode.Decode"),
        (2, "server", "Decode.Decode"),
    }
    def flatten(nodes):
        for n in nodes:
            yield n
            yield from flatten(n["children"])
    while time.time() < deadline:
        ctype, body = server._builtin.dispatch(
            "rpcz", {"trace_id": "%%x" %% tid})
        stitched = json.loads(body)
        flat = list(flatten(stitched.get("tree") or []))
        got = {(n["process"], n["side"], n["method"]) for n in flat
               if n["side"] != "transfer"}
        xfers = [n for n in flat if n["side"] == "transfer"]
        if want_methods <= got and len(xfers) >= 2:
            break
        time.sleep(0.2)
    assert want_methods <= got, (sorted(want_methods - got),
                                 json.dumps(stitched, indent=1)[:3000])
    # the KV handoff's device-plane transfer events, BOTH halves: the
    # sender's (prefill, proc 1) and the receiver's (decode, proc 2)
    assert {n["process"] for n in xfers} == {1, 2}, xfers
    ann = {n["process"]: " | ".join(a for _, a in n["annotations"])
           for n in xfers}
    assert "posted" in ann[1] and "seq" in ann[1]
    assert "complete" in ann[1] and "pin_held_us=" in ann[1]
    assert "seq" in ann[2] and "complete" in ann[2]
    # every transfer hangs under the LoadKv client span (proc 1): the
    # descriptor carried the trace context to proc 2
    loadkv_client = [n for n in flat
                     if (n["process"], n["side"], n["method"])
                     == (1, "client", "Decode.LoadKv")][0]
    for n in xfers:
        assert n["parent"] == loadkv_client["span_id"], (
            n["parent"], loadkv_client["span_id"])
    # causal order: every child starts no earlier than its parent minus
    # the combined clock bounds (sibling/parent order is explicit and
    # bounded, never assumed)
    def check(node):
        nb = max(node["clock_bound_us"], 0)
        for c in node["children"]:
            cb = max(c["clock_bound_us"], 0)
            slack = nb + cb + 5
            assert c["aligned_start_us"] >= \
                node["aligned_start_us"] - slack, (
                node["method"], node["aligned_start_us"],
                c["method"], c["aligned_start_us"], slack)
            check(c)
    roots = stitched["tree"]
    assert len(roots) == 1 and roots[0]["side"] == "client", roots
    check(roots[0])
    # exactly one trace: 8 RPC spans + the transfer pair
    assert stitched["span_count"] >= 10, stitched["span_count"]
    kv.key_value_set("dt_done", "1")
else:
    kv.blocking_key_value_get("dt_done", 180000)
    if pid == 1:
        # the handoff really rode the sequenced device plane
        socks = [s for s in fabric_socks()
                 if s.dplane_bytes_sent >= kv_nbytes(len(TOKENS))]
        assert socks, [(s.remote_side, s.dplane_bytes_sent)
                       for s in fabric_socks()]
        svc.close()
kv.wait_at_barrier("dt_exit", 180000)
if pid == 0:
    svc.close()
    ch.close()
server.stop()
pod.leave()
print("DT%%d_OK" %% pid, flush=True)
"""


@pytest.mark.pod
def test_disagg_pod_trace_is_complete_from_one_query_n3():
    """Acceptance: a single /rpcz?trace_id= query on the router member
    of the 3-process disagg pod returns the complete
    router→prefill→decode trace — client+server spans from all three
    processes plus the device-plane KV-handoff transfer events (posted /
    seq-admit / complete, pin hold) — as one causally-ordered tree."""
    outs = _run_pod(_TRACE_DISAGG % {"repo": REPO}, n=3, timeout=300,
                    tag="disagg_trace")
    for i in range(3):
        assert f"DT{i}_OK" in outs[i], outs[i][-3000:]
