"""End-to-end RPC tests over mem:// and tcp:// — the in-process loopback
pattern of reference test/brpc_channel_unittest.cpp:166-395."""
import threading
import time

import pytest

import brpc_tpu.policy  # registers protocols
from brpc_tpu import rpc
from brpc_tpu.rpc import errors
from tests.echo_pb2 import EchoRequest, EchoResponse

_name_seq = [0]


def unique_name(prefix="echo"):
    _name_seq[0] += 1
    return f"{prefix}-{_name_seq[0]}"


class EchoService(rpc.Service):
    def __init__(self):
        self.call_count = 0

    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        self.call_count += 1
        if request.sleep_us:
            time.sleep(request.sleep_us / 1e6)
        response.message = request.message
        # attachment round-trip (reference attachment semantics)
        if len(cntl.request_attachment):
            cntl.response_attachment.append(cntl.request_attachment)
        done()

    @rpc.method(EchoRequest, EchoResponse)
    def Fail(self, cntl, request, response, done):
        cntl.set_failed(errors.EINTERNAL, "deliberate failure")
        done()

    @rpc.method(EchoRequest, EchoResponse)
    def Boom(self, cntl, request, response, done):
        raise RuntimeError("kaboom")


@pytest.fixture()
def mem_server():
    server = rpc.Server()
    svc = EchoService()
    server.add_service(svc)
    name = unique_name()
    assert server.start(f"mem://{name}") == 0
    yield server, svc, f"mem://{name}"
    server.stop()


def make_channel(target, **opts):
    ch = rpc.Channel()
    options = rpc.ChannelOptions(**opts) if opts else None
    assert ch.init(target, options=options) == 0
    return ch


class TestMemEcho:
    def test_sync_echo(self, mem_server):
        server, svc, target = mem_server
        ch = make_channel(target)
        cntl = rpc.Controller()
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="hello"), EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "hello"
        assert svc.call_count == 1
        assert cntl.latency_us > 0

    def test_async_echo(self, mem_server):
        server, svc, target = mem_server
        ch = make_channel(target)
        done_evt = threading.Event()
        results = {}

        def on_done(cntl):
            results["failed"] = cntl.failed()
            results["resp"] = cntl.response
            done_evt.set()

        cntl = rpc.Controller()
        ch.call_method("EchoService.Echo", cntl,
                       EchoRequest(message="async"), EchoResponse, on_done)
        assert done_evt.wait(10)
        assert not results["failed"]
        assert results["resp"].message == "async"

    def test_many_concurrent_calls(self, mem_server):
        server, svc, target = mem_server
        ch = make_channel(target)
        n = 50
        done = threading.Event()
        ok = []
        lock = threading.Lock()

        def on_done(cntl):
            with lock:
                ok.append(not cntl.failed() and cntl.response.message)
                if len(ok) == n:
                    done.set()

        for i in range(n):
            ch.call_method("EchoService.Echo", rpc.Controller(),
                           EchoRequest(message=f"m{i}"), EchoResponse, on_done)
        assert done.wait(30)
        assert len(ok) == n and all(ok)
        assert svc.call_count == n

    def test_attachment_roundtrip(self, mem_server):
        server, svc, target = mem_server
        ch = make_channel(target)
        cntl = rpc.Controller()
        cntl.request_attachment.append(b"\x00\x01raw-bytes")
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="a"), EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert cntl.response_attachment.to_bytes() == b"\x00\x01raw-bytes"

    def test_compressed_call(self, mem_server):
        server, svc, target = mem_server
        ch = make_channel(target)
        cntl = rpc.Controller()
        cntl.compress_type = rpc.compress.COMPRESS_TYPE_GZIP
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="z" * 5000), EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "z" * 5000

    def test_server_side_failure(self, mem_server):
        server, svc, target = mem_server
        ch = make_channel(target)
        cntl = rpc.Controller()
        ch.call_method("EchoService.Fail", cntl,
                       EchoRequest(message="x"), EchoResponse)
        assert cntl.failed()
        assert cntl.error_code == errors.EINTERNAL
        assert "deliberate" in cntl.error_text

    def test_uncaught_exception_becomes_einternal(self, mem_server):
        server, svc, target = mem_server
        ch = make_channel(target)
        cntl = rpc.Controller()
        ch.call_method("EchoService.Boom", cntl,
                       EchoRequest(message="x"), EchoResponse)
        assert cntl.failed()
        assert cntl.error_code == errors.EINTERNAL
        assert "kaboom" in cntl.error_text

    def test_no_such_method(self, mem_server):
        server, svc, target = mem_server
        ch = make_channel(target)
        cntl = rpc.Controller()
        ch.call_method("EchoService.Nope", cntl,
                       EchoRequest(), EchoResponse)
        assert cntl.error_code == errors.ENOMETHOD

    def test_no_such_service(self, mem_server):
        server, svc, target = mem_server
        ch = make_channel(target)
        cntl = rpc.Controller()
        ch.call_method("NopeService.Echo", cntl,
                       EchoRequest(), EchoResponse)
        assert cntl.error_code == errors.ENOSERVICE

    def test_timeout(self, mem_server):
        server, svc, target = mem_server
        ch = make_channel(target, timeout_ms=50, max_retry=0)
        cntl = rpc.Controller()
        t0 = time.monotonic()
        ch.call_method("EchoService.Echo", cntl,
                       EchoRequest(message="slow", sleep_us=500_000),
                       EchoResponse)
        assert cntl.error_code == errors.ERPCTIMEDOUT
        assert time.monotonic() - t0 < 5.0

    def test_method_stats_recorded(self, mem_server):
        server, svc, target = mem_server
        ch = make_channel(target)
        for _ in range(3):
            ch.call_method("EchoService.Echo", rpc.Controller(),
                           EchoRequest(message="s"), EchoResponse)
        st = server.method_status("EchoService.Echo")
        assert st.latency_rec.count() == 3
        assert st.concurrency == 0

    def test_connection_refused(self):
        ch = make_channel("mem://nobody-listens", max_retry=1, timeout_ms=200)
        cntl = rpc.Controller()
        ch.call_method("EchoService.Echo", cntl, EchoRequest(), EchoResponse)
        assert cntl.failed()


class TestTcpEcho:
    def test_sync_echo_over_tcp(self):
        server = rpc.Server()
        svc = EchoService()
        server.add_service(svc)
        assert server.start("127.0.0.1:0") == 0
        try:
            port = server.listen_port
            ch = make_channel(f"127.0.0.1:{port}")
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="over-tcp"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "over-tcp"
        finally:
            server.stop()

    def test_large_payload_tcp(self):
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start("127.0.0.1:0") == 0
        try:
            ch = make_channel(f"127.0.0.1:{server.listen_port}",
                              timeout_ms=20000)
            big = "x" * (2 * 1024 * 1024)
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message=big), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == big
        finally:
            server.stop()

    def test_server_stop_fails_inflight_cleanly(self):
        server = rpc.Server()
        server.add_service(EchoService())
        server.start("127.0.0.1:0")
        ch = make_channel(f"127.0.0.1:{server.listen_port}",
                          timeout_ms=2000, max_retry=0)
        cntl = rpc.Controller()
        done = threading.Event()
        ch.call_method("EchoService.Echo", cntl,
                       EchoRequest(message="x", sleep_us=300_000),
                       EchoResponse, lambda c: done.set())
        time.sleep(0.05)
        server.stop()
        assert done.wait(10)
        # either clean response (already processed) or socket failure
        assert cntl.error_code in (0, errors.EFAILEDSOCKET, errors.EEOF,
                                   errors.ELOGOFF, errors.ECONNRESET)
