"""Test harness configuration.

The reference tests "distributed" behavior with multiple in-process servers on
localhost TCP (see SURVEY.md §4).  The TPU-native equivalent is a virtual
multi-device CPU mesh: we force JAX onto the CPU platform with 8 virtual
devices *before* jax is imported anywhere, so every test can build a real
jax.sharding.Mesh and exercise the ici:// data plane (ppermute/psum/
all_gather) without TPU hardware.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The env var alone is not enough when a TPU platform plugin (e.g. the axon
# tunnel) is installed — pin the platform explicitly before any test touches
# jax.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) >= 8, (
    "tests require the 8-device virtual CPU mesh; got %d" % len(jax.devices()))
