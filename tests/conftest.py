"""Test harness configuration.

The reference tests "distributed" behavior with multiple in-process servers on
localhost TCP (see SURVEY.md §4).  The TPU-native equivalent is a virtual
multi-device CPU mesh: we force JAX onto the CPU platform with 8 virtual
devices *before* jax is imported anywhere, so every test can build a real
jax.sharding.Mesh and exercise the ici:// data plane (ppermute/psum/
all_gather) without TPU hardware.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# Runtime custody ledger (ISSUE 20): every tier-1 test runs with the
# declared acquire/release points instrumented, so the census below can
# name the ACQUIRING file:line of a leaked pin/reservation/handle —
# not just the test that tripped over it.  Must be set before any
# brpc_tpu import (the flag is read at define time, like
# BRPC_TPU_DEBUG_LOCK_ORDER).
os.environ.setdefault("BRPC_TPU_DEBUG_CUSTODY", "1")

# The env var alone is not enough when a TPU platform plugin (e.g. the axon
# tunnel) is installed — pin the platform explicitly before any test touches
# jax.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) >= 8, (
    "tests require the 8-device virtual CPU mesh; got %d" % len(jax.devices()))


# ---- seeded port / UDS-path allocator ----------------------------------
#
# N-process tests (the chaos harness, the pod suite, the fabric bench)
# need coordinator ports and unix-socket paths that (a) are DETERMINISTIC
# per test — a failure reproduces with the same addresses — and (b) can't
# collide when several pytest processes run the same suite on one host
# (parallel CI).  The implementation lives in netalloc.py (jax-free) so
# __graft_entry__'s dryrun can import the N-process harnesses from a
# parent without the 8-device mesh; re-exported here for test use.

import pytest  # noqa: E402

from netalloc import alloc_port, alloc_uds  # noqa: E402,F401


# ---- resource-census plugin --------------------------------------------
#
# The LeakSanitizer-shaped leg of the concurrency tooling (see
# docs/CONCURRENCY.md): every test must leave behind no net-new
#
#   * non-daemon thread (the PR 2/4 exit-race class: a live thread at
#     interpreter/static teardown),
#   * live Socket/Stream payload in the versioned-id pools (a leaked
#     connection pins buffers and fds), or
#   * device-plane pin (DevicePlane.active_transfers > 0 means an HBM
#     source block is still pinned by an incomplete transfer).
#
# The census snapshots at fixture-setup time and compares at teardown,
# so module/session-scoped servers (created before the snapshot) and
# the test's own function-scoped fixtures (torn down before the
# comparison) are both accounted.  Teardown is given a settle window:
# socket death propagates through reader threads/tasklets, so a leak is
# only failed after it survives ~2s of polling.  Opt out per test with
# @pytest.mark.allow_leaks("<why>").

import threading  # noqa: E402

import pytest  # noqa: E402

_SETTLE_S = 2.0


def _census():
    from brpc_tpu.rpc.controller import server_controller_pool
    from brpc_tpu.rpc.socket import _socket_pool
    from brpc_tpu.rpc.stream import _streams
    from brpc_tpu.ici.device_plane import DevicePlane
    threads = {t for t in threading.enumerate()
               if t.is_alive() and not t.daemon
               and t is not threading.main_thread()}
    # keyed by the VERSIONED pool id, never id(obj): CPython recycles
    # addresses, so a leaked object at a dead baseline object's address
    # would otherwise mask the leak
    sockets = {s.id: s for s in _socket_pool.live_payloads()}
    streams = {s.sid: s for s in _streams.live_payloads()}
    plane = DevicePlane._instance      # never CREATE one from the census
    pins = plane.active_transfers() if plane is not None else 0
    cntls = server_controller_pool.live()
    # native att custody (ISSUE 12): device-ref registry entries +
    # parked native att-table entries.  At rest BOTH must be zero — a
    # key is either inside an IOBuf (Python custody, not in the
    # registry) or parked under a handle that some live view/struct
    # still names.  A net-new entry at teardown = a custody exit was
    # skipped (the exactly-one-exit invariant).
    import sys as _sys
    np_mod = _sys.modules.get("brpc_tpu.ici.native_plane")
    if np_mod is not None:
        devrefs = np_mod.registry().live()
        atts = np_mod.att_table_live()
    else:
        devrefs = atts = 0
    # custody ledger multiset: (resource, key, acquiring site) with a
    # multiplicity per outstanding hold — the attribution leg.  A leak
    # that ALSO shows up above gets its acquiring file:line from here.
    from brpc_tpu.butil import custody_ledger
    ledger = {}
    for r in custody_ledger.outstanding():
        k = (r["resource"], tuple(r["key"]), r["site"])
        ledger[k] = ledger.get(k, 0) + 1
    return threads, sockets, streams, pins, cntls, devrefs, atts, ledger


def _leaks_vs(base):
    (threads0, sockets0, streams0, pins0, cntls0, devrefs0, atts0,
     ledger0) = base
    (threads1, sockets1, streams1, pins1, cntls1, devrefs1, atts1,
     ledger1) = _census()
    leaks = []
    for t in threads1 - threads0:
        leaks.append(f"non-daemon thread {t.name!r}")
    for k in set(sockets1) - set(sockets0):
        leaks.append(f"live socket {sockets1[k].description()}")
    for k in set(streams1) - set(streams0):
        s = streams1[k]
        leaks.append(f"live stream sid={s.sid} closed={s.closed}")
    if pins1 > max(pins0, 0):
        leaks.append(f"device-plane pins: {pins1} active transfers "
                     f"(was {pins0})")
    if cntls1 > cntls0:
        # a pooled server Controller acquired for a request and never
        # recycled: its request never sent a response (or a new code
        # path skipped _maybe_recycle) — the pool's versioned-id leg
        # makes the leak countable here
        leaks.append(f"pooled server Controllers in flight: {cntls1} "
                     f"(was {cntls0})")
    if devrefs1 > devrefs0:
        leaks.append(f"ici device-ref registry entries: {devrefs1} "
                     f"(was {devrefs0}) — a key never exited custody")
    if atts1 > atts0:
        leaks.append(f"native att-table entries parked: {atts1} "
                     f"(was {atts0}) — an att handle never exited")
    for k, n in ledger1.items():
        extra = n - ledger0.get(k, 0)
        if extra > 0:
            resource, key, site = k
            leaks.append(
                f"custody ledger: {extra} unreleased {resource!r} "
                f"hold(s) acquired at {site} (key={list(key)})")
    return leaks


@pytest.fixture(autouse=True)
def _resource_census(request):
    base = _census()
    yield
    allow = request.node.get_closest_marker("allow_leaks")
    if allow is not None:
        return
    import time as _time
    deadline = _time.monotonic() + _SETTLE_S
    leaks = _leaks_vs(base)
    while leaks and _time.monotonic() < deadline:
        if any("custody" in l or "att handle" in l for l in leaks):
            # att views release via __del__ — collect cycles so a
            # cyclically-referenced controller can't read as a custody
            # leak while the GC simply hasn't run yet
            import gc
            gc.collect()
        _time.sleep(0.05)
        leaks = _leaks_vs(base)
    if leaks:
        pytest.fail(
            "resource census: test %s leaked:\n  %s"
            % (request.node.nodeid, "\n  ".join(leaks)), pytrace=False)
