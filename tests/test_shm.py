"""Same-host shared-memory bulk tier: the mmap ring transport
(native/fabric.cpp nshm), its route-table selection (ici/route.py), and
its chaos/degradation/revival semantics (ROADMAP item 3).

Two tiers of coverage:

  * ring units — drive the native API directly over a small ring so
    wraparound, out-of-order release (consume-to-release head advance),
    full-ring doorbell blocking, dead-ring fail-fast, and the chaos
    knobs (drop, sever-mid-slot) all fire deterministically;
  * 2-process — the full RPC stack over a real fabric pair: the shm
    route carries attachments and stream frames byte-exactly (asserted
    on the shm/bulk byte counters), segment kill falls back to the
    UDS bulk tier with ZERO client-visible failures and revives
    (epoch bump + bytes resume), a refused handshake degrades cleanly,
    and unlink-while-mapped is a no-op by design (the attach unlinks
    the name; the mapping is the resource).
"""
import ctypes
import os
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.shm

u8p = ctypes.POINTER(ctypes.c_uint8)


def _lib():
    from brpc_tpu.butil import native
    lib = native.load()
    if lib is None or not hasattr(lib, "brpc_tpu_shm_create"):
        pytest.skip("native core without shm support")
    return lib


def _ring_pair(lib, name: str, ring_bytes: int):
    """Create+attach one segment in-process (two mappings of the same
    pages — exactly what two processes see) and unlink immediately."""
    lib.brpc_tpu_shm_unlink(name.encode())
    h0 = lib.brpc_tpu_shm_create(name.encode(), ring_bytes)
    if not h0:
        pytest.skip("/dev/shm unavailable in this sandbox")
    h1 = lib.brpc_tpu_shm_attach(name.encode())
    assert h1, "attach failed on a just-created segment"
    assert lib.brpc_tpu_shm_unlink(name.encode()) == 0
    assert not os.path.exists(f"/dev/shm/{name}")
    return h0, h1


def _send(lib, h, uuid, payload: bytes, timeout_us=5_000_000) -> int:
    buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
    return lib.brpc_tpu_shm_send(h, uuid, buf, len(payload), timeout_us)


def _recv(lib, h, uuid, timeout_us=5_000_000):
    out, olen = u8p(), ctypes.c_uint64()
    rc = lib.brpc_tpu_shm_recv(h, uuid, timeout_us,
                               ctypes.byref(out), ctypes.byref(olen))
    return rc, out, olen.value


def _stats(lib, h):
    st = (ctypes.c_uint64 * 6)()
    assert lib.brpc_tpu_shm_stats(h, st, 6) == 6
    return {"bytes_out": st[0], "bytes_in": st[1], "tx_occ": st[2],
            "rx_occ": st[3], "db_waits": st[4], "ring_bytes": st[5]}


class TestShmRingUnits:
    def test_byte_exact_incl_wraparound_and_gather(self):
        lib = _lib()
        h0, h1 = _ring_pair(lib, f"shm_t_wrap.{os.getpid()}", 1 << 20)
        payload = bytes(range(256)) * 1000          # 256000 B
        # 24 frames through a 1MB ring: wraps several times
        for i in range(24):
            if i % 2 == 0:
                assert _send(lib, h0, 100 + i, payload) == 0
            else:
                # gather: three segments reassemble into one frame
                b = (ctypes.c_uint8 * len(payload)).from_buffer_copy(
                    payload)
                base = ctypes.addressof(b)
                ptrs = (ctypes.c_void_p * 3)(base, base + 1000,
                                             base + 50000)
                lens = (ctypes.c_uint64 * 3)(1000, 49000,
                                             len(payload) - 50000)
                assert lib.brpc_tpu_shm_sendv(
                    h0, 100 + i, ptrs, lens, 3, 5_000_000) == 0
            rc, out, n = _recv(lib, h1, 100 + i)
            assert rc == 0 and n == len(payload)
            assert ctypes.string_at(out, n) == payload
            lib.brpc_tpu_shm_release(h1, out, n)
        st = _stats(lib, h1)
        assert st["bytes_in"] == 24 * len(payload)
        lib.brpc_tpu_shm_close(h0)
        lib.brpc_tpu_shm_close(h1)

    def test_out_of_order_release_advances_head_in_order(self):
        lib = _lib()
        h0, h1 = _ring_pair(lib, f"shm_t_ooo.{os.getpid()}", 1 << 20)
        payload = b"z" * 100_000
        foot = 16 + (len(payload) + 15) // 16 * 16
        claims = []
        for i in range(3):
            assert _send(lib, h0, i + 1, payload) == 0
            rc, out, n = _recv(lib, h1, i + 1)
            assert rc == 0
            claims.append((out, n))
        # release the MIDDLE first: every footprint still held (head
        # may only advance over the retired PREFIX)
        lib.brpc_tpu_shm_release(h1, claims[1][0], claims[1][1])
        assert _stats(lib, h1)["rx_occ"] >= 3 * foot
        # releasing the head retires slots 0 AND 1 together
        lib.brpc_tpu_shm_release(h1, claims[0][0], claims[0][1])
        assert _stats(lib, h1)["rx_occ"] == foot
        lib.brpc_tpu_shm_release(h1, claims[2][0], claims[2][1])
        assert _stats(lib, h1)["rx_occ"] == 0
        lib.brpc_tpu_shm_close(h0)
        lib.brpc_tpu_shm_close(h1)

    def test_full_ring_blocks_then_doorbell_wakes(self):
        lib = _lib()
        # fresh 1MB ring: two 400KB frames fit, the third cannot until
        # space retires
        h0, h1 = _ring_pair(lib, f"shm_t_full.{os.getpid()}", 1 << 20)
        payload = b"f" * 400_000
        assert _send(lib, h0, 1, payload) == 0
        assert _send(lib, h0, 2, payload) == 0
        t0 = time.monotonic()
        assert _send(lib, h0, 3, payload, timeout_us=250_000) == -1
        assert 0.2 < time.monotonic() - t0 < 3.0, "timeout not honored"

        def drain():
            time.sleep(0.25)
            for i in (1, 2):
                rc, out, n = _recv(lib, h1, i)
                assert rc == 0
                lib.brpc_tpu_shm_release(h1, out, n)
        t = threading.Thread(target=drain, daemon=True)
        t.start()
        # blocked on the space doorbell until the drain retires slots
        t0 = time.monotonic()
        assert _send(lib, h0, 3, payload, timeout_us=10_000_000) == 0
        assert time.monotonic() - t0 >= 0.2, "send did not block"
        t.join()
        assert _stats(lib, h0)["db_waits"] > 0
        rc, out, n = _recv(lib, h1, 3)
        assert rc == 0
        lib.brpc_tpu_shm_release(h1, out, n)
        lib.brpc_tpu_shm_close(h0)
        lib.brpc_tpu_shm_close(h1)

    def test_oversize_frame_routes_elsewhere_ring_stays_alive(self):
        lib = _lib()
        h0, h1 = _ring_pair(lib, f"shm_t_big.{os.getpid()}", 1 << 20)
        assert _send(lib, h0, 1, b"x" * (2 << 20), timeout_us=0) == -3
        assert lib.brpc_tpu_shm_alive(h0) == 1
        assert _send(lib, h0, 2, b"ok") == 0
        rc, out, n = _recv(lib, h1, 2)
        assert rc == 0 and ctypes.string_at(out, n) == b"ok"
        lib.brpc_tpu_shm_release(h1, out, n)
        lib.brpc_tpu_shm_close(h0)
        lib.brpc_tpu_shm_close(h1)

    def test_wrap_unfittable_frame_fails_fast_not_dead(self):
        """A frame that fits the ring in principle but NOT at the
        current wrap position (remainder + footprint > ring) must
        return -3 IMMEDIATELY — not park out the send timeout and get
        the healthy ring declared dead (review finding)."""
        lib = _lib()
        h0, h1 = _ring_pair(lib, f"shm_t_wrapbig.{os.getpid()}", 1 << 20)
        # advance the cursor to ~400KB, fully drained
        assert _send(lib, h0, 1, b"a" * 400_000) == 0
        rc, out, n = _recv(lib, h1, 1)
        assert rc == 0
        lib.brpc_tpu_shm_release(h1, out, n)
        # 700KB frame: footprint < ring but wrap cost pushes the need
        # past the ring — instant -3 even with a generous timeout
        t0 = time.monotonic()
        rc = _send(lib, h0, 2, b"b" * 700_000, timeout_us=10_000_000)
        assert rc == -3, rc
        assert time.monotonic() - t0 < 1.0, "did not fail fast"
        assert lib.brpc_tpu_shm_alive(h0) == 1
        # normal traffic continues
        assert _send(lib, h0, 3, b"c" * 100_000) == 0
        rc, out, n = _recv(lib, h1, 3)
        assert rc == 0 and n == 100_000
        lib.brpc_tpu_shm_release(h1, out, n)
        lib.brpc_tpu_shm_close(h0)
        lib.brpc_tpu_shm_close(h1)

    def test_dead_ring_fails_fast_but_parked_frames_claimable(self):
        lib = _lib()
        h0, h1 = _ring_pair(lib, f"shm_t_dead.{os.getpid()}", 1 << 20)
        assert _send(lib, h0, 7, b"before-death") == 0
        # a claim parked on a frame that never arrives fails the moment
        # the ring dies — not after its full timeout
        got = {}

        def parked():
            rc, _, _ = _recv(lib, h1, 999, timeout_us=30_000_000)
            got["rc"] = rc
        t = threading.Thread(target=parked, daemon=True)
        t.start()
        time.sleep(0.1)
        lib.brpc_tpu_shm_close(h0)
        t.join(5)
        assert not t.is_alive(), "claim not woken by ring death"
        assert got["rc"] == -2
        # but the frame published BEFORE death is still claimable
        rc, out, n = _recv(lib, h1, 7)
        assert rc == 0 and ctypes.string_at(out, n) == b"before-death"
        lib.brpc_tpu_shm_release(h1, out, n)
        assert _send(lib, h1, 8, b"x", timeout_us=100_000) == -1
        lib.brpc_tpu_shm_close(h1)

    def test_chaos_drop_frames_loses_bytes_not_ring(self):
        lib = _lib()
        h0, h1 = _ring_pair(lib, f"shm_t_drop.{os.getpid()}", 1 << 20)
        assert lib.brpc_tpu_shm_chaos(h1, 2, 1) == 0   # drop next rx frame
        assert _send(lib, h0, 1, b"vanishes") == 0
        rc, _, _ = _recv(lib, h1, 1, timeout_us=200_000)
        assert rc == -1                                # claim times out
        assert lib.brpc_tpu_shm_alive(h1) == 1
        assert _send(lib, h0, 2, b"arrives") == 0
        rc, out, n = _recv(lib, h1, 2)
        assert rc == 0 and ctypes.string_at(out, n) == b"arrives"
        lib.brpc_tpu_shm_release(h1, out, n)
        lib.brpc_tpu_shm_close(h0)
        lib.brpc_tpu_shm_close(h1)

    def test_chaos_sever_mid_slot_is_producer_crash(self):
        lib = _lib()
        h0, h1 = _ring_pair(lib, f"shm_t_sever.{os.getpid()}", 1 << 20)
        assert _send(lib, h0, 1, b"a" * 10_000) == 0
        rc, out, n = _recv(lib, h1, 1)
        assert rc == 0
        lib.brpc_tpu_shm_release(h1, out, n)
        # watermark lands inside the next frame: a PARTIAL slot is
        # copied, tail never advances, the ring dies — the receiver can
        # never observe a torn frame, only conn death
        assert lib.brpc_tpu_shm_chaos(h0, 1, 12_000) == 0
        assert _send(lib, h0, 2, b"b" * 10_000) == -1
        assert lib.brpc_tpu_shm_alive(h0) == 0
        assert lib.brpc_tpu_shm_alive(h1) == 0
        rc, _, _ = _recv(lib, h1, 2, timeout_us=5_000_000)
        assert rc == -2
        lib.brpc_tpu_shm_close(h0)
        lib.brpc_tpu_shm_close(h1)

    def test_unlink_while_mapped_is_harmless(self):
        """The crash-safety design: the attach unlinks the name, the
        MAPPING is the resource — a racing/duplicate unlink (or a chaos
        'unlink the segment' fault) changes nothing for live traffic."""
        lib = _lib()
        name = f"shm_t_unlink.{os.getpid()}"
        h0, h1 = _ring_pair(lib, name, 1 << 20)   # already unlinked
        assert lib.brpc_tpu_shm_unlink(name.encode()) == -1  # idempotent
        for i in range(8):
            payload = bytes([i]) * 50_000
            assert _send(lib, h0, i + 1, payload) == 0
            rc, out, n = _recv(lib, h1, i + 1)
            assert rc == 0 and ctypes.string_at(out, n) == payload
            lib.brpc_tpu_shm_release(h1, out, n)
        lib.brpc_tpu_shm_close(h0)
        lib.brpc_tpu_shm_close(h1)

    def test_claimed_slot_readable_after_close_until_release(self):
        """Zero-copy custody across teardown: the mapping is unmapped
        only when the LAST claimed slot is released, so a Python view
        held across socket close never reads freed memory."""
        lib = _lib()
        h0, h1 = _ring_pair(lib, f"shm_t_hold.{os.getpid()}", 1 << 20)
        assert _send(lib, h0, 1, b"\x5a" * 4096) == 0
        rc, out, n = _recv(lib, h1, 1)
        assert rc == 0
        lib.brpc_tpu_shm_close(h0)
        lib.brpc_tpu_shm_close(h1)              # claim out: unmap deferred
        assert out[0] == 0x5A and out[n - 1] == 0x5A
        lib.brpc_tpu_shm_release(h1, out, n)    # last release unmaps


# ---------------------------------------------------------------------------
# 2-process: the full RPC stack over a real fabric pair.
# ---------------------------------------------------------------------------

_SHM_ROUTE_CHILD = r"""
import os, sys, threading, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

pid = int(sys.argv[1]); coord = sys.argv[2]
from brpc_tpu.ici.fabric import FabricNode, FabricSocket
node = FabricNode.initialize(coord, num_processes=2, process_id=pid)
kv = node._kv
import brpc_tpu.policy
from brpc_tpu import rpc, ici
from brpc_tpu.rpc.socket import list_sockets
from brpc_tpu.ici.route import route_stats
from echo_pb2 import EchoRequest, EchoResponse
mesh = ici.IciMesh(); ici.IciMesh.set_default(mesh)

def fabric_socks():
    return [s for s in list_sockets() if isinstance(s, FabricSocket)]

CHUNK = 512 * 1024

if pid == 0:
    class EchoService(rpc.Service):
        @rpc.method(EchoRequest, EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = "srv0:" + request.message
            if len(cntl.request_attachment):
                cntl.response_attachment.append(cntl.request_attachment)
            done()

    server = rpc.Server(); server.add_service(EchoService())
    assert server.start("ici://0") == 0
    kv.key_value_set("shm_srv_up", "1")
    kv.wait_at_barrier("shm_echo_done", 180000)
    # the server's socket claimed the request payloads off its ring
    socks = fabric_socks()
    assert socks and socks[0].shm_bound(), "server socket has no shm ring"
    assert socks[0].shm_bytes_claimed >= 4 * CHUNK, \
        socks[0].shm_bytes_claimed
    server.stop()
    print("SHMR0_OK", flush=True)
else:
    kv.blocking_key_value_get("shm_srv_up", 60000)
    local_dev = next(i for i, d in enumerate(jax.devices())
                     if d.process_index == pid)
    payload = jax.device_put(jnp.arange(CHUNK, dtype=jnp.uint8) %% 251,
                             jax.devices()[local_dev])
    jax.block_until_ready(payload)
    expect = bytes(np.asarray(payload))
    ch = rpc.Channel()
    ch.init("ici://0", options=rpc.ChannelOptions(timeout_ms=120000,
                                                  max_retry=0))
    for i in range(4):
        cntl = rpc.Controller()
        cntl.request_attachment.append_device_array(payload)
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="m%%d" %% i),
                              EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "srv0:m%%d" %% i
        assert cntl.response_attachment.to_bytes() == expect, \
            "bounced payload corrupted"
    s = fabric_socks()[0]
    assert s.shm_bound(), "client socket has no shm ring"
    # the payloads rode the RING both ways — not the socket bulk conn
    assert s.shm_bytes_sent >= 4 * CHUNK, s.shm_bytes_sent
    assert s.shm_bytes_claimed >= 4 * CHUNK, s.shm_bytes_claimed
    assert s.bulk_bytes_sent == 0, s.bulk_bytes_sent
    rs = route_stats()
    assert rs.get("shm", {}).get("bytes", 0) >= 4 * CHUNK, rs
    assert s.describe_shm()["epoch"] == 1
    kv.wait_at_barrier("shm_echo_done", 180000)
    print("SHMR1_OK", flush=True)
"""


_SHM_KILL_CHILD = r"""
import os, sys, threading, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

pid = int(sys.argv[1]); coord = sys.argv[2]
from brpc_tpu.ici.fabric import FabricNode, FabricSocket
node = FabricNode.initialize(coord, num_processes=2, process_id=pid)
kv = node._kv
import brpc_tpu.policy
from brpc_tpu import rpc, ici
from brpc_tpu.rpc import fault_injection as fi
from brpc_tpu.rpc.socket import list_sockets
from echo_pb2 import EchoRequest, EchoResponse
mesh = ici.IciMesh(); ici.IciMesh.set_default(mesh)

def fabric_socks():
    return [s for s in list_sockets() if isinstance(s, FabricSocket)]

CHUNK = 256 * 1024
PHASE = 4
MODE = %(mode)r      # "kill" (segment dead now) or "midslot" (producer
                     # crash mid-copy via the byte watermark)

if pid == 0:
    total = [0]
    lock = threading.Lock()

    class Sink(rpc.Service):
        @rpc.method(EchoRequest, EchoResponse)
        def Push(self, cntl, request, response, done):
            with lock:
                total[0] += len(cntl.request_attachment)
            # verify every chunk byte-exactly — fallback must not
            # corrupt or reorder
            got = cntl.request_attachment.to_bytes()
            seq = int(request.message)
            want = bytes([seq %% 251]) * CHUNK
            assert got == want, "corrupt payload at seq %%d" %% seq
            response.message = str(total[0])
            done()

    server = rpc.Server(); server.add_service(Sink())
    assert server.start("ici://0") == 0
    kv.key_value_set("sk_srv_up", "1")
    kv.wait_at_barrier("sk_done", 300000)
    assert total[0] == 3 * PHASE * CHUNK, total[0]
    server.stop()
    print("SK0_OK", flush=True)
else:
    kv.blocking_key_value_get("sk_srv_up", 60000)
    local_dev = next(i for i, d in enumerate(jax.devices())
                     if d.process_index == pid)
    ch = rpc.Channel()
    ch.init("ici://0", options=rpc.ChannelOptions(timeout_ms=120000,
                                                  max_retry=0))

    def push(seq):
        arr = jax.device_put(jnp.full((CHUNK,), seq %% 251, jnp.uint8),
                             jax.devices()[local_dev])
        jax.block_until_ready(arr)
        cntl = rpc.Controller()
        cntl.request_attachment.append_device_array(arr)
        ch.call_method("Sink.Push", cntl,
                       EchoRequest(message=str(seq)), EchoResponse)
        assert not cntl.failed(), (seq, cntl.error_text)

    seq = 0
    # phase 1: healthy — chunks ride the ring
    for _ in range(PHASE):
        push(seq); seq += 1
    s = fabric_socks()[0]
    assert s.shm_bound() and s.shm_bytes_sent >= PHASE * CHUNK
    assert s.shm_epoch() == 1
    bulk_before = s.bulk_bytes_sent

    # CHAOS: kill the ring under the live control channel
    if MODE == "kill":
        with s._bulk_lock:
            h, lib = s._shm, s._shmlib
        lib.brpc_tpu_shm_chaos(h, fi.CHAOS_SEVER_NOW, 0)
    else:     # producer crash mid-slot: the NEXT ring write dies
              # half-copied without publishing
        with s._bulk_lock:
            h, lib = s._shm, s._shmlib
        lib.brpc_tpu_shm_chaos(h, fi.CHAOS_SEVER_AFTER_OUT_BYTES,
                               s.shm_bytes_sent + CHUNK // 2)

    # phase 2: degraded — ZERO client-visible failures, chunks fall
    # back to the socket bulk tier byte-exactly.  At least the first
    # degraded chunk MUST ride bulk; background revival may legally
    # reclaim the rest of the phase for the ring.
    for _ in range(PHASE):
        push(seq); seq += 1
    assert s.bulk_bytes_sent >= bulk_before + CHUNK, (
        s.bulk_bytes_sent, bulk_before)

    # phase 3: revival — a fresh segment re-establishes in the
    # background (epoch bumps) and the ring carries bytes again
    deadline = time.time() + 30
    while s.shm_epoch() < 2 and time.time() < deadline:
        time.sleep(0.05)
    assert s.shm_epoch() >= 2, "shm ring never re-established"
    shm_before = s.shm_bytes_sent
    for _ in range(PHASE):
        push(seq); seq += 1
    assert s.shm_bytes_sent >= shm_before + (PHASE - 1) * CHUNK, (
        s.shm_bytes_sent, shm_before)
    assert not s.failed, "socket died over an shm-plane fault"
    kv.wait_at_barrier("sk_done", 300000)
    print("SK1_OK", flush=True)
"""


_SHM_REFUSE_CHILD = r"""
import os, sys, threading, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

pid = int(sys.argv[1]); coord = sys.argv[2]
from brpc_tpu.ici.fabric import FabricNode, FabricSocket
node = FabricNode.initialize(coord, num_processes=2, process_id=pid)
kv = node._kv
import brpc_tpu.policy
from brpc_tpu import rpc, ici
from brpc_tpu.rpc import fault_injection as fi
from brpc_tpu.rpc.socket import list_sockets
from echo_pb2 import EchoRequest, EchoResponse
mesh = ici.IciMesh(); ici.IciMesh.set_default(mesh)

CHUNK = 256 * 1024

if pid == 0:
    # refuse the shm attach at HELLO: the pair must come up WITHOUT an
    # shm ring and serve byte-exact traffic on the socket bulk tier
    plan = fi.FabricFaultPlan(refuse_shm_handshakes=1)
    fi.install_fabric(plan)

    class EchoService(rpc.Service):
        @rpc.method(EchoRequest, EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = "ok"
            if len(cntl.request_attachment):
                cntl.response_attachment.append(cntl.request_attachment)
            done()

    server = rpc.Server(); server.add_service(EchoService())
    assert server.start("ici://0") == 0
    kv.key_value_set("sr_srv_up", "1")
    kv.wait_at_barrier("sr_done", 180000)
    assert plan.injected["refuse_shm"] == 1, plan.injected
    socks = [s for s in list_sockets() if isinstance(s, FabricSocket)]
    assert socks and not socks[0].shm_bound()
    server.stop()
    print("SR0_OK", flush=True)
else:
    kv.blocking_key_value_get("sr_srv_up", 60000)
    local_dev = next(i for i, d in enumerate(jax.devices())
                     if d.process_index == pid)
    payload = jax.device_put(jnp.arange(CHUNK, dtype=jnp.uint8) %% 251,
                             jax.devices()[local_dev])
    jax.block_until_ready(payload)
    expect = bytes(np.asarray(payload))
    ch = rpc.Channel()
    ch.init("ici://0", options=rpc.ChannelOptions(timeout_ms=120000,
                                                  max_retry=0))
    cntl = rpc.Controller()
    cntl.request_attachment.append_device_array(payload)
    ch.call_method("EchoService.Echo", cntl,
                   EchoRequest(message="x"), EchoResponse)
    assert not cntl.failed(), cntl.error_text
    assert cntl.response_attachment.to_bytes() == expect
    s = [s for s in list_sockets() if isinstance(s, FabricSocket)][0]
    assert not s.shm_bound(), "client bound shm despite server refusal"
    assert s.shm_bytes_sent == 0
    assert s.bulk_bytes_sent >= CHUNK       # the bulk tier carried it
    kv.wait_at_barrier("sr_done", 180000)
    print("SR1_OK", flush=True)
"""


_SHM_STREAM_CHILD = r"""
import os, sys, threading, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1]); coord = sys.argv[2]
from brpc_tpu.ici.fabric import FabricNode, FabricSocket
node = FabricNode.initialize(coord, num_processes=2, process_id=pid)
kv = node._kv
import brpc_tpu.policy
from brpc_tpu import rpc, ici
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc.socket import list_sockets
from echo_pb2 import EchoRequest, EchoResponse
mesh = ici.IciMesh(); ici.IciMesh.set_default(mesh)

CHUNK = 256 * 1024
N = 24

def body_for(seq):
    return b"%%08d" %% seq + bytes([(seq * 7 + 3) %% 251]) * (CHUNK - 8)

if pid == 0:
    state = {"next": 0, "bad": 0}
    done_evt = threading.Event()

    class Sink:
        def on_received_messages(self, sid, msgs):
            for m in msgs:
                if m.to_bytes() != body_for(state["next"]):
                    state["bad"] += 1
                state["next"] += 1
        def on_closed(self, sid):
            done_evt.set()

    class StreamSvc(rpc.Service):
        @rpc.method(EchoRequest, EchoResponse)
        def Start(self, cntl, request, response, done):
            rpc.stream_accept(cntl, rpc.StreamOptions(handler=Sink()))
            response.message = "ok"
            done()

    server = rpc.Server(); server.add_service(StreamSvc())
    assert server.start("ici://0") == 0
    kv.key_value_set("ss_srv_up", "1")
    assert done_evt.wait(180), ("stream never closed", state["next"])
    assert state["next"] == N and state["bad"] == 0, state
    socks = [s for s in list_sockets() if isinstance(s, FabricSocket)]
    assert socks and socks[0].shm_bytes_claimed >= N * CHUNK
    kv.wait_at_barrier("ss_done", 120000)
    server.stop()
    print("SS0_OK", flush=True)
else:
    kv.blocking_key_value_get("ss_srv_up", 60000)
    ch = rpc.Channel()
    ch.init("ici://0", options=rpc.ChannelOptions(timeout_ms=60000,
                                                  max_retry=0))
    cntl = rpc.Controller()
    stream = rpc.stream_create(cntl, rpc.StreamOptions(max_buf_size=8 << 20))
    ch.call_method("StreamSvc.Start", cntl,
                   EchoRequest(message="s"), EchoResponse)
    assert not cntl.failed(), cntl.error_text
    assert stream.wait_connected(10)
    for seq in range(N):
        assert stream.write(IOBuf(body_for(seq)), timeout=30) == 0
    s = [s for s in list_sockets() if isinstance(s, FabricSocket)][0]
    # every DATA frame's payload rode the RING (FRAME_DATA_SHM), none
    # the socket bulk conn
    assert s.shm_bytes_sent >= N * CHUNK, s.shm_bytes_sent
    assert s.bulk_bytes_sent == 0, s.bulk_bytes_sent
    stream.close()
    kv.wait_at_barrier("ss_done", 120000)
    print("SS1_OK", flush=True)
"""


_SHM_STREAM_KILL_CHILD = r"""
import os, sys, threading, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1]); coord = sys.argv[2]
from brpc_tpu.ici.fabric import FabricNode, FabricSocket
node = FabricNode.initialize(coord, num_processes=2, process_id=pid)
kv = node._kv
import brpc_tpu.policy
from brpc_tpu import rpc, ici
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc import fault_injection as fi
from brpc_tpu.rpc.socket import list_sockets
from echo_pb2 import EchoRequest, EchoResponse
mesh = ici.IciMesh(); ici.IciMesh.set_default(mesh)

CHUNK = 200 * 1024
N = 18          # 6 pre-kill (descriptors BATCHED, some unflushed when
                # the ring dies), 6 degraded, 6 post-revival

def body_for(seq):
    return b"%%08d" %% seq + bytes([(seq * 13 + 1) %% 251]) * (CHUNK - 8)

if pid == 0:
    state = {"next": 0, "bad": 0}
    done_evt = threading.Event()

    class Sink:
        def on_received_messages(self, sid, msgs):
            for m in msgs:
                if m.to_bytes() != body_for(state["next"]):
                    state["bad"] += 1
                state["next"] += 1
        def on_closed(self, sid):
            done_evt.set()

    class StreamSvc(rpc.Service):
        @rpc.method(EchoRequest, EchoResponse)
        def Start(self, cntl, request, response, done):
            rpc.stream_accept(cntl, rpc.StreamOptions(handler=Sink()))
            response.message = "ok"
            done()

    server = rpc.Server(); server.add_service(StreamSvc())
    assert server.start("ici://0") == 0
    kv.key_value_set("sks_srv_up", "1")
    # EVERY frame must arrive, in order, byte-exact — the kill lands
    # while descriptors for published ring frames are still batched
    # unflushed, and _F_SHM_DOWN reaches us BEFORE them: the retired
    # ring must stay claimable or those frames are lost (regression:
    # the receiver used to close its handle on DOWN and fail the
    # stream with rc -2 claims)
    assert done_evt.wait(180), ("stream never closed", state["next"])
    assert state["next"] == N, state
    assert state["bad"] == 0, state
    kv.wait_at_barrier("sks_done", 180000)
    server.stop()
    print("SKS0_OK", flush=True)
else:
    kv.blocking_key_value_get("sks_srv_up", 60000)
    ch = rpc.Channel()
    ch.init("ici://0", options=rpc.ChannelOptions(timeout_ms=60000,
                                                  max_retry=0))
    cntl = rpc.Controller()
    stream = rpc.stream_create(cntl, rpc.StreamOptions(max_buf_size=8 << 20))
    ch.call_method("StreamSvc.Start", cntl,
                   EchoRequest(message="s"), EchoResponse)
    assert not cntl.failed(), cntl.error_text
    assert stream.wait_connected(10)
    seq = 0
    for _ in range(6):
        assert stream.write(IOBuf(body_for(seq)), timeout=30) == 0
        seq += 1
    s = [x for x in list_sockets() if isinstance(x, FabricSocket)][0]
    assert s.shm_bound() and s.shm_epoch() == 1
    # kill the segment with published-but-unannounced descriptors
    # pending (batch default 32 >> 6, nothing flushed yet)
    with s._bulk_lock:
        h, lib = s._shm, s._shmlib
    lib.brpc_tpu_shm_chaos(h, fi.CHAOS_SEVER_NOW, 0)
    for _ in range(6):
        assert stream.write(IOBuf(body_for(seq)), timeout=30) == 0
        seq += 1
    deadline = time.time() + 30
    while s.shm_epoch() < 2 and time.time() < deadline:
        time.sleep(0.05)
    assert s.shm_epoch() >= 2, "shm ring never re-established"
    for _ in range(6):
        assert stream.write(IOBuf(body_for(seq)), timeout=30) == 0
        seq += 1
    stream.close()
    assert not s.failed, "socket died over an shm-plane fault"
    kv.wait_at_barrier("sks_done", 180000)
    print("SKS1_OK", flush=True)
"""


def test_shm_route_carries_attachments_byte_exact():
    from test_fabric import _run_pair
    outs = _run_pair(_SHM_ROUTE_CHILD % {"repo": REPO}, timeout=240)
    assert "SHMR0_OK" in outs[0]
    assert "SHMR1_OK" in outs[1]


@pytest.mark.chaos
def test_shm_segment_kill_falls_back_to_bulk_and_revives():
    from test_fabric import _run_pair
    outs = _run_pair(_SHM_KILL_CHILD % {"repo": REPO, "mode": "kill"},
                     timeout=300)
    assert "SK0_OK" in outs[0]
    assert "SK1_OK" in outs[1]


@pytest.mark.chaos
def test_shm_producer_crash_mid_slot_falls_back_and_revives():
    from test_fabric import _run_pair
    outs = _run_pair(_SHM_KILL_CHILD % {"repo": REPO, "mode": "midslot"},
                     timeout=300)
    assert "SK0_OK" in outs[0]
    assert "SK1_OK" in outs[1]


@pytest.mark.chaos
def test_shm_refused_handshake_degrades_to_bulk():
    from test_fabric import _run_pair
    outs = _run_pair(_SHM_REFUSE_CHILD % {"repo": REPO}, timeout=240)
    assert "SR0_OK" in outs[0]
    assert "SR1_OK" in outs[1]


def test_streaming_rides_shm_ring_byte_exact():
    from test_fabric import _run_pair
    outs = _run_pair(_SHM_STREAM_CHILD % {"repo": REPO}, timeout=240)
    assert "SS0_OK" in outs[0]
    assert "SS1_OK" in outs[1]


@pytest.mark.chaos
def test_shm_kill_mid_stream_with_batched_descriptors_loses_nothing():
    """Segment kill while descriptors for published ring frames are
    still COALESCED unflushed: every frame must still arrive byte-exact
    (the retired ring stays claimable after _F_SHM_DOWN), later frames
    fall back to the socket bulk tier without a single client-visible
    failure, and the ring revives for the tail."""
    from test_fabric import _run_pair
    outs = _run_pair(_SHM_STREAM_KILL_CHILD % {"repo": REPO},
                     timeout=300)
    assert "SKS0_OK" in outs[0]
    assert "SKS1_OK" in outs[1]


# ======================================================================
# STRIPED shm (ISSUE 12): N independent SPSC ring pairs per segment on
# multi-core hosts.  Units drive the v2 native API directly; the
# 2-process legs force ici_shm_stripes=4 (this CI host is 1-core, where
# auto keeps the v1 single ring — byte-identical to PR 10, which the
# unchanged tests above keep proving) and assert the route per-stripe:
# round-robin spread for unary attachment frames, ONE stripe per stream
# (affinity by stream id), and stripe-kill degrading the WHOLE plane
# in-frame with zero client-visible failures.
# ======================================================================


class TestShmStripedUnits:
    def test_striped_create_attach_byte_exact_per_stripe(self):
        lib = _lib()
        if not hasattr(lib, "brpc_tpu_shm_create2"):
            pytest.skip("native core without striped shm")
        name = f"brpc_tpu_stripe_u1.{os.getpid()}"
        lib.brpc_tpu_shm_unlink(name.encode())
        h0 = lib.brpc_tpu_shm_create2(name.encode(), 128 * 1024, 4)
        if not h0:
            pytest.skip("/dev/shm unavailable in this sandbox")
        h1 = lib.brpc_tpu_shm_attach(name.encode())
        assert h1, "v2 attach failed (layout auto-detect)"
        assert lib.brpc_tpu_shm_unlink(name.encode()) == 0
        assert lib.brpc_tpu_shm_stripes(h0) == 4
        assert lib.brpc_tpu_shm_stripes(h1) == 4
        try:
            for stripe in range(4):
                payload = bytes([(stripe * 31 + i) % 251
                                 for i in range(5000)])
                buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(
                    payload)
                assert lib.brpc_tpu_shm_send2(
                    h0, stripe, 100 + stripe, buf, len(payload),
                    5_000_000) == 0
            for stripe in range(4):
                out, olen = u8p(), ctypes.c_uint64()
                assert lib.brpc_tpu_shm_recv2(
                    h1, stripe, 100 + stripe, 5_000_000,
                    ctypes.byref(out), ctypes.byref(olen)) == 0
                got = ctypes.string_at(out, olen.value)
                want = bytes([(stripe * 31 + i) % 251
                              for i in range(5000)])
                assert got == want, f"stripe {stripe} corrupt"
                lib.brpc_tpu_shm_release(h1, out, olen.value)
            # per-stripe truth + conn aggregate
            st = (ctypes.c_uint64 * 6)()
            total = 0
            for stripe in range(4):
                assert lib.brpc_tpu_shm_stripe_stats(
                    h0, stripe, st, 6) == 6
                assert st[0] == 5000, (stripe, st[0])
                total += st[0]
            assert lib.brpc_tpu_shm_stats(h0, st, 6) == 6
            assert st[0] == total
            assert st[5] == 128 * 1024       # per-stripe ring capacity
            # a stripe that does not exist fails cleanly, plane healthy
            one = (ctypes.c_uint8 * 4).from_buffer_copy(b"abcd")
            assert lib.brpc_tpu_shm_send2(h0, 7, 1, one, 4, 1000) == -1
            assert lib.brpc_tpu_shm_alive(h0)
        finally:
            lib.brpc_tpu_shm_close(h0)
            lib.brpc_tpu_shm_close(h1)

    def test_stripe_kill_degrades_whole_plane(self):
        """Chaos mode 5: one stripe's next send dies and the SHARED
        death word takes the plane with it — health is segment-wide,
        exactly the single-ring discipline; a claimed slot on another
        stripe stays readable until released (deferred unmap)."""
        lib = _lib()
        if not hasattr(lib, "brpc_tpu_shm_create2"):
            pytest.skip("native core without striped shm")
        name = f"brpc_tpu_stripe_u2.{os.getpid()}"
        lib.brpc_tpu_shm_unlink(name.encode())
        h0 = lib.brpc_tpu_shm_create2(name.encode(), 128 * 1024, 4)
        if not h0:
            pytest.skip("/dev/shm unavailable in this sandbox")
        h1 = lib.brpc_tpu_shm_attach(name.encode())
        assert h1
        lib.brpc_tpu_shm_unlink(name.encode())
        one = (ctypes.c_uint8 * 64).from_buffer_copy(b"\x5a" * 64)
        assert lib.brpc_tpu_shm_send2(h0, 0, 0x901, one, 64,
                                      1_000_000) == 0
        out, olen = u8p(), ctypes.c_uint64()
        assert lib.brpc_tpu_shm_recv2(h1, 0, 0x901, 1_000_000,
                                      ctypes.byref(out),
                                      ctypes.byref(olen)) == 0
        assert lib.brpc_tpu_shm_chaos(h0, 5, 2) == 0   # arm stripe-2 kill
        assert lib.brpc_tpu_shm_send2(h0, 2, 0x902, one, 64,
                                      1_000_000) == -1
        assert lib.brpc_tpu_shm_alive(h0) == 0
        assert lib.brpc_tpu_shm_alive(h1) == 0
        # sends on OTHER stripes fail too: the plane degrades as one
        assert lib.brpc_tpu_shm_send2(h0, 1, 0x903, one, 64, 1000) == -1
        # parked frame published before death is still claimable;
        # a missing one fails fast (-2), no timeout burn
        o2, l2 = u8p(), ctypes.c_uint64()
        assert lib.brpc_tpu_shm_recv2(h1, 3, 0xBEEF, 5_000_000,
                                      ctypes.byref(o2),
                                      ctypes.byref(l2)) == -2
        assert ctypes.string_at(out, olen.value) == b"\x5a" * 64
        lib.brpc_tpu_shm_close(h0)
        lib.brpc_tpu_shm_close(h1)       # claim out: unmap deferred
        assert ctypes.string_at(out, 1) == b"\x5a"
        lib.brpc_tpu_shm_release(h1, out, olen.value)

    def test_create2_single_stripe_is_v1_layout(self):
        """nstripes<=1 delegates to the v1 creator: the 1-core shape is
        the SAME file format and machinery as PR 10, byte-identical."""
        lib = _lib()
        if not hasattr(lib, "brpc_tpu_shm_create2"):
            pytest.skip("native core without striped shm")
        name = f"brpc_tpu_stripe_u3.{os.getpid()}"
        lib.brpc_tpu_shm_unlink(name.encode())
        h0 = lib.brpc_tpu_shm_create2(name.encode(), 64 * 1024, 1)
        if not h0:
            pytest.skip("/dev/shm unavailable in this sandbox")
        try:
            with open(f"/dev/shm/{name}", "rb") as f:
                magic = f.read(4)
            # v1 magic 0x53484d31 little-endian on disk = b"1MHS"
            assert magic == b"1MHS", magic
            assert lib.brpc_tpu_shm_stripes(h0) == 1
        finally:
            lib.brpc_tpu_shm_unlink(name.encode())
            lib.brpc_tpu_shm_close(h0)

    def test_stripe_resolution_and_uuid_tagging(self, monkeypatch):
        """auto = 1 on a 1-core host (the byte-identical path), else
        min(4, cores); the uuid tag rides the top byte and decodes
        clamped."""
        from brpc_tpu.ici import fabric as fab
        from brpc_tpu.butil import flags as _fl
        prev = _fl.get_flag("ici_shm_stripes")
        try:
            _fl.set_flag("ici_shm_stripes", 0)
            monkeypatch.setattr(fab._os, "cpu_count", lambda: 1)
            assert fab._resolve_shm_stripes() == 1
            monkeypatch.setattr(fab._os, "cpu_count", lambda: 8)
            assert fab._resolve_shm_stripes() == 4
            monkeypatch.setattr(fab._os, "cpu_count", lambda: 2)
            assert fab._resolve_shm_stripes() == 2
            _fl.set_flag("ici_shm_stripes", 6)
            assert fab._resolve_shm_stripes() == 6
        finally:
            _fl.set_flag("ici_shm_stripes", prev)
        # stripe decode: identity at 1 stripe, clamped at N
        sof = fab.FabricSocket._shm_stripe_of
        assert sof(0x123, 1) == 0
        assert sof((3 << 56) | 0x123, 4) == 3
        assert sof((9 << 56) | 0x123, 4) == 3     # clamped, never OOR


_SHM_STRIPED_CHILD = r"""
import os, sys, threading, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

pid = int(sys.argv[1]); coord = sys.argv[2]
from brpc_tpu.butil import flags as _fl
from brpc_tpu.ici.fabric import FabricNode, FabricSocket
_fl.set_flag("ici_shm_stripes", 4)      # force striping on this 1-core CI
node = FabricNode.initialize(coord, num_processes=2, process_id=pid)
kv = node._kv
import brpc_tpu.policy
from brpc_tpu import rpc, ici
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.rpc.socket import list_sockets
from brpc_tpu.ici.route import route_stats
from echo_pb2 import EchoRequest, EchoResponse
mesh = ici.IciMesh(); ici.IciMesh.set_default(mesh)

def fabric_socks():
    return [s for s in list_sockets() if isinstance(s, FabricSocket)]

def stripe_bytes():
    rs = route_stats()
    return {k: v["bytes"] for k, v in rs.items()
            if k.startswith("shm_stripe_")}

CHUNK = 512 * 1024
SCHUNK = 256 * 1024
NSTREAM = 6

if pid == 0:
    state = {"next": 0, "bad": 0}
    done_evt = threading.Event()

    class Sink:
        def on_received_messages(self, sid, msgs):
            for m in msgs:
                want = b"%%08d" %% state["next"] + \
                    bytes([(state["next"] * 7 + 3) %% 251]) * (SCHUNK - 8)
                if m.to_bytes() != want:
                    state["bad"] += 1
                state["next"] += 1
        def on_closed(self, sid):
            done_evt.set()

    class EchoService(rpc.Service):
        @rpc.method(EchoRequest, EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = "srv0:" + request.message
            if len(cntl.request_attachment):
                cntl.response_attachment.append(cntl.request_attachment)
            done()

    class StreamSvc(rpc.Service):
        @rpc.method(EchoRequest, EchoResponse)
        def Start(self, cntl, request, response, done):
            rpc.stream_accept(cntl, rpc.StreamOptions(handler=Sink()))
            response.message = "ok"
            done()

    server = rpc.Server()
    server.add_service(EchoService()); server.add_service(StreamSvc())
    assert server.start("ici://0") == 0
    kv.key_value_set("sst_srv_up", "1")
    assert done_evt.wait(180), ("stream never closed", state["next"])
    assert state["next"] == NSTREAM and state["bad"] == 0, state
    socks = fabric_socks()
    assert socks and socks[0].shm_bound()
    d = socks[0].describe_shm()
    assert d["stripes"] == 4, d
    # the server's RESPONSES round-robined its stripes too
    sb = stripe_bytes()
    assert sum(sb.values()) >= 8 * CHUNK, sb
    kv.wait_at_barrier("sst_done", 180000)
    server.stop()
    print("SST0_OK", flush=True)
else:
    kv.blocking_key_value_get("sst_srv_up", 60000)
    local_dev = next(i for i, d in enumerate(jax.devices())
                     if d.process_index == pid)
    payload = jax.device_put(jnp.arange(CHUNK, dtype=jnp.uint8) %% 251,
                             jax.devices()[local_dev])
    jax.block_until_ready(payload)
    expect = bytes(np.asarray(payload))
    ch = rpc.Channel()
    ch.init("ici://0", options=rpc.ChannelOptions(timeout_ms=120000,
                                                  max_retry=0))
    # phase 1: 8 unary attachment echoes — round-robin should spread
    # the sends over EVERY stripe (8 frames, 4 stripes)
    for i in range(8):
        cntl = rpc.Controller()
        cntl.request_attachment.append_device_array(payload)
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="m%%d" %% i),
                              EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "srv0:m%%d" %% i
        assert cntl.response_attachment.to_bytes() == expect
    s = fabric_socks()[0]
    d = s.describe_shm()
    assert d["stripes"] == 4, d
    sb1 = stripe_bytes()
    hit = [k for k, v in sb1.items() if v >= CHUNK]
    assert len(hit) == 4, ("round-robin left stripes idle", sb1)
    assert s.bulk_bytes_sent == 0, s.bulk_bytes_sent
    # phase 2: ONE stream — affinity pins every DATA frame to a single
    # stripe (per-stream ordering decided by one ring)
    cntl = rpc.Controller()
    stream = rpc.stream_create(cntl,
                               rpc.StreamOptions(max_buf_size=8 << 20))
    ch.call_method("StreamSvc.Start", cntl,
                   EchoRequest(message="s"), EchoResponse)
    assert not cntl.failed(), cntl.error_text
    assert stream.wait_connected(10)
    for seq in range(NSTREAM):
        body = b"%%08d" %% seq + \
            bytes([(seq * 7 + 3) %% 251]) * (SCHUNK - 8)
        assert stream.write(IOBuf(body), timeout=30) == 0
    stream.close()
    sb2 = stripe_bytes()
    grew = [k for k in sb2
            if sb2[k] - sb1.get(k, 0) > 0]
    assert len(grew) == 1, ("stream frames crossed stripes", sb1, sb2)
    assert sb2[grew[0]] - sb1.get(grew[0], 0) >= NSTREAM * SCHUNK
    kv.wait_at_barrier("sst_done", 180000)
    print("SST1_OK", flush=True)
"""


def test_striped_shm_round_robin_and_stream_affinity_2proc():
    """Forced 4-stripe plane over a real fabric pair: unary attachment
    frames round-robin over every stripe (per-stripe counters assert
    the route), ONE stream's frames stay on ONE stripe (affinity), all
    byte-exact, zero bulk fallbacks."""
    from test_fabric import _run_pair
    outs = _run_pair(_SHM_STRIPED_CHILD % {"repo": REPO}, timeout=300)
    assert "SST0_OK" in outs[0]
    assert "SST1_OK" in outs[1]


_SHM_STRIPED_KILL_CHILD = r"""
import os, sys, threading, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np

pid = int(sys.argv[1]); coord = sys.argv[2]
from brpc_tpu.butil import flags as _fl
from brpc_tpu.ici.fabric import FabricNode, FabricSocket
_fl.set_flag("ici_shm_stripes", 4)
node = FabricNode.initialize(coord, num_processes=2, process_id=pid)
kv = node._kv
import brpc_tpu.policy
from brpc_tpu import rpc, ici
from brpc_tpu.rpc.socket import list_sockets
from echo_pb2 import EchoRequest, EchoResponse
mesh = ici.IciMesh(); ici.IciMesh.set_default(mesh)

def fabric_socks():
    return [s for s in list_sockets() if isinstance(s, FabricSocket)]

CHUNK = 256 * 1024
PHASE = 4

if pid == 0:
    total = [0]
    lock = threading.Lock()

    class Sink(rpc.Service):
        @rpc.method(EchoRequest, EchoResponse)
        def Push(self, cntl, request, response, done):
            got = cntl.request_attachment.to_bytes()
            seq = int(request.message)
            want = bytes([seq %% 251]) * CHUNK
            assert got == want, "corrupt payload at seq %%d" %% seq
            with lock:
                total[0] += 1
            response.message = str(total[0])
            done()

    server = rpc.Server(); server.add_service(Sink())
    assert server.start("ici://0") == 0
    kv.key_value_set("stk_srv_up", "1")
    kv.wait_at_barrier("stk_done", 240000)
    assert total[0] == 3 * PHASE, total[0]
    server.stop()
    print("STK0_OK", flush=True)
else:
    kv.blocking_key_value_get("stk_srv_up", 60000)
    local_dev = next(i for i, d in enumerate(jax.devices())
                     if d.process_index == pid)
    ch = rpc.Channel()
    ch.init("ici://0", options=rpc.ChannelOptions(timeout_ms=120000,
                                                  max_retry=0))

    def push(seq):
        arr = jax.device_put(
            jnp.full(CHUNK, seq %% 251, dtype=jnp.uint8),
            jax.devices()[local_dev])
        jax.block_until_ready(arr)
        cntl = rpc.Controller()
        cntl.request_attachment.append_device_array(arr)
        ch.call_method("Sink.Push", cntl,
                       EchoRequest(message=str(seq)), EchoResponse)
        assert not cntl.failed(), (seq, cntl.error_text)

    seq = 0
    for _ in range(PHASE):            # striped plane up
        push(seq); seq += 1
    s = fabric_socks()[0]
    assert s.shm_bound() and s.describe_shm()["stripes"] == 4
    epoch0 = s.shm_epoch()
    # stripe-targeted kill: stripe 1's NEXT send dies and takes the
    # whole plane (shared death word) — the degrade must be IN-FRAME
    with s._bulk_lock:
        h, lib = s._shm, s._shmlib
    assert lib.brpc_tpu_shm_chaos(h, 5, 1) == 0
    for _ in range(PHASE):            # degraded: bulk tier, zero failures
        push(seq); seq += 1
    assert s.shm_bytes_sent < 3 * PHASE * CHUNK   # some went bulk
    assert s.bulk_bytes_sent >= CHUNK, s.bulk_bytes_sent
    deadline = time.time() + 60
    while s.shm_epoch() == epoch0 and time.time() < deadline:
        time.sleep(0.05)
    assert s.shm_epoch() > epoch0, "striped ring never re-established"
    assert s.describe_shm()["stripes"] == 4   # revived STRIPED
    for _ in range(PHASE):            # revived
        push(seq); seq += 1
    assert not s.failed
    kv.wait_at_barrier("stk_done", 240000)
    print("STK1_OK", flush=True)
"""


@pytest.mark.chaos
def test_striped_shm_stripe_kill_degrades_in_frame_and_revives():
    """Stripe-kill on a live striped plane: the killed stripe's send
    fails IN-FRAME, the whole plane degrades to the socket bulk tier
    with zero client-visible failures, and revival comes back striped
    (epoch bump, stripes=4)."""
    from test_fabric import _run_pair
    outs = _run_pair(_SHM_STRIPED_KILL_CHILD % {"repo": REPO},
                     timeout=360)
    assert "STK0_OK" in outs[0]
    assert "STK1_OK" in outs[1]
