"""HTTP/2 + gRPC protocol tests (reference
test/brpc_grpc_protocol_unittest.cpp pattern: frame/HPACK golden checks +
in-process client↔server)."""
import struct

import pytest

import brpc_tpu.policy
from brpc_tpu import rpc
from brpc_tpu.policy import grpc as g2
from brpc_tpu.policy import hpack
from brpc_tpu.rpc import errors
from tests.echo_pb2 import EchoRequest, EchoResponse

_seq = [7000]


def unique(p):
    _seq[0] += 1
    return f"{p}-{_seq[0]}"


class TestHpack:
    def test_static_indexed_roundtrip(self):
        enc, dec = hpack.Encoder(), hpack.Decoder()
        headers = [(b":method", b"POST"), (b":scheme", b"http"),
                   (b":status", b"200")]
        assert dec.decode(enc.encode(headers)) == headers

    def test_literal_roundtrip(self):
        enc, dec = hpack.Encoder(), hpack.Decoder()
        headers = [(b":path", b"/Echo/Do"), (b"grpc-status", b"0"),
                   (b"x-custom", b"v" * 300)]
        assert dec.decode(enc.encode(headers)) == headers

    def test_dynamic_table_incremental(self):
        # encode literal-with-incremental-indexing by hand; decoder must
        # index it and resolve a later indexed reference
        dec = hpack.Decoder()
        name, value = b"x-session", b"abc"
        block = (bytes([0x40])                    # literal w/ indexing, new name
                 + bytes([len(name)]) + name
                 + bytes([len(value)]) + value)
        assert dec.decode(block) == [(name, value)]
        # index 62 = first dynamic entry
        assert dec.decode(bytes([0x80 | 62])) == [(name, value)]

    def test_huffman_decode(self):
        # "www.example.com" huffman-coded (RFC 7541 C.4.1)
        data = bytes.fromhex("f1e3c2e5f23a6ba0ab90f4ff")
        assert hpack.huffman_decode(data) == b"www.example.com"

    def test_integer_coding(self):
        assert hpack._encode_int(10, 5, 0) == bytes([10])
        raw = hpack._encode_int(1337, 5, 0)
        v, pos = hpack._decode_int(raw, 0, 5)
        assert v == 1337 and pos == len(raw)


class TestFrames:
    def test_frame_header(self):
        f = g2.frame(g2.FRAME_DATA, g2.FLAG_END_STREAM, 5, b"hello")
        assert len(f) == 9 + 5
        assert int.from_bytes(f[:3], "big") == 5
        assert f[3] == g2.FRAME_DATA
        assert f[4] == g2.FLAG_END_STREAM
        assert int.from_bytes(f[5:9], "big") == 5

    def test_grpc_message_framing(self):
        m = g2.grpc_message(b"PAYLOAD")
        assert m[0] == 0
        assert struct.unpack(">I", m[1:5])[0] == 7
        assert g2.split_grpc_messages(m + g2.grpc_message(b"x")) == \
            [b"PAYLOAD", b"x"]


class GrpcEchoService(rpc.Service):
    SERVICE_NAME = "EchoService"

    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = "grpc:" + request.message
        done()

    @rpc.method(EchoRequest, EchoResponse)
    def Fail(self, cntl, request, response, done):
        cntl.set_failed(errors.EINTERNAL, "grpc boom")
        done()


class TestGrpcEndToEnd:
    def _start(self, transport="mem"):
        server = rpc.Server()
        server.add_service(GrpcEchoService())
        if transport == "mem":
            name = unique("grpc")
            assert server.start(f"mem://{name}") == 0
            target = f"mem://{name}"
        else:
            assert server.start("127.0.0.1:0") == 0
            target = f"127.0.0.1:{server.listen_port}"
        ch = rpc.Channel()
        ch.init(target, options=rpc.ChannelOptions(protocol="grpc",
                                                   timeout_ms=5000))
        return server, ch

    def test_unary_call_mem(self):
        server, ch = self._start("mem")
        try:
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="hi"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "grpc:hi"
        finally:
            server.stop()

    def test_unary_call_tcp(self):
        server, ch = self._start("tcp")
        try:
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="tcp"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "grpc:tcp"
        finally:
            server.stop()

    def test_multiple_calls_one_connection(self):
        server, ch = self._start("mem")
        try:
            for i in range(10):
                cntl = rpc.Controller()
                resp = ch.call_method("EchoService.Echo", cntl,
                                      EchoRequest(message=f"n{i}"),
                                      EchoResponse)
                assert not cntl.failed(), cntl.error_text
                assert resp.message == f"grpc:n{i}"
        finally:
            server.stop()

    def test_server_error_maps_to_grpc_status(self):
        server, ch = self._start("mem")
        try:
            cntl = rpc.Controller()
            ch.call_method("EchoService.Fail", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert cntl.failed()
            assert "grpc boom" in cntl.error_text
        finally:
            server.stop()

    def test_unknown_method_is_unimplemented(self):
        server, ch = self._start("mem")
        try:
            cntl = rpc.Controller()
            ch.call_method("EchoService.Nope", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert cntl.failed()
            assert cntl.error_code == errors.ENOMETHOD
        finally:
            server.stop()

    def test_grpc_timeout_header_propagates_deadline(self):
        """The gRPC spec's grpc-timeout header crosses the wire onto
        cntl.method_deadline — the SAME server-side field tpu_std sets,
        so handler code is transport-independent."""
        import time as _time
        seen = {}

        class DeadlineProbe(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                seen["deadline"] = cntl.method_deadline
                seen["now"] = _time.monotonic()
                response.message = "ok"
                done()

        server = rpc.Server()
        server.add_service(DeadlineProbe())
        name = unique("grpc-deadline")
        assert server.start(f"mem://{name}") == 0
        try:
            ch = rpc.Channel()
            ch.init(f"mem://{name}",
                    options=rpc.ChannelOptions(protocol="grpc",
                                               timeout_ms=2345))
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="x"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "ok"
            assert seen["deadline"] is not None
            left = seen["deadline"] - seen["now"]
            # remaining budget: positive, and no more than the client's
            # 2345ms total
            assert 0 < left <= 2.345 + 0.05, left
        finally:
            server.stop()

    def test_grpc_server_enforces_max_concurrency(self):
        """ServerOptions(max_concurrency) must produce RESOURCE_EXHAUSTED
        over grpc like every other protocol (overload protection)."""
        import threading
        gate = threading.Event()
        entered = threading.Event()

        class Slow(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                entered.set()
                gate.wait(10)
                response.message = "done"
                done()

        opts = rpc.ServerOptions()
        opts.max_concurrency = 1
        server = rpc.Server(opts)
        server.add_service(Slow())
        name = unique("grpc-limit")
        assert server.start(f"mem://{name}") == 0
        try:
            ch = rpc.Channel()
            ch.init(f"mem://{name}",
                    options=rpc.ChannelOptions(protocol="grpc",
                                               timeout_ms=15000))
            first = {}

            def occupy():
                c = rpc.Controller()
                r = ch.call_method("EchoService.Echo", c,
                                   EchoRequest(message="a"), EchoResponse)
                first["failed"] = c.failed()
                first["resp"] = r

            t = threading.Thread(target=occupy)
            t.start()
            assert entered.wait(10)          # slot occupied
            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="b"), EchoResponse)
            assert cntl.failed()
            assert cntl.error_code == errors.ELIMIT
            gate.set()
            t.join(10)
            assert first["failed"] is False
            assert first["resp"].message == "done"
        finally:
            gate.set()
            server.stop()

    def test_grpc_timeout_unit_parsing(self):
        from brpc_tpu.policy.grpc import parse_grpc_timeout_ms
        assert parse_grpc_timeout_ms(b"100m") == 100
        assert parse_grpc_timeout_ms(b"2S") == 2000
        assert parse_grpc_timeout_ms(b"1M") == 60000
        assert parse_grpc_timeout_ms(b"500u") == 1   # rounds up to >=1ms
        assert parse_grpc_timeout_ms(b"") is None
        assert parse_grpc_timeout_ms(b"abcm") is None
        assert parse_grpc_timeout_ms(b"100x") is None

    def test_status_codes_map_both_directions(self):
        """ELIMIT → RESOURCE_EXHAUSTED(8) on the wire → ELIMIT back at
        the client (reference grpc.cpp ErrorCode↔GrpcStatus)."""
        class Limited(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                cntl.set_failed(errors.ELIMIT, "too busy")
                done()

        server = rpc.Server()
        server.add_service(Limited())
        name = unique("grpc-status")
        assert server.start(f"mem://{name}") == 0
        try:
            ch = rpc.Channel()
            ch.init(f"mem://{name}",
                    options=rpc.ChannelOptions(protocol="grpc",
                                               timeout_ms=5000))
            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert cntl.failed()
            assert cntl.error_code == errors.ELIMIT
            assert "too busy" in cntl.error_text
        finally:
            server.stop()

    def test_large_message_crosses_flow_control_window(self):
        """A message several times the 65535-byte default window only
        completes if WINDOW_UPDATE credit is honored both directions
        (VERDICT r3 #4 done-criterion)."""
        server, ch = self._start("mem")
        try:
            big = "x" * 300_000
            cntl = rpc.Controller()
            cntl.timeout_ms = 30000
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message=big), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "grpc:" + big
        finally:
            server.stop()

    def test_multiplexed_concurrent_calls(self):
        """Many streams interleaved on ONE h2 connection from concurrent
        threads — correlation by stream id must never cross wires."""
        import threading
        server, ch = self._start("mem")
        errs = []
        try:
            def worker(wid):
                try:
                    for i in range(8):
                        cntl = rpc.Controller()
                        msg = f"w{wid}:{i}:" + "y" * (wid * 997)
                        resp = ch.call_method("EchoService.Echo", cntl,
                                              EchoRequest(message=msg),
                                              EchoResponse)
                        assert not cntl.failed(), cntl.error_text
                        assert resp.message == "grpc:" + msg
                except Exception as e:   # pragma: no cover
                    errs.append(e)

            threads = [threading.Thread(target=worker, args=(w,))
                       for w in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errs, errs
        finally:
            server.stop()


class _FakeH2Socket:
    """Capture-only socket for frame-layer unit tests (mimics the real
    Socket's failure-hook contract)."""

    def __init__(self):
        self.sent = bytearray()
        self.remote_side = "fake"
        self.on_failed_callbacks = []
        self.failed_with = None
        self.logoff = False

    def write(self, buf, **kw):
        self.sent.extend(buf.to_bytes())
        return 0

    def set_failed(self, code, text=""):
        if self.failed_with is not None:
            return False
        self.failed_with = (code, text)
        for cb in list(self.on_failed_callbacks):
            cb(self)
        return True

    def drain_frames(self):
        """Parse what the code under test wrote: [(type, flags, sid,
        payload)]."""
        out = []
        data = bytes(self.sent)
        pos = 0
        while pos + 9 <= len(data):
            length = int.from_bytes(data[pos:pos + 3], "big")
            out.append((data[pos + 3], data[pos + 4],
                        int.from_bytes(data[pos + 5:pos + 9], "big"),
                        data[pos + 9:pos + 9 + length]))
            pos += 9 + length
        self.sent.clear()
        return out


class TestH2FlowControl:
    """RFC 7540 §5.2/§6.9: our DATA respects the peer's windows."""

    def _conn(self):
        from brpc_tpu.policy import grpc as g
        sock = _FakeH2Socket()
        conn = g._H2Conn(is_server=False)
        sock._h2_conn = conn
        return g, sock, conn

    def test_data_parks_beyond_window_and_drains_on_update(self):
        g, sock, conn = self._conn()
        out = __import__("brpc_tpu.butil.iobuf", fromlist=["IOBuf"]).IOBuf()
        payload = b"z" * 100_000          # > 65535 default window
        with conn.lock:
            g._send_data(conn, out, 1, payload, end_stream=True)
        sock.write(out)
        frames = sock.drain_frames()
        sent = sum(len(p) for _t, _f, _s, p in frames)
        assert sent == 65535              # exactly the window, split at
        assert all(len(p) <= g.DEFAULT_MAX_FRAME  # max_frame_size
                   for _t, _f, _s, p in frames)
        assert not any(f & g.FLAG_END_STREAM for _t, f, _s, p in frames)
        assert conn.send_window == 0
        assert 1 in conn.pending
        # credit returns → the tail drains with END_STREAM on the last
        g._on_window_update(conn, sock, 0, 100_000)
        g._on_window_update(conn, sock, 1, 100_000)
        frames = sock.drain_frames()
        rest = sum(len(p) for _t, _f, _s, p in frames)
        assert rest == 100_000 - 65535
        assert frames[-1][1] & g.FLAG_END_STREAM
        assert not conn.pending

    def test_settings_initial_window_retro_adjusts(self):
        g, sock, conn = self._conn()
        import struct as _st
        from brpc_tpu.butil.iobuf import IOBuf
        out = IOBuf()
        with conn.lock:
            g._send_data(conn, out, 1, b"a" * 65535, end_stream=False)
        assert conn.stream_send[1] == 0
        # peer raises INITIAL_WINDOW_SIZE by 1000: open streams gain it
        payload = _st.pack(">HI", g.SETTINGS_INITIAL_WINDOW_SIZE, 66535)
        g._apply_settings(conn, sock, payload)
        assert conn.stream_send[1] == 1000
        assert conn.max_frame_size == g.DEFAULT_MAX_FRAME
        payload = _st.pack(">HI", g.SETTINGS_MAX_FRAME_SIZE, 32768)
        g._apply_settings(conn, sock, payload)
        assert conn.max_frame_size == 32768

    def test_window_update_before_first_data_keeps_credit(self):
        """A peer funding a large response upfront sends WINDOW_UPDATE
        before our first response DATA — the grant must survive until
        _send_data (whose bare setdefault(initial_window) used to forget
        it and park DATA the peer had already funded), whether it lands
        while the request is still arriving (conn.streams) or between
        request-complete and response-send (conn.serving)."""
        from brpc_tpu.policy import grpc as g
        from brpc_tpu.butil.iobuf import IOBuf
        for known_via in ("streams", "serving"):
            sock = _FakeH2Socket()
            conn = g._H2Conn(is_server=True)
            sock._h2_conn = conn
            if known_via == "streams":
                conn.streams[1] = g._H2Stream(1)
            else:
                conn.serving.add(1)
            g._on_window_update(conn, sock, 1, 10_000)
            assert 1 not in conn.stream_send      # booked aside, no entry
            conn.send_window = 1 << 20            # isolate stream window
            out = IOBuf()
            payload = b"y" * (g.DEFAULT_WINDOW + 10_000)
            with conn.lock:
                g._send_data(conn, out, 1, payload, end_stream=True)
            sock.write(out)
            frames = sock.drain_frames()
            assert sum(len(p) for _t, _f, _s, p in frames) == len(payload)
            assert frames[-1][1] & g.FLAG_END_STREAM
            assert 1 not in conn.pending          # nothing parked
            # everything retired: long-lived conns must not accumulate
            assert not conn.stream_send and not conn.early_credit \
                and not conn.serving
        # an update for a stream the conn has never seen is ignored
        g._on_window_update(conn, sock, 99, 5_000)
        assert 99 not in conn.stream_send and 99 not in conn.early_credit

    def test_client_conn_does_not_leak_per_call_state(self):
        """Review finding r5: the peer's auto-replenish WINDOW_UPDATE
        arriving after our request's END_STREAM must not re-create a
        stream_send entry — one leaked entry per completed call grows
        forever on a long-lived client conn."""
        from brpc_tpu.policy import grpc as g
        from brpc_tpu.butil.iobuf import IOBuf
        sock = _FakeH2Socket()
        conn = g._H2Conn(is_server=False)
        sock._h2_conn = conn
        for call in range(5):
            sid = 1 + 2 * call
            conn.cid_by_stream[sid] = 100 + sid
            out = IOBuf()
            with conn.lock:
                g._send_data(conn, out, sid, b"req", end_stream=True)
            # server's per-DATA auto-replenish lands post-END_STREAM
            g._on_window_update(conn, sock, sid, 3)
            # response arrives and completes the stream
            conn.streams[sid] = g._H2Stream(sid)
            g._handle_frame(conn, sock, g.FRAME_DATA, g.FLAG_END_STREAM,
                            sid, b"", [])
            conn.cid_by_stream.pop(sid, None)     # process_response does
        assert conn.stream_send == {}
        assert conn.early_credit == {}
        assert conn.streams == {} and conn.serving == set()

    def test_padded_frame_validation(self):
        """RFC 7540 §6.1: pad length ≥ remaining payload is a
        connection-level PROTOCOL_ERROR, and an empty PADDED frame must
        not crash the parser."""
        from brpc_tpu.policy import grpc as g
        for payload in (b"", bytes([5]) + b"abc"):   # empty; pad 5 > 3
            sock = _FakeH2Socket()
            conn = g._H2Conn(is_server=True)
            sock._h2_conn = conn
            g._handle_frame(conn, sock, g.FRAME_DATA, g.FLAG_PADDED, 1,
                            payload, [])
            assert sock.failed_with is not None
            sock2 = _FakeH2Socket()
            conn2 = g._H2Conn(is_server=True)
            sock2._h2_conn = conn2
            g._handle_frame(conn2, sock2, g.FRAME_HEADERS,
                            g.FLAG_PADDED | g.FLAG_END_HEADERS, 1,
                            payload, [])
            assert sock2.failed_with is not None
        # pad exactly len-1 (all-padding, empty fragment) is legal
        sock = _FakeH2Socket()
        conn = g._H2Conn(is_server=True)
        sock._h2_conn = conn
        g._handle_frame(conn, sock, g.FRAME_DATA, g.FLAG_PADDED, 1,
                        bytes([3]) + b"\0\0\0", [])
        assert sock.failed_with is None
        assert bytes(conn.streams[1].data) == b""
        # PADDED|PRIORITY: the 5 priority bytes count against the
        # payload too — pad=2 in an 8-byte payload (1+5+2=8) is legal,
        # pad=3 is not
        good = bytes([2]) + b"\x00\x00\x00\x00\x10" + b"\0\0"  # 1+5+2=8
        bad = bytes([3]) + b"\x00\x00\x00\x00\x10" + b"\0\0"   # pad 3, room 2
        for payload, ok in ((good, True), (bad, False)):
            sock = _FakeH2Socket()
            conn = g._H2Conn(is_server=True)
            sock._h2_conn = conn
            g._handle_frame(conn, sock, g.FRAME_HEADERS,
                            g.FLAG_PADDED | g.FLAG_PRIORITY |
                            g.FLAG_END_HEADERS, 1, payload, [])
            assert (sock.failed_with is None) == ok, (payload, ok)

    def test_trailers_never_jump_parked_data(self):
        """A response whose DATA is parked behind the window must hold
        its trailers back too — frame order per stream is the protocol."""
        g, sock, conn = self._conn()
        conn.settings_sent = True
        g._send_grpc_response(sock, 1, b"q" * 100_000, 0, "")
        frames = sock.drain_frames()
        # HEADERS + windowful of DATA, NO trailing HEADERS yet
        assert frames[0][0] == g.FRAME_HEADERS
        assert frames[-1][0] == g.FRAME_DATA
        g._on_window_update(conn, sock, 0, 1 << 20)
        g._on_window_update(conn, sock, 1, 1 << 20)
        frames = sock.drain_frames()
        assert frames[-1][0] == g.FRAME_HEADERS      # trailers, last
        assert frames[-1][1] & g.FLAG_END_STREAM


class TestH2Continuation:
    def test_header_block_split_mid_string_reassembles(self):
        """An HPACK string split across HEADERS/CONTINUATION must decode
        only after reassembly (decoding per-fragment corrupts it)."""
        from brpc_tpu.policy import grpc as g
        enc = hpack.Encoder(index=False)
        block = enc.encode([(b":path", b"/Svc/Method"),
                            (b"x-long", b"v" * 100)])
        sock = _FakeH2Socket()
        conn = g._H2Conn(is_server=True)
        sock._h2_conn = conn
        completed = []
        cut = len(block) // 2             # mid-string on purpose
        g._handle_frame(conn, sock, g.FRAME_HEADERS, 0, 1, block[:cut],
                        completed)
        assert conn.streams[1].headers == []     # nothing decoded yet
        g._handle_frame(conn, sock, g.FRAME_CONTINUATION,
                        g.FLAG_END_HEADERS, 1, block[cut:], completed)
        st = conn.streams[1]
        assert (b":path", b"/Svc/Method") in st.headers
        assert (b"x-long", b"v" * 100) in st.headers

    def test_outgoing_giant_header_block_splits(self):
        from brpc_tpu.policy import grpc as g
        from brpc_tpu.butil.iobuf import IOBuf
        conn = g._H2Conn(is_server=False)
        out = IOBuf()
        block = b"h" * (g.DEFAULT_MAX_FRAME * 2 + 100)
        with conn.lock:
            g._append_header_block(conn, out, 1, block, end_stream=False)
        sock = _FakeH2Socket()
        sock.write(out)
        frames = sock.drain_frames()
        assert [f[0] for f in frames] == [g.FRAME_HEADERS,
                                          g.FRAME_CONTINUATION,
                                          g.FRAME_CONTINUATION]
        assert not frames[0][1] & g.FLAG_END_HEADERS
        assert not frames[1][1] & g.FLAG_END_HEADERS
        assert frames[2][1] & g.FLAG_END_HEADERS
        assert b"".join(f[3] for f in frames) == block

    def test_padded_and_priority_flags_stripped(self):
        from brpc_tpu.policy import grpc as g
        enc = hpack.Encoder(index=False)
        block = enc.encode([(b":path", b"/x")])
        sock = _FakeH2Socket()
        conn = g._H2Conn(is_server=True)
        sock._h2_conn = conn
        completed = []
        # PADDED(0x8) + PRIORITY(0x20): padlen byte + 5 priority bytes +
        # block + padding
        payload = bytes([3]) + b"\x00\x00\x00\x00\x10" + block + b"\0\0\0"
        g._handle_frame(conn, sock, g.FRAME_HEADERS,
                        g.FLAG_END_HEADERS | g.FLAG_PADDED |
                        g.FLAG_PRIORITY, 1, payload, completed)
        assert conn.streams[1].headers == [(b":path", b"/x")]


class TestH2Rest:
    """Non-gRPC content on HTTP/2 — the REST half of the reference's h2
    protocol: JSON request in, plain HTTP response (no trailers)."""

    def _roundtrip(self, path: str, body: bytes,
                   content_type: bytes = b"application/json",
                   server=None, extra_headers=()):
        from brpc_tpu.policy import grpc as g
        from brpc_tpu.butil.iobuf import IOBuf
        if server is None:
            server = rpc.Server()
            server.add_service(GrpcEchoService())
        sock = _FakeH2Socket()

        class _Arg:
            pass
        arg = _Arg()
        arg.server = server
        enc = hpack.Encoder(index=False)
        block = enc.encode([(b":method", b"POST"), (b":path", path.encode()),
                            (b":scheme", b"http"),
                            (b"content-type", content_type),
                            *extra_headers])
        wire = (g.PREFACE
                + g.frame(g.FRAME_SETTINGS, 0, 0, b"")
                + g.frame(g.FRAME_HEADERS, g.FLAG_END_HEADERS, 1, block)
                + g.frame(g.FRAME_DATA, g.FLAG_END_STREAM, 1, body))
        source = IOBuf(wire)
        result = g.parse(source, sock, False, arg)
        sock.sent.clear()                 # drop server SETTINGS/acks
        g.process_request(result.message, sock, server)
        frames = sock.drain_frames()
        dec = hpack.Decoder()
        headers = []
        data = bytearray()
        for ftype, flags, sid, payload in frames:
            if ftype == g.FRAME_HEADERS:
                headers.extend(dec.decode(payload))
            elif ftype == g.FRAME_DATA:
                data.extend(payload)
        return dict(headers), bytes(data), frames

    def test_json_request_gets_http_response(self):
        import json
        headers, data, frames = self._roundtrip(
            "/EchoService/Echo", b'{"message":"rest"}')
        assert headers[b":status"] == b"200"
        assert headers[b"content-type"] == b"application/json"
        assert json.loads(data)["message"] == "grpc:rest"
        # plain HTTP shape: END_STREAM on the last DATA, NO trailers
        from brpc_tpu.policy import grpc as g
        assert frames[-1][0] == g.FRAME_DATA
        assert frames[-1][1] & g.FLAG_END_STREAM
        assert sum(1 for f in frames if f[0] == g.FRAME_HEADERS) == 1

    def test_unknown_path_is_404(self):
        headers, data, _ = self._roundtrip("/No/Such", b"{}")
        assert headers[b":status"] == b"404"

    def test_bad_json_is_400(self):
        headers, data, _ = self._roundtrip("/EchoService/Echo",
                                           b"not-json{")
        assert headers[b":status"] == b"400"

    def test_rest_cannot_bypass_authenticator(self):
        """Switching content-type away from application/grpc must NOT
        skip the server authenticator (review finding r4: an
        unauthenticated entry point to every method)."""
        class Auth:
            def verify(self, token, socket):
                return token == "Bearer ok"

        sopts = rpc.ServerOptions()
        sopts.auth = Auth()
        server = rpc.Server(sopts)
        server.add_service(GrpcEchoService())
        headers, _, _ = self._roundtrip("/EchoService/Echo",
                                        b'{"message":"x"}', server=server)
        assert headers[b":status"] == b"401"
        headers, data, _ = self._roundtrip(
            "/EchoService/Echo", b'{"message":"x"}', server=server,
            extra_headers=[(b"authorization", b"Bearer ok")])
        assert headers[b":status"] == b"200"

    def test_rest_counts_against_server_concurrency(self):
        """h2 REST traffic participates in server max_concurrency — the
        overload guard cannot be bypassed by content-type."""
        sopts = rpc.ServerOptions()
        sopts.max_concurrency = 1
        server = rpc.Server(sopts)
        server.add_service(GrpcEchoService())
        # artificially occupy the only slot
        assert server.on_request_in()
        headers, _, _ = self._roundtrip("/EchoService/Echo",
                                        b'{"message":"x"}', server=server)
        assert headers[b":status"] == b"503"
        server.on_request_out()
        headers, _, _ = self._roundtrip("/EchoService/Echo",
                                        b'{"message":"x"}', server=server)
        assert headers[b":status"] == b"200"
        # the REST path released its slot (send decrements)
        assert server._server_concurrency == 0


class TestH2StreamFailure:
    """A dead stream must COMPLETE its call with an error, not burn the
    deadline (RFC 7540 §6.4/§6.8)."""

    def _client_conn_with_call(self):
        from brpc_tpu.policy import grpc as g
        from brpc_tpu.bthread import id as bthread_id
        sock = _FakeH2Socket()
        conn = g._conn(sock, is_server=False)   # registers failure hook
        results = {}

        def on_error(_data, cid, code):
            # the Controller's completion entry point (retry machinery
            # lives behind it) — here we just record the delivery
            results["code"] = code
            bthread_id.unlock_and_destroy(cid)

        cid = bthread_id.create(None, on_error)
        conn.cid_by_stream[1] = cid
        return g, sock, conn, results

    def test_rst_stream_fails_the_call(self):
        g, sock, conn, results = self._client_conn_with_call()
        # CANCEL (0x8): not safe to retry → ECANCELED
        g._handle_frame(conn, sock, g.FRAME_RST_STREAM, 0, 1,
                        (8).to_bytes(4, "big"), [])
        assert results.get("code") == errors.ECANCELED
        assert 1 not in conn.cid_by_stream

    def test_refused_stream_is_retryable(self):
        """REFUSED_STREAM (0x7) guarantees non-processing (RFC 7540
        §8.1.4): the failure code must be one the retry machinery acts
        on."""
        from brpc_tpu.rpc.controller import Controller
        g, sock, conn, results = self._client_conn_with_call()
        g._handle_frame(conn, sock, g.FRAME_RST_STREAM, 0, 1,
                        (7).to_bytes(4, "big"), [])
        assert results.get("code") == errors.EAGAIN
        assert Controller._retryable(results["code"])

    def test_goaway_refuses_unprocessed_streams_retryably(self):
        """GOAWAY last_stream_id=0: our stream 1 was NOT processed
        (RFC 7540 §8.1.4) — it fails retryably NOW, its parked DATA is
        dropped, and the connection is logged off (no set_failed: a
        graceful peer may still be draining other streams)."""
        from brpc_tpu.rpc.controller import Controller
        g, sock, conn, results = self._client_conn_with_call()
        conn.pending[1] = [[b"parked", True]]    # window-parked DATA
        g._handle_frame(conn, sock, g.FRAME_GOAWAY, 0, 0,
                        (0).to_bytes(4, "big") + b"\x00" * 4, [])
        assert results.get("code") == errors.EAGAIN
        assert Controller._retryable(results["code"])
        assert 1 not in conn.pending
        assert not conn.cid_by_stream
        assert sock.logoff                       # no new streams
        # nothing left to drain → the useless conn closes immediately
        assert sock.failed_with is not None
        assert "drained" in sock.failed_with[1]

    def test_goaway_honors_last_stream_id(self):
        """Graceful GOAWAY-and-drain (nginx, grpc servers): streams the
        server already accepted (id ≤ last_stream_id) keep waiting for
        their responses — auto-retrying them would double-execute
        non-idempotent RPCs; only streams above the watermark fail
        (retryably).  New packs on the conn are refused."""
        from brpc_tpu.policy import grpc as g
        from brpc_tpu.bthread import id as bthread_id
        import pytest
        sock = _FakeH2Socket()
        conn = g._conn(sock, is_server=False)
        results = {}

        def on_error(sid):
            def cb(_data, cid, code):
                results[sid] = code
                bthread_id.unlock_and_destroy(cid)
            return cb

        conn.cid_by_stream[1] = bthread_id.create(None, on_error(1))
        conn.cid_by_stream[3] = bthread_id.create(None, on_error(3))
        g._handle_frame(conn, sock, g.FRAME_GOAWAY, 0, 0,
                        (1).to_bytes(4, "big") + b"\x00" * 4, [])
        assert results == {3: errors.EAGAIN}     # 3 refused, 1 drains
        assert 1 in conn.cid_by_stream           # still awaiting response
        assert sock.logoff and sock.failed_with is None
        # no NEW stream may be packed onto a going-away connection
        class _Cntl:
            pass
        cntl = _Cntl()
        cntl._pack_socket = sock
        from brpc_tpu.butil.iobuf import IOBuf
        with pytest.raises(ConnectionError):
            g.pack_request(IOBuf(), 7, cntl, "Svc.Method")
        # the drain stream's response arrives → the call completes AND
        # the now-useless logged-off conn is closed by US (the peer may
        # legally hold it open forever): no orphaned fd per GOAWAY cycle
        conn.streams[1] = g._H2Stream(1)
        g._handle_frame(conn, sock, g.FRAME_DATA, g.FLAG_END_STREAM, 1,
                        b"", [])
        # simulate process_response completing the call
        with conn.lock:
            conn.cid_by_stream.pop(1, None)
        g._close_if_drained(conn, sock)
        assert sock.failed_with is not None
        assert "drained" in sock.failed_with[1]

    def test_any_socket_death_fails_outstanding_calls(self):
        """Not just GOAWAY: a TCP reset (set_failed from anywhere) must
        complete in-flight h2 calls instead of burning their deadlines."""
        g, sock, conn, results = self._client_conn_with_call()
        sock.set_failed(errors.EFAILEDSOCKET, "connection reset by peer")
        assert results.get("code") == errors.EFAILEDSOCKET

    def test_server_stop_sends_goaway(self):
        """Graceful Server.stop emits GOAWAY naming the last processed
        stream before failing the connection."""
        from brpc_tpu.policy import grpc as g
        sock = _FakeH2Socket()
        conn = g._H2Conn(is_server=True)
        conn.last_processed_sid = 5
        sock._h2_conn = conn
        g.send_goaway(sock)
        frames = sock.drain_frames()
        assert frames[0][0] == g.FRAME_GOAWAY
        last_sid, err = __import__("struct").unpack(">II", frames[0][3])
        assert last_sid == 5 and err == 0

    def test_goaway_is_idempotent(self):
        """Repeated GOAWAY must not double-deliver a refusal."""
        g, sock, conn, results = self._client_conn_with_call()
        g._handle_frame(conn, sock, g.FRAME_GOAWAY, 0, 0,
                        (0).to_bytes(4, "big") + b"\x00" * 4, [])
        assert results.get("code") == errors.EAGAIN
        results.clear()
        g._handle_frame(conn, sock, g.FRAME_GOAWAY, 0, 0,
                        (0).to_bytes(4, "big") + b"\x00" * 4, [])
        assert "code" not in results


class TestGrpcAuth:
    def test_authorization_header_round_trip(self):
        """Channel auth credential rides the h2 authorization header; the
        server authenticator verifies it (UNAUTHENTICATED on mismatch)."""
        class TokenAuth:
            def generate_credential(self, cntl):
                return "Bearer sesame"

            def verify(self, token, socket):
                return token == "Bearer sesame"

        class BadAuth(TokenAuth):
            def generate_credential(self, cntl):
                return "Bearer wrong"

        sopts = rpc.ServerOptions()
        sopts.auth = TokenAuth()
        server = rpc.Server(sopts)
        server.add_service(GrpcEchoService())
        name = unique("grpc-auth")
        assert server.start(f"mem://{name}") == 0
        try:
            ch = rpc.Channel()
            ch.init(f"mem://{name}",
                    options=rpc.ChannelOptions(protocol="grpc",
                                               timeout_ms=5000,
                                               auth=TokenAuth()))
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="a"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "grpc:a"
            bad = rpc.Channel()
            bad.init(f"mem://{name}",
                     options=rpc.ChannelOptions(protocol="grpc",
                                                timeout_ms=5000,
                                                auth=BadAuth()))
            cntl = rpc.Controller()
            bad.call_method("EchoService.Echo", cntl,
                            EchoRequest(message="b"), EchoResponse)
            assert cntl.failed()
            assert cntl.error_code == errors.ERPCAUTH
        finally:
            server.stop()


class TestGrpcWireFixture:
    """Fixed golden bytes for a unary gRPC request — catches any drift in
    the frame layout, hpack encoding, or gRPC message framing (the
    reference pins its h2 bytes in brpc_grpc_protocol_unittest.cpp)."""

    def test_pack_request_golden(self):
        from brpc_tpu.policy import grpc as g
        from brpc_tpu.butil.iobuf import IOBuf

        class _Cntl:
            remote_side = None
            _pack_socket = _FakeH2Socket()

        cntl = _Cntl()
        payload = IOBuf(b"\x0a\x02hi")        # EchoRequest(message="hi")
        out = g.pack_request(payload, cid=7, cntl=cntl,
                             method_full_name="EchoService.Echo")
        assert len(out) == 0                  # frames were written direct
        got = bytes(cntl._pack_socket.sent)
        # preface + empty SETTINGS
        assert got.startswith(g.PREFACE)
        rest = got[len(g.PREFACE):]
        settings = bytes.fromhex("000000040000000000")
        assert rest.startswith(settings)
        rest = rest[len(settings):]
        # HEADERS frame: hpack of the 6 request headers (indexed encoder,
        # no huffman), stream 1, END_HEADERS
        hdr_block = bytes.fromhex(
            # :method POST (indexed 3), :scheme http (6), :path literal
            # incr name-idx 4 len 17, :authority literal incr name-idx 1
            # len 6 "fabric", content-type literal incr name-idx 31 len
            # 22, te literal incr (literal name len 2) len 8 "trailers"
            "8386"
            "44112f4563686f536572766963652f4563686f"
            "4106666162726963"
            "5f166170706c69636174696f6e2f677270632b70726f746f"
            "4002746508747261696c657273")
        hdr_frame = bytes.fromhex("%06x" % len(hdr_block)) + \
            bytes([g.FRAME_HEADERS, g.FLAG_END_HEADERS]) + \
            (1).to_bytes(4, "big") + hdr_block
        assert rest.startswith(hdr_frame), (rest[:60].hex(),
                                            hdr_frame[:60].hex())
        rest = rest[len(hdr_frame):]
        # DATA frame: 5-byte gRPC message prefix + pb, END_STREAM
        msg = b"\x00" + (4).to_bytes(4, "big") + b"\x0a\x02hi"
        data_frame = bytes.fromhex("%06x" % len(msg)) + \
            bytes([g.FRAME_DATA, g.FLAG_END_STREAM]) + \
            (1).to_bytes(4, "big") + msg
        assert rest == data_frame


class TestHpackEncoderGolden:
    """RFC 7541 Appendix C, ENCODER direction: our encoder must emit the
    RFC's exact bytes (it implements the RFC's own example encoder —
    incremental indexing, shared-table evolution, optional huffman).
    These fail on any encoder drift (VERDICT r3 #4)."""

    REQ1 = [(b":method", b"GET"), (b":scheme", b"http"), (b":path", b"/"),
            (b":authority", b"www.example.com")]
    REQ2 = REQ1 + [(b"cache-control", b"no-cache")]
    REQ3 = [(b":method", b"GET"), (b":scheme", b"https"),
            (b":path", b"/index.html"), (b":authority", b"www.example.com"),
            (b"custom-key", b"custom-value")]
    RESP1 = [(b":status", b"302"), (b"cache-control", b"private"),
             (b"date", b"Mon, 21 Oct 2013 20:13:21 GMT"),
             (b"location", b"https://www.example.com")]
    RESP2 = [(b":status", b"307"), (b"cache-control", b"private"),
             (b"date", b"Mon, 21 Oct 2013 20:13:21 GMT"),
             (b"location", b"https://www.example.com")]
    RESP3 = [(b":status", b"200"), (b"cache-control", b"private"),
             (b"date", b"Mon, 21 Oct 2013 20:13:22 GMT"),
             (b"location", b"https://www.example.com"),
             (b"content-encoding", b"gzip"),
             (b"set-cookie",
              b"foo=ASDJKHQKBZXOQWEOPIUAXQWEOIU; max-age=3600; version=1")]

    def test_c3_encode_requests_without_huffman(self):
        e = hpack.Encoder(index=True, use_huffman=False)
        assert e.encode(self.REQ1) == bytes.fromhex(
            "828684410f7777772e6578616d706c652e636f6d")
        assert e.table_size() == 57          # C.3.1 table state
        assert e.encode(self.REQ2) == bytes.fromhex(
            "828684be58086e6f2d6361636865")
        assert e.table_size() == 110         # C.3.2
        assert e.encode(self.REQ3) == bytes.fromhex(
            "828785bf400a637573746f6d2d6b65790c637573746f6d2d76616c7565")
        assert e.table_size() == 164         # C.3.3

    def test_c4_encode_requests_with_huffman(self):
        e = hpack.Encoder(index=True, use_huffman=True)
        assert e.encode(self.REQ1) == bytes.fromhex(
            "828684418cf1e3c2e5f23a6ba0ab90f4ff")
        assert e.encode(self.REQ2) == bytes.fromhex(
            "828684be5886a8eb10649cbf")
        assert e.encode(self.REQ3) == bytes.fromhex(
            "828785bf408825a849e95ba97d7f8925a849e95bb8e8b4bf")
        assert e.table_size() == 164

    def test_c5_encode_responses_without_huffman(self):
        e = hpack.Encoder(index=True, use_huffman=False,
                          max_table_size=256)
        assert e.encode(self.RESP1) == bytes.fromhex(
            "4803333032580770726976617465611d4d6f6e2c203231204f63742032"
            "3031332032303a31333a323120474d546e1768747470733a2f2f777777"
            "2e6578616d706c652e636f6d")
        # eviction at 256 bytes: adding :status 307 pushes out :status 302
        assert e.encode(self.RESP2) == bytes.fromhex("4803333037c1c0bf")
        assert e.encode(self.RESP3) == bytes.fromhex(
            "88c1611d4d6f6e2c203231204f637420323031332032303a31333a3232"
            "20474d54c05a04677a69707738666f6f3d4153444a4b48514b425a584f"
            "5157454f50495541585157454f49553b206d61782d6167653d33363030"
            "3b2076657273696f6e3d31")

    def test_c6_encode_responses_with_huffman(self):
        e = hpack.Encoder(index=True, use_huffman=True, max_table_size=256)
        assert e.encode(self.RESP1) == bytes.fromhex(
            "488264025885aec3771a4b6196d07abe941054d444a8200595040b8166"
            "e082a62d1bff6e919d29ad171863c78f0b97c8e9ae82ae43d3")
        assert e.encode(self.RESP2) == bytes.fromhex("4883640effc1c0bf")

    def test_encoder_decoder_table_convergence(self):
        """Both ends evolve the same dynamic table from the same stream —
        10 header blocks through encode→decode stay identical."""
        e = hpack.Encoder(index=True, use_huffman=True)
        d = hpack.Decoder()
        for i in range(10):
            hdrs = [(b":method", b"POST"),
                    (b":path", f"/svc/M{i % 3}".encode()),
                    (b"x-request-id", f"req-{i}".encode()),
                    (b"x-shared", b"constant-value")]
            assert d.decode(e.encode(hdrs)) == hdrs
        # repeated headers must have become 1-byte indexed fields
        small = e.encode([(b"x-shared", b"constant-value")])
        assert len(small) == 1


class TestHpackIntegerAndLiteralVectors:
    """RFC 7541 C.1 integer primitives + C.2 literal forms."""

    def test_c1_integers(self):
        assert hpack._encode_int(10, 5, 0) == b"\x0a"
        assert hpack._encode_int(1337, 5, 0) == b"\x1f\x9a\x0a"
        assert hpack._encode_int(42, 8, 0) == b"\x2a"
        assert hpack._decode_int(b"\x0a", 0, 5) == (10, 1)
        assert hpack._decode_int(b"\x1f\x9a\x0a", 0, 5) == (1337, 3)
        assert hpack._decode_int(b"\x2a", 0, 8) == (42, 1)

    def test_c2_1_literal_with_indexing(self):
        d = hpack.Decoder()
        block = bytes.fromhex(
            "400a637573746f6d2d6b65790d637573746f6d2d686561646572")
        assert d.decode(block) == [(b"custom-key", b"custom-header")]
        assert len(d.dynamic) == 1

    def test_c2_2_literal_without_indexing(self):
        d = hpack.Decoder()
        block = bytes.fromhex("040c2f73616d706c652f70617468")
        assert d.decode(block) == [(b":path", b"/sample/path")]
        assert len(d.dynamic) == 0

    def test_c2_3_literal_never_indexed(self):
        d = hpack.Decoder()
        block = bytes.fromhex("100870617373776f726406736563726574")
        assert d.decode(block) == [(b"password", b"secret")]
        assert len(d.dynamic) == 0

    def test_c2_4_indexed(self):
        d = hpack.Decoder()
        assert d.decode(b"\x82") == [(b":method", b"GET")]

    def test_huffman_encode_roundtrip(self):
        for s in (b"www.example.com", b"no-cache", b"custom-value",
                  b"Mon, 21 Oct 2013 20:13:21 GMT", bytes(range(256))):
            assert hpack.huffman_decode(hpack.huffman_encode(s)) == s
        # golden: the RFC's own huffman example
        assert hpack.huffman_encode(b"www.example.com") == bytes.fromhex(
            "f1e3c2e5f23a6ba0ab90f4ff")


class TestHpackRfc7541Vectors:
    """RFC 7541 Appendix C golden byte sequences — decoding foreign-encoder
    output proves interop without an h2 peer in the image."""

    def test_c3_requests_without_huffman(self):
        d = hpack.Decoder()
        # C.3.1
        block1 = bytes.fromhex("828684410f7777772e6578616d706c652e636f6d")
        assert d.decode(block1) == [
            (b":method", b"GET"), (b":scheme", b"http"),
            (b":path", b"/"), (b":authority", b"www.example.com")]
        # C.3.2 — dynamic table entry from C.3.1 must resolve
        block2 = bytes.fromhex("828684be58086e6f2d6361636865")
        assert d.decode(block2) == [
            (b":method", b"GET"), (b":scheme", b"http"),
            (b":path", b"/"), (b":authority", b"www.example.com"),
            (b"cache-control", b"no-cache")]
        # C.3.3
        block3 = bytes.fromhex(
            "828785bf400a637573746f6d2d6b65790c637573746f6d2d76616c7565")
        assert d.decode(block3) == [
            (b":method", b"GET"), (b":scheme", b"https"),
            (b":path", b"/index.html"), (b":authority", b"www.example.com"),
            (b"custom-key", b"custom-value")]

    def test_c4_requests_with_huffman(self):
        d = hpack.Decoder()
        # C.4.1
        block1 = bytes.fromhex("828684418cf1e3c2e5f23a6ba0ab90f4ff")
        assert d.decode(block1) == [
            (b":method", b"GET"), (b":scheme", b"http"),
            (b":path", b"/"), (b":authority", b"www.example.com")]
        # C.4.2
        block2 = bytes.fromhex("828684be5886a8eb10649cbf")
        assert d.decode(block2)[-1] == (b"cache-control", b"no-cache")
        # C.4.3
        block3 = bytes.fromhex(
            "828785bf408825a849e95ba97d7f8925a849e95bb8e8b4bf")
        assert d.decode(block3)[-1] == (b"custom-key", b"custom-value")

    def test_c6_responses_with_huffman(self):
        d = hpack.Decoder(max_table_size=256)
        # C.6.1
        block1 = bytes.fromhex(
            "488264025885aec3771a4b6196d07abe941054d444a8200595040b8166"
            "e082a62d1bff6e919d29ad171863c78f0b97c8e9ae82ae43d3")
        assert d.decode(block1) == [
            (b":status", b"302"), (b"cache-control", b"private"),
            (b"date", b"Mon, 21 Oct 2013 20:13:21 GMT"),
            (b"location", b"https://www.example.com")]
        # C.6.2 — :status 307 indexes over the evicted 302 entry
        block2 = bytes.fromhex("4883640effc1c0bf")
        assert d.decode(block2) == [
            (b":status", b"307"), (b"cache-control", b"private"),
            (b"date", b"Mon, 21 Oct 2013 20:13:21 GMT"),
            (b"location", b"https://www.example.com")]
