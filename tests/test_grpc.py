"""HTTP/2 + gRPC protocol tests (reference
test/brpc_grpc_protocol_unittest.cpp pattern: frame/HPACK golden checks +
in-process client↔server)."""
import struct

import pytest

import brpc_tpu.policy
from brpc_tpu import rpc
from brpc_tpu.policy import grpc as g2
from brpc_tpu.policy import hpack
from brpc_tpu.rpc import errors
from tests.echo_pb2 import EchoRequest, EchoResponse

_seq = [7000]


def unique(p):
    _seq[0] += 1
    return f"{p}-{_seq[0]}"


class TestHpack:
    def test_static_indexed_roundtrip(self):
        enc, dec = hpack.Encoder(), hpack.Decoder()
        headers = [(b":method", b"POST"), (b":scheme", b"http"),
                   (b":status", b"200")]
        assert dec.decode(enc.encode(headers)) == headers

    def test_literal_roundtrip(self):
        enc, dec = hpack.Encoder(), hpack.Decoder()
        headers = [(b":path", b"/Echo/Do"), (b"grpc-status", b"0"),
                   (b"x-custom", b"v" * 300)]
        assert dec.decode(enc.encode(headers)) == headers

    def test_dynamic_table_incremental(self):
        # encode literal-with-incremental-indexing by hand; decoder must
        # index it and resolve a later indexed reference
        dec = hpack.Decoder()
        name, value = b"x-session", b"abc"
        block = (bytes([0x40])                    # literal w/ indexing, new name
                 + bytes([len(name)]) + name
                 + bytes([len(value)]) + value)
        assert dec.decode(block) == [(name, value)]
        # index 62 = first dynamic entry
        assert dec.decode(bytes([0x80 | 62])) == [(name, value)]

    def test_huffman_decode(self):
        # "www.example.com" huffman-coded (RFC 7541 C.4.1)
        data = bytes.fromhex("f1e3c2e5f23a6ba0ab90f4ff")
        assert hpack.huffman_decode(data) == b"www.example.com"

    def test_integer_coding(self):
        assert hpack._encode_int(10, 5, 0) == bytes([10])
        raw = hpack._encode_int(1337, 5, 0)
        v, pos = hpack._decode_int(raw, 0, 5)
        assert v == 1337 and pos == len(raw)


class TestFrames:
    def test_frame_header(self):
        f = g2.frame(g2.FRAME_DATA, g2.FLAG_END_STREAM, 5, b"hello")
        assert len(f) == 9 + 5
        assert int.from_bytes(f[:3], "big") == 5
        assert f[3] == g2.FRAME_DATA
        assert f[4] == g2.FLAG_END_STREAM
        assert int.from_bytes(f[5:9], "big") == 5

    def test_grpc_message_framing(self):
        m = g2.grpc_message(b"PAYLOAD")
        assert m[0] == 0
        assert struct.unpack(">I", m[1:5])[0] == 7
        assert g2.split_grpc_messages(m + g2.grpc_message(b"x")) == \
            [b"PAYLOAD", b"x"]


class GrpcEchoService(rpc.Service):
    SERVICE_NAME = "EchoService"

    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = "grpc:" + request.message
        done()

    @rpc.method(EchoRequest, EchoResponse)
    def Fail(self, cntl, request, response, done):
        cntl.set_failed(errors.EINTERNAL, "grpc boom")
        done()


class TestGrpcEndToEnd:
    def _start(self, transport="mem"):
        server = rpc.Server()
        server.add_service(GrpcEchoService())
        if transport == "mem":
            name = unique("grpc")
            assert server.start(f"mem://{name}") == 0
            target = f"mem://{name}"
        else:
            assert server.start("127.0.0.1:0") == 0
            target = f"127.0.0.1:{server.listen_port}"
        ch = rpc.Channel()
        ch.init(target, options=rpc.ChannelOptions(protocol="grpc",
                                                   timeout_ms=5000))
        return server, ch

    def test_unary_call_mem(self):
        server, ch = self._start("mem")
        try:
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="hi"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "grpc:hi"
        finally:
            server.stop()

    def test_unary_call_tcp(self):
        server, ch = self._start("tcp")
        try:
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="tcp"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "grpc:tcp"
        finally:
            server.stop()

    def test_multiple_calls_one_connection(self):
        server, ch = self._start("mem")
        try:
            for i in range(10):
                cntl = rpc.Controller()
                resp = ch.call_method("EchoService.Echo", cntl,
                                      EchoRequest(message=f"n{i}"),
                                      EchoResponse)
                assert not cntl.failed(), cntl.error_text
                assert resp.message == f"grpc:n{i}"
        finally:
            server.stop()

    def test_server_error_maps_to_grpc_status(self):
        server, ch = self._start("mem")
        try:
            cntl = rpc.Controller()
            ch.call_method("EchoService.Fail", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert cntl.failed()
            assert "grpc boom" in cntl.error_text
        finally:
            server.stop()

    def test_unknown_method_is_unimplemented(self):
        server, ch = self._start("mem")
        try:
            cntl = rpc.Controller()
            ch.call_method("EchoService.Nope", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert cntl.failed()
            assert cntl.error_code == errors.ENOMETHOD
        finally:
            server.stop()


class TestHpackRfc7541Vectors:
    """RFC 7541 Appendix C golden byte sequences — decoding foreign-encoder
    output proves interop without an h2 peer in the image."""

    def test_c3_requests_without_huffman(self):
        d = hpack.Decoder()
        # C.3.1
        block1 = bytes.fromhex("828684410f7777772e6578616d706c652e636f6d")
        assert d.decode(block1) == [
            (b":method", b"GET"), (b":scheme", b"http"),
            (b":path", b"/"), (b":authority", b"www.example.com")]
        # C.3.2 — dynamic table entry from C.3.1 must resolve
        block2 = bytes.fromhex("828684be58086e6f2d6361636865")
        assert d.decode(block2) == [
            (b":method", b"GET"), (b":scheme", b"http"),
            (b":path", b"/"), (b":authority", b"www.example.com"),
            (b"cache-control", b"no-cache")]
        # C.3.3
        block3 = bytes.fromhex(
            "828785bf400a637573746f6d2d6b65790c637573746f6d2d76616c7565")
        assert d.decode(block3) == [
            (b":method", b"GET"), (b":scheme", b"https"),
            (b":path", b"/index.html"), (b":authority", b"www.example.com"),
            (b"custom-key", b"custom-value")]

    def test_c4_requests_with_huffman(self):
        d = hpack.Decoder()
        # C.4.1
        block1 = bytes.fromhex("828684418cf1e3c2e5f23a6ba0ab90f4ff")
        assert d.decode(block1) == [
            (b":method", b"GET"), (b":scheme", b"http"),
            (b":path", b"/"), (b":authority", b"www.example.com")]
        # C.4.2
        block2 = bytes.fromhex("828684be5886a8eb10649cbf")
        assert d.decode(block2)[-1] == (b"cache-control", b"no-cache")
        # C.4.3
        block3 = bytes.fromhex(
            "828785bf408825a849e95ba97d7f8925a849e95bb8e8b4bf")
        assert d.decode(block3)[-1] == (b"custom-key", b"custom-value")

    def test_c6_responses_with_huffman(self):
        d = hpack.Decoder(max_table_size=256)
        # C.6.1
        block1 = bytes.fromhex(
            "488264025885aec3771a4b6196d07abe941054d444a8200595040b8166"
            "e082a62d1bff6e919d29ad171863c78f0b97c8e9ae82ae43d3")
        assert d.decode(block1) == [
            (b":status", b"302"), (b"cache-control", b"private"),
            (b"date", b"Mon, 21 Oct 2013 20:13:21 GMT"),
            (b"location", b"https://www.example.com")]
        # C.6.2 — :status 307 indexes over the evicted 302 entry
        block2 = bytes.fromhex("4883640effc1c0bf")
        assert d.decode(block2) == [
            (b":status", b"307"), (b"cache-control", b"private"),
            (b"date", b"Mon, 21 Oct 2013 20:13:21 GMT"),
            (b"location", b"https://www.example.com")]
