"""Native RPC datapath tests (native/rpc.cpp + rpc/native_fabric.py).

Covers the four peer pairings on the one TRPC wire format:
  1. native channel ↔ native server (native echo handler, zero Python)
  2. native channel ↔ native server (Python service handler)
  3. Python rpc.Channel (tcp://) → native server   [wire interop A]
  4. native channel → Python rpc.Server (tcp://)   [wire interop B]
plus error paths (no method, timeout) and the in-C benchmark entries.

The reference's analogue is brpc_channel_unittest.cpp's in-process
client/server fixtures; interop here additionally pins the hand-rolled C++
proto3 codec against python-protobuf's output byte-for-byte.
"""
import threading
import time

import pytest

import brpc_tpu.policy  # noqa: F401  (registers protocols)
from brpc_tpu import rpc
from brpc_tpu.butil import native
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.native_fabric import NativeChannel, NativeServer

from echo_pb2 import EchoRequest, EchoResponse

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native core unavailable")


class EchoService(rpc.Service):
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        if len(cntl.request_attachment):
            cntl.response_attachment.append(cntl.request_attachment)
        done()

    @rpc.method(EchoRequest, EchoResponse)
    def Fail(self, cntl, request, response, done):
        cntl.set_failed(errors.EINTERNAL, "deliberate")
        done()

    @rpc.method(EchoRequest, EchoResponse)
    def Slow(self, cntl, request, response, done):
        time.sleep((request.sleep_us or 0) / 1e6)
        response.message = "slow"
        done()


def test_native_to_native_echo():
    server = NativeServer()
    server.register_native_echo("EchoService.Echo")
    port = server.start()
    ch = NativeChannel()
    ch.init(f"127.0.0.1:{port}")
    try:
        cntl = rpc.Controller()
        req = EchoRequest(message="hello-native")
        resp = ch.call_method("EchoService.Echo", cntl, req, EchoResponse)
        assert not cntl.failed(), cntl.error_text_
        # native echo reflects bytes; EchoRequest/EchoResponse share field 1
        assert resp.message == "hello-native"
    finally:
        ch.close()
        server.stop()


def test_native_server_python_service():
    server = NativeServer()
    server.add_service(EchoService())
    port = server.start()
    ch = NativeChannel()
    ch.init(f"127.0.0.1:{port}")
    try:
        cntl = rpc.Controller()
        cntl.request_attachment.append(b"att-bytes")
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="py-handler"), EchoResponse)
        assert not cntl.failed(), cntl.error_text_
        assert resp.message == "py-handler"
        assert cntl.response_attachment.to_bytes() == b"att-bytes"
        # error propagation
        cntl2 = rpc.Controller()
        ch.call_method("EchoService.Fail", cntl2, EchoRequest(message="x"),
                       EchoResponse)
        assert cntl2.failed()
        assert cntl2.error_code_ == errors.EINTERNAL
        assert "deliberate" in cntl2.error_text_
    finally:
        ch.close()
        server.stop()


def test_native_server_no_method():
    server = NativeServer()
    server.add_service(EchoService())
    port = server.start()
    ch = NativeChannel()
    ch.init(f"127.0.0.1:{port}")
    try:
        cntl = rpc.Controller()
        ch.call_method("EchoService.Nope", cntl, EchoRequest(message="x"),
                       EchoResponse)
        assert cntl.failed()
        assert cntl.error_code_ == errors.ENOMETHOD
    finally:
        ch.close()
        server.stop()


def test_native_channel_timeout():
    server = NativeServer()
    server.add_service(EchoService())
    port = server.start()
    ch = NativeChannel()
    ch.init(f"127.0.0.1:{port}")
    try:
        cntl = rpc.Controller()
        cntl.timeout_ms = 50
        ch.call_method("EchoService.Slow", cntl,
                       EchoRequest(message="x", sleep_us=300_000),
                       EchoResponse)
        assert cntl.failed()
        assert cntl.error_code_ == errors.ERPCTIMEDOUT
    finally:
        ch.close()
        server.stop()


def test_python_channel_to_native_server():
    """Wire interop A: the Python stack's tcp:// channel (tpu_std protocol,
    python-protobuf-encoded meta) against the C++ frame parser."""
    server = NativeServer()
    server.add_service(EchoService())
    server.register_native_echo("NativeEcho.Echo")
    port = server.start()
    try:
        ch = rpc.Channel()
        ch.init(f"tcp://127.0.0.1:{port}",
                options=rpc.ChannelOptions(timeout_ms=5000, max_retry=0))
        cntl = rpc.Controller()
        cntl.request_attachment.append(b"pyatt")
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="from-python"),
                              EchoResponse)
        assert not cntl.failed(), cntl.error_text_
        assert resp.message == "from-python"
        assert cntl.response_attachment.to_bytes() == b"pyatt"
        # and the zero-python native echo tier
        cntl2 = rpc.Controller()
        resp2 = ch.call_method("NativeEcho.Echo", cntl2,
                               EchoRequest(message="native-tier"),
                               EchoResponse)
        assert not cntl2.failed(), cntl2.error_text_
        assert resp2.message == "native-tier"
    finally:
        server.stop()


def test_native_channel_to_python_server():
    """Wire interop B: the C++ channel's hand-encoded meta parsed by the
    Python server (python-protobuf decoder)."""
    opts = rpc.ServerOptions()
    opts.usercode_inline = True
    server = rpc.Server(opts)
    server.add_service(EchoService())
    server.start("127.0.0.1:0")
    port = server.listen_port
    ch = NativeChannel()
    ch.init(f"127.0.0.1:{port}")
    try:
        cntl = rpc.Controller()
        cntl.request_attachment.append(b"natt")
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="from-native"),
                              EchoResponse)
        assert not cntl.failed(), cntl.error_text_
        assert resp.message == "from-native"
        assert cntl.response_attachment.to_bytes() == b"natt"
    finally:
        ch.close()
        server.stop()


def test_meta_codec_matches_python_protobuf():
    """Byte-level pin: C++ encoder output must parse with python-protobuf
    and embed the same fields (unknown-field skipping covers the rest)."""
    from brpc_tpu.proto import rpc_meta_pb2 as meta_pb
    # encode with python protobuf, ship through the native server: covered
    # by interop A.  Here: decode a python-encoded meta that contains
    # stream_settings (a field the C++ side skips) — the native server must
    # still answer the RPC (skip-unknown correctness).
    server = NativeServer()
    server.add_service(EchoService())
    port = server.start()
    import socket as pysock
    s = pysock.create_connection(("127.0.0.1", port))
    try:
        meta = meta_pb.RpcMeta()
        meta.request.service_name = "EchoService"
        meta.request.method_name = "Echo"
        meta.correlation_id = 77
        meta.stream_settings.stream_id = 5          # unknown to C++ parser
        meta.stream_settings.frame_type = 4
        body = EchoRequest(message="skipfield").SerializeToString()
        mb = meta.SerializeToString()
        frame = (b"TRPC" + len(mb).to_bytes(4, "big")
                 + len(body).to_bytes(4, "big") + mb + body)
        s.sendall(frame)
        # read one response frame
        hdr = b""
        while len(hdr) < 12:
            hdr += s.recv(12 - len(hdr))
        assert hdr[:4] == b"TRPC"
        msize = int.from_bytes(hdr[4:8], "big")
        bsize = int.from_bytes(hdr[8:12], "big")
        rest = b""
        while len(rest) < msize + bsize:
            rest += s.recv(msize + bsize - len(rest))
        rmeta = meta_pb.RpcMeta()
        rmeta.ParseFromString(rest[:msize])
        assert rmeta.correlation_id == 77
        assert rmeta.response.error_code == 0
        resp = EchoResponse()
        resp.ParseFromString(rest[msize:])
        assert resp.message == "skipfield"
    finally:
        s.close()
        server.stop()


def test_native_concurrent_calls():
    """Many threads share one native channel: correlation must not cross."""
    server = NativeServer()
    server.add_service(EchoService())
    port = server.start()
    ch = NativeChannel()
    ch.init(f"127.0.0.1:{port}")
    failures = []

    def worker(i):
        for j in range(20):
            cntl = rpc.Controller()
            msg = f"w{i}-{j}"
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message=msg), EchoResponse)
            if cntl.failed() or resp.message != msg:
                failures.append((i, j, cntl.error_text_))
    try:
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads: t.start()
        for t in threads: t.join()
        assert not failures, failures[:3]
    finally:
        ch.close()
        server.stop()


def test_native_rpc_bench_entries():
    p50 = native.native_rpc_echo_p50_us(iters=300, payload=1024)
    assert p50 > 0, "bench entry failed"
    assert p50 < 2000  # generous CI bound; ~10us on quiet hardware
    qps = native.native_rpc_qps(threads=4, duration_ms=300, payload=64)
    assert qps > 1000


def test_native_async_call():
    """Async completion API (VERDICT r3 #5): the callback fires from the
    channel's reader thread with the parsed response; wait() blocks."""
    from brpc_tpu.rpc.native_fabric import NativeServer, NativeChannel
    server = NativeServer()
    server.add_service(EchoService())
    port = server.start(0)
    ch = NativeChannel()
    ch.init(f"127.0.0.1:{port}")
    try:
        import threading
        seen = []
        ev = threading.Event()

        def done(cntl):
            seen.append((cntl.failed(), cntl.response))
            ev.set()

        cntl = rpc.Controller()
        cntl.timeout_ms = 5000
        fut = ch.call_method_async("EchoService.Echo", cntl,
                                   EchoRequest(message="async-hi"),
                                   EchoResponse, done=done)
        assert fut.wait(10)
        assert fut.done()
        assert ev.wait(5)
        assert seen[0][0] is False
        assert fut.response.message == "async-hi"
        # several overlapping async calls on one channel
        futs = []
        for i in range(8):
            c = rpc.Controller()
            c.timeout_ms = 5000
            futs.append((i, ch.call_method_async(
                "EchoService.Echo", c, EchoRequest(message=f"a{i}"),
                EchoResponse)))
        for i, f in futs:
            assert f.wait(10), f"async call {i} never completed"
            assert not f.cntl.failed(), f.cntl.error_text
            assert f.response.message == f"a{i}"
    finally:
        ch.close()
        server.stop()


def test_native_async_timeout():
    """An async call against a Python-handled method that never responds
    times out via the reader's deadline sweep."""
    from brpc_tpu.rpc.native_fabric import NativeServer, NativeChannel

    class BlackHole(rpc.Service):
        SERVICE_NAME = "EchoService"

        @rpc.method(EchoRequest, EchoResponse)
        def Echo(self, cntl, request, response, done):
            pass                        # never calls done()

    server = NativeServer()
    server.add_service(BlackHole())
    port = server.start(0)
    ch = NativeChannel()
    ch.init(f"127.0.0.1:{port}")
    try:
        cntl = rpc.Controller()
        cntl.timeout_ms = 200
        fut = ch.call_method_async("EchoService.Echo", cntl,
                                   EchoRequest(message="x"), EchoResponse)
        assert fut.wait(10)
        assert fut.cntl.failed()
        assert fut.cntl.error_code_ == errors.ERPCTIMEDOUT
    finally:
        ch.close()
        server.stop()


def test_native_pooled_channel():
    """Pooled multi-connection channel: concurrent callers round-robin
    over N native connections (reference pooled sockets)."""
    import threading
    from brpc_tpu.rpc.native_fabric import NativeServer, NativePooledChannel
    server = NativeServer()
    server.add_service(EchoService())
    port = server.start(0)
    pool = NativePooledChannel()
    pool.init(f"127.0.0.1:{port}", nconns=3)
    errs = []
    try:
        def worker(wid):
            try:
                for i in range(10):
                    cntl = rpc.Controller()
                    cntl.timeout_ms = 5000
                    resp = pool.call_method(
                        "EchoService.Echo", cntl,
                        EchoRequest(message=f"p{wid}-{i}"), EchoResponse)
                    assert not cntl.failed(), cntl.error_text
                    assert resp.message == f"p{wid}-{i}"
            except Exception as e:   # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
    finally:
        pool.close()
        server.stop()


def test_native_server_tasklet_dispatch():
    """usercode_inline=False parks handlers on bthread tasklets (the
    Python Server's tail-isolation default) instead of the epoll loop."""
    from brpc_tpu.rpc.native_fabric import NativeServer, NativeChannel
    from brpc_tpu.bthread import scheduler
    where = {}

    class Probe(rpc.Service):
        SERVICE_NAME = "EchoService"

        @rpc.method(EchoRequest, EchoResponse)
        def Echo(self, cntl, request, response, done):
            where["tasklet"] = scheduler.current_tasklet() is not None
            response.message = request.message
            done()

    server = NativeServer(usercode_inline=False)
    server.add_service(Probe())
    port = server.start(0)
    ch = NativeChannel()
    ch.init(f"127.0.0.1:{port}")
    try:
        cntl = rpc.Controller()
        cntl.timeout_ms = 5000
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="t"), EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "t"
        assert where["tasklet"] is True
    finally:
        ch.close()
        server.stop()
