"""Mongo protocol tests: BSON codec round-trips, OP_MSG framing, and an
in-process MongoService server driven by the mongo client channel (the
reference covers this in test/brpc_mongo_protocol_unittest.cpp with golden
buffers + in-process servers)."""
import struct

import pytest

import brpc_tpu.policy  # noqa: F401
from brpc_tpu import rpc
from brpc_tpu.policy.mongo import (MongoHead, MongoRequest, MongoResponse,
                                   MongoService, bson_decode, bson_encode,
                                   OP_MSG, OP_QUERY, HEAD_SIZE,
                                   _pack_op_msg, _parse_op_msg)


class TestBson:
    def test_roundtrip_scalars(self):
        doc = {"int": 42, "big": 1 << 40, "f": 3.5, "s": "hello",
               "b": True, "n": None, "raw": b"\x00\x01\x02"}
        assert bson_decode(bson_encode(doc)) == doc

    def test_roundtrip_nested(self):
        doc = {"outer": {"inner": [1, 2, {"deep": "x"}]}, "arr": ["a", "b"]}
        assert bson_decode(bson_encode(doc)) == doc

    def test_negative_and_bounds(self):
        doc = {"neg": -5, "min32": -(1 << 31), "max32": (1 << 31) - 1,
               "over": 1 << 31}
        out = bson_decode(bson_encode(doc))
        assert out == doc

    def test_bool_not_int(self):
        # bool must encode as BSON bool (0x08), not int32
        data = bson_encode({"t": True})
        assert data[4] == 0x08

    def test_empty_doc(self):
        assert bson_decode(bson_encode({})) == {}


class TestOpMsg:
    def test_kind0_roundtrip(self):
        doc = {"ping": 1, "$db": "admin"}
        assert _parse_op_msg(_pack_op_msg(doc)) == doc

    def test_kind1_sequence(self):
        # kind 0 command + kind 1 document sequence named "documents"
        body = struct.pack("<I", 0)
        body += b"\x00" + bson_encode({"insert": "c"})
        seq = b"documents\x00" + bson_encode({"a": 1}) + bson_encode({"a": 2})
        body += b"\x01" + struct.pack("<i", len(seq) + 4) + seq
        doc = _parse_op_msg(body)
        assert doc["insert"] == "c"
        assert doc["documents"] == [{"a": 1}, {"a": 2}]

    def test_head_roundtrip(self):
        h = MongoHead(100, 7, 3, OP_MSG)
        h2 = MongoHead.unpack(h.pack())
        assert (h2.message_length, h2.request_id, h2.response_to,
                h2.op_code) == (100, 7, 3, OP_MSG)


class PingPongService(MongoService):
    def process(self, cntl, doc):
        if "ping" in doc:
            return {"ok": 1, "pong": True}
        if "echo" in doc:
            return {"ok": 1, "echoed": doc["echo"]}
        if "boom" in doc:
            raise RuntimeError("kaboom")
        return None        # default {"ok": 1}


class TestMongoRpc:
    def _serve(self, scheme="mem://mongo-test"):
        server = rpc.Server()
        server.add_service(PingPongService())
        server.start(scheme)
        ch = rpc.Channel()
        ch.init(scheme, options=rpc.ChannelOptions(timeout_ms=5000,
                                                   protocol="mongo"))
        return server, ch

    def test_ping(self):
        server, ch = self._serve()
        try:
            cntl = rpc.Controller()
            resp = ch.call_method("mongo", cntl,
                                  MongoRequest({"ping": 1, "$db": "admin"}),
                                  MongoResponse)
            assert not cntl.failed(), cntl.error_text_
            assert resp.doc["ok"] == 1 and resp.doc["pong"] is True
        finally:
            server.stop()

    def test_echo_nested_doc(self):
        server, ch = self._serve("mem://mongo-echo")
        try:
            cntl = rpc.Controller()
            payload = {"list": [1, "two", {"three": 3}], "flag": False}
            resp = ch.call_method("mongo", cntl,
                                  MongoRequest({"echo": payload}), None)
            assert not cntl.failed(), cntl.error_text_
            assert resp.doc["echoed"] == payload
        finally:
            server.stop()

    def test_handler_exception_becomes_error_doc(self):
        server, ch = self._serve("mem://mongo-err")
        try:
            cntl = rpc.Controller()
            resp = ch.call_method("mongo", cntl,
                                  MongoRequest({"boom": 1}), None)
            assert not cntl.failed()       # transport-level ok
            assert resp.doc["ok"] == 0
            assert "kaboom" in resp.doc["errmsg"]
        finally:
            server.stop()

    def test_over_tcp(self):
        server = rpc.Server()
        server.add_service(PingPongService())
        server.start("127.0.0.1:0")
        try:
            ch = rpc.Channel()
            ch.init(f"127.0.0.1:{server.listen_port}",
                    options=rpc.ChannelOptions(timeout_ms=5000,
                                               protocol="mongo"))
            cntl = rpc.Controller()
            resp = ch.call_method("mongo", cntl, MongoRequest({"ping": 1}),
                                  None)
            assert not cntl.failed(), cntl.error_text_
            assert resp.doc["ok"] == 1
        finally:
            server.stop()

    def test_no_service_registered(self):
        server = rpc.Server()
        server.start("mem://mongo-nosvc")
        try:
            ch = rpc.Channel()
            ch.init("mem://mongo-nosvc",
                    options=rpc.ChannelOptions(timeout_ms=2000,
                                               protocol="mongo"))
            cntl = rpc.Controller()
            resp = ch.call_method("mongo", cntl, MongoRequest({"ping": 1}),
                                  None)
            assert not cntl.failed()
            assert resp.doc["ok"] == 0
        finally:
            server.stop()
