"""Redis + memcache protocol tests (reference test/brpc_redis_unittest.cpp /
brpc_memcache_unittest.cpp patterns: golden-byte codec checks + in-process
servers)."""
import struct
import threading

import pytest

import brpc_tpu.policy
from brpc_tpu import rpc
from brpc_tpu.policy import redis as redis_proto
from brpc_tpu.policy import memcache as mc
from brpc_tpu.butil.iobuf import IOBuf

_seq = [3000]


def unique(p):
    _seq[0] += 1
    return f"{p}-{_seq[0]}"


class TestRespCodec:
    def test_encode_command_golden(self):
        assert redis_proto.encode_command("SET", "k", "v") == \
            b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$1\r\nv\r\n"

    def test_parse_replies_golden(self):
        cases = [
            (b"+OK\r\n", redis_proto.REPLY_STATUS, "OK"),
            (b"-ERR nope\r\n", redis_proto.REPLY_ERROR, "ERR nope"),
            (b":42\r\n", redis_proto.REPLY_INTEGER, 42),
            (b"$5\r\nhello\r\n", redis_proto.REPLY_BULK, b"hello"),
            (b"$-1\r\n", redis_proto.REPLY_NIL, None),
        ]
        for raw, typ, val in cases:
            reply, consumed = redis_proto._parse_one(raw, 0)
            assert consumed == len(raw)
            assert reply.type == typ
            assert reply.value == val

    def test_parse_array(self):
        raw = b"*2\r\n$1\r\na\r\n:7\r\n"
        reply, consumed = redis_proto._parse_one(raw, 0)
        assert reply.type == redis_proto.REPLY_ARRAY
        assert reply.value[0].value == b"a"
        assert reply.value[1].value == 7

    def test_partial_returns_none(self):
        assert redis_proto._parse_one(b"$5\r\nhel", 0) is None
        assert redis_proto._parse_one(b"*2\r\n$1\r\na\r\n", 0) is None

    def test_encode_reply_roundtrip(self):
        for value in ["s", b"b", 7, None, [b"x", 1]]:
            raw = redis_proto.encode_reply(value)
            reply, consumed = redis_proto._parse_one(raw, 0)
            assert consumed == len(raw)


class KvRedis(redis_proto.RedisService):
    def __init__(self):
        super().__init__()
        self.data = {}
        self.add_handler("SET", self._set)
        self.add_handler("GET", self._get)
        self.add_handler("DEL", self._del)
        self.add_handler("INCR", self._incr)

    def _set(self, args):
        self.data[bytes(args[0])] = bytes(args[1])
        return redis_proto.RedisReply(redis_proto.REPLY_STATUS, "OK")

    def _get(self, args):
        return self.data.get(bytes(args[0]))

    def _del(self, args):
        return 1 if self.data.pop(bytes(args[0]), None) is not None else 0

    def _incr(self, args):
        v = int(self.data.get(bytes(args[0]), b"0")) + 1
        self.data[bytes(args[0])] = str(v).encode()
        return v


class TestRedisEndToEnd:
    def _start(self):
        server = rpc.Server()
        server.add_service(KvRedis())
        name = unique("redis")
        assert server.start(f"mem://{name}") == 0
        ch = rpc.Channel()
        ch.init(f"mem://{name}",
                options=rpc.ChannelOptions(protocol="redis", timeout_ms=5000))
        return server, ch

    def test_set_get(self):
        server, ch = self._start()
        try:
            req = redis_proto.RedisRequest()
            req.add_command("SET", "name", "tpu")
            req.add_command("GET", "name")
            cntl = rpc.Controller()
            resp = ch.call_method("redis", cntl, req, None)
            assert not cntl.failed(), cntl.error_text
            assert resp.reply(0).value == "OK"
            assert resp.reply(1).value == b"tpu"
        finally:
            server.stop()

    def test_pipeline_many(self):
        server, ch = self._start()
        try:
            req = redis_proto.RedisRequest()
            for i in range(10):
                req.add_command("INCR", "ctr")
            cntl = rpc.Controller()
            resp = ch.call_method("redis", cntl, req, None)
            assert not cntl.failed(), cntl.error_text
            assert [r.value for r in resp.replies] == list(range(1, 11))
        finally:
            server.stop()

    def test_unknown_command(self):
        server, ch = self._start()
        try:
            cntl = rpc.Controller()
            resp = ch.call_method("redis", cntl, ("BOGUS",), None)
            assert not cntl.failed()
            assert resp.reply(0).is_error()
        finally:
            server.stop()

    def test_ping(self):
        server, ch = self._start()
        try:
            cntl = rpc.Controller()
            resp = ch.call_method("redis", cntl, ("PING",), None)
            assert resp.reply(0).value == "PONG"
        finally:
            server.stop()


class MiniMemcached:
    """In-process memcached speaking the binary protocol (test fixture —
    the reference tests against golden bytes + real memcached)."""

    def __init__(self, sasl_expect: bytes = b""):
        self.data = {}
        self.sasl_expect = sasl_expect    # b"\0user\0pass" when required
        self.sasl_seen = 0

    def handle_frame(self, frame: bytes) -> bytes:
        (magic, opcode, keylen, extraslen, _dt, _vb, bodylen, opaque,
         cas) = mc._HDR.unpack(frame[:24])
        body = frame[24:24 + bodylen]
        extras = body[:extraslen]
        key = body[extraslen:extraslen + keylen]
        value = body[extraslen + keylen:]
        status = mc.STATUS_OK
        rextras = b""
        rvalue = b""
        if opcode == mc.OP_SET:
            self.data[key] = value
        elif opcode == mc.OP_GET:
            if key in self.data:
                rextras = struct.pack(">I", 0)
                rvalue = self.data[key]
            else:
                status = mc.STATUS_KEY_NOT_FOUND
        elif opcode == mc.OP_DELETE:
            if self.data.pop(key, None) is None:
                status = mc.STATUS_KEY_NOT_FOUND
        elif opcode == mc.OP_INCREMENT:
            delta, initial, _ = struct.unpack(">QQI", extras)
            cur = int(self.data.get(key, str(initial).encode()))
            if key in self.data:
                cur += delta
            self.data[key] = str(cur).encode()
            rvalue = struct.pack(">Q", cur)
        elif opcode == mc.OP_VERSION:
            rvalue = b"1.6.0-tpu"
        elif opcode == mc.OP_SASL_AUTH:
            self.sasl_seen += 1
            if self.sasl_expect and value != self.sasl_expect:
                status = 0x20             # auth error
        hdr = mc._HDR.pack(mc.MAGIC_RESPONSE, opcode, 0, len(rextras), 0,
                           status, len(rextras) + len(rvalue), opaque, cas)
        return hdr + rextras + rvalue


def start_mini_memcached(sasl_expect: bytes = b""):
    """Serve the binary protocol over a mem:// listener."""
    from brpc_tpu.rpc.mem_transport import mem_listen
    from brpc_tpu.rpc.protocol import Protocol
    from brpc_tpu.rpc.input_messenger import InputMessenger

    backend = MiniMemcached(sasl_expect)

    def parse_req(source, socket, read_eof, arg):
        from brpc_tpu.rpc.protocol import ParseResult
        data = source.fetch(len(source)) or b""
        if len(data) < 24:
            return ParseResult.not_enough_data()
        if data[0] != mc.MAGIC_REQUEST:
            return ParseResult.try_others()
        frames, pos = [], 0
        while pos + 24 <= len(data):
            bodylen = mc._HDR.unpack(data[pos:pos + 24])[6]
            if pos + 24 + bodylen > len(data):
                break
            frames.append(data[pos:pos + 24 + bodylen])
            pos += 24 + bodylen
        if not frames:
            return ParseResult.not_enough_data()
        source.pop_front(pos)
        return ParseResult.ok(frames)

    def process_req(frames, socket, server):
        out = b"".join(backend.handle_frame(f) for f in frames)
        socket.write(IOBuf(out))

    proto = Protocol(name="mini_memcached", parse=parse_req,
                     process_request=process_req)
    messenger = InputMessenger(protocols=[proto], server=object())

    name = unique("mc")

    def on_accept(sock):
        sock.messenger = messenger

    listener = mem_listen(name, on_accept)
    return backend, f"mem://{name}", listener


class TestMemcacheClient:
    def test_set_get_delete_incr(self):
        backend, target, listener = start_mini_memcached()
        try:
            ch = rpc.Channel()
            ch.init(target, options=rpc.ChannelOptions(protocol="memcache",
                                                       timeout_ms=5000))
            req = mc.MemcacheRequest()
            req.set("k", "val")
            req.get("k")
            req.incr("n", 5, initial=10)
            req.delete("k")
            req.get("k")
            cntl = rpc.Controller()
            resp = ch.call_method("memcache", cntl, req, None)
            assert not cntl.failed(), cntl.error_text
            assert resp.op(0).ok()
            assert resp.op(1).value == b"val"
            assert struct.unpack(">Q", resp.op(2).value)[0] == 10
            assert resp.op(3).ok()
            assert resp.op(4).status == mc.STATUS_KEY_NOT_FOUND
        finally:
            ch.close()
            from brpc_tpu.rpc.mem_transport import mem_unlisten
            mem_unlisten(listener.name)

    def test_version(self):
        backend, target, listener = start_mini_memcached()
        try:
            ch = rpc.Channel()
            ch.init(target, options=rpc.ChannelOptions(protocol="memcache",
                                                       timeout_ms=5000))
            req = mc.MemcacheRequest()
            req.version()
            cntl = rpc.Controller()
            resp = ch.call_method("memcache", cntl, req, None)
            assert resp.op(0).value == b"1.6.0-tpu"
        finally:
            ch.close()
            from brpc_tpu.rpc.mem_transport import mem_unlisten
            mem_unlisten(listener.name)
