"""Cascading request context (rpc/request_context.py): a handler's
outbound calls inherit the inbound priority/tenant and the DECREMENTED
deadline budget by default — the PR-9 follow-on that keeps admission
metadata meaningful across fan-out hops (proxy/orchestrator shapes)."""
import threading
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc import errors
from brpc_tpu.rpc import request_context as reqctx

from echo_pb2 import EchoRequest, EchoResponse


class TestScopeUnits:
    def _cntl(self, priority=None, tenant="", deadline=0):
        c = rpc.Controller()
        if priority is not None:
            c.priority = priority
        if tenant:
            c.tenant = tenant
        if deadline:
            c.deadline_left_ms = deadline
        return c

    def test_scope_installs_and_restores(self):
        assert reqctx.current() is None
        with reqctx.scope(self._cntl(priority=1, tenant="t")):
            ctx = reqctx.current()
            assert ctx is not None
            assert ctx.priority == 1 and ctx.tenant == "t"
        assert reqctx.current() is None

    def test_no_metadata_installs_no_context(self):
        with reqctx.scope(self._cntl()):
            assert reqctx.current() is None

    def test_nested_scope_shadows_then_restores(self):
        with reqctx.scope(self._cntl(priority=0)):
            outer = reqctx.current()
            with reqctx.scope(self._cntl(priority=3)):
                assert reqctx.current().priority == 3
            assert reqctx.current() is outer

    def test_residual_deadline_decrements_with_elapsed_time(self):
        with reqctx.scope(self._cntl(deadline=100)):
            ctx = reqctx.current()
            r0 = ctx.residual_deadline_ms()
            assert r0 is not None and r0 <= 100
            time.sleep(0.05)
            r1 = ctx.residual_deadline_ms()
            assert r1 < r0 and r1 <= 100 - 45

    def test_no_deadline_means_no_residual(self):
        with reqctx.scope(self._cntl(priority=2)):
            assert reqctx.current().residual_deadline_ms() is None

    def test_scope_is_thread_local(self):
        seen = {}
        with reqctx.scope(self._cntl(priority=1)):
            def other():
                seen["ctx"] = reqctx.current()
            t = threading.Thread(target=other)
            t.start()
            t.join()
        assert seen["ctx"] is None


def _start_server(name, service):
    opts = rpc.ServerOptions()
    opts.usercode_inline = True
    s = rpc.Server(opts)
    s.add_service(service)
    assert s.start(f"mem://{name}") == 0
    return s


class TestTwoHopEndToEnd:
    """A → B → C over mem:// transports: B's handler calls C through a
    plain channel and C must observe A's metadata, decremented."""

    def test_priority_tenant_and_deadline_inherit_across_two_hops(self):
        seen = {}

        class CService(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Leaf(self, cntl, request, response, done):
                seen["priority"] = cntl.priority
                seen["tenant"] = cntl.tenant
                seen["deadline_left_ms"] = cntl.deadline_left_ms
                response.message = "leaf"
                done()

        class BService(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Mid(self, cntl, request, response, done):
                # burn a slice of the budget before fanning out, so the
                # decrement is observable
                time.sleep(0.05)
                ch = rpc.Channel()
                ch.init("mem://reqctx-c")
                sub = rpc.Controller()
                r = ch.call_method("CService.Leaf", sub,
                                   EchoRequest(message="x"), EchoResponse)
                assert not sub.failed(), sub.error_text
                seen["sub_timeout_ms"] = sub.timeout_ms
                response.message = "mid:" + r.message
                done()

        sc = _start_server("reqctx-c", CService())
        sb = _start_server("reqctx-b", BService())
        try:
            ch = rpc.Channel()
            ch.init("mem://reqctx-b")
            cntl = rpc.Controller()
            cntl.priority = 0
            cntl.tenant = "gold"
            cntl.timeout_ms = 2000
            resp = ch.call_method("BService.Mid", cntl,
                                  EchoRequest(message="x"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "mid:leaf"
            # C saw A's class, not a re-originated default
            assert seen["priority"] == 0
            assert seen["tenant"] == "gold"
            # and a budget strictly below A's, shrunk by B's 50ms burn
            assert 0 < seen["deadline_left_ms"] <= 2000 - 40, seen
            # the sub-call's timeout was capped at the residual budget
            assert seen["sub_timeout_ms"] <= 2000 - 40, seen
        finally:
            sb.stop()
            sc.stop()

    def test_explicit_override_beats_inheritance(self):
        seen = {}

        class CService(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Leaf(self, cntl, request, response, done):
                seen["priority"] = cntl.priority
                seen["tenant"] = cntl.tenant
                response.message = "leaf"
                done()

        class BService(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Mid(self, cntl, request, response, done):
                ch = rpc.Channel()
                ch.init("mem://reqctx-c2")
                sub = rpc.Controller()
                sub.priority = 3            # explicit per-call override
                sub.tenant = "scrap"
                ch.call_method("CService.Leaf", sub,
                               EchoRequest(message="x"), EchoResponse)
                assert not sub.failed(), sub.error_text
                response.message = "mid"
                done()

        sc = _start_server("reqctx-c2", CService())
        sb = _start_server("reqctx-b2", BService())
        try:
            ch = rpc.Channel()
            ch.init("mem://reqctx-b2")
            cntl = rpc.Controller()
            cntl.priority = 0
            cntl.tenant = "gold"
            ch.call_method("BService.Mid", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert seen["priority"] == 3
            assert seen["tenant"] == "scrap"
        finally:
            sb.stop()
            sc.stop()

    def test_inherited_beats_channel_defaults(self):
        seen = {}

        class CService(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Leaf(self, cntl, request, response, done):
                seen["priority"] = cntl.priority
                seen["tenant"] = cntl.tenant
                response.message = "leaf"
                done()

        class BService(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Mid(self, cntl, request, response, done):
                ch = rpc.Channel()
                # a static channel-wide default must NOT demote the
                # critical inbound request
                ch.init("mem://reqctx-c3",
                        options=rpc.ChannelOptions(priority=3,
                                                   tenant="bulkload"))
                sub = rpc.Controller()
                ch.call_method("CService.Leaf", sub,
                               EchoRequest(message="x"), EchoResponse)
                assert not sub.failed(), sub.error_text
                response.message = "mid"
                done()

        sc = _start_server("reqctx-c3", CService())
        sb = _start_server("reqctx-b3", BService())
        try:
            ch = rpc.Channel()
            ch.init("mem://reqctx-b3")
            cntl = rpc.Controller()
            cntl.priority = 0
            cntl.tenant = "gold"
            ch.call_method("BService.Mid", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert seen["priority"] == 0
            assert seen["tenant"] == "gold"
        finally:
            sb.stop()
            sc.stop()

    def test_spent_budget_fails_subcall_before_any_work(self):
        leaf_ran = []

        class CService(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Leaf(self, cntl, request, response, done):
                leaf_ran.append(1)
                response.message = "leaf"
                done()

        class BService(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Mid(self, cntl, request, response, done):
                time.sleep(0.08)            # burn past the inbound budget
                ch = rpc.Channel()
                ch.init("mem://reqctx-c4")
                sub = rpc.Controller()
                ch.call_method("CService.Leaf", sub,
                               EchoRequest(message="x"), EchoResponse)
                # the sub-call failed fast with the deadline code and
                # never dispatched
                assert sub.failed()
                assert sub.error_code_ == errors.ERPCTIMEDOUT, \
                    (sub.error_code_, sub.error_text)
                response.message = "mid"
                done()

        sc = _start_server("reqctx-c4", CService())
        sb = _start_server("reqctx-b4", BService())
        try:
            ch = rpc.Channel()
            ch.init("mem://reqctx-b4")
            cntl = rpc.Controller()
            cntl.timeout_ms = 50            # the whole budget B burns past
            ch.call_method("BService.Mid", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert not leaf_ran, "sub-call dispatched on a spent budget"
        finally:
            sb.stop()
            sc.stop()

    def test_async_done_sees_failed_subcall_on_spent_budget(self):
        fired = []

        class CService(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Leaf(self, cntl, request, response, done):
                response.message = "leaf"
                done()

        class BService(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Mid(self, cntl, request, response, done):
                time.sleep(0.08)
                ch = rpc.Channel()
                ch.init("mem://reqctx-c5")
                sub = rpc.Controller()
                evt = threading.Event()

                def sub_done(c):
                    fired.append(c.error_code_)
                    evt.set()
                ch.call_method("CService.Leaf", sub,
                               EchoRequest(message="x"), EchoResponse,
                               done=sub_done)
                assert evt.wait(2), "async done never fired"
                response.message = "mid"
                done()

        sc = _start_server("reqctx-c5", CService())
        sb = _start_server("reqctx-b5", BService())
        try:
            ch = rpc.Channel()
            ch.init("mem://reqctx-b5")
            cntl = rpc.Controller()
            cntl.timeout_ms = 50
            ch.call_method("BService.Mid", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert fired == [errors.ERPCTIMEDOUT], fired
        finally:
            sb.stop()
            sc.stop()
