"""Load balancer / naming / limiter / breaker tests (mirrors reference
test/brpc_load_balancer_unittest.cpp, brpc_naming_service_unittest.cpp,
brpc_circuit_breaker_unittest.cpp patterns)."""
import collections
import threading
import time

import pytest

from brpc_tpu.butil.endpoint import parse_endpoint
from brpc_tpu.policy import load_balancers as lbs
from brpc_tpu.policy import naming, limiters
from brpc_tpu.rpc.circuit_breaker import (CircuitBreaker,
                                          ClusterRecoverPolicy)

EPS = [parse_endpoint(f"10.0.0.{i}:80") for i in range(1, 6)]


def make(name, n=3):
    lb = lbs.create_load_balancer(name)
    for ep in EPS[:n]:
        lb.add_server(ep)
    return lb


class TestLoadBalancers:
    def test_factory_covers_all_nine(self):
        assert sorted(lbs.list_load_balancers()) == sorted([
            "rr", "wrr", "random", "wr", "c_murmurhash", "c_md5",
            "c_ketama", "la", "dynpart"])
        for name in lbs.list_load_balancers():
            assert lbs.create_load_balancer(name).server_count() == 0

    def test_rr_even_distribution(self):
        lb = make("rr")
        counts = collections.Counter(lb.select_server() for _ in range(300))
        assert all(abs(c - 100) <= 1 for c in counts.values())

    def test_wrr_respects_weights(self):
        lb = lbs.create_load_balancer("wrr")
        lb.add_server(EPS[0], weight=300)
        lb.add_server(EPS[1], weight=100)
        counts = collections.Counter(lb.select_server() for _ in range(400))
        assert 280 <= counts[EPS[0]] <= 320
        assert 80 <= counts[EPS[1]] <= 120

    def test_random_covers_all(self):
        lb = make("random")
        counts = collections.Counter(lb.select_server() for _ in range(600))
        assert set(counts) == set(EPS[:3])
        assert all(c > 100 for c in counts.values())

    def test_weighted_random(self):
        lb = lbs.create_load_balancer("wr")
        lb.add_server(EPS[0], weight=900)
        lb.add_server(EPS[1], weight=100)
        counts = collections.Counter(lb.select_server() for _ in range(1000))
        assert counts[EPS[0]] > counts[EPS[1]] * 4

    @pytest.mark.parametrize("kind", ["c_murmurhash", "c_md5", "c_ketama"])
    def test_consistent_hash_stickiness(self, kind):
        lb = make(kind, n=5)

        class C:
            request_code = b"user-12345"
        first = lb.select_server(C())
        assert all(lb.select_server(C()) == first for _ in range(20))

    def test_consistent_hash_minimal_reshuffle(self):
        lb = make("c_ketama", n=5)

        class C:
            def __init__(self, code): self.request_code = code
        before = {i: lb.select_server(C(b"key-%d" % i)) for i in range(200)}
        lb.remove_server(EPS[0])
        after = {i: lb.select_server(C(b"key-%d" % i)) for i in range(200)}
        moved = sum(1 for i in before if before[i] != after[i])
        # only keys previously on the removed node move (~1/5 of keys)
        assert moved < 200 * 0.45
        assert all(after[i] != EPS[0] for i in after)

    def test_locality_aware_prefers_fast_server(self):
        lb = make("la", n=2)
        for _ in range(50):
            lb.feedback(EPS[0], 0, 100)       # fast
            lb.feedback(EPS[1], 0, 10000)     # 100x slower
        # pair every selection with immediate feedback at the server's
        # characteristic latency: selections without feedback accumulate
        # IN-FLIGHT entries, and the divided-weight extrapolation then
        # collapses the fast server's weight by wall-clock elapsed — a
        # loaded CI host made the old feedback-less loop flaky
        counts = collections.Counter()
        lat = {EPS[0]: 100, EPS[1]: 10000}
        for _ in range(500):
            ep = lb.select_server()
            counts[ep] += 1
            lb.feedback(ep, 0, lat[ep])
        assert counts[EPS[0]] > counts[EPS[1]] * 5

    def test_locality_aware_punishes_errors(self):
        lb = make("la", n=2)
        for _ in range(20):
            lb.feedback(EPS[0], 0, 1000)
            lb.feedback(EPS[1], 1009, 1000)   # failing
        assert lb.weight_of(EPS[0]) > lb.weight_of(EPS[1]) * 3

    def test_exclusion_and_fallback(self):
        lb = make("rr", n=2)
        lb.exclude(EPS[0], time.monotonic() + 60)
        assert all(lb.select_server() == EPS[1] for _ in range(10))
        lb.exclude(EPS[1], time.monotonic() + 60)
        # everything excluded → serve anyway (cluster recover guard)
        assert lb.select_server() in (EPS[0], EPS[1])

    def test_membership_changes_during_selection(self):
        lb = make("rr", n=3)
        stop = threading.Event()
        errs = []

        def churn():
            while not stop.is_set():
                lb.remove_server(EPS[0])
                lb.add_server(EPS[0])

        def select():
            try:
                for _ in range(2000):
                    lb.select_server()
            except Exception as e:
                errs.append(e)

        t1 = threading.Thread(target=churn)
        t2 = threading.Thread(target=select)
        t1.start(); t2.start()
        t2.join(30); stop.set(); t1.join(5)
        assert not errs


class TestNaming:
    def test_list_ns(self):
        ns = naming.create_naming_service("list://10.0.0.1:80,10.0.0.2:81")
        eps = [e.endpoint for e in ns.get_servers()]
        assert eps == [parse_endpoint("10.0.0.1:80"),
                       parse_endpoint("10.0.0.2:81")]

    def test_list_ns_ici_coords_and_mixed_schemes(self):
        # commas inside mesh coords are not entry separators; spaces
        # around them are squeezed; bare slugs are mem registries
        ns = naming.create_naming_service(
            "list://ici://(0, 1),ici://(0,2),backend-a,tcp://1.2.3.4:80")
        eps = [e.endpoint for e in ns.get_servers()]
        assert eps == [parse_endpoint("ici://(0,1)"),
                       parse_endpoint("ici://(0,2)"),
                       parse_endpoint("backend-a"),
                       parse_endpoint("1.2.3.4:80")]
        assert eps[0].coords == (0, 1)
        assert eps[2].scheme == "mem"

    def test_file_ns_with_tags(self, tmp_path):
        p = tmp_path / "servers"
        p.write_text("10.0.0.1:80 100 0/2\n"
                     "10.0.0.2:80 100 1/2\n"
                     "# comment\n"
                     "10.0.0.3:80\n")
        ns = naming.create_naming_service(f"file://{p}")
        entries = ns.get_servers()
        assert len(entries) == 3
        assert entries[0].tag == "0/2"
        assert entries[2].tag == ""

    def test_mesh_ns_matches_device_mesh(self):
        ns = naming.create_naming_service("mesh://")
        entries = ns.get_servers()
        import jax
        assert len(entries) == len(jax.devices())
        assert entries[0].endpoint == parse_endpoint("ici://0")

    def test_dns_ns_localhost(self):
        ns = naming.create_naming_service("dns://localhost:1234")
        entries = ns.get_servers()
        assert entries and entries[0].endpoint.port == 1234

    def test_ns_thread_pushes_updates(self, tmp_path):
        p = tmp_path / "servers"
        p.write_text("10.0.0.1:80\n")
        got = []

        class Watcher:
            def reset_servers(self, entries):
                got.append([str(e.endpoint) for e in entries])

        t = naming.NamingServiceThread(f"file://{p}")
        t.add_watcher(Watcher())
        assert got and got[-1] == ["10.0.0.1:80"]
        p.write_text("10.0.0.1:80\n10.0.0.2:80\n")
        t._poll_once()
        assert got[-1] == ["10.0.0.1:80", "10.0.0.2:80"]
        t.stop()


class TestLimiters:
    def test_constant(self):
        lim = limiters.ConstantConcurrencyLimiter(2)
        assert lim.on_requested(0) and lim.on_requested(1)
        assert not lim.on_requested(2)

    @staticmethod
    def _drive(lim, windows, concurrency_fn, base_us=10_000, knee=20,
               now_us=1_000_000):
        """Simulated server with an explicit knee: latency is flat at
        base_us up to `knee` concurrent requests, then grows linearly
        (queueing).  Drives the limiter with an injected clock — fully
        deterministic.  Returns the advanced clock so multi-phase tests
        keep time monotonic."""
        for _ in range(windows):
            c = max(1, min(concurrency_fn(lim.max_concurrency()), 200))
            lat = base_us if c <= knee else int(base_us * c / knee)
            # steady state: `c` in flight, each taking `lat` us
            qps = c / (lat / 1e6)
            span_us = 200_000
            n = max(int(qps * span_us / 1e6), 1)
            step = span_us // n
            for _ in range(n):
                now_us += step
                lim.add_sample(0, lat, now_us)
        return now_us

    def test_auto_gradient_converges_near_the_knee(self):
        """Simulated-load convergence: with a capacity knee at 20
        concurrent requests, the gradient limit must settle in the
        Little's-law band around knee×(1+alpha) — neither collapsing to
        MIN_LIMIT nor running away with offered load of 150."""
        lim = limiters.AutoConcurrencyLimiter(initial=40)
        self._drive(lim, windows=300, concurrency_fn=lambda m: min(m, 150))
        got = lim.max_concurrency()
        assert 14 <= got <= 45, got
        # the periodic exploration actually ran (noise-filtered floor
        # was re-measured under reduced load)
        assert lim.remeasure_count >= 1

    def test_auto_gradient_tracks_a_capacity_collapse(self):
        """Closed loop: after converging against a knee of 20, the
        server's capacity collapses to a knee of 3 — the gradient must
        walk the limit down into the small-knee band instead of holding
        the stale one."""
        lim = limiters.AutoConcurrencyLimiter(
            initial=40, remeasure_interval_us=60_000_000)
        now = self._drive(lim, windows=150,
                          concurrency_fn=lambda m: min(m, 150))
        assert lim.max_concurrency() >= 14
        self._drive(lim, windows=300, concurrency_fn=lambda m: min(m, 150),
                    knee=3, now_us=now)
        assert lim.max_concurrency() <= 10, lim.max_concurrency()

    def test_auto_gradient_failures_punish_the_window(self):
        """Failed responses drag the window's punished latency up (the
        fail_punish_ratio term), shrinking the limit even when successes
        stay fast."""
        healthy = limiters.AutoConcurrencyLimiter(
            initial=40, remeasure_interval_us=60_000_000)
        degraded = limiters.AutoConcurrencyLimiter(
            initial=40, remeasure_interval_us=60_000_000)
        now_h = self._drive(healthy, 30, lambda m: min(m, 10))
        now_d = self._drive(degraded, 30, lambda m: min(m, 10))
        for _ in range(200):
            now_h += 5_000
            now_d += 5_000
            healthy.add_sample(0, 10_000, now_h)
            degraded.add_sample(0, 10_000, now_d)
            degraded.add_sample(1, 80_000, now_d)   # timeouts punished
        assert degraded.max_concurrency() < healthy.max_concurrency()

    def test_timeout_limiter(self):
        lim = limiters.TimeoutConcurrencyLimiter(timeout_ms=10)
        for _ in range(20):
            lim.on_responded(0, 5000)       # 5ms per request
        assert lim.on_requested(1)
        assert not lim.on_requested(50)     # 50×5ms queue > 10ms budget


class TestCircuitBreaker:
    def test_trips_on_errors_and_recovers(self):
        cb = CircuitBreaker()
        tripped = False
        for _ in range(30):
            if not cb.on_call_end(1009):
                tripped = True
                break
        assert tripped
        assert cb.is_isolated()
        cb.mark_recovered()
        assert not cb.is_isolated()

    def test_healthy_traffic_never_trips(self):
        cb = CircuitBreaker()
        assert all(cb.on_call_end(0) for _ in range(1000))

    def test_isolation_duration_doubles(self):
        cb = CircuitBreaker()
        for _ in range(50):
            cb.on_call_end(1009)
        first = cb._isolation_ms
        cb._isolated_until = 0  # force re-trip eligibility
        for _ in range(50):
            cb.on_call_end(1009)
        assert cb._isolation_ms >= first

    def test_cluster_recover_policy(self):
        crp = ClusterRecoverPolicy(min_working_instances=2, hold_seconds=0.05)
        assert crp.on_cluster_size(3, 5)
        assert not crp.on_cluster_size(1, 5)      # entered recovery
        time.sleep(0.06)
        assert crp.on_cluster_size(1, 5)          # hold-off elapsed


class TestLalbDividedWeight:
    """The reference LALB algorithm (locality_aware_load_balancer.cpp /
    docs/cn/lalb.md): divided weight under a mixed fast/slow/erroring
    fixture — qualitative selection frequencies, starvation-freedom,
    punishment, recovery, and in-flight extrapolation."""

    LAT = {0: 1_000, 1: 10_000}      # fast 1ms, slow 10ms (us)

    def _drive(self, lb, rounds, err_eps=(), lat=None):
        lat = lat or self.LAT
        counts = collections.Counter()
        for _ in range(rounds):
            ep = lb.select_server()
            counts[ep] += 1
            i = EPS.index(ep)
            if ep in err_eps:
                lb.feedback(ep, 1009, lat.get(i, 1_000))
            else:
                lb.feedback(ep, 0, lat.get(i, 1_000))
        return counts

    def test_converges_to_inverse_latency_frequencies(self):
        lb = make("la", n=2)
        self._drive(lb, 400)                      # converge
        counts = self._drive(lb, 2000)
        # weight ∝ 1/latency: the 10x-faster server should see roughly
        # 10x the traffic; demand at least 5x (loose, seedless RNG)
        assert counts[EPS[0]] > counts[EPS[1]] * 5, counts
        # ...but the slow server is NOT starved
        assert counts[EPS[1]] > 0, counts

    def test_erroring_server_is_punished_but_not_starved(self):
        lb = make("la", n=3)
        lat = {0: 1_000, 1: 1_000, 2: 1_000}
        self._drive(lb, 600, err_eps={EPS[2]}, lat=lat)
        counts = self._drive(lb, 3000, err_eps={EPS[2]}, lat=lat)
        healthy = counts[EPS[0]] + counts[EPS[1]]
        # punished samples are avg*4 compounding through the window:
        # the erroring server ends with a small fraction of traffic...
        assert counts[EPS[2]] < healthy * 0.2, counts
        # ...but still some (starvation-freedom: it must be probed to
        # ever recover)
        assert counts[EPS[2]] > 0, counts

    def test_weight_recovers_after_errors_stop(self):
        lb = make("la", n=2)
        lat = {0: 1_000, 1: 1_000}
        self._drive(lb, 400, err_eps={EPS[1]}, lat=lat)
        punished = lb.weight_of(EPS[1])
        assert punished < lb.weight_of(EPS[0]) / 3
        # errors stop: real samples wash the punishment out of the
        # window and the weight climbs back toward parity.  Recovery is
        # a positive-feedback loop (more weight -> more probe traffic ->
        # faster washing), so it starts slow; bound the total rounds and
        # assert parity is actually REACHED, not just approached.
        for _ in range(30):
            self._drive(lb, 1000, lat=lat)
            if lb.weight_of(EPS[1]) > lb.weight_of(EPS[0]) * 0.5:
                break
        recovered = lb.weight_of(EPS[1])
        assert recovered > punished * 3
        assert recovered > lb.weight_of(EPS[0]) * 0.5

    def test_inflight_extrapolation_divides_a_stuck_servers_weight(self):
        import time as _time
        lb = make("la", n=2)
        lat = {0: 1_000, 1: 1_000}
        self._drive(lb, 200, lat=lat)
        w_before = lb.weight_of(EPS[1])
        # EPS[1] freezes: selections pile up in flight, no feedback.
        # Force-select it via per-call exclusion of EPS[0].
        class C:
            _excluded_servers = {EPS[0]}
        for _ in range(4):
            assert lb.select_server(C()) == EPS[1]
        _time.sleep(0.02)     # 20ms elapsed >> 1ms avg latency
        w_stuck = lb.weight_of(EPS[1])
        # divided weight: avg/elapsed ≈ 1ms/20ms → at least 5x down,
        # with NO feedback ever having arrived
        assert w_stuck < w_before / 5, (w_before, w_stuck)
        # the healthy server is untouched
        assert lb.weight_of(EPS[0]) > w_stuck * 5
