"""Native ici:// datapath (native/rpc.cpp ici plane + ici/native_plane.py).

The fusion VERDICT r3 #1 demanded: framing, window accounting, dispatch and
correlation in C++, with Python upcalled only for device-ref relocation.
These tests pin down the custody discipline (no registry leaks on ANY
path), the credit window, cross-device relocation on the 8-device CPU
mesh, and interop with the rpc.Server/Channel front doors.
"""
import threading
import time

import numpy as np
import pytest

import brpc_tpu.policy  # noqa: F401  (registers protocols)
from brpc_tpu import rpc, ici
from brpc_tpu.ici import native_plane
from tests.echo_pb2 import EchoRequest, EchoResponse

pytestmark = pytest.mark.skipif(not native_plane.available(),
                                reason="native core unavailable")


@pytest.fixture(scope="module")
def mesh():
    import jax
    m = ici.IciMesh(jax.devices())
    ici.IciMesh.set_default(m)
    return m


def _device_payload(mesh, dev=0, n=4096):
    import jax
    import jax.numpy as jnp
    arr = jax.device_put(jnp.arange(n, dtype=jnp.uint8), mesh.device(dev))
    jax.block_until_ready(arr)
    return arr


class EchoService(rpc.Service):
    SERVICE_NAME = "EchoService"

    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        if len(cntl.request_attachment):
            cntl.response_attachment.append(cntl.request_attachment)
        done()


class TestNativeDatapath:
    def test_channel_rides_native_plane(self, mesh):
        """rpc.Channel → ici:// routes through the C++ plane: the native
        request counter moves, and the registry never leaks."""
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start("ici://2") == 0
        try:
            binding = getattr(server, "_native_ici", None)
            assert binding is not None, "native ici plane not attached"
            ch = rpc.Channel()
            ch.init("ici://2")
            payload = _device_payload(mesh)
            before = binding.requests()
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="native"),
                                  EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "native"
            assert cntl.response_attachment.to_bytes() == bytes(
                np.arange(4096, dtype=np.uint8))
            assert binding.requests() == before + 1
        finally:
            server.stop()
        assert native_plane.registry().live() == 0

    def test_native_echo_tier_and_relocation(self, mesh):
        """Compiled echo tier: zero Python dispatch; a payload resident on
        another mesh device is relocated toward the CLIENT device on the
        way back (the rdma zero-copy SGE pass-through)."""
        if mesh.size < 2:
            pytest.skip("needs >=2 devices")
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start("ici://3") == 0
        try:
            server._native_ici.register_native_echo("EchoService.Echo")
            ch = rpc.Channel()
            ch.init("ici://3")
            payload = _device_payload(mesh, dev=1)
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="m"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            refs = cntl.response_attachment.device_refs()
            assert len(refs) == 1
            # echoed ref was relocated to the channel's local device
            # (ici_connect default: the neighbor of ici://3 → device 4)
            local_dev = ch._native_ici.local_dev
            assert {str(d) for d in refs[0].block.data.devices()} == \
                {str(mesh.device(local_dev))}
            assert cntl.response_attachment.to_bytes() == bytes(
                np.arange(4096, dtype=np.uint8))
        finally:
            server.stop()
        assert native_plane.registry().live() == 0

    def test_handler_sees_resident_attachment(self, mesh):
        """Python-tier handler observes its device refs already resident
        on the SERVER device (relocation happened before the upcall)."""
        if mesh.size < 3:
            pytest.skip("needs >=3 devices")
        seen = {}

        class Probe(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def P(self, cntl, request, response, done):
                refs = cntl.request_attachment.device_refs()
                seen["devs"] = {str(d) for r in refs
                                for d in r.block.data.devices()}
                response.message = "ok"
                done()

        server = rpc.Server()
        server.add_service(Probe())
        assert server.start("ici://4") == 0
        try:
            ch = rpc.Channel()
            ch.init("ici://4")
            payload = _device_payload(mesh, dev=2)
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            ch.call_method("Probe.P", cntl, EchoRequest(message="x"),
                           EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert seen["devs"] == {str(mesh.device(4))}
        finally:
            server.stop()
        assert native_plane.registry().live() == 0

    def test_mixed_host_device_attachment_order(self, mesh):
        """Interleaved host/device attachment segments keep their order
        across the plane (the segment-descriptor sidecar)."""
        got = {}

        class Mix(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def M(self, cntl, request, response, done):
                got["bytes"] = cntl.request_attachment.to_bytes()
                got["blocks"] = [
                    cntl.request_attachment.backing_block(i).block.kind
                    for i in range(
                        cntl.request_attachment.backing_block_num())]
                response.message = "ok"
                done()

        server = rpc.Server()
        server.add_service(Mix())
        assert server.start("ici://5") == 0
        try:
            from brpc_tpu.butil.iobuf import DEVICE, HOST
            ch = rpc.Channel()
            ch.init("ici://5")
            payload = _device_payload(mesh, n=16)
            cntl = rpc.Controller()
            cntl.request_attachment.append(b"head-")
            cntl.request_attachment.append_device_array(payload)
            cntl.request_attachment.append(b"-tail")
            ch.call_method("Mix.M", cntl, EchoRequest(message="x"),
                           EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert got["bytes"] == b"head-" + bytes(range(16)) + b"-tail"
            assert got["blocks"][0] == HOST
            assert DEVICE in got["blocks"]
        finally:
            server.stop()
        assert native_plane.registry().live() == 0

    def test_error_paths_release_custody(self, mesh):
        """ENOMETHOD with a device attachment must release the refs (the
        drop-path release upcall), not leak them pinned forever."""
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start("ici://6") == 0
        try:
            ch = rpc.Channel()
            ch.init("ici://6")
            payload = _device_payload(mesh)
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            ch.call_method("NoSuch.Method", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert cntl.failed()
            assert cntl.error_code_ == rpc.errors.ENOMETHOD
        finally:
            server.stop()
        assert native_plane.registry().live() == 0

    def test_error_response_with_segs_releases_on_client(self, mesh,
                                                         monkeypatch):
        """An ABI server may respond err != 0 AND device segs (the Python
        server never does, but brpc_tpu_ici_respond allows it); native
        copies segs_out regardless of rc, so the CLIENT must release the
        keys on its rc != 0 path or they strand in the registry forever
        (exactly-one-exit custody)."""
        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.ici.native_plane import split_attachment

        class Failing(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def F(self, cntl, request, response, done):
                cntl.set_failed(rpc.errors.EINTERNAL, "deliberate")
                done()

        server = rpc.Server()
        server.add_service(Failing())
        assert server.start("ici://5") == 0
        try:
            binding = server._native_ici
            arr = _device_payload(mesh)

            def err_with_segs(token, err, text, collector=None, post=None,
                              retry_after=0):
                att = IOBuf()
                att.append_device_array(arr)
                att_host, segs = split_attachment(att)
                binding._respond_flush([(token, err, text.encode(), b"",
                                         att_host, segs, post,
                                         retry_after, 0)])

            monkeypatch.setattr(binding, "_respond_one", err_with_segs)
            ch = rpc.Channel()
            ch.init("ici://5")
            cntl = rpc.Controller()
            ch.call_method("Failing.F", cntl, EchoRequest(message="x"),
                           EchoResponse)
            assert cntl.failed()
            assert cntl.error_code_ == rpc.errors.EINTERNAL
        finally:
            server.stop()
        assert native_plane.registry().live() == 0

    def test_timeout_drops_late_response_and_releases(self, mesh):
        """A handler answering after the client deadline: the client gets
        ERPCTIMEDOUT, the late response is dropped, custody released."""
        release = threading.Event()
        responded = threading.Event()

        class Slow(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def S(self, cntl, request, response, done):
                def later():
                    release.wait(5)
                    if len(cntl.request_attachment):
                        cntl.response_attachment.append(
                            cntl.request_attachment)
                    response.message = "late"
                    done()
                    responded.set()
                threading.Thread(target=later, daemon=True).start()

        server = rpc.Server()
        server.add_service(Slow())
        assert server.start("ici://7") == 0
        try:
            ch = rpc.Channel()
            ch.init("ici://7",
                    options=rpc.ChannelOptions(timeout_ms=150, max_retry=0))
            payload = _device_payload(mesh)
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            ch.call_method("Slow.S", cntl, EchoRequest(message="x"),
                           EchoResponse)
            assert cntl.failed()
            assert cntl.error_code_ == rpc.errors.ERPCTIMEDOUT
            release.set()
            assert responded.wait(5)
            deadline = time.monotonic() + 5
            while native_plane.registry().live() and \
                    time.monotonic() < deadline:
                time.sleep(0.01)
            assert native_plane.registry().live() == 0
        finally:
            release.set()
            server.stop()

    def test_oversize_frame_fails_fast(self, mesh):
        """A frame that can never fit the send window fails EOVERCROWDED
        immediately instead of burning the whole deadline."""
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start("ici://8") == 0
        try:
            binding = native_plane.ChannelBinding(8, window_bytes=1024)
            try:
                cntl = rpc.Controller()
                cntl.timeout_ms = 10000
                cntl.request_attachment.append(b"x" * 8192)
                t0 = time.monotonic()
                binding.call("EchoService.Echo", cntl,
                             EchoRequest(message="x"), EchoResponse)
                assert cntl.failed()
                assert cntl.error_code_ == rpc.errors.EOVERCROWDED
                assert time.monotonic() - t0 < 2.0   # did NOT wait 10 s
            finally:
                binding.close()
        finally:
            server.stop()
        assert native_plane.registry().live() == 0

    def test_concurrent_callers(self, mesh):
        """Many threads over one channel: correlation never crosses wires
        and nothing leaks."""
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start("ici://9") == 0
        errs = []
        try:
            ch = rpc.Channel()
            ch.init("ici://9")

            def worker(wid):
                try:
                    for i in range(25):
                        cntl = rpc.Controller()
                        msg = f"w{wid}-{i}"
                        resp = ch.call_method("EchoService.Echo", cntl,
                                              EchoRequest(message=msg),
                                              EchoResponse)
                        assert not cntl.failed(), cntl.error_text
                        assert resp.message == msg
                except Exception as e:   # pragma: no cover
                    errs.append(e)

            threads = [threading.Thread(target=worker, args=(w,))
                       for w in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errs, errs
        finally:
            server.stop()
        assert native_plane.registry().live() == 0

    def test_server_stop_fails_inflight_cleanly(self, mesh):
        """Channel outliving its server gets EFAILEDSOCKET, and a fresh
        server on the same device id serves a fresh channel."""
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start("ici://10") == 0
        ch = rpc.Channel()
        ch.init("ici://10")
        cntl = rpc.Controller()
        ch.call_method("EchoService.Echo", cntl,
                       EchoRequest(message="a"), EchoResponse)
        assert not cntl.failed()
        server.stop()
        cntl = rpc.Controller()
        ch.call_method("EchoService.Echo", cntl,
                       EchoRequest(message="b"), EchoResponse)
        assert cntl.failed()
        # fresh server, fresh channel: the device id is reusable
        server2 = rpc.Server()
        server2.add_service(EchoService())
        assert server2.start("ici://10") == 0
        try:
            ch2 = rpc.Channel()
            ch2.init("ici://10")
            cntl = rpc.Controller()
            resp = ch2.call_method("EchoService.Echo", cntl,
                                   EchoRequest(message="c"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "c"
        finally:
            server2.stop()
        assert native_plane.registry().live() == 0

    def test_async_done_callback(self, mesh):
        """done= callbacks run off the caller thread and see the filled
        controller (the ParallelChannel composition contract)."""
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start("ici://11") == 0
        try:
            ch = rpc.Channel()
            ch.init("ici://11")
            ev = threading.Event()
            out = {}

            def done(cntl):
                out["failed"] = cntl.failed()
                out["resp"] = cntl.response
                ev.set()

            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="async"), EchoResponse,
                           done=done)
            assert ev.wait(10)
            assert out["failed"] is False
            assert out["resp"].message == "async"
        finally:
            server.stop()


class TestReviewFindings:
    """Regression pins for the r4 code-review findings."""

    def test_channel_survives_server_restart(self, mesh):
        """A long-lived Channel must keep working across a server restart
        (the cached native conn is invalidated and the call re-routes)."""
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start("ici://12") == 0
        ch = rpc.Channel()
        ch.init("ici://12")
        cntl = rpc.Controller()
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="one"), EchoResponse)
        assert not cntl.failed() and resp.message == "one"
        server.stop()
        server2 = rpc.Server()
        server2.add_service(EchoService())
        assert server2.start("ici://12") == 0
        try:
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="two"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "two"
        finally:
            server2.stop()
        assert native_plane.registry().live() == 0

    def test_oversize_attachment_falls_back_to_python_plane(self, mesh):
        """An attachment bigger than the native send window rides the
        Python plane (which drains it chunkwise) instead of failing."""
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start("ici://13") == 0
        try:
            ch = rpc.Channel()
            ch.init("ici://13",
                    options=rpc.ChannelOptions(timeout_ms=60000,
                                               max_retry=0))
            big = b"z" * (6 * 1024 * 1024)      # > the 4MB native window
            cntl = rpc.Controller()
            cntl.request_attachment.append(big)
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="big"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "big"
            assert cntl.response_attachment.to_bytes() == big
        finally:
            server.stop()

    def test_no_deadline_means_no_deadline(self, mesh):
        """timeout_ms=0 over the native plane waits, matching the Python
        plane's no-deadline semantics (not a silent 5s default)."""
        gate = threading.Event()

        class Slowish(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def S(self, cntl, request, response, done):
                def later():
                    gate.wait(10)
                    response.message = "eventually"
                    done()
                threading.Thread(target=later, daemon=True).start()

        server = rpc.Server()
        server.add_service(Slowish())
        assert server.start("ici://14") == 0
        try:
            ch = rpc.Channel()
            ch.init("ici://14", options=rpc.ChannelOptions(timeout_ms=0))
            out = {}
            def call():
                cntl = rpc.Controller()
                cntl.timeout_ms = 0
                out["resp"] = ch.call_method(
                    "Slowish.S", cntl, EchoRequest(message="x"),
                    EchoResponse)
                out["failed"] = cntl.failed()
            t = threading.Thread(target=call, daemon=True)
            t.start()
            time.sleep(0.3)
            assert t.is_alive()          # still waiting, not timed out
            gate.set()
            t.join(10)
            assert not t.is_alive()
            assert out["failed"] is False
            assert out["resp"].message == "eventually"
        finally:
            gate.set()
            server.stop()

    def test_out_of_mesh_array_still_relocates(self, mesh):
        """An attachment on a device OUTSIDE the mesh gets dev=-1 and is
        relocated via the upcall (never silently passed through)."""
        import jax
        if len(jax.devices()) == mesh.size:
            # build a smaller mesh so an out-of-mesh device exists
            if mesh.size < 2:
                pytest.skip("needs >=2 devices")
            small = ici.IciMesh(jax.devices()[:1])
            old = mesh
            ici.IciMesh.set_default(small)
            try:
                seen = {}

                class Probe(rpc.Service):
                    @rpc.method(EchoRequest, EchoResponse)
                    def P(self, cntl, request, response, done):
                        refs = cntl.request_attachment.device_refs()
                        seen["devs"] = {str(d) for r in refs
                                        for d in r.block.data.devices()}
                        response.message = "ok"
                        done()

                server = rpc.Server()
                server.add_service(Probe())
                assert server.start("ici://0") == 0
                try:
                    import jax.numpy as jnp
                    outside = jax.device_put(
                        jnp.arange(64, dtype=jnp.uint8), jax.devices()[1])
                    jax.block_until_ready(outside)
                    ch = rpc.Channel()
                    ch.init("ici://0")
                    cntl = rpc.Controller()
                    cntl.request_attachment.append_device_array(outside)
                    ch.call_method("Probe.P", cntl,
                                   EchoRequest(message="x"), EchoResponse)
                    assert not cntl.failed(), cntl.error_text
                    # resident on the SERVER's mesh device, not the
                    # out-of-mesh source
                    assert seen["devs"] == {str(small.device(0))}
                finally:
                    server.stop()
            finally:
                ici.IciMesh.set_default(old)
        assert native_plane.registry().live() == 0


class TestAsyncPoolSafety:
    def test_async_calls_beyond_pool_size_complete(self, mesh):
        """More concurrent async (done=) calls than bthread workers, each
        parking in the native condvar while its Python-tier handler needs
        a tasklet: blocked-worker compensation must keep the pool live
        (review finding r4: without note_worker_blocked this deadlocks
        until timeout)."""
        class Nap(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def N(self, cntl, request, response, done):
                time.sleep(0.05)
                response.message = request.message
                done()

        server = rpc.Server()
        server.add_service(Nap())
        assert server.start("ici://15") == 0
        try:
            ch = rpc.Channel()
            ch.init("ici://15",
                    options=rpc.ChannelOptions(timeout_ms=10000))
            n = 8                       # > bthread_concurrency default (4)
            evs = [threading.Event() for _ in range(n)]
            outs = [None] * n

            def make_done(i):
                def done(cntl):
                    outs[i] = (cntl.failed(), cntl.response)
                    evs[i].set()
                return done

            t0 = time.monotonic()
            for i in range(n):
                cntl = rpc.Controller()
                ch.call_method("Nap.N", cntl,
                               EchoRequest(message=f"m{i}"), EchoResponse,
                               done=make_done(i))
            for i, ev in enumerate(evs):
                assert ev.wait(8), f"call {i} never completed (deadlock?)"
            assert time.monotonic() - t0 < 8
            for i, (failed, resp) in enumerate(outs):
                assert failed is False
                assert resp.message == f"m{i}"
        finally:
            server.stop()
        assert native_plane.registry().live() == 0


class TestNativeLoopBench:
    def test_cpp_loop_echo_runs(self, mesh):
        p50 = native_plane.native_ici_echo_p50_us(200, 64)
        assert p50 > 0
        arr = _device_payload(mesh, n=1024)
        p50d = native_plane.native_ici_echo_p50_us(200, 64,
                                                   device_array=arr)
        assert p50d > 0
        assert native_plane.registry().live() == 0


class TestFaultInjectionOnFastPlane:
    def test_injected_fault_reaches_native_ici_calls(self, mesh):
        """Fault injection covers the native plane (the Python plane
        injects at Socket.write; the binding is the equivalent edge)."""
        from brpc_tpu.rpc import fault_injection as fi
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start("ici://16") == 0
        try:
            ch = rpc.Channel()
            ch.init("ici://16",
                    options=rpc.ChannelOptions(timeout_ms=2000,
                                               max_retry=0))
            with fi.inject(fi.FaultInjector(error_ratio=1.0)):
                cntl = rpc.Controller()
                ch.call_method("EchoService.Echo", cntl,
                               EchoRequest(message="x"), EchoResponse)
                assert cntl.failed()
                assert cntl.error_code_ == rpc.errors.EFAILEDSOCKET
            # injector uninstalled: the plane works again
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="y"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "y"
        finally:
            server.stop()
        assert native_plane.registry().live() == 0


class TestRelocateCustody:
    def test_relocate_detaches_ctypes_backed_views(self, mesh):
        """ADVICE r5: _relocate used to jax.device_put ctypes-backed
        numpy views (host-delivered fabric bulk payloads forwarded into
        an in-process native-plane call) directly — device_put zero-copy
        ALIASES such buffers without retaining them, so recycling the
        native receive buffer corrupted the relocated payload.  The fix
        detaches into an owned copy first (transport.py discipline)."""
        import ctypes

        import jax

        n = 4096
        # 64-byte-aligned backing memory, like the native plane's malloc'd
        # receive buffers: XLA only zero-copy-aliases sufficiently aligned
        # hosts, so an unaligned buffer would mask the bug
        raw = (ctypes.c_uint8 * (n + 64))()
        addr = ctypes.addressof(raw)
        buf = (ctypes.c_uint8 * n).from_address(addr + (-addr) % 64)
        np.ctypeslib.as_array(buf)[:] = np.arange(n, dtype=np.uint8) % 251
        view = np.frombuffer(buf, dtype=np.uint8)   # what _bulk_claim_array
        expect = view.copy()                        # hands to host delivery
        reg = native_plane.registry()
        key = reg.put(view)
        new_key = 0
        try:
            new_key = native_plane._relocate(key, 0)
            assert new_key != 0, "relocate failed"
            assert new_key != key, "numpy view cannot be 'resident'"
            moved = reg.peek(new_key)
            jax.block_until_ready(moved)
            # the native pool recycles the receive buffer under the view
            ctypes.memset(buf, 0, n)
            np.testing.assert_array_equal(np.asarray(moved), expect)
        finally:
            reg.release(key)
            if new_key and new_key != key:
                reg.release(new_key)


class TestNativeAttCustody:
    """ISSUE 12: native-side attachment custody.  Every path a parked
    handle can take — pass-through, materialize, pool-recycle dispose,
    reject, per-request batch failure, late response after timeout —
    must end with the exactly-one-exit invariant: the device-ref
    registry AND the native att table drain to zero (also enforced
    fleet-wide by the conftest census)."""

    def _echo_server(self, dev, body):
        class Svc(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                body(cntl, request, response)
                done()

        server = rpc.Server()
        server.add_service(Svc())
        assert server.start(f"ici://{dev}") == 0
        ch = rpc.Channel()
        ch.init(f"ici://{dev}",
                options=rpc.ChannelOptions(timeout_ms=10000, max_retry=0,
                                           ici_local_device=dev))
        return server, ch

    @staticmethod
    def _drained():
        deadline = time.monotonic() + 3
        import gc
        while time.monotonic() < deadline:
            if (native_plane.registry().live() == 0
                    and native_plane.att_table_live() == 0):
                return True
            gc.collect()
            time.sleep(0.02)
        return False

    def test_passthrough_view_is_lazy_and_byte_exact(self, mesh):
        """The echo shape: the handler sees a lazily-materialized
        NativeAttachment (len answers WITHOUT inflating), assigns it as
        the response, and the handle rides back natively — the client's
        view materializes to the exact bytes."""
        seen = {}

        def body(cntl, request, response):
            att = cntl.request_attachment
            seen["type"] = type(att).__name__
            seen["len"] = len(att)
            seen["mat_before_len"] = att._mat
            response.message = request.message
            cntl.response_attachment = att

        server, ch = self._echo_server(20, body)
        try:
            payload = _device_payload(mesh, dev=20)
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="pt"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "pt"
            assert seen["type"] == "NativeAttachment"
            assert seen["len"] == 4096
            assert seen["mat_before_len"] is False, \
                "len() must not materialize the view"
            out = cntl.response_attachment
            assert type(out).__name__ == "NativeAttachment"
            assert len(out) == 4096 and not out._mat
            assert out.to_bytes() == bytes(np.arange(4096, dtype=np.uint8))
            assert out._mat                     # touch materialized it
            del cntl, out
        finally:
            server.stop()
        assert self._drained()

    def test_append_pattern_materializes_and_stays_correct(self, mesh):
        """The PR-8 idiom (response_attachment.append(request_attachment))
        keeps working: appending an unmaterialized view into another
        IOBuf inflates it (keys taken, entry dropped) and the bytes are
        exact — slower than the pass-through, never wrong."""
        def body(cntl, request, response):
            response.message = request.message
            if len(cntl.request_attachment):
                cntl.response_attachment.append(cntl.request_attachment)

        server, ch = self._echo_server(21, body)
        try:
            payload = _device_payload(mesh, dev=21)
            for _ in range(3):
                cntl = rpc.Controller()
                cntl.request_attachment.append_device_array(payload)
                ch.call_method("EchoService.Echo", cntl,
                               EchoRequest(message="ap"), EchoResponse)
                assert not cntl.failed(), cntl.error_text
                assert cntl.response_attachment.to_bytes() == bytes(
                    np.arange(4096, dtype=np.uint8))
            del cntl
        finally:
            server.stop()
        assert self._drained()

    def test_ignored_attachment_disposed_at_pool_recycle(self, mesh):
        """A handler that never touches its attachment: the parked
        handle's ONLY exit is Controller pool-recycle — the registry
        and att table must still drain."""
        def body(cntl, request, response):
            response.message = "ok"        # attachment deliberately unread

        server, ch = self._echo_server(22, body)
        try:
            payload = _device_payload(mesh, dev=22)
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            del cntl
        finally:
            server.stop()
        assert self._drained()

    def test_reject_path_disposes_view(self, mesh):
        """ENOMETHOD with a device attachment: the reject runs before
        any handler — _release_attachment_custody must dispose the
        parked handle."""
        server = rpc.Server()
        server.add_service(EchoService())
        assert server.start("ici://23") == 0
        try:
            ch = rpc.Channel()
            ch.init("ici://23",
                    options=rpc.ChannelOptions(timeout_ms=5000,
                                               max_retry=0,
                                               ici_local_device=23))
            payload = _device_payload(mesh, dev=23)
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            ch.call_method("NoSuch.Method", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert cntl.failed()
            assert cntl.error_code_ == rpc.errors.ENOMETHOD
            del cntl
        finally:
            server.stop()
        assert self._drained()

    def test_per_request_failure_isolation_disposes_handle(self, mesh):
        """A handler raising mid-request: the EINTERNAL answer must not
        strand the parked handle (the batch loop's isolation path or
        the invoke error path dispose it)."""
        def body(cntl, request, response):
            if request.message == "boom":
                raise RuntimeError("deliberate")
            response.message = request.message

        server, ch = self._echo_server(24, body)
        try:
            payload = _device_payload(mesh, dev=24)
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="boom"), EchoResponse)
            assert cntl.failed()
            assert cntl.error_code_ == rpc.errors.EINTERNAL
            # a healthy request right after: the route stays up
            cntl2 = rpc.Controller()
            cntl2.request_attachment.append_device_array(payload)
            resp = ch.call_method("EchoService.Echo", cntl2,
                                  EchoRequest(message="fine"),
                                  EchoResponse)
            assert not cntl2.failed() and resp.message == "fine"
            del cntl, cntl2
        finally:
            server.stop()
        assert self._drained()

    def test_late_passthrough_after_timeout_releases(self, mesh):
        """Chaos kill mid-batch shape: the client times out, the handler
        passes the handle back LATE — native delivers to an abandoned
        slot and must release the parked keys (no strand)."""
        release = threading.Event()
        responded = threading.Event()

        class Slow(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def S(self, cntl, request, response, done):
                def later():
                    release.wait(5)
                    cntl.response_attachment = cntl.request_attachment
                    response.message = "late"
                    done()
                    responded.set()
                threading.Thread(target=later, daemon=True).start()

        server = rpc.Server()
        server.add_service(Slow())
        assert server.start("ici://25") == 0
        try:
            ch = rpc.Channel()
            ch.init("ici://25",
                    options=rpc.ChannelOptions(timeout_ms=150,
                                               max_retry=0,
                                               ici_local_device=25))
            payload = _device_payload(mesh, dev=25)
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            ch.call_method("Slow.S", cntl, EchoRequest(message="x"),
                           EchoResponse)
            assert cntl.failed()
            assert cntl.error_code_ == rpc.errors.ERPCTIMEDOUT
            release.set()
            assert responded.wait(5)
            del cntl
        finally:
            release.set()
            server.stop()
        assert self._drained()

    def test_client_view_del_is_the_release(self, mesh):
        """A client that never reads its response attachment: dropping
        the view (refcount/GC) must dispose the handle — the steady
        bench shape, where cleanup rides __del__ between calls."""
        def body(cntl, request, response):
            response.message = "ok"
            cntl.response_attachment = cntl.request_attachment

        server, ch = self._echo_server(26, body)
        try:
            payload = _device_payload(mesh, dev=26)
            for _ in range(4):
                cntl = rpc.Controller()
                cntl.request_attachment.append_device_array(payload)
                ch.call_method("EchoService.Echo", cntl,
                               EchoRequest(message="x"), EchoResponse)
                assert not cntl.failed(), cntl.error_text
                # response view intentionally untouched; the rebind of
                # `cntl` next iteration drops it
            del cntl
        finally:
            server.stop()
        assert self._drained()

    def test_legacy_mode_byte_identical(self, mesh):
        """ici_native_att_custody=False restores the PR-8 walk: plain
        IOBuf both sides, same bytes, same drained registry."""
        from brpc_tpu.butil import flags as _fl
        from brpc_tpu.butil.iobuf import IOBuf
        prev = _fl.get_flag("ici_native_att_custody")
        _fl.set_flag("ici_native_att_custody", False)
        try:
            seen = {}

            def body(cntl, request, response):
                seen["type"] = type(cntl.request_attachment).__name__
                response.message = request.message
                cntl.response_attachment.append(cntl.request_attachment)

            server, ch = self._echo_server(27, body)
            try:
                payload = _device_payload(mesh, dev=27)
                cntl = rpc.Controller()
                cntl.request_attachment.append_device_array(payload)
                ch.call_method("EchoService.Echo", cntl,
                               EchoRequest(message="x"), EchoResponse)
                assert not cntl.failed(), cntl.error_text
                assert seen["type"] == "IOBuf"
                assert type(cntl.response_attachment) is IOBuf
                assert cntl.response_attachment.to_bytes() == bytes(
                    np.arange(4096, dtype=np.uint8))
                del cntl
            finally:
                server.stop()
        finally:
            _fl.set_flag("ici_native_att_custody", prev)
        assert self._drained()

    def test_proxy_forwarding_view_as_request(self, mesh):
        """Proxy shape: handler A forwards its (unmaterialized) view as
        the REQUEST attachment of a nested call to server B —
        materialization + re-registration keep bytes and custody
        exact end to end."""
        inner_server = rpc.Server()
        inner_server.add_service(EchoService())
        assert inner_server.start("ici://28") == 0
        inner_ch = rpc.Channel()
        inner_ch.init("ici://28",
                      options=rpc.ChannelOptions(timeout_ms=10000,
                                                 max_retry=0,
                                                 ici_local_device=28))

        def body(cntl, request, response):
            inner = rpc.Controller()
            inner.request_attachment.append(cntl.request_attachment)
            r = inner_ch.call_method("EchoService.Echo", inner,
                                     EchoRequest(message="inner"),
                                     EchoResponse)
            assert not inner.failed(), inner.error_text
            response.message = r.message
            cntl.response_attachment = inner.response_attachment

        server, ch = self._echo_server(29, body)
        try:
            payload = _device_payload(mesh, dev=29)
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="outer"),
                                  EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "inner"
            assert cntl.response_attachment.to_bytes() == bytes(
                np.arange(4096, dtype=np.uint8))
            del cntl
        finally:
            server.stop()
            inner_server.stop()
        assert self._drained()


class TestBuildAttachmentExceptionSafety:
    """ISSUE 12 satellite: build_attachment_from_c used to strand every
    not-yet-walked device key when IOBuf construction raised mid-walk
    (native clears its seg list when the upcall returns — the remaining
    keys had no owner left).  Pinned with a fault-injected mid-walk
    failure at the unit level."""

    def test_midwalk_failure_releases_unwalked_keys(self, mesh,
                                                    monkeypatch):
        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.ici.native_plane import (build_attachment_from_c,
                                               fill_seg_array)
        reg = native_plane.registry()
        base = reg.live()
        arrs = [_device_payload(mesh, dev=0, n=256) for _ in range(3)]
        segs = [(reg.put(a), 256, 0, 1) for a in arrs]
        seg_arr = fill_seg_array(segs)
        calls = {"n": 0}
        real = IOBuf.append_device_array_unchecked

        def flaky(self, arr, nbytes):
            calls["n"] += 1
            if calls["n"] == 2:
                raise MemoryError("injected mid-walk failure")
            return real(self, arr, nbytes)

        monkeypatch.setattr(IOBuf, "append_device_array_unchecked", flaky)
        with pytest.raises(MemoryError):
            build_attachment_from_c(b"", seg_arr, 3)
        # seg 0: taken into the dropped buf (custody exited into Python);
        # seg 1: taken then the append failed (the local ref released it);
        # seg 2: NEVER walked — the fix releases it before re-raising
        assert reg.live() == base, (
            f"{reg.live() - base} keys stranded after mid-walk failure")

    def test_clean_walk_unchanged(self, mesh):
        from brpc_tpu.ici.native_plane import (build_attachment_from_c,
                                               fill_seg_array)
        reg = native_plane.registry()
        arrs = [_device_payload(mesh, dev=0, n=128) for _ in range(2)]
        segs = [(reg.put(arrs[0]), 128, 0, 1), (0, 3, 0, 0),
                (reg.put(arrs[1]), 128, 0, 1)]
        buf = build_attachment_from_c(b"abc", fill_seg_array(segs), 3)
        assert len(buf) == 128 + 3 + 128
        assert buf.to_bytes() == bytes(np.arange(128, dtype=np.uint8)) \
            + b"abc" + bytes(np.arange(128, dtype=np.uint8))
        assert reg.live() == 0


class TestFusedDispatch:
    """ISSUE 13 tentpole: the fused per-RPC code objects (server
    _process_fused/_FusedDone, client call_fused) must be semantically
    byte-identical to the legacy PR-12 chain — same responses, same
    error codes, same gate ordering, same custody exits — while the
    frame count per RPC stays inside the pinned budget."""

    def _server_channel(self, dev, fused, service=None, opts=None):
        from brpc_tpu.butil import flags as fl
        prev = fl.get_flag("ici_fused_dispatch")
        fl.set_flag("ici_fused_dispatch", fused)
        try:
            server = rpc.Server(opts or rpc.ServerOptions(
                usercode_inline=True))
            server.add_service(service or EchoService())
            assert server.start(f"ici://{dev}") == 0
            ch = rpc.Channel()
            ch.init(f"ici://{dev}",
                    options=rpc.ChannelOptions(timeout_ms=10000,
                                               max_retry=0,
                                               ici_local_device=dev))
        finally:
            fl.set_flag("ici_fused_dispatch", prev)
        return server, ch

    def _echo(self, ch, mesh, msg="m", n=512):
        payload = _device_payload(mesh, dev=0, n=n)
        cntl = rpc.Controller()
        cntl.request_attachment.append_device_array(payload)
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message=msg), EchoResponse)
        return cntl, resp

    def test_fused_vs_legacy_byte_parity(self, mesh):
        """The same echo (attachment + payload) through both dispatch
        generations produces identical bytes; the route counters prove
        which chain actually ran."""
        results = {}
        for fused in (True, False):
            server, ch = self._server_channel(3, fused)
            try:
                cntl, resp = self._echo(ch, mesh, msg="parity")
                assert not cntl.failed(), cntl.error_text
                results[fused] = (resp.message,
                                  cntl.response_attachment.to_bytes())
                binding = server._native_ici
                if fused:
                    assert binding.fused_dispatched >= 1
                    assert binding.legacy_dispatched == 0
                else:
                    assert binding.legacy_dispatched >= 1
                    assert binding.fused_dispatched == 0
            finally:
                server.stop()
        assert results[True] == results[False]

    def test_fused_error_paths_match_legacy(self, mesh):
        """ENOMETHOD, handler exception, and parse failure return the
        same codes through both chains."""
        class Boom(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                raise ValueError("kaboom")

        for fused in (True, False):
            server, ch = self._server_channel(3, fused, service=Boom())
            try:
                cntl = rpc.Controller()
                ch.call_method("EchoService.Nope", cntl,
                               EchoRequest(message="x"), EchoResponse)
                assert cntl.error_code == rpc.errors.ENOMETHOD
                cntl = rpc.Controller()
                ch.call_method("EchoService.Echo", cntl,
                               EchoRequest(message="x"), EchoResponse)
                assert cntl.error_code == rpc.errors.EINTERNAL
                assert "kaboom" in cntl.error_text
                # parse failure: raw garbage bytes as the request
                cntl = rpc.Controller()
                ch.call_method("EchoService.Echo", cntl,
                               b"\xff\xff\xff\xff\xff", None)
                assert cntl.error_code == rpc.errors.EREQUEST, \
                    cntl.error_text
            finally:
                server.stop()

    def test_fused_async_handler_and_send_response(self, mesh):
        """A handler that parks done() for a later thread, and one that
        answers via cntl.send_response(), both complete under fusion."""
        import threading as _th

        class Async(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                if request.message == "sendresp":
                    response.message = "via-send-response"
                    cntl.send_response()
                    return
                response.message = "later"
                _th.Timer(0.03, done).start()

        server, ch = self._server_channel(3, True, service=Async())
        try:
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="park"),
                                  EchoResponse)
            assert not cntl.failed() and resp.message == "later"
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="sendresp"),
                                  EchoResponse)
            assert not cntl.failed() \
                and resp.message == "via-send-response"
        finally:
            server.stop()

    def test_fused_admission_delegates_to_legacy_chain(self, mesh):
        """An admission-controlled server keeps the full shed/WFQ
        decision tree: the fused entry resolves the method but the
        request rides the legacy chain (counter proves it)."""
        opts = rpc.ServerOptions(usercode_inline=True, admission=True)
        server, ch = self._server_channel(3, True, opts=opts)
        try:
            cntl, resp = self._echo(ch, mesh, msg="adm")
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "adm"
            binding = server._native_ici
            assert binding.fused_dispatched == 0
            assert binding.legacy_dispatched >= 1
        finally:
            server.stop()

    def test_fused_draining_bounces_elogoff(self, mesh):
        server, ch = self._server_channel(3, True)
        try:
            cntl, resp = self._echo(ch, mesh)
            assert not cntl.failed()
            server._draining = True
            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert cntl.error_code == rpc.errors.ELOGOFF
        finally:
            server._draining = False
            server.stop()

    def test_fused_context_masking_for_nested_dispatch(self, mesh):
        """A handler WITHOUT admission meta must not leak an outer
        inline context into its own outbound calls: the fused path
        masks exactly like _reqctx.scope."""
        from brpc_tpu.rpc import request_context as reqctx
        seen = {}

        class Svc(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                seen["ctx"] = reqctx.current()
                seen["ddl"] = cntl.deadline_left_ms
                response.message = "ok"
                done()

        server, ch = self._server_channel(3, True, service=Svc())
        try:
            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert not cntl.failed()
            # the channel stamps deadline_left from timeout_ms, so the
            # handler sees a real inbound context with that budget (the
            # legacy scope() behavior)
            assert seen["ctx"] is not None
            assert seen["ctx"].deadline_left_ms == seen["ddl"] > 0
        finally:
            server.stop()

    def test_frame_budget(self, mesh):
        """ISSUE 13 satellite: interpreter frames per RPC on the
        native-ici echo path, measured with sys.setprofile around ONE
        call_method.  The budget pins this PR's measured number (+
        slack) so frame creep fails a named test instead of surfacing
        as a bench surprise.  PR-12's equivalent-methodology count was
        93 (the cProfile figure in ROADMAP, ~170, also counted C
        calls); this PR measured ~40 fused."""
        import sys as _sys
        server, ch = self._server_channel(3, True)
        try:
            # resident payload (the bench shape): a cross-device payload
            # would add jax's whole device_put stack to every call and
            # measure relocation, not dispatch
            payload = _device_payload(mesh, dev=3, n=512)
            req = EchoRequest(message="f")

            def one():
                cntl = rpc.Controller()
                cntl.request_attachment.append_device_array(payload)
                return cntl

            for _ in range(30):
                cntl = one()
                ch.call_method("EchoService.Echo", cntl, req,
                               EchoResponse)
                assert not cntl.failed(), cntl.error_text
            counts = []
            for _ in range(20):
                cntl = one()
                n = [0]

                def prof(frame, event, arg, _n=n):
                    if event == "call":
                        _n[0] += 1

                _sys.setprofile(prof)
                ch.call_method("EchoService.Echo", cntl, req,
                               EchoResponse)
                _sys.setprofile(None)
                assert not cntl.failed(), cntl.error_text
                counts.append(n[0])
            counts.sort()
            frames = counts[len(counts) // 2]
            BUDGET = 60          # measured ~40 + slack
            assert frames <= BUDGET, (
                f"frame creep: {frames} frames/RPC on the fused "
                f"native-ici echo path (budget {BUDGET}; PR-12 "
                f"same-methodology baseline was 93)")
        finally:
            server.stop()


class TestAppendPassThrough:
    """ISSUE 13 satellite: the PR-8 append idiom on a WHOLE, untouched
    NativeAttachment view adopts the parked handle (ResponseAttachment)
    instead of materializing — byte-exact, with exactly-one-exit
    holding (census-enforced per test, asserted explicitly here)."""

    @staticmethod
    def _drained():
        deadline = time.monotonic() + 3
        import gc
        while time.monotonic() < deadline:
            if (native_plane.registry().live() == 0
                    and native_plane.att_table_live() == 0):
                return True
            gc.collect()
            time.sleep(0.02)
        return False

    def _run(self, mesh, body, n=1024):
        class Svc(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                body(cntl, response)
                done()

        server = rpc.Server(rpc.ServerOptions(usercode_inline=True))
        server.add_service(Svc())
        assert server.start("ici://3") == 0
        try:
            ch = rpc.Channel()
            ch.init("ici://3",
                    options=rpc.ChannelOptions(timeout_ms=10000,
                                               max_retry=0,
                                               ici_local_device=3))
            payload = _device_payload(mesh, dev=3, n=n)
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="a"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            out = cntl.response_attachment.to_bytes()
        finally:
            server.stop()
        return out

    def test_append_whole_view_adopts_handle(self, mesh):
        """The idiom's destination ADOPTS the parked handle (no
        materialization: the response attachment stays lazy inside the
        handler) and the bytes come back exact."""
        adopted = {}

        def body(cntl, response):
            response.message = "x"
            cntl.response_attachment.append(cntl.request_attachment)
            ra = cntl._peek_response_attachment()
            adopted["lazy"] = isinstance(ra, native_plane.NativeAttachment) \
                and not ra._mat and ra._h != 0
            adopted["donor_surrendered"] = \
                cntl.request_attachment._h == 0

        out = self._run(mesh, body)
        assert out == bytes(np.arange(1024, dtype=np.uint8))
        assert adopted["lazy"], "append materialized instead of adopting"
        assert adopted["donor_surrendered"]
        assert self._drained()

    def test_append_then_more_bytes_materializes(self, mesh):
        """Touching the adopted buffer again inflates it — correctness
        beats the fast path."""
        def body(cntl, response):
            response.message = "x"
            cntl.response_attachment.append(cntl.request_attachment)
            cntl.response_attachment.append(b"tail")

        out = self._run(mesh, body, n=256)
        assert out == bytes(np.arange(256, dtype=np.uint8)) + b"tail"
        assert self._drained()

    def test_append_into_nonempty_keeps_legacy_path(self, mesh):
        """A non-empty destination cannot adopt: the view materializes
        (the pre-fix behavior) and the bytes stay exact."""
        def body(cntl, response):
            response.message = "x"
            cntl.response_attachment.append(b"head")
            cntl.response_attachment.append(cntl.request_attachment)

        out = self._run(mesh, body, n=256)
        assert out == b"head" + bytes(np.arange(256, dtype=np.uint8))
        assert self._drained()
