"""Protocol authenticators, HTTP-registry naming services, compack
serialization, trackme pings.

Reference patterns: brpc_naming_service_unittest.cpp mocks registry
payloads; redis/couchbase authenticator tests drive the client against
in-process backends (SURVEY.md §4)."""
import http.server
import json
import struct
import threading
import time

import pytest

import brpc_tpu.policy
from brpc_tpu import rpc
from brpc_tpu.butil import flags as _flags
from brpc_tpu.codec.mcpack import (FIELD_ISOARRAY, FIELD_INT8, FIELD_INT32,
                                   mcpack_decode, mcpack_encode)
from brpc_tpu.policy import memcache as mc
from brpc_tpu.policy import redis as redis_proto
from brpc_tpu.policy.auth import (CouchbaseAuthenticator, EspAuthenticator,
                                  RedisAuthenticator)
from brpc_tpu.policy.naming import create_naming_service
from brpc_tpu.rpc import errors
from tests.test_redis_memcache import (KvRedis, start_mini_memcached,
                                       unique)


# ------------------------------------------------------ redis AUTH ------

class AuthKvRedis(KvRedis):
    def __init__(self, password):
        super().__init__()
        self.password = password
        self.auth_attempts = []
        self.add_handler("AUTH", self._auth)

    def _auth(self, args):
        self.auth_attempts.append(bytes(args[0]))
        if bytes(args[0]).decode() == self.password:
            return redis_proto.RedisReply(redis_proto.REPLY_STATUS, "OK")
        return redis_proto.RedisReply(redis_proto.REPLY_ERROR,
                                      "ERR invalid password")


class TestRedisAuth:
    def _start(self, password="sesame", auth=None):
        server = rpc.Server()
        svc = AuthKvRedis(password)
        server.add_service(svc)
        name = unique("redisauth")
        assert server.start(f"mem://{name}") == 0
        ch = rpc.Channel()
        ch.init(f"mem://{name}", options=rpc.ChannelOptions(
            protocol="redis", timeout_ms=5000, auth=auth))
        return server, svc, ch

    def test_auth_sent_once_and_hidden(self):
        server, svc, ch = self._start(
            auth=RedisAuthenticator("sesame"))
        try:
            for i in range(3):
                cntl = rpc.Controller()
                resp = ch.call_method("redis", cntl, ("PING",), None)
                assert not cntl.failed(), cntl.error_text
                # the AUTH +OK must never leak into user replies
                assert resp.reply(0).value == "PONG"
            assert svc.auth_attempts == [b"sesame"]   # once per connection
        finally:
            server.stop()

    def test_bad_password_fails_rpc(self):
        server, svc, ch = self._start(
            auth=RedisAuthenticator("wrong"))
        try:
            cntl = rpc.Controller()
            ch.call_method("redis", cntl, ("PING",), None)
            assert cntl.failed()
            assert cntl.error_code == errors.ERPCAUTH
        finally:
            server.stop()


# -------------------------------------------------- memcache SASL -------

class TestCouchbaseAuth:
    def test_sasl_plain_sent_and_hidden(self):
        backend, target, listener = start_mini_memcached(
            sasl_expect=b"\x00bucket\x00pw")
        ch = rpc.Channel()
        try:
            ch.init(target, options=rpc.ChannelOptions(
                protocol="memcache", timeout_ms=5000,
                auth=CouchbaseAuthenticator("bucket", "pw")))
            req = mc.MemcacheRequest()
            req.set("k", b"v")
            req.get("k")
            cntl = rpc.Controller()
            resp = ch.call_method("memcache", cntl, req, None)
            assert not cntl.failed(), cntl.error_text
            assert backend.sasl_seen == 1
            assert len(resp.ops) == 2                 # SASL reply consumed
            assert resp.op(1).value == b"v"
        finally:
            ch.close()
            from brpc_tpu.rpc.mem_transport import mem_unlisten
            mem_unlisten(listener.name)

    def test_sasl_rejected(self):
        backend, target, listener = start_mini_memcached(
            sasl_expect=b"\x00bucket\x00right")
        ch = rpc.Channel()
        try:
            ch.init(target, options=rpc.ChannelOptions(
                protocol="memcache", timeout_ms=5000,
                auth=CouchbaseAuthenticator("bucket", "wrong")))
            req = mc.MemcacheRequest()
            req.get("k")
            cntl = rpc.Controller()
            ch.call_method("memcache", cntl, req, None)
            assert cntl.failed() and cntl.error_code == errors.ERPCAUTH
        finally:
            ch.close()
            from brpc_tpu.rpc.mem_transport import mem_unlisten
            mem_unlisten(listener.name)

    def test_esp_authenticator_magic(self):
        cred = EspAuthenticator().generate_credential(None)
        assert cred.encode("latin-1").startswith(b"\x00ESP\x01\x02")


# ------------------------------------- HTTP-registry naming services ----

class _Registry(http.server.BaseHTTPRequestHandler):
    payloads = {}

    def do_GET(self):
        for prefix, body in self.payloads.items():
            if self.path.startswith(prefix):
                data = body if isinstance(body, bytes) else \
                    json.dumps(body).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
        self.send_response(404)
        self.end_headers()

    def log_message(self, *a):
        pass


@pytest.fixture(scope="module")
def registry():
    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Registry)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"127.0.0.1:{httpd.server_port}"
    httpd.shutdown()


class TestRegistryNaming:
    def test_nacos(self, registry):
        _Registry.payloads["/nacos/v1/ns/instance/list"] = {
            "hosts": [
                {"ip": "10.0.0.1", "port": 8000, "weight": 2.0,
                 "healthy": True, "enabled": True, "clusterName": "c1"},
                {"ip": "10.0.0.2", "port": 8000, "weight": 1.0,
                 "healthy": False},
                {"ip": "10.0.0.3", "port": 8001, "weight": 1.0,
                 "healthy": True, "enabled": False},
            ]}
        ns = create_naming_service(f"nacos://{registry}/my-service")
        servers = ns.get_servers()
        assert len(servers) == 1                  # only healthy+enabled
        assert servers[0].endpoint.host == "10.0.0.1"
        assert servers[0].weight == 200
        assert servers[0].tag == "c1"

    def test_discovery(self, registry):
        _Registry.payloads["/discovery/fetchs"] = {
            "data": {"my.app": {"instances": [
                {"addrs": ["grpc://10.1.0.1:9000",
                           "http://10.1.0.1:8080"], "status": 1,
                 "zone": "sh001"},
                {"addrs": ["grpc://10.1.0.2:9000"], "status": 3},
            ]}}}
        ns = create_naming_service(f"discovery://{registry}/my.app")
        servers = ns.get_servers()
        assert [(s.endpoint.host, s.endpoint.port) for s in servers] == \
            [("10.1.0.1", 9000), ("10.1.0.1", 8080)]
        assert servers[0].tag == "sh001"

    def test_remotefile(self, registry):
        _Registry.payloads["/servers.txt"] = \
            b"10.2.0.1:80 tagA\n# comment\n10.2.0.2:81\n"
        ns = create_naming_service(f"remotefile://{registry}/servers.txt")
        servers = ns.get_servers()
        assert len(servers) == 2
        assert servers[0].endpoint.port == 80 and servers[0].tag == "tagA"


# -------------------------------------------------------- compack -------

class TestCompack:
    def test_primitive_array_becomes_isoarray(self):
        data = mcpack_encode({"xs": [1, 2, 3]}, compack=True)
        # short isoarray head present with int8 item type
        assert bytes([FIELD_ISOARRAY | 0x80]) in data
        assert mcpack_decode(data) == {"xs": [1, 2, 3]}

    def test_widest_int_type_wins(self):
        data = mcpack_encode({"xs": [1, 70000]}, compack=True)
        assert mcpack_decode(data) == {"xs": [1, 70000]}
        i = data.index(bytes([FIELD_ISOARRAY | 0x80]))
        # short head: [type][name_size][value_size] + name + item-type byte
        assert data[i + 3 + data[i + 1]] == FIELD_INT32

    def test_doubles_and_bools(self):
        for xs in ([1.5, -2.5], [True, False, True]):
            data = mcpack_encode({"xs": xs}, compack=True)
            assert mcpack_decode(data) == {"xs": xs}

    def test_mixed_list_falls_back(self):
        data = mcpack_encode({"xs": [1, "two"]}, compack=True)
        assert bytes([FIELD_ISOARRAY | 0x80]) not in data
        assert mcpack_decode(data) == {"xs": [1, "two"]}

    def test_mcpack_v2_unchanged_by_default(self):
        assert mcpack_encode({"xs": [1, 2, 3]}) == \
            mcpack_encode({"xs": [1, 2, 3]}, compack=False)


# -------------------------------------------------------- trackme -------

class TestTrackme:
    def test_ping_and_bulletin(self):
        from brpc_tpu.rpc import trackme
        from brpc_tpu.tools.trackme_server import TrackMeService
        from brpc_tpu.proto.trackme_pb2 import TRACKME_WARNING

        svc = TrackMeService()
        svc.add_bulletin(0, 10**9, TRACKME_WARNING, "upgrade me")
        hub = rpc.Server()
        hub.add_service(svc)
        name = unique("trackme")
        assert hub.start(f"mem://{name}") == 0
        _flags.set_flag("trackme_server", f"mem://{name}")
        _flags.set_flag("trackme_interval", 1)
        try:
            app = rpc.Server()
            assert app.start(f"mem://{unique('app')}") == 0
            deadline = time.monotonic() + 5
            while not svc.version_counts() and time.monotonic() < deadline:
                time.sleep(0.05)
            counts = svc.version_counts()
            assert counts.get(trackme.RPC_VERSION, 0) >= 1
            app.stop()
        finally:
            trackme.stop_trackme()
            _flags.set_flag("trackme_server", "")
            hub.stop()

    def test_off_by_default(self):
        from brpc_tpu.rpc import trackme
        assert _flags.get_flag("trackme_server") == ""
        assert trackme.start_trackme("x") is False
