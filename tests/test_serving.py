"""Production serving subsystem (brpc_tpu/serving, ISSUE 14 + the
ISSUE-15 zero-copy KV handoff).

Legs:

  * **PagedKvPool units** — block accounting, byte-exact custody,
    admission-aware eviction order (band before weight before LRU, the
    protected-band fence), pins, and the TIMER-DRIVEN expiry sweep (the
    ISSUE-14 bugfix regression: a parked session on an otherwise-idle
    worker is reclaimed with zero new traffic);
  * **zero-copy KV handoff** (ISSUE 15) — byte parity of the adopted /
    scattered / materialized load routes incl. straddling segments and
    partial-tail zeroing, abort-clean fills, counted pins, the
    snapshot-view materialize bugfix, RPC-level route assertions with
    custody census, and the 2-PROCESS shm claim-to-pool leg;
  * **CoW prefix sharing + outside-the-lock fills** (ISSUE 16) — the
    >= 5x capacity A/B on a 50 %-shared-prefix mix, refcounted dedupe
    accounting, mid-block divergence and ``write_rows`` CoW splits with
    co-owners' bytes intact, refcount-aware eviction order, reload
    keeping other tenants' bytes, read-only views over shared blocks,
    load/load_into locking parity under BOTH fill disciplines, the
    two-thread concurrent-fill stress, the commit-race window
    (last-commit-wins / pinned SessionBusy abort), and the RPC-level
    concurrent LoadKv leg with /status truth and custody census;
  * **ContinuousBatchScheduler units** (manual stepping) — per-step
    admit/retire, tokens bit-exact against the single-process reference
    under staggered joins, interactive preemption preserving progress,
    deadline expiry in the batch queue, compiled-step parity;
  * **service level** — the rebuilt disaggregated workers: batched
    decode end-to-end with the route asserted through the /status
    serving block, LALB prefill→decode routing, pool-saturation sheds
    with retry hints, and the idle-reclaim regression over a real RPC;
  * **autoscaler units** — watermark/hysteresis/cooldown decisions on
    an injected clock;
  * **elastic chaos** (tier-1, one subprocess with a real pod) —
    scale-up + kill + revive + scale-down mid-traffic: zero
    client-visible failures, every completion bit-exact, the pod epoch
    delta asserted.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import brpc_tpu.policy  # noqa: F401
from brpc_tpu import rpc

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _model():
    from examples.disagg_serving import model
    return model


def _rows(tokens):
    """Prompt → token-major pool rows (the LoadKv transpose)."""
    m = _model()
    kv = np.asarray(m.toy_kv_blocks(tokens))
    seq = len(tokens)
    return kv.reshape(m.KV_LAYERS, seq, m.KV_DMODEL).transpose(
        1, 0, 2).reshape(seq, m.KV_LAYERS * m.KV_DMODEL)


def _mk_pool(num_blocks=32, block_tokens=8, ttl_s=120.0,
             use_timers=False, now=None, **kw):
    from brpc_tpu.serving import KvPoolOptions, PagedKvPool
    m = _model()
    opts = KvPoolOptions(bytes_per_token=m.KV_LAYERS * m.KV_DMODEL,
                         num_blocks=num_blocks,
                         block_tokens=block_tokens, ttl_s=ttl_s,
                         use_timers=use_timers, **kw)
    return PagedKvPool(opts, now=now)


def _mk_sched(pool, max_batch=8, **kw):
    from brpc_tpu.serving import (BatchSchedulerOptions,
                                  ContinuousBatchScheduler)
    m = _model()
    kw.setdefault("auto_start", False)
    return ContinuousBatchScheduler(
        pool, BatchSchedulerOptions(vocab=m.VOCAB, max_batch=max_batch,
                                    **kw))


class _Sink:
    """Collects one StepRequest outcome."""

    def __init__(self):
        self.tokens = None
        self.error = None

    def emit(self, tokens):
        self.tokens = list(tokens)

    def fail(self, code, text, retry_after_ms):
        self.error = (code, text, retry_after_ms)


def _submit(sched, session, steps, priority=None, tenant="",
            deadline_us=None):
    from brpc_tpu.serving import StepRequest
    sink = _Sink()
    sched.submit(StepRequest(session, steps, sink.emit, sink.fail,
                             priority=priority, tenant=tenant,
                             deadline_us=deadline_us))
    return sink


# ---------------------------------------------------------------------------
# Paged KV pool.
# ---------------------------------------------------------------------------

class TestPagedKvPool:
    def test_load_materialize_byte_exact_and_accounting(self):
        pool = _mk_pool(num_blocks=16, block_tokens=8)
        try:
            t1 = [3 * j % 97 for j in range(20)]     # 3 blocks
            t2 = [5 * j % 89 for j in range(8)]      # 1 block
            r1, r2 = _rows(t1), _rows(t2)
            pool.load("a", r1, last_token=t1[-1])
            pool.load("b", r2, last_token=t2[-1])
            d = pool.describe()
            assert d["blocks_used"] == 4 and d["sessions"] == 2
            assert np.array_equal(pool.materialize("a"), r1)
            assert np.array_equal(pool.materialize("b"), r2)
            s = pool.get("a")
            assert s.seq_len == 20 and s.acc == int(
                r1.sum(dtype=np.int64))
            assert pool.release("a") and not pool.release("a")
            assert pool.describe()["blocks_used"] == 1
        finally:
            pool.close()

    def test_partial_tail_block_zeroed(self):
        # a partially-filled tail block must not leak the previous
        # tenant's bytes or reduction sums
        pool = _mk_pool(num_blocks=2, block_tokens=8)
        try:
            full = [7] * 16                           # both blocks, full
            pool.load("x", _rows(full), last_token=7)
            pool.release("x")
            short = [11] * 9                          # 2 blocks, 7 stale
            s = pool.load("y", _rows(short), last_token=11)
            tail_blk = int(s.blocks[1])
            assert pool._pos_sums[tail_blk, 1:].sum() == 0
            assert np.array_equal(pool.materialize("y"), _rows(short))
        finally:
            pool.close()

    def test_lru_eviction_within_band_and_touch(self):
        pool = _mk_pool(num_blocks=4, block_tokens=8)
        try:
            # DISTINCT content per session: identical rows would
            # prefix-share one physical block (ISSUE 16) and the pool
            # would never feel the pressure this test is about
            for i, name in enumerate(("old", "mid", "new")):
                pool.load(name, _rows([10 + i] * 8), last_token=1,
                          priority=2)
                time.sleep(0.002)
            pool.touch("old")                 # now "mid" is LRU
            pool.load("D", _rows([2] * 16), last_token=2, priority=2)
            assert pool.get("mid") is None
            assert pool.get("old") is not None
            assert pool.evicted_reason("mid") == "pressure"
        finally:
            pool.close()

    def test_batch_evicted_before_interactive(self):
        pool = _mk_pool(num_blocks=3, block_tokens=8)
        try:
            pool.load("inter", _rows([1] * 8), last_token=1, priority=0)
            time.sleep(0.002)
            pool.load("batch", _rows([2] * 8), last_token=2, priority=3)
            # interactive is OLDER, but the batch band absorbs pressure
            pool.load("new", _rows([3] * 16), last_token=3, priority=1)
            assert pool.get("batch") is None
            assert pool.get("inter") is not None
        finally:
            pool.close()

    def test_tenant_weight_tiebreak_from_admission(self):
        from brpc_tpu.rpc.admission import AdmissionOptions
        from brpc_tpu.serving import KvPoolOptions, PagedKvPool
        m = _model()
        adm = AdmissionOptions(tenant_weights={"gold": 8, "bronze": 1})
        opts = KvPoolOptions.from_admission(
            adm, bytes_per_token=m.KV_LAYERS * m.KV_DMODEL,
            num_blocks=3, block_tokens=8, use_timers=False)
        assert opts.tenant_weights == {"gold": 8, "bronze": 1}
        pool = PagedKvPool(opts)
        try:
            # same band; bronze is NEWER but lighter — evicted first
            pool.load("g", _rows([1] * 8), last_token=1, priority=2,
                      tenant="gold")
            time.sleep(0.002)
            pool.load("b", _rows([2] * 8), last_token=2, priority=2,
                      tenant="bronze")
            pool.load("n", _rows([3] * 16), last_token=3, priority=2)
            assert pool.get("b") is None
            assert pool.get("g") is not None
            assert any(k.startswith("evicted_pressure[bronze]")
                       for k in pool.describe()["by_tenant"])
        finally:
            pool.close()

    def test_requester_cannot_evict_more_protected_band(self):
        from brpc_tpu.serving import PoolSaturated
        pool = _mk_pool(num_blocks=2, block_tokens=8)
        try:
            pool.load("inter", _rows([1] * 16), last_token=1,
                      priority=0)
            with pytest.raises(PoolSaturated):
                pool.load("batch", _rows([2] * 8), last_token=2,
                          priority=3)
            assert pool.get("inter") is not None
        finally:
            pool.close()

    def test_pinned_never_evicted_or_expired(self):
        from brpc_tpu.serving import PoolSaturated
        pool = _mk_pool(num_blocks=2, block_tokens=8, ttl_s=0.0)
        try:
            pool.load("run", _rows([1] * 16), last_token=1, priority=3)
            assert pool.pin("run")
            with pytest.raises(PoolSaturated):
                pool.load("x", _rows([2] * 8), last_token=2, priority=0)
            assert pool.expire_idle() == 0    # pinned: ttl ignored
            pool.unpin("run")
            assert pool.expire_idle() == 1
        finally:
            pool.close()

    def test_timer_sweep_reclaims_idle_session_without_traffic(self):
        """THE ISSUE-14 regression: expiry is timer-driven — a parked
        session on an otherwise-idle pool is reclaimed on time with
        ZERO further loads or decodes (the old example swept only
        inside LoadKv)."""
        pool = _mk_pool(num_blocks=4, block_tokens=8, ttl_s=0.15,
                        use_timers=True, sweep_interval_s=0.05)
        try:
            pool.load("parked", _rows([1] * 8), last_token=1)
            deadline = time.monotonic() + 5.0
            while pool.sessions() and time.monotonic() < deadline:
                time.sleep(0.02)
            assert pool.sessions() == 0, "idle session never reclaimed"
            assert pool.expirations.get_value() >= 1
            assert pool.describe()["blocks_free"] == 4
        finally:
            pool.close()

    def test_reload_of_pinned_session_refused(self):
        """Re-prefilling a session that is PINNED in the step roster is
        refused (SessionBusy): freeing a rostered session's blocks
        would hand them to the new bytes mid-program — the running
        gather would read the replacement's KV (review finding)."""
        from brpc_tpu.serving import SessionBusy
        pool = _mk_pool(num_blocks=8, block_tokens=8)
        try:
            r1 = _rows([1] * 8)
            pool.load("s", r1, last_token=1)
            assert pool.pin("s")
            with pytest.raises(SessionBusy):
                pool.load("s", _rows([2] * 8), last_token=2)
            # the rostered table is untouched
            assert np.array_equal(pool.materialize("s"), r1)
            pool.unpin("s")
            pool.load("s", _rows([2] * 8), last_token=2)  # now fine
            assert np.array_equal(pool.materialize("s"), _rows([2] * 8))
        finally:
            pool.close()

    def test_zero_length_session_rejected(self):
        pool = _mk_pool()
        try:
            with pytest.raises(ValueError):
                pool.load("empty", np.zeros(
                    (0, pool.options.bytes_per_token), np.uint8),
                    last_token=0)
        finally:
            pool.close()

    def test_manual_expiry_with_injected_clock(self):
        clock = [100.0]
        pool = _mk_pool(num_blocks=4, block_tokens=8, ttl_s=10.0,
                        now=lambda: clock[0])
        try:
            pool.load("s", _rows([1] * 8), last_token=1)
            clock[0] = 109.0
            assert pool.expire_idle() == 0
            clock[0] = 111.0
            assert pool.expire_idle() == 1
            assert pool.evicted_reason("s") == "expired"
        finally:
            pool.close()


# ---------------------------------------------------------------------------
# Zero-copy KV handoff (ISSUE 15): attachment bytes land DIRECTLY in
# pool blocks — byte parity across all three load routes (straddling
# segments, partial tails, prior-tenant zeroing), custody census,
# abort-clean fills, and the snapshot-view bugfix pins.
# ---------------------------------------------------------------------------

def _wire(tokens):
    """Prompt → the layer-major wire payload LoadKv receives."""
    return np.asarray(_model().toy_kv_blocks(tokens))


class TestKvZeroCopyHandoff:
    def _adopt(self, pool, session, tokens, segments, **kw):
        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.serving import load_wire_attachment
        m = _model()
        buf = IOBuf()
        for seg in segments:
            buf.append_user_data(memoryview(seg))
        kw.setdefault("last_token", tokens[-1])
        return load_wire_attachment(pool, buf, session, len(tokens),
                                    m.KV_LAYERS, m.KV_DMODEL, **kw)

    def test_three_routes_byte_parity_incl_straddle(self):
        """adopted (host segs) vs scattered (device segs) vs
        materialized (load) produce IDENTICAL pool state — stored rows,
        pos_sums arena, and acc — including a multi-segment source cut
        at boundaries that straddle blocks, tokens, and even a single
        layer row."""
        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.serving import (kv_load_stats,
                                      load_wire_attachment, wire_source)
        m = _model()
        tokens = [(7 * j) % 499 for j in range(21)]     # partial tail
        blob = _wire(tokens).tobytes()
        pool = _mk_pool(num_blocks=16, block_tokens=8)
        try:
            s0 = kv_load_stats()
            # adopted: one host segment
            self._adopt(pool, "one", tokens, [blob])
            # adopted: segments cut mid-token and mid-layer-row
            cuts = [0, 13, 777, 781, 5000, 5003, len(blob)]
            segs = [blob[cuts[i]:cuts[i + 1]]
                    for i in range(len(cuts) - 1)]
            self._adopt(pool, "straddle", tokens, segs)
            # scattered: the device-array shape (loopback plane)
            buf = IOBuf()
            buf.append_device_array(m.toy_kv_blocks(tokens))
            load_wire_attachment(pool, buf, "dev", 21, m.KV_LAYERS,
                                 m.KV_DMODEL, last_token=tokens[-1])
            # scattered with an OFFSET device ref (a cut moved the ref,
            # not the bytes): only the referenced slice crosses D2H
            import jax.numpy as jnp
            padded = jnp.concatenate([
                jnp.zeros(7, jnp.uint8),
                jnp.asarray(np.frombuffer(blob, np.uint8))])
            buf2 = IOBuf()
            buf2.append_device_array(padded)
            buf2.pop_front(7)
            load_wire_attachment(pool, buf2, "devcut", 21, m.KV_LAYERS,
                                 m.KV_DMODEL, last_token=tokens[-1])
            # materialized: the PR-14 reference
            ref = pool.load("ref", _rows(tokens), last_token=tokens[-1])
            want = _rows(tokens)
            for name in ("one", "straddle", "dev", "devcut"):
                s = pool.get(name)
                assert np.array_equal(pool.materialize(name), want), name
                assert s.acc == ref.acc, name
                for k in range(len(s.blocks)):
                    assert np.array_equal(
                        pool._pos_sums[int(s.blocks[k])],
                        pool._pos_sums[int(ref.blocks[k])]), (name, k)
            s1 = kv_load_stats()
            assert s1["adopted"] - s0["adopted"] == 2
            assert s1["scattered"] - s0["scattered"] == 2
            # one copy pass per adopted/scattered load
            assert s1["copy_bytes"] - s0["copy_bytes"] == 4 * len(blob)
        finally:
            pool.close()

    def test_load_route_consults_plane_health(self):
        """ISSUE 17 seam: when the fabric socket that carried the
        LoadKv is supplied, the adopt-vs-scatter label comes from the
        SHARED route table — every descriptor plane down means the load
        records SCATTERED, a healthy plane (or no sock, the in-process
        path) keeps ADOPTED, and DEVICE-class payloads scatter no
        matter what the planes say."""
        from brpc_tpu.butil.iobuf import IOBuf
        from brpc_tpu.ici import fabric as _  # registers route flags
        from brpc_tpu.ici import route as _route
        from brpc_tpu.serving.kv_source import (ADOPTED, SCATTERED,
                                                wire_source)

        class _Sock:
            def __init__(self, up):
                self.up = up

            def plane_usable(self, plane, nbytes=0):
                return self.up

        # big enough to clear ici_fabric_bulk_host_min (64 KiB): below
        # it candidates() short-circuits to [INLINE] regardless of
        # plane health
        big = bytes(128 * 1024)
        layers, dmodel = 4, 64
        seq = len(big) // (layers * dmodel)

        def host_buf():
            buf = IOBuf()
            buf.append_user_data(memoryview(big))
            return buf

        # no sock: the in-process path, label untouched
        assert wire_source(host_buf(), layers, seq,
                           dmodel).route == ADOPTED
        # healthy descriptor planes: adopt in place
        assert wire_source(host_buf(), layers, seq, dmodel,
                           sock=_Sock(True)).route == ADOPTED
        # every descriptor plane has left UP: the counters must not
        # claim an in-place adoption rode a healthy plane
        assert wire_source(host_buf(), layers, seq, dmodel,
                           sock=_Sock(False)).route == SCATTERED
        # sanity: the fake's truth table IS what candidates() consults
        assert _route.SHM in _route.candidates(_Sock(True), _route.HOST,
                                               len(big))
        assert _route.candidates(_Sock(False), _route.HOST,
                                 len(big)) == [_route.INLINE]
        # DEVICE class scatters even on healthy planes (the D2H
        # crossing is the wire transfer itself)
        import jax.numpy as jnp
        dev = IOBuf()
        dev.append_device_array(
            jnp.zeros(len(big), jnp.uint8))
        assert wire_source(dev, layers, seq, dmodel,
                           sock=_Sock(True)).route == SCATTERED

    def test_partial_tail_zeroed_after_prior_tenant_adoption(self):
        """Tail-zeroing must hold on the ADOPTED path too: a short
        session scattered over a block a longer prior tenant filled
        leaves no stale bytes or reduction sums in the tail."""
        pool = _mk_pool(num_blocks=2, block_tokens=8)
        try:
            full = [7] * 16
            self._adopt(pool, "x", full, [_wire(full).tobytes()])
            pool.release("x")
            short = [11] * 9                       # 2 blocks, 7 stale
            s = self._adopt(pool, "y", short, [_wire(short).tobytes()])
            tail_blk = int(s.blocks[1])
            bpt = pool.options.bytes_per_token
            assert pool._pos_sums[tail_blk, 1:].sum() == 0
            assert pool._store[tail_blk, bpt:].sum() == 0
            assert np.array_equal(pool.materialize("y"), _rows(short))
        finally:
            pool.close()

    def test_fill_abort_returns_blocks_clean(self):
        """A fill that raises mid-load aborts the reservation: blocks
        back on the free list, no session entry, the failure counted —
        the eviction-mid-load / bad-source custody leg."""
        pool = _mk_pool(num_blocks=8, block_tokens=8)
        try:
            free0 = pool.describe()["blocks_free"]
            aborts0 = pool.fill_aborts.get_value()

            def bad_fill(views):
                views[0][0, 0] = 1          # partial write, then die
                raise RuntimeError("source died mid-scatter")

            with pytest.raises(RuntimeError, match="mid-scatter"):
                pool.load_into("victim", 20, bad_fill, last_token=1)
            assert pool.get("victim") is None
            assert pool.describe()["blocks_free"] == free0
            assert pool.fill_aborts.get_value() == aborts0 + 1
            # the pool still loads fine afterwards
            t = [5] * 20
            self._adopt(pool, "after", t, [_wire(t).tobytes()])
            assert np.array_equal(pool.materialize("after"), _rows(t))
            # a RELOAD whose fill aborts keeps the session's PREVIOUS
            # KV valid when the free list covered the reservation (the
            # old table's free is deferred to commit)
            with pytest.raises(RuntimeError, match="mid-scatter"):
                pool.load_into("after", 20, bad_fill, last_token=1)
            assert np.array_equal(pool.materialize("after"), _rows(t))
        finally:
            pool.close()

    def test_free_list_keeps_extent_order_after_churn(self):
        """The descending-sorted free list invariant: after arbitrary
        release order, pops still hand out ASCENDING block runs so
        adopted fills coalesce into few contiguous extents (the perf
        contract load_into's one-strided-pass fill depends on)."""
        pool = _mk_pool(num_blocks=8, block_tokens=8)
        try:
            for name in ("a", "b", "c", "d"):
                pool.load(name, _rows([1] * 16), last_token=1)
            for name in ("c", "a", "d", "b"):   # scrambled release
                pool.release(name)
            assert pool._free == sorted(pool._free, reverse=True)
            s = pool.load("big", _rows([2] * 64), last_token=2)
            assert np.array_equal(
                s.blocks, np.arange(int(s.blocks[0]),
                                    int(s.blocks[0]) + 8))
        finally:
            pool.close()

    def test_snapshot_view_and_straddle_copy(self):
        """THE ISSUE-15 materialize bugfix, pinned both ways: a
        contiguous-extent session snapshots as a READ-ONLY zero-copy
        view (pinned until unpin; release refused while read), and a
        non-contiguous session keeps the defensive copy."""
        pool = _mk_pool(num_blocks=4, block_tokens=8)
        try:
            t = [3] * 16
            pool.load("v", _rows(t), last_token=3)
            rows, seq, last, is_view = pool.snapshot("v", view=True)
            assert is_view and not rows.flags.writeable
            assert np.shares_memory(rows, pool._store)
            assert np.array_equal(rows, _rows(t))
            # pinned: eviction fenced; a racing release is DEFERRED to
            # the last unpin, never dropped and never freed mid-read
            assert pool.expire_idle(now=pool._now() + 1e9) == 0
            assert pool.release("v") is True      # accepted, deferred
            assert pool.get("v") is not None      # ...but not yet freed
            pool.unpin("v")                       # last reader out
            assert pool.get("v") is None          # now freed
            assert pool.release("v") is False     # idempotent: gone
            # force non-contiguous: fill, punch a hole, reload bigger
            pool.load("f1", _rows([1] * 8), last_token=1)
            pool.load("f2", _rows([2] * 8), last_token=2)
            pool.load("f3", _rows([3] * 8), last_token=3)
            pool.release("f2")
            pool.release("f1")
            pool.load("nc", _rows([4] * 24), last_token=4)  # 0,1,3
            s = pool.get("nc")
            assert not np.array_equal(
                s.blocks, np.arange(int(s.blocks[0]),
                                    int(s.blocks[0]) + 3))
            rows, _seq, _last, is_view = pool.snapshot("nc", view=True)
            assert not is_view
            assert not np.shares_memory(rows, pool._store)
            assert np.array_equal(rows, _rows([4] * 24))
            # no pin owed on the copy path
            assert pool.release("nc") is True
            # legacy 3-tuple surface unchanged; materialize stays
            # copy-only (it cannot carry the is-a-pin-owed flag)
            pool.load("old", _rows(t), last_token=3)
            snap = pool.snapshot("old")
            assert len(snap) == 3
            mat = pool.materialize("old")
            assert not np.shares_memory(mat, pool._store)
        finally:
            pool.close()

    def test_pins_are_counted_not_boolean(self):
        """A roster pin and a snapshot-view pin on the SAME session
        nest: releasing one must not unfence the other (the pinned
        bool→count change)."""
        pool = _mk_pool(num_blocks=4, block_tokens=8)
        try:
            pool.load("s", _rows([1] * 8), last_token=1)
            assert pool.pin("s")                      # roster
            rows, *_rest, is_view = pool.snapshot("s", view=True)
            assert is_view                            # + view pin
            pool.unpin("s")                           # view done
            # still fenced by the roster pin
            assert pool.expire_idle(now=pool._now() + 1e9) == 0
            assert pool.release("s") is True          # deferred again
            assert pool.get("s") is not None
            # a deferred-released session is LOGICALLY gone to new
            # readers: no new pin, no new snapshot — only the old
            # pinned reader drains it
            assert pool.pin("s") is False
            assert pool.snapshot("s") is None
            pool.unpin("s")                           # roster out: freed
            assert pool.get("s") is None
        finally:
            pool.close()

    def test_rpc_routes_asserted_and_custody_drains(self):
        """Service level: LoadKv over loopback rides the scattered
        route (DEVICE block), the flag-off leg rides materialized, and
        over the NATIVE-ICI plane the parked att handle is taken
        segment-wise — byte-exact decode on every route, with the att
        table and device-ref registry drained after each (the census
        fixture enforces it again at teardown)."""
        import gc

        from brpc_tpu.butil import flags as _fl
        from brpc_tpu.ici import native_plane as npl
        from brpc_tpu.serving import kv_load_stats
        from examples.example_echo_pb2 import EchoRequest, EchoResponse
        m = _model()
        tokens = [(17 * j) % 499 for j in range(40)]
        want = m.reference_generate(tokens, 9)

        def load(ch, session):
            kv = m.toy_kv_blocks(tokens)
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(kv)
            ch.call_method("Decode.LoadKv", cntl, EchoRequest(
                message=json.dumps({"session": session,
                                    "seq_len": len(tokens),
                                    "last_token": tokens[-1]})),
                EchoResponse)
            assert not cntl.failed(), cntl.error_text

        def decode(ch, session):
            cntl = rpc.Controller()
            resp = ch.call_method("Decode.Decode", cntl, EchoRequest(
                message=json.dumps({"session": session, "steps": 9,
                                    "mode": "sync"})), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            return json.loads(resp.message)["tokens"]

        from examples.disagg_serving.workers import DecodeService
        for plane, addr in (("loopback", "mem://kv-route"),
                            ("ici", "ici://5")):
            server = rpc.Server()
            svc = DecodeService()
            server.add_service(svc)
            assert server.start(addr) == 0
            ch = rpc.Channel()
            ch.init(addr, options=rpc.ChannelOptions(timeout_ms=30000))
            try:
                s0 = kv_load_stats()
                load(ch, "r1")
                assert decode(ch, "r1") == want, plane
                s1 = kv_load_stats()
                assert s1["scattered"] - s0["scattered"] == 1, plane
                assert s1["materialized"] == s0["materialized"], plane
                # flag-off leg: the PR-14 path byte-for-byte
                _fl.set_flag("serving_kv_adopt", False)
                try:
                    load(ch, "r2")
                finally:
                    _fl.set_flag("serving_kv_adopt", True)
                assert decode(ch, "r2") == want, plane
                s2 = kv_load_stats()
                assert s2["materialized"] - s1["materialized"] == 1
                gc.collect()
                assert npl.registry().live() == 0, plane
                assert npl.att_table_live() == 0, plane
                # the /status serving block carries the route counters
                blk = svc.describe_serving()
                assert blk["kv_load"]["scattered"] >= 1
            finally:
                ch.close()
                svc.close()
                server.stop()

    def test_saturated_adopted_load_sheds_clean(self):
        """PoolSaturated during an ADOPTED load (reservation refused
        before any fill): the RPC sheds with a retry hint and no
        custody leaks — the eviction-mid-load RPC leg."""
        from brpc_tpu.rpc import errors
        from brpc_tpu.serving import KvPoolOptions
        from examples.disagg_serving.workers import DecodeService
        from examples.example_echo_pb2 import EchoRequest, EchoResponse
        m = _model()
        server = rpc.Server()
        svc = DecodeService(pool_options=KvPoolOptions(
            bytes_per_token=m.KV_LAYERS * m.KV_DMODEL,
            num_blocks=2, block_tokens=8, use_timers=False))
        server.add_service(svc)
        assert server.start("mem://kv-shed") == 0
        ch = rpc.Channel()
        ch.init("mem://kv-shed",
                options=rpc.ChannelOptions(timeout_ms=30000,
                                           max_retry=0))
        try:
            def load(session, tokens, priority):
                kv = m.toy_kv_blocks(tokens)
                cntl = rpc.Controller()
                cntl.priority = priority
                cntl.request_attachment.append_device_array(kv)
                ch.call_method("Decode.LoadKv", cntl, EchoRequest(
                    message=json.dumps({"session": session,
                                        "seq_len": len(tokens),
                                        "last_token": tokens[-1]})),
                    EchoResponse)
                return cntl

            assert not load("inter", [1] * 16, 0).failed()
            cntl = load("batch", [2] * 8, 3)
            assert cntl.failed() and cntl.error_code_ == errors.ELIMIT
            assert cntl.retry_after_ms > 0
            assert svc.live_sessions() == 1
        finally:
            ch.close()
            svc.close()
            server.stop()


# ---------------------------------------------------------------------------
# Copy-on-write prefix sharing + outside-the-lock fills (ISSUE 16).
# ---------------------------------------------------------------------------

class TestKvPrefixSharing:
    def test_capacity_on_shared_prefix_mix_ab(self):
        """The acceptance A/B at pool level: a 50 %-shared-prefix mix
        (sessions alternate two 96-token system prompts + a unique
        4-token tail) fits >= 5x more concurrent sessions at fixed
        arena size with sharing ON than OFF, with zero byte mismatches
        across the whole resident set on both legs."""
        from brpc_tpu.butil import flags as _fl
        from brpc_tpu.serving import PoolSaturated
        pre_a = [(7 * j) % 499 for j in range(96)]     # 12 full blocks
        pre_b = [(11 * j + 3) % 499 for j in range(96)]

        def mk(i):
            pre = pre_a if i % 2 == 0 else pre_b
            return pre + [(13 * i + j + 1) % 499 for j in range(4)]

        cap = {}
        try:
            for flag in (True, False):
                _fl.set_flag("serving_kv_prefix_share", flag)
                pool = _mk_pool(num_blocks=64, block_tokens=8)
                loaded = []
                try:
                    i = 0
                    while i < 200:
                        toks = mk(i)
                        name = f"cap{i}"
                        try:
                            pool.load(name, _rows(toks),
                                      last_token=toks[-1])
                        except PoolSaturated:
                            break
                        # pinned: capacity under load, not LRU churn
                        assert pool.pin(name)
                        loaded.append((name, toks))
                        i += 1
                    for name, toks in loaded:
                        assert np.array_equal(pool.materialize(name),
                                              _rows(toks)), name
                    cap[flag] = len(loaded)
                    d = pool.describe()["prefix"]
                    if flag:
                        # both 12-block prompts fully shared
                        assert d["shared_blocks"] == 24
                        assert d["sharing_ratio"] > 2.0
                        assert d["prefix_hits"] > 0
                    else:
                        assert d["shared_blocks"] == 0
                        assert d["prefix_hits"] == 0
                finally:
                    for name, _ in loaded:
                        pool.unpin(name)
                    pool.close()
        finally:
            _fl.set_flag("serving_kv_prefix_share", True)
        assert cap[True] >= 5 * cap[False], cap

    def test_identical_sessions_share_all_full_blocks(self):
        pool = _mk_pool(num_blocks=16, block_tokens=8)
        try:
            toks = [(3 * j) % 499 for j in range(16)]  # 2 FULL blocks
            a = pool.load("a", _rows(toks), last_token=toks[-1])
            free1 = len(pool._free)
            b = pool.load("b", _rows(toks), last_token=toks[-1])
            assert np.array_equal(a.blocks, b.blocks)
            # the second load kept ZERO new physical blocks
            assert len(pool._free) == free1
            assert all(pool._refs[int(x)] == 2 for x in a.blocks)
            d = pool.describe()["prefix"]
            assert d["shared_blocks"] == 2 and d["prefix_hits"] == 2
            assert d["logical_blocks"] == 4
            assert d["physical_blocks"] == 2
            assert d["sharing_ratio"] == 2.0
            # releasing one owner keeps the other byte-exact (refcount
            # order: the physical free happens at ZERO, not at first)
            pool.release("a")
            assert np.array_equal(pool.materialize("b"), _rows(toks))
            pool.release("b")
            assert len(pool._free) == 16
            assert not pool._refs and not pool._prefix_index \
                and not pool._block_hash
        finally:
            pool.close()

    def test_cow_divergence_mid_block_and_write_split(self):
        pool = _mk_pool(num_blocks=16, block_tokens=8)
        try:
            pre = [(5 * j) % 499 for j in range(8)]    # 1 full block
            ta = pre + [7, 8, 9, 10]
            tb = pre + [7, 8, 99, 10]      # diverges MID second block
            a = pool.load("a", _rows(ta), last_token=ta[-1])
            b = pool.load("b", _rows(tb), last_token=tb[-1])
            assert int(a.blocks[0]) == int(b.blocks[0])   # shared
            assert int(a.blocks[1]) != int(b.blocks[1])   # private
            assert np.array_equal(pool.materialize("a"), _rows(ta))
            assert np.array_equal(pool.materialize("b"), _rows(tb))
            # a SHORTER session still shares the longer one's prefix
            c = pool.load("c", _rows(pre), last_token=pre[-1])
            assert int(c.blocks[0]) == int(a.blocks[0])
            # in-place mutation of the shared block CoW-splits: the
            # co-owners' bytes survive untouched
            splits0 = pool.cow_splits.get_value()
            new_row = np.full((1, pool.options.bytes_per_token), 7,
                              np.uint8)
            assert pool.write_rows("b", 0, new_row) == 1
            assert pool.cow_splits.get_value() == splits0 + 1
            assert int(pool.get("b").blocks[0]) != int(a.blocks[0])
            assert np.array_equal(pool.materialize("a"), _rows(ta))
            assert np.array_equal(pool.materialize("c"), _rows(pre))
            got = pool.materialize("b")
            assert np.array_equal(got[0], new_row[0])
            assert np.array_equal(got[1:], _rows(tb)[1:])
            # the reduction arena followed the write
            assert pool.get("b").acc == int(got.sum(dtype=np.int64))
        finally:
            pool.close()

    def test_shared_block_eviction_refcount_order(self):
        """Evicting one co-owner of a shared prefix frees NOTHING (the
        victim simulation knows); pressure that needs those blocks
        takes BOTH owners, and a pinned co-owner saturates instead."""
        from brpc_tpu.serving import PoolSaturated
        pool = _mk_pool(num_blocks=4, block_tokens=8)
        try:
            toks = [(9 * j) % 499 for j in range(16)]  # 2 full blocks
            pool.load("a", _rows(toks), last_token=1, priority=3)
            time.sleep(0.002)
            pool.load("b", _rows(toks), last_token=1, priority=3)
            big = [(2 * j + 1) % 499 for j in range(24)]   # 3 blocks
            # with one co-owner PINNED the shared blocks cannot free:
            # a typed shed, never a corrupting eviction
            assert pool.pin("b")
            with pytest.raises(PoolSaturated):
                pool.load("big", _rows(big), last_token=1, priority=2)
            pool.unpin("b")
            # unpinned: evicting LRU "a" alone frees nothing, so the
            # picker takes BOTH
            pool.load("big", _rows(big), last_token=1, priority=2)
            assert pool.get("a") is None and pool.get("b") is None
            assert np.array_equal(pool.materialize("big"), _rows(big))
            assert len(pool._free) == 1
        finally:
            pool.close()

    def test_reload_shared_prefix_keeps_other_tenants_bytes(self):
        pool = _mk_pool(num_blocks=16, block_tokens=8)
        try:
            pre = [(3 * j + 1) % 499 for j in range(16)]
            ta = pre + [5, 6, 7]
            tb = pre + [8, 9, 10]
            pool.load("a", _rows(ta), last_token=1)
            pool.load("b", _rows(tb), last_token=1)
            hits0 = pool.prefix_hits.get_value()
            # reload b with DIFFERENT content: a's bytes survive
            tb2 = [(7 * j + 2) % 499 for j in range(20)]
            pool.load("b", _rows(tb2), last_token=1)
            assert np.array_equal(pool.materialize("a"), _rows(ta))
            assert np.array_equal(pool.materialize("b"), _rows(tb2))
            # reload b BACK to the shared prefix: dedupes against a
            pool.load("b", _rows(tb), last_token=1)
            assert pool.prefix_hits.get_value() >= hits0 + 2
            assert int(pool.get("b").blocks[0]) == \
                int(pool.get("a").blocks[0])
            assert np.array_equal(pool.materialize("a"), _rows(ta))
            assert np.array_equal(pool.materialize("b"), _rows(tb))
        finally:
            pool.close()

    def test_readonly_view_over_shared_blocks(self):
        pool = _mk_pool(num_blocks=16, block_tokens=8)
        try:
            toks = [(13 * j + 5) % 499 for j in range(16)]  # 2 FULL
            pool.load("a", _rows(toks), last_token=toks[-1])
            b = pool.load("b", _rows(toks), last_token=toks[-1])
            # a fully-shared run is still one ascending extent
            assert b.contiguous
            rows, seq, last, is_view = pool.snapshot("b", view=True)
            assert is_view and not rows.flags.writeable
            with pytest.raises((ValueError, RuntimeError)):
                rows[0, 0] = 1
            assert np.array_equal(rows, _rows(toks))
            pool.unpin("b")
        finally:
            pool.close()

    def test_load_and_load_into_parity_both_disciplines(self):
        """The locking-parity satellite: load() delegates to
        load_into(), so both surfaces ride ONE reserve/fill/commit
        shape — identical session state and identical fill-route
        counters under BOTH fill disciplines."""
        from brpc_tpu.butil import flags as _fl
        toks = [(11 * j) % 499 for j in range(20)]
        rows = _rows(toks)
        try:
            for conc in (True, False):
                _fl.set_flag("serving_kv_concurrent_fill", conc)
                pool = _mk_pool(num_blocks=16, block_tokens=8)
                try:
                    a = pool.load("a", rows, last_token=toks[-1])

                    def fill(views):
                        off = 0
                        for v in views:
                            v[:] = rows[off:off + v.shape[0]]
                            off += v.shape[0]

                    b = pool.load_into("b", len(toks), fill,
                                       last_token=toks[-1])
                    route = (pool.unlocked_fills if conc
                             else pool.locked_fills)
                    other = (pool.locked_fills if conc
                             else pool.unlocked_fills)
                    assert route.get_value() == 2
                    assert other.get_value() == 0
                    assert a.acc == b.acc and a.seq_len == b.seq_len
                    assert np.array_equal(pool.materialize("a"),
                                          pool.materialize("b"))
                    # identical content: b shared a's FULL blocks
                    assert np.array_equal(a.blocks[:2], b.blocks[:2])
                finally:
                    pool.close()
        finally:
            _fl.set_flag("serving_kv_concurrent_fill", True)

    def test_concurrent_load_into_stress(self):
        """Two threads load/materialize/release disjoint session sets
        concurrently (fills outside the lock): byte-exact, no
        double-free, census intact after full release."""
        pool = _mk_pool(num_blocks=64, block_tokens=8)
        errors = []
        N = 40

        def worker(tag, salt):
            try:
                for i in range(N):
                    toks = [(7 * j + 31 * i + salt) % 499
                            for j in range(12 + (i % 3) * 8)]
                    name = f"{tag}{i}"
                    pool.load(name, _rows(toks), last_token=toks[-1])
                    got = pool.materialize(name)
                    if not np.array_equal(got, _rows(toks)):
                        errors.append(f"{name}: byte mismatch")
                    pool.release(name)
            except Exception as e:   # pragma: no cover
                errors.append(f"{tag}: {e!r}")

        ts = [threading.Thread(target=worker, args=("x", 1)),
              threading.Thread(target=worker, args=("y", 2))]
        try:
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert errors == []
            # no double-free, no leak: every block back exactly once,
            # the descending order invariant intact
            assert len(pool._free) == 64
            assert pool._free == sorted(pool._free, reverse=True)
            assert len(set(pool._free)) == 64
            assert not pool._refs
            assert pool.describe()["prefix"]["unlocked_fills"] == 2 * N
        finally:
            pool.close()

    def test_concurrent_fill_no_longer_serializes(self):
        """The concurrency claim asserted structurally: with the flag
        ON a second session's load COMPLETES while another fill is
        parked inside the pool; with the flag OFF the same load cannot
        finish until the stalled fill releases the pool lock."""
        from brpc_tpu.butil import flags as _fl
        toks_a = [(3 * j + 1) % 499 for j in range(16)]
        toks_b = [(5 * j + 2) % 499 for j in range(16)]
        try:
            for conc in (True, False):
                _fl.set_flag("serving_kv_concurrent_fill", conc)
                pool = _mk_pool(num_blocks=32, block_tokens=8)
                in_fill = threading.Event()
                unblock = threading.Event()
                done_b = threading.Event()
                try:
                    def slow_fill(views):
                        rows = _rows(toks_a)
                        off = 0
                        for v in views:
                            v[:] = rows[off:off + v.shape[0]]
                            off += v.shape[0]
                        in_fill.set()
                        assert unblock.wait(10)

                    ta = threading.Thread(
                        target=lambda: pool.load_into(
                            "a", len(toks_a), slow_fill,
                            last_token=toks_a[-1]))
                    ta.start()
                    assert in_fill.wait(10)
                    tb = threading.Thread(target=lambda: (
                        pool.load("b", _rows(toks_b),
                                  last_token=toks_b[-1]),
                        done_b.set()))
                    tb.start()
                    if conc:
                        assert done_b.wait(5), \
                            "concurrent fill serialized"
                    else:
                        assert not done_b.wait(0.3), \
                            "locked fill should serialize"
                    unblock.set()
                    ta.join(10)
                    tb.join(10)
                    assert done_b.is_set()
                    assert np.array_equal(pool.materialize("a"),
                                          _rows(toks_a))
                    assert np.array_equal(pool.materialize("b"),
                                          _rows(toks_b))
                    d = pool.describe()["prefix"]
                    if conc:
                        assert d["unlocked_fills"] == 2
                        assert d["locked_fills"] == 0
                    else:
                        assert d["locked_fills"] == 2
                        assert d["unlocked_fills"] == 0
                finally:
                    unblock.set()
                    pool.close()
        finally:
            _fl.set_flag("serving_kv_concurrent_fill", True)

    def test_commit_race_last_commit_wins_and_pinned_abort(self):
        """Two loaders race ONE session id across the fill window: the
        later commit wins when the incumbent is unpinned; a PINNED
        incumbent aborts the late fill with SessionBusy — blocks
        returned, incumbent bytes intact, race counted either way."""
        from brpc_tpu.serving import SessionBusy
        pool = _mk_pool(num_blocks=32, block_tokens=8)
        toks_slow = [(3 * j + 2) % 499 for j in range(12)]
        toks_fast = [(9 * j + 4) % 499 for j in range(12)]
        try:
            for pinned in (False, True):
                in_fill = threading.Event()
                unblock = threading.Event()
                result = {}

                def slow_fill(views):
                    rows = _rows(toks_slow)
                    off = 0
                    for v in views:
                        v[:] = rows[off:off + v.shape[0]]
                        off += v.shape[0]
                    in_fill.set()
                    assert unblock.wait(10)

                def racer():
                    try:
                        pool.load_into("s", len(toks_slow), slow_fill,
                                       last_token=toks_slow[-1])
                        result["ok"] = True
                    except SessionBusy:
                        result["busy"] = True

                races0 = pool.commit_races.get_value()
                t = threading.Thread(target=racer)
                t.start()
                assert in_fill.wait(10)
                # the fast loader commits the same id mid-fill
                pool.load("s", _rows(toks_fast),
                          last_token=toks_fast[-1])
                if pinned:
                    assert pool.pin("s")
                free_before = len(pool._free)
                unblock.set()
                t.join(10)
                assert pool.commit_races.get_value() == races0 + 1
                if pinned:
                    assert result.get("busy") and "ok" not in result
                    assert np.array_equal(pool.materialize("s"),
                                          _rows(toks_fast))
                    pool.unpin("s")
                else:
                    assert result.get("ok")
                    assert np.array_equal(pool.materialize("s"),
                                          _rows(toks_slow))
                # either way the loser's 2 blocks came back
                assert len(pool._free) == free_before + 2
                pool.release("s")
                result.clear()
            assert len(pool._free) == 32 and not pool._refs
        finally:
            pool.close()

    def test_pin_during_reload_fill_window_aborts_commit(self):
        """A same-session reload whose OLD entry gets PINNED (roster
        or snapshot view) during the outside-the-lock fill window must
        not free the pinned blocks at commit: the late fill aborts
        with SessionBusy, the incumbent's bytes stay intact, and the
        reservation returns clean.  The reserve-time pinned check
        cannot see this pin — only the commit-time re-check can."""
        from brpc_tpu.serving import SessionBusy
        pool = _mk_pool(num_blocks=32, block_tokens=8)
        toks_old = [(3 * j + 2) % 499 for j in range(12)]
        toks_new = [(9 * j + 4) % 499 for j in range(12)]
        in_fill = threading.Event()
        unblock = threading.Event()
        result = {}
        try:
            pool.load("s", _rows(toks_old), last_token=toks_old[-1])

            def slow_fill(views):
                rows = _rows(toks_new)
                off = 0
                for v in views:
                    v[:] = rows[off:off + v.shape[0]]
                    off += v.shape[0]
                in_fill.set()
                assert unblock.wait(10)

            def reloader():
                try:
                    pool.load_into("s", len(toks_new), slow_fill,
                                   last_token=toks_new[-1])
                    result["ok"] = True
                except SessionBusy:
                    result["busy"] = True

            t = threading.Thread(target=reloader)
            t.start()
            assert in_fill.wait(10)
            # the old entry enters a roster/view mid-fill
            assert pool.pin("s")
            races0 = pool.commit_races.get_value()
            free_before = len(pool._free)
            unblock.set()
            t.join(10)
            assert result.get("busy") and "ok" not in result
            # our own deferred_old is not a two-loader race
            assert pool.commit_races.get_value() == races0
            assert np.array_equal(pool.materialize("s"),
                                  _rows(toks_old))
            assert len(pool._free) == free_before + 2
            pool.unpin("s")
            pool.release("s")
            assert len(pool._free) == 32 and not pool._refs
        finally:
            unblock.set()
            pool.close()

    def test_write_rows_never_evicts_writing_session(self):
        """``write_rows`` needing a free block for a CoW split must
        never evict the session it is mutating (the writer's stale
        last_used made it the likely LRU pick), and when the eviction
        takes the block's last CO-OWNER the refcount re-check writes
        IN PLACE instead of stranding a 0-refcount block off both the
        free list and every table."""
        pool = _mk_pool(num_blocks=4, block_tokens=8)
        try:
            shared = [(9 * j) % 499 for j in range(16)]  # 2 full blocks
            other = [(2 * j + 1) % 499 for j in range(16)]
            pool.load("a", _rows(shared), last_token=shared[-1])
            time.sleep(0.002)
            pool.load("b", _rows(shared), last_token=shared[-1])
            time.sleep(0.002)
            pool.load("c", _rows(other), last_token=other[-1])
            assert not pool._free   # a+b share 2 blocks, c owns 2
            splits0 = pool.cow_splits.get_value()
            new_row = np.full((1, pool.options.bytes_per_token), 7,
                              np.uint8)
            # "a" is the unpinned LRU candidate — the bug evicted it
            # out from under its own write
            assert pool.write_rows("a", 0, new_row) == 0
            s = pool.get("a")
            assert s is not None, "writer evicted itself"
            got = pool.materialize("a")
            assert np.array_equal(got[0], new_row[0])
            assert np.array_equal(got[1:], _rows(shared)[1:])
            # the eviction took co-owner "b", so the re-check wrote in
            # place: no split, no stranded block — census exact
            assert pool.cow_splits.get_value() == splits0
            assert pool.get("b") is None
            assert all(pool._refs[int(x)] == 1 for x in s.blocks)
            assert len(pool._free) + len(pool._refs) == 4
            pool.release("a")
            assert len(pool._free) == 4 and not pool._refs \
                and not pool._prefix_index and not pool._block_hash
        finally:
            pool.close()

    def test_write_rows_split_after_eviction_keeps_coowner(self):
        """When the eviction for a split frees a THIRD session (the
        co-owner is pinned and survives), the refcount re-check still
        splits and the co-owner's bytes stay intact."""
        pool = _mk_pool(num_blocks=4, block_tokens=8)
        try:
            shared = [(9 * j) % 499 for j in range(16)]
            other = [(2 * j + 1) % 499 for j in range(16)]
            pool.load("a", _rows(shared), last_token=shared[-1])
            pool.load("b", _rows(shared), last_token=shared[-1])
            pool.load("c", _rows(other), last_token=other[-1])
            assert pool.pin("a")
            splits0 = pool.cow_splits.get_value()
            new_row = np.full((1, pool.options.bytes_per_token), 7,
                              np.uint8)
            assert pool.write_rows("b", 0, new_row) == 1
            assert pool.cow_splits.get_value() == splits0 + 1
            # "c" paid for the split block; pinned "a" is untouched
            assert pool.get("c") is None
            assert np.array_equal(pool.materialize("a"), _rows(shared))
            got = pool.materialize("b")
            assert np.array_equal(got[0], new_row[0])
            assert np.array_equal(got[1:], _rows(shared)[1:])
            assert int(pool.get("b").blocks[0]) != \
                int(pool.get("a").blocks[0])
            pool.unpin("a")
            pool.release("a")
            pool.release("b")
            assert len(pool._free) == 4 and not pool._refs
        finally:
            pool.close()

    def test_rpc_concurrent_loadkv_shares_prefix_and_status(self):
        """Service level: two CONCURRENT LoadKv RPCs ride the
        outside-the-lock fill (route-asserted from counter deltas),
        the identical prompts prefix-share one set of physical
        blocks, both decodes are byte-exact, /status carries the new
        truth, and custody drains."""
        import gc

        from brpc_tpu.ici import native_plane as npl
        from examples.disagg_serving.workers import DecodeService
        from examples.example_echo_pb2 import EchoRequest, EchoResponse
        m = _model()
        tokens = [(19 * j) % 499 for j in range(48)]
        want = m.reference_generate(tokens, 7)
        server = rpc.Server()
        svc = DecodeService()
        server.add_service(svc)
        assert server.start("mem://kv-prefix") == 0
        ch = rpc.Channel()
        ch.init("mem://kv-prefix",
                options=rpc.ChannelOptions(timeout_ms=30000))
        try:
            p0 = svc.describe_serving()["pool"]["prefix"]
            errs = []

            def load(session):
                try:
                    kv = m.toy_kv_blocks(tokens)
                    cntl = rpc.Controller()
                    cntl.request_attachment.append_device_array(kv)
                    ch.call_method("Decode.LoadKv", cntl, EchoRequest(
                        message=json.dumps(
                            {"session": session,
                             "seq_len": len(tokens),
                             "last_token": tokens[-1]})),
                        EchoResponse)
                    if cntl.failed():
                        errs.append(cntl.error_text)
                except Exception as e:   # pragma: no cover
                    errs.append(repr(e))

            ts = [threading.Thread(target=load, args=(f"p{i}",))
                  for i in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert errs == []
            p1 = svc.describe_serving()["pool"]["prefix"]
            assert p1["unlocked_fills"] - p0["unlocked_fills"] == 2
            assert p1["locked_fills"] == p0["locked_fills"]
            assert p1["shared_blocks"] >= 1
            assert p1["prefix_hits"] - p0["prefix_hits"] >= 1
            assert p1["sharing_ratio"] > 1.0
            for i in range(2):
                cntl = rpc.Controller()
                resp = ch.call_method(
                    "Decode.Decode", cntl, EchoRequest(
                        message=json.dumps({"session": f"p{i}",
                                            "steps": 7,
                                            "mode": "sync"})),
                    EchoResponse)
                assert not cntl.failed(), cntl.error_text
                assert json.loads(resp.message)["tokens"] == want
            gc.collect()
            assert npl.registry().live() == 0
            assert npl.att_table_live() == 0
        finally:
            ch.close()
            svc.close()
            server.stop()


# ---------------------------------------------------------------------------
# Tiered KV memory: host-tier spill / restore (ISSUE 19).
# ---------------------------------------------------------------------------

class TestKvTiers:
    def test_pressure_demotes_and_restore_is_byte_exact(self):
        """The tentpole invariant: the victim the PR-16 picker would
        have EVICTED instead demotes to the host tier, and the next
        lookup faults it back in byte-exact."""
        pool = _mk_pool(num_blocks=4, block_tokens=8, host_blocks=8)
        try:
            ta = [(3 * j) % 499 for j in range(16)]
            tb = [(5 * j + 1) % 499 for j in range(16)]
            pool.load("a", _rows(ta), last_token=ta[-1])
            pool.load("b", _rows(tb), last_token=tb[-1])
            # pressure: "a" (LRU) demotes instead of dying
            tc = [(7 * j + 2) % 499 for j in range(16)]
            pool.load("c", _rows(tc), last_token=tc[-1])
            assert pool.spilled_sessions() == ["a"]
            assert pool.evicted_reason("a") == "spilled"
            d = pool.describe()["tiers"]
            assert d["demotions"] == 1 and d["spilled_sessions"] == 1
            assert d["spilled_blocks"] == 2
            assert d["host_blocks_free"] == 6
            # restore (transparent, via materialize→get): "b" demotes
            # to make device room, "a" comes back byte-exact
            assert np.array_equal(pool.materialize("a"), _rows(ta))
            assert "a" not in pool.spilled_sessions()
            d = pool.describe()["tiers"]
            assert d["restores"] == 1 and d["restore_p50_us"] > 0
            assert d["plane"]["state"] == "up"
            # nobody died: zero evictions, all three sessions live
            assert pool.evictions.get_value() == 0
            for name, toks in (("a", ta), ("b", tb), ("c", tc)):
                assert np.array_equal(pool.materialize(name),
                                      _rows(toks)), name
        finally:
            pool.close()

    def test_spill_off_flag_is_the_pr16_eviction_ab(self):
        from brpc_tpu.butil import flags as _fl
        pool = _mk_pool(num_blocks=2, block_tokens=8, host_blocks=8)
        try:
            _fl.set_flag("serving_kv_spill", False)
            ta = [3] * 16
            pool.load("a", _rows(ta), last_token=3)
            pool.load("b", _rows([5] * 16), last_token=5)
            assert pool.spilled_sessions() == []
            assert pool.get("a") is None
            assert pool.evicted_reason("a") == "pressure"
        finally:
            _fl.set_flag("serving_kv_spill", True)
            pool.close()

    def test_corrupt_host_copy_degrades_to_reprefill(self):
        """Byte verification on restore: a corrupted host block makes
        the restore ABORT into a typed "corrupt" re-prefill shed —
        wrong bytes are never published, and the plane stays up
        (corruption is not plane death)."""
        pool = _mk_pool(num_blocks=2, block_tokens=8, host_blocks=4)
        try:
            ta = [(3 * j) % 499 for j in range(16)]
            pool.load("a", _rows(ta), last_token=ta[-1])
            pool.load("b", _rows([5] * 16), last_token=5)   # spills a
            assert pool.spilled_sessions() == ["a"]
            hb = int(pool._spilled["a"].hblocks[0])
            pool._host_store[hb, 7] ^= 0xFF                 # flip a byte
            assert pool.get("a") is None
            assert pool.materialize("a") is None
            assert pool.evicted_reason("a") == "corrupt"
            # the restore's own reservation demoted "b" first; only
            # "a"'s corrupt record died
            assert pool.spilled_sessions() == ["b"]
            d = pool.describe()["tiers"]
            assert d["restore_corrupt"] == 1 and d["restores"] == 0
            assert d["plane"]["state"] == "up"
            # "a"'s 2 host blocks reclaimed; "b" still holds 2
            assert d["host_blocks_free"] == 2
            # the surviving session's bytes never moved
            assert np.array_equal(pool.materialize("b"), _rows([5] * 16))
            assert pool.describe()["tiers"]["host_blocks_free"] == 4
        finally:
            pool.close()

    def test_shared_prefix_spills_once_restores_n(self):
        """A refcounted shared block spills ONE host copy and restores
        N sessions: demote both co-owners, census the host arena, then
        restore both and assert the dedupe re-shares the blocks."""
        pool = _mk_pool(num_blocks=8, block_tokens=8, host_blocks=4)
        try:
            toks = [(3 * j) % 499 for j in range(16)]   # 2 FULL blocks
            pool.load("a", _rows(toks), last_token=toks[-1])
            pool.load("b", _rows(toks), last_token=toks[-1])
            assert pool.describe()["prefix"]["shared_blocks"] == 2
            assert pool.spill("a") and pool.spill("b")
            d = pool.describe()["tiers"]
            assert d["spilled_sessions"] == 2
            # the 2 shared device blocks took 2 host blocks TOTAL (one
            # copy each), not 4 — the co-owner rode the _spill_map
            assert d["host_blocks_free"] == 2
            assert d["spilled_blocks"] == 2
            assert all(r == 2 for r in pool._host_refs.values())
            # restore both: first re-registers, second dedupes onto it
            assert np.array_equal(pool.materialize("a"), _rows(toks))
            assert np.array_equal(pool.materialize("b"), _rows(toks))
            sa, sb = pool.get("a"), pool.get("b")
            assert np.array_equal(sa.blocks, sb.blocks)
            assert all(pool._refs[int(x)] == 2 for x in sa.blocks)
            d = pool.describe()["tiers"]
            assert d["restores"] == 2 and d["spilled_sessions"] == 0
            assert d["host_blocks_free"] == 4 and not pool._host_refs
        finally:
            pool.close()

    def test_pinned_session_refuses_spill(self):
        pool = _mk_pool(num_blocks=4, block_tokens=8, host_blocks=4)
        try:
            from brpc_tpu.serving import SessionBusy
            pool.load("a", _rows([3] * 16), last_token=3)
            assert pool.pin("a")
            with pytest.raises(SessionBusy):
                pool.spill("a")
            pool.unpin("a")
            assert pool.spill("a")
            assert pool.spilled_sessions() == ["a"]
        finally:
            pool.close()

    def test_picker_prefers_whole_shared_set_over_unshared(self):
        """Satellite 2: with demotion available the picker takes the
        whole shared-owner GROUP (higher per-victim yield once the set
        completes) before any unshared live session, and the cumulative
        free-bytes simulation stays exact: what the picker promised is
        exactly what demotion freed."""
        pool = _mk_pool(num_blocks=6, block_tokens=8, host_blocks=8)
        try:
            toks = [(3 * j) % 499 for j in range(16)]   # 2 full blocks
            pool.load("s1", _rows(toks), last_token=toks[-1])
            pool.load("s2", _rows(toks), last_token=toks[-1])  # shares
            pool.load("u", _rows([7] * 16), last_token=7)  # unshared
            # census: s1+s2 share 2 physical, u owns 2 → 2 free
            assert len(pool._free) == 2
            free_before = len(pool._free)
            victims = pool._pick_victims_locked(4, pool.options
                                                .default_priority,
                                                spill=True)
            names = [v.session for v in victims]
            # the SHARED SET first — both owners, before the unshared
            assert set(names[:2]) == {"s1", "s2"}
            assert names[2] == "u"
            # drive the actual demotion through pressure and assert the
            # simulation was exact: 4 blocks wanted, 4 blocks freed
            big = [(11 * j) % 499 for j in range(48)]   # 6 blocks
            pool.load("big", _rows(big), last_token=big[-1])
            # promised 4 freed + 2 already free == exactly the 6 taken
            assert free_before == 2 and len(pool._free) == 0
            assert set(pool.spilled_sessions()) == {"s1", "s2", "u"}
            assert np.array_equal(pool.materialize("big"), _rows(big))
        finally:
            pool.close()

    def test_capacity_under_pressure_ab_spill_retains_more(self):
        """Acceptance A/B: same arena, same load pattern — spill-on
        retains STRICTLY more live (still-retrievable) sessions than
        spill-off, and every retained session is byte-exact."""
        from brpc_tpu.butil import flags as _fl
        alive = {}
        try:
            for flag in (True, False):
                _fl.set_flag("serving_kv_spill", flag)
                pool = _mk_pool(num_blocks=8, block_tokens=8,
                                host_blocks=32)
                sessions = {}
                try:
                    for i in range(16):
                        toks = [(7 * i + j) % 499 for j in range(16)]
                        pool.load(f"s{i}", _rows(toks),
                                  last_token=toks[-1])
                        sessions[f"s{i}"] = toks
                    live = 0
                    for name, toks in sessions.items():
                        got = pool.materialize(name)
                        if got is not None:
                            assert np.array_equal(got, _rows(toks)), name
                            live += 1
                    alive[flag] = live
                finally:
                    pool.close()
        finally:
            _fl.set_flag("serving_kv_spill", True)
        # spill-on keeps EVERY session retrievable; spill-off can only
        # hold what the device arena holds
        assert alive[True] == 16
        assert alive[True] > alive[False], alive

    def test_spill_plane_faults_latch_and_revive(self):
        """Chaos at the pool level: an injected demote-IO failure
        latches the spill plane down (pressure degrades to PR-16
        eviction — no client hangs on a dead host arena), and the
        timer latch revives it through the standard counters."""
        from brpc_tpu.butil import flags as _fl
        from brpc_tpu.ici import route
        pool = _mk_pool(num_blocks=2, block_tokens=8, host_blocks=8)
        try:
            _fl.set_flag("serving_kv_spill_reprobe_s", 0.1)
            before = route.plane_stats()
            pool.inject_spill_fault("demote")
            pool.load("a", _rows([3] * 16), last_token=3)
            pool.load("b", _rows([5] * 16), last_token=5)  # pressure
            # demote failed → fell back to eviction, plane latched
            assert pool.spilled_sessions() == []
            assert pool.evicted_reason("a") == "pressure"
            d = pool.describe()["tiers"]
            assert d["plane"]["state"] == "down"
            assert d["plane"]["reason"] == "demote_io"
            pool.inject_spill_fault(None)
            # while latched, pressure KEEPS evicting (fast, no retry
            # storm at the failing arena)
            pool.load("c", _rows([7] * 16), last_token=7)
            assert pool.spilled_sessions() == []
            time.sleep(0.15)       # the timer latch lapses
            pool.load("e", _rows([11] * 16), last_token=11)
            assert pool.spilled_sessions() == ["c"]
            after = route.plane_stats()
            assert after["spill_down"] >= before.get("spill_down",
                                                     0) + 1
            assert after["spill_reprobe"] >= before.get("spill_reprobe",
                                                        0) + 1
            assert after["spill_revived"] >= before.get("spill_revived",
                                                        0) + 1
            assert pool.describe()["tiers"]["plane"]["state"] == "up"
        finally:
            _fl.set_flag("serving_kv_spill_reprobe_s", 0.25)
            pool.close()

    def test_restore_io_fault_keeps_host_copy_and_sheds(self):
        pool = _mk_pool(num_blocks=2, block_tokens=8, host_blocks=8)
        try:
            from brpc_tpu.butil import flags as _fl
            _fl.set_flag("serving_kv_spill_reprobe_s", 0.05)
            ta = [(3 * j) % 499 for j in range(16)]
            pool.load("a", _rows(ta), last_token=ta[-1])
            assert pool.spill("a")
            pool.inject_spill_fault("restore")
            assert pool.get("a") is None          # shed, not corrupt
            assert pool.spilled_sessions() == ["a"]   # record intact
            assert pool.describe()["tiers"]["plane"]["reason"] \
                == "restore_io"
            pool.inject_spill_fault(None)
            time.sleep(0.1)
            assert np.array_equal(pool.materialize("a"), _rows(ta))
        finally:
            _fl.set_flag("serving_kv_spill_reprobe_s", 0.25)
            pool.close()

    def test_restore_saturated_stays_spilled(self):
        """No device room even after pressure (everything pinned): the
        restore refuses, the session STAYS host-resident, and the
        scheduler-visible reason is the retryable "spilled"."""
        pool = _mk_pool(num_blocks=2, block_tokens=8, host_blocks=8)
        try:
            ta = [(3 * j) % 499 for j in range(16)]
            pool.load("a", _rows(ta), last_token=ta[-1])
            assert pool.spill("a")
            pool.load("b", _rows([5] * 16), last_token=5)
            assert pool.pin("b")                  # device arena fenced
            assert pool.get("a") is None
            assert pool.spilled_sessions() == ["a"]
            assert pool.evicted_reason("a") == "spilled"
            pool.unpin("b")
            assert np.array_equal(pool.materialize("a"), _rows(ta))
        finally:
            pool.close()

    def test_host_arena_reclaim_drops_oldest_spilled(self):
        """Host arena full: demoting one more session reclaims the
        most sheddable SPILLED session (band→weight→LRU, typed
        "pressure" shed) rather than refusing the demotion."""
        _clock = [100.0]
        pool = _mk_pool(num_blocks=2, block_tokens=8, host_blocks=2,
                        now=lambda: _clock[0])
        try:
            ta = [(3 * j) % 499 for j in range(16)]
            pool.load("a", _rows(ta), last_token=ta[-1])
            _clock[0] += 1
            pool.load("b", _rows([5] * 16), last_token=5)  # spills a
            assert pool.spilled_sessions() == ["a"]
            _clock[0] += 1
            pool.load("c", _rows([7] * 16), last_token=7)  # spills b
            # host arena (2 blocks) could not hold both: "a" died for
            # real to make room for "b"
            assert pool.spilled_sessions() == ["b"]
            assert pool.evicted_reason("a") == "pressure"
            d = pool.describe()["tiers"]
            assert d["host_evictions"] == 1 and d["demotions"] == 2
            assert np.array_equal(pool.materialize("b"), _rows([5] * 16))
        finally:
            pool.close()

    def test_release_of_spilled_session_frees_host_blocks(self):
        pool = _mk_pool(num_blocks=2, block_tokens=8, host_blocks=4)
        try:
            pool.load("a", _rows([3] * 16), last_token=3)
            assert pool.spill("a")
            assert pool.release("a")
            assert pool.spilled_sessions() == []
            assert pool.describe()["tiers"]["host_blocks_free"] == 4
            assert not pool.release("a")
        finally:
            pool.close()

    def test_scheduler_decodes_through_restored_session(self):
        """Service-level truth: a spilled session submitted to the
        scheduler restores transparently and the tokens are bit-exact
        against the never-spilled reference."""
        m = _model()
        pool = _mk_pool(num_blocks=8, block_tokens=8, host_blocks=8)
        sched = _mk_sched(pool, max_batch=4)
        try:
            toks = [(3 * j) % 499 for j in range(16)]
            want = m.reference_generate(toks, 6)
            pool.load("a", _rows(toks), last_token=toks[-1])
            assert pool.spill("a")
            sink = _submit(sched, "a", 6)
            for _ in range(10):
                sched.step_once()
                if sink.tokens is not None or sink.error:
                    break
            assert sink.error is None, sink.error
            assert sink.tokens == want
            assert pool.describe()["tiers"]["restores"] == 1
        finally:
            sched.stop()
            pool.close()


# ---------------------------------------------------------------------------
# Custody-sweep regressions (ISSUE 20): the true positives the static
# custody pass found, each drivable — these fail on the pre-fix shape.
# ---------------------------------------------------------------------------

class TestCustodyRegressions:
    def _assert_no_reserve_outstanding(self, pool):
        from brpc_tpu.butil import custody_ledger
        held = [r for r in custody_ledger.outstanding()
                if r["resource"] == "kv.reserve"
                and r["key"][0] == id(pool)]
        assert held == [], held

    @pytest.mark.parametrize("concurrent", [True, False])
    def test_session_construction_failure_aborts_reservation(
            self, monkeypatch, concurrent):
        """Sweep true positive (load_into): _extent_views and the
        _KvSession construction sit between reserve and commit — a
        raise there leaked the reservation pre-fix (blocks off the
        free list forever).  Both fill disciplines now route every
        edge through the abort."""
        from brpc_tpu.butil import flags as _fl
        from brpc_tpu.serving import kv_pool as kp
        pool = _mk_pool(num_blocks=4, block_tokens=8)
        try:
            _fl.set_flag("serving_kv_concurrent_fill", concurrent)
            free0 = len(pool._free)
            aborts0 = pool.fill_aborts.get_value()

            real = kp._KvSession

            def boom(*a, **kw):
                raise MemoryError("allocator pressure mid-load")

            monkeypatch.setattr(kp, "_KvSession", boom)
            with pytest.raises(MemoryError):
                pool.load("s1", _rows([3] * 16), last_token=3)
            monkeypatch.setattr(kp, "_KvSession", real)
            # the reservation aborted clean: free list restored, abort
            # counted, no ledger hold, and the pool still loads at
            # full capacity
            assert len(pool._free) == free0
            assert pool.fill_aborts.get_value() == aborts0 + 1
            self._assert_no_reserve_outstanding(pool)
            assert pool.get("s1") is None
            toks = [(3 * j) % 499 for j in range(16)]
            pool.load("s1", _rows(toks), last_token=toks[-1])
            assert np.array_equal(pool.materialize("s1"), _rows(toks))
        finally:
            _fl.set_flag("serving_kv_concurrent_fill", True)
            pool.close()

    def test_restore_copy_failure_releases_reservation_and_host_refs(
            self, monkeypatch):
        """Sweep true positive (_restore): the outside-the-lock
        host→device copy can raise (allocator pressure); pre-fix that
        leaked the device reservation AND the restore's host refs.
        Every outcome now resolves through _finish_restore_locked —
        the exception propagates, the session stays spilled, and the
        host copy restores byte-exact afterwards."""
        from brpc_tpu.serving import kv_pool as kp
        pool = _mk_pool(num_blocks=4, block_tokens=8, host_blocks=4)
        try:
            toks = [(3 * j) % 499 for j in range(16)]
            pool.load("a", _rows(toks), last_token=toks[-1])
            assert pool.spill("a")
            free0 = len(pool._free)

            real = kp.zlib.crc32

            def boom(data, chain=0):
                raise MemoryError("copy failed mid-restore")

            monkeypatch.setattr(kp.zlib, "crc32", boom)
            with pytest.raises(MemoryError):
                pool.get("a")
            monkeypatch.setattr(kp.zlib, "crc32", real)
            # reservation returned, host record + refs intact, no
            # ledger hold; the next lookup restores byte-exact
            assert len(pool._free) == free0
            assert pool.spilled_sessions() == ["a"]
            self._assert_no_reserve_outstanding(pool)
            assert np.array_equal(pool.materialize("a"), _rows(toks))
            assert pool.describe()["tiers"]["restores"] == 1
        finally:
            pool.close()


# ---------------------------------------------------------------------------
# Live cross-worker migration (ISSUE 19).
# ---------------------------------------------------------------------------

class TestKvMigration:
    def _mk_pair(self):
        src = _mk_pool(num_blocks=8, block_tokens=8)
        dst = _mk_pool(num_blocks=8, block_tokens=8)
        return src, dst

    def _sender(self, dst):
        def send(meta, payload):
            rows = np.frombuffer(payload, np.uint8).reshape(
                meta["seq_len"], dst.options.bytes_per_token)
            dst.load(meta["session"], rows,
                     last_token=meta["last_token"],
                     tenant=meta["tenant"], priority=meta["priority"])
            return True, "", False
        return send

    def test_migrate_out_cutover_then_release(self):
        """Custody: the cutover flip runs while the SOURCE copy is
        still resident; only after it does the source release."""
        from brpc_tpu.serving import migrate_out
        src, dst = self._mk_pair()
        try:
            toks = [(3 * j) % 499 for j in range(16)]
            src.load("m1", _rows(toks), last_token=toks[-1])
            order = []

            def flip():
                assert src.get("m1") is not None   # source still live
                order.append("flip")
            ok, err = migrate_out(src, "m1", self._sender(dst),
                                  on_cutover=flip)
            assert ok, err
            assert order == ["flip"]
            assert src.get("m1") is None           # released after
            assert np.array_equal(dst.materialize("m1"), _rows(toks))
        finally:
            src.close()
            dst.close()

    def test_shed_abort_keeps_source_no_plane_event(self):
        from brpc_tpu.ici import route
        from brpc_tpu.serving import migrate_out, migration_stats
        src, dst = self._mk_pair()
        try:
            toks = [(3 * j) % 499 for j in range(16)]
            src.load("m1", _rows(toks), last_token=toks[-1])
            before = route.plane_stats()
            a0 = migration_stats()["aborts"]

            def shed(meta, payload):
                return False, "kv pool saturated (shed)", True
            ok, err = migrate_out(src, "m1", shed)
            assert not ok and "saturated" in err
            assert migration_stats()["aborts"] == a0 + 1
            # a clean shed does NOT latch the plane
            after = route.plane_stats()
            assert after.get("migrate_down", 0) \
                == before.get("migrate_down", 0)
            assert np.array_equal(src.materialize("m1"), _rows(toks))
        finally:
            src.close()
            dst.close()

    def test_transfer_deadline_latches_and_revives(self):
        """Satellite 1 (the PR-17 residue): a HUNG peer is detected by
        the transfer-deadline latch — the migrate plane goes down with
        no client in the blast radius, later migrations refuse FAST,
        and the timer latch revives through reprobe/revived."""
        from brpc_tpu.butil import flags as _fl
        from brpc_tpu.ici import route
        from brpc_tpu.serving import migrate_out
        src, dst = self._mk_pair()
        gate = threading.Event()
        try:
            _fl.set_flag("serving_migrate_reprobe_s", 0.1)
            toks = [(3 * j) % 499 for j in range(16)]
            src.load("m1", _rows(toks), last_token=toks[-1])
            before = route.plane_stats()

            def hung(meta, payload):
                gate.wait(5.0)
                return True, "", False
            t0 = time.monotonic()
            ok, err = migrate_out(src, "m1", hung, deadline_ms=150)
            assert not ok and "deadline" in err
            assert time.monotonic() - t0 < 2.0
            # latched: the next migrate refuses in microseconds, no
            # send is even attempted
            calls = []
            ok, err = migrate_out(
                src, "m1", lambda m, p: calls.append(1) or (True, "",
                                                            False))
            assert not ok and "latched" in err and not calls
            # the source never stopped serving
            assert np.array_equal(src.materialize("m1"), _rows(toks))
            gate.set()
            time.sleep(0.15)
            ok, err = migrate_out(src, "m1", self._sender(dst))
            assert ok, err
            after = route.plane_stats()
            assert after["migrate_down"] >= before.get("migrate_down",
                                                       0) + 1
            assert after["migrate_revived"] \
                >= before.get("migrate_revived", 0) + 1
            assert np.array_equal(dst.materialize("m1"), _rows(toks))
        finally:
            _fl.set_flag("serving_migrate_reprobe_s", 0.5)
            gate.set()
            src.close()
            dst.close()

    def test_peer_unreachable_latches_plane(self):
        from brpc_tpu.butil import flags as _fl
        from brpc_tpu.serving import migrate_out, migration_stats
        src, dst = self._mk_pair()
        try:
            _fl.set_flag("serving_migrate_reprobe_s", 0.05)
            toks = [3] * 16
            src.load("m1", _rows(toks), last_token=3)

            def dead(meta, payload):
                raise ConnectionError("connection refused")
            ok, err = migrate_out(src, "m1", dead)
            assert not ok and "ConnectionError" in err
            st = migration_stats()
            assert st["plane"]["state"] == "down"
            assert st["plane"]["reason"] == "peer_unreachable"
            assert np.array_equal(src.materialize("m1"), _rows(toks))
            # the latch is PROCESS-wide: heal it (timer lapse + probe)
            # so later migration tests start from an UP plane
            from brpc_tpu.serving.migration import migrate_health
            time.sleep(0.1)
            assert migrate_health().usable()
        finally:
            _fl.set_flag("serving_migrate_reprobe_s", 0.5)
            src.close()
            dst.close()

    def test_scheduler_fence_refuses_decoding_session(self):
        from brpc_tpu.serving import migrate_out
        src, dst = self._mk_pair()
        sched = _mk_sched(src, max_batch=4)
        try:
            toks = [(3 * j) % 499 for j in range(16)]
            src.load("m1", _rows(toks), last_token=toks[-1])
            _submit(sched, "m1", 50)
            sched.step_once()  # roster admits m1 → owned
            ok, err = migrate_out(src, "m1", self._sender(dst),
                                  scheduler=sched)
            assert not ok and "decoding" in err
        finally:
            sched.stop()
            src.close()
            dst.close()

    def test_spilled_session_migrates_via_restore(self):
        """A migration is a READ: a host-parked session restores
        first, then ships — the destination gets device-verified
        bytes, never the raw host copy."""
        from brpc_tpu.serving import migrate_out
        src = _mk_pool(num_blocks=4, block_tokens=8, host_blocks=4)
        dst = _mk_pool(num_blocks=8, block_tokens=8)
        try:
            toks = [(3 * j) % 499 for j in range(16)]
            src.load("m1", _rows(toks), last_token=toks[-1])
            assert src.spill("m1")
            ok, err = migrate_out(src, "m1", self._sender(dst))
            assert ok, err
            assert src.describe()["tiers"]["restores"] == 1
            assert src.spilled_sessions() == []
            assert np.array_equal(dst.materialize("m1"), _rows(toks))
        finally:
            src.close()
            dst.close()

    def test_router_affinity_bind_rebind_unbind(self):
        """The cutover surface: rebind is the atomic routing flip and
        reports the previous binding so the caller releases the source
        AFTER the flip."""
        from brpc_tpu.serving import LoadAwareRouter
        r = LoadAwareRouter(["ici://0", "ici://1"])
        try:
            assert r.session_url("s") is None
            r.bind_session("s", "ici://0")
            assert r.session_url("s") == "ici://0"
            assert r.rebind("s", "ici://1") == "ici://0"
            assert r.session_url("s") == "ici://1"
            assert r.rebind("new", "ici://0") is None
            d = r.describe()
            assert d["sessions_bound"] == 2 and d["rebinds"] == 1
            r.unbind("s")
            assert r.session_url("s") is None
            # cardinality cap: binds never grow without bound
            for i in range(r.MAX_BOUND_SESSIONS + 10):
                r.bind_session(f"x{i}", "ici://0")
            assert r.describe()["sessions_bound"] \
                <= r.MAX_BOUND_SESSIONS
        finally:
            r.close()

    def test_autoscaler_drain_runs_before_scale_down(self):
        from brpc_tpu.serving import (AutoscalerOptions,
                                      LoadThresholdAutoscaler)
        order = []
        a = LoadThresholdAutoscaler(
            load_fn=lambda: 0.0, size_fn=lambda: 2,
            scale_up=lambda: True,
            scale_down=lambda: order.append("down") or True,
            drain=lambda: order.append("drain"),
            options=AutoscalerOptions(samples_to_scale=1,
                                      cooldown_s=0.0))
        assert a.tick(now=1.0) == "down"
        assert order == ["drain", "down"]
        # a raising drain logs and the scale-down still proceeds
        order.clear()

        def bad_drain():
            order.append("drain")
            raise RuntimeError("migrate failed")
        a2 = LoadThresholdAutoscaler(
            load_fn=lambda: 0.0, size_fn=lambda: 2,
            scale_up=lambda: True,
            scale_down=lambda: order.append("down") or True,
            drain=bad_drain,
            options=AutoscalerOptions(samples_to_scale=1,
                                      cooldown_s=0.0))
        assert a2.tick(now=1.0) == "down"
        assert order == ["drain", "down"]


# ---------------------------------------------------------------------------
# Continuous-batching scheduler (manual stepping).
# ---------------------------------------------------------------------------

class TestContinuousBatchScheduler:
    def _load(self, pool, session, tokens, **kw):
        pool.load(session, _rows(tokens), last_token=tokens[-1], **kw)

    def test_tokens_bit_exact_with_staggered_joins(self):
        m = _model()
        pool = _mk_pool(num_blocks=32, block_tokens=8)
        sched = _mk_sched(pool, max_batch=8)
        try:
            specs = {f"s{i}": ([(7 * i + j) % 997
                                for j in range(16 + 11 * i)], 5 + 3 * i)
                     for i in range(3)}
            sinks = {}
            for s, (tokens, steps) in specs.items():
                self._load(pool, s, tokens)
                sinks[s] = _submit(sched, s, steps)
            for _ in range(4):
                sched.step_once()
            # a session JOINS mid-stream, between steps
            late = [(13 * j) % 499 for j in range(21)]
            specs["late"] = (late, 6)
            self._load(pool, "late", late)
            sinks["late"] = _submit(sched, "late", 6)
            for _ in range(20):
                sched.step_once()
            for s, (tokens, steps) in specs.items():
                assert sinks[s].tokens == m.reference_generate(
                    tokens, steps), f"session {s} diverged"
            d = sched.describe()
            assert d["retired"] == 4 and d["steps"] > 0
            assert d["batch_occupancy_avg"] > 1.0
        finally:
            sched.stop()
            pool.close()

    def test_max_batch_admits_per_step(self):
        pool = _mk_pool()
        sched = _mk_sched(pool, max_batch=2)
        try:
            sinks = []
            for i in range(3):
                tokens = [(i + j) % 97 for j in range(8)]
                self._load(pool, f"s{i}", tokens)
                sinks.append(_submit(sched, f"s{i}", 2))
            assert sched.step_once() == 2          # roster capped at 2
            assert sched.active() == 2 and sched.queued() == 1
            sched.step_once()                      # first two retire
            assert sched.step_once() == 1          # third admitted
            sched.step_once()
            assert all(s.tokens is not None for s in sinks)
        finally:
            sched.stop()
            pool.close()

    def test_interactive_preemption_preserves_progress(self):
        m = _model()
        pool = _mk_pool()
        sched = _mk_sched(pool, max_batch=1, interactive_priority_max=1)
        try:
            batch_toks = [3 * j % 97 for j in range(16)]
            self._load(pool, "batch", batch_toks, priority=3)
            b = _submit(sched, "batch", 10, priority=3)
            for _ in range(3):
                sched.step_once()
            assert sched.active() == 1
            inter_toks = [5 * j % 89 for j in range(8)]
            self._load(pool, "inter", inter_toks, priority=0)
            i = _submit(sched, "inter", 4, priority=0)
            # next boundary: batch preempted mid-decode, interactive in
            sched.step_once()
            assert sched.preempted.get_value() == 1
            for _ in range(12):
                sched.step_once()
            assert i.tokens == m.reference_generate(inter_toks, 4)
            # the preempted session RESUMED from its next token
            assert b.tokens == m.reference_generate(batch_toks, 10)
        finally:
            sched.stop()
            pool.close()

    def test_deadline_expired_in_queue(self):
        from brpc_tpu.rpc import errors
        pool = _mk_pool()
        sched = _mk_sched(pool, max_batch=4)
        try:
            self._load(pool, "s", [1] * 8)
            sink = _submit(sched, "s", 4,
                           deadline_us=time.monotonic_ns() // 1000 - 10)
            sched.step_once()
            assert sink.error is not None
            assert sink.error[0] == errors.ERPCTIMEDOUT
            assert sched.expired.get_value() == 1
        finally:
            sched.stop()
            pool.close()

    def test_unknown_and_evicted_session_refusals(self):
        from brpc_tpu.rpc import errors
        pool = _mk_pool(num_blocks=1, block_tokens=8)
        sched = _mk_sched(pool)
        try:
            sink = _submit(sched, "ghost", 4)
            sched.step_once()
            assert sink.error[0] == errors.EREQUEST
            self._load(pool, "victim", [1] * 8, priority=3)
            self._load(pool, "usurper", [2] * 8, priority=0)  # evicts
            sink2 = _submit(sched, "victim", 4)
            sched.step_once()
            assert sink2.error[0] == errors.ELIMIT
            assert "re-prefill" in sink2.error[1]
        finally:
            sched.stop()
            pool.close()

    def test_duplicate_submit_refused_and_custody_safe(self):
        """A retry storm re-issuing a Decode whose first copy is still
        running is REFUSED: two roster entries on one session would let
        the first completion release the pool blocks the second still
        gathers through (cross-tenant bytes after block reuse — the
        soak caught this as a token mismatch)."""
        from brpc_tpu.rpc import errors
        m = _model()
        pool = _mk_pool()
        sched = _mk_sched(pool, max_batch=4)
        try:
            tokens = [9 * j % 97 for j in range(12)]
            self._load(pool, "dup", tokens)
            first = _submit(sched, "dup", 6)
            second = _submit(sched, "dup", 6)
            assert second.error is not None
            assert second.error[0] == errors.EREQUEST
            assert "duplicate" in second.error[1]
            for _ in range(8):
                sched.step_once()
            assert first.tokens == m.reference_generate(tokens, 6)
            # ownership released at completion: a FRESH submit works
            third = _submit(sched, "dup", 3)
            for _ in range(5):
                sched.step_once()
            assert third.tokens == m.reference_generate(tokens, 3)
        finally:
            sched.stop()
            pool.close()

    def test_compiled_step_parity(self):
        """The jit-compiled XLA step produces the numpy step's tokens
        bit for bit (the TPU-pod shape, parity-pinned)."""
        from brpc_tpu.butil import flags as fl
        m = _model()
        pool = _mk_pool(num_blocks=32, block_tokens=8)
        sched = _mk_sched(pool, max_batch=4)
        saved = fl.get_flag("serving_compiled_step")
        fl.set_flag("serving_compiled_step", True)
        try:
            sinks = {}
            specs = {}
            for i in range(3):
                tokens = [(11 * i + j) % 499 for j in range(10 + 7 * i)]
                specs[f"c{i}"] = (tokens, 6)
                self._load(pool, f"c{i}", tokens)
                sinks[f"c{i}"] = _submit(sched, f"c{i}", 6)
            for _ in range(10):
                sched.step_once()
            for s, (tokens, steps) in specs.items():
                assert sinks[s].tokens == m.reference_generate(
                    tokens, steps)
            assert sched.describe()["compiled_step"] is True
        finally:
            fl.set_flag("serving_compiled_step", saved)
            sched.stop()
            pool.close()

    def test_step_loop_survives_a_step_exception(self):
        """One bad roster must not wedge the worker: the loop fails the
        crashed roster with EINTERNAL and keeps serving (review
        finding: an unguarded step thread died permanently and every
        later Decode queued forever)."""
        from brpc_tpu.rpc import errors
        m = _model()
        pool = _mk_pool()
        sched = _mk_sched(pool, max_batch=4, auto_start=True)
        try:
            boom = {"armed": True}
            orig = sched._step_numpy

            def exploding(bt):
                if boom["armed"]:
                    boom["armed"] = False
                    raise RuntimeError("injected step fault")
                return orig(bt)

            sched._step_numpy = exploding
            tokens = [3 * j % 97 for j in range(8)]
            self._load(pool, "crash", tokens)
            sink = _submit(sched, "crash", 4)
            deadline = time.monotonic() + 5.0
            while sink.error is None and time.monotonic() < deadline:
                time.sleep(0.01)
            assert sink.error is not None, "crashed roster never failed"
            assert sink.error[0] == errors.EINTERNAL
            # the loop is ALIVE: a fresh session decodes bit-exact
            self._load(pool, "after", tokens)
            sink2 = _submit(sched, "after", 4)
            deadline = time.monotonic() + 5.0
            while sink2.tokens is None and sink2.error is None \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert sink2.tokens == m.reference_generate(tokens, 4)
        finally:
            sched.stop()
            pool.close()

    def test_stop_fails_pending_with_elogoff(self):
        from brpc_tpu.rpc import errors
        pool = _mk_pool()
        sched = _mk_sched(pool)
        try:
            self._load(pool, "s", [1] * 8)
            sink = _submit(sched, "s", 4)
            sched.stop()
            assert sink.error[0] == errors.ELOGOFF
            late = _submit(sched, "s", 4)
            assert late.error[0] == errors.ELOGOFF
        finally:
            pool.close()


# ---------------------------------------------------------------------------
# Service level: the rebuilt disaggregated workers.
# ---------------------------------------------------------------------------

class TestServingServices:
    def _decode_worker(self, name, **kw):
        from examples.disagg_serving.workers import DecodeService
        server = rpc.Server()
        svc = DecodeService(**kw)
        server.add_service(svc)
        assert server.start(f"mem://{name}") == 0
        return server, svc

    def _load_session(self, ch, session, tokens, priority=None,
                      tenant=""):
        from examples.example_echo_pb2 import EchoRequest, EchoResponse
        m = _model()
        kv = np.asarray(m.toy_kv_blocks(tokens)).tobytes()
        cntl = rpc.Controller()
        if priority is not None:
            cntl.priority = priority
        if tenant:
            cntl.tenant = tenant
        cntl.request_attachment.append(kv)
        ch.call_method("Decode.LoadKv", cntl, EchoRequest(
            message=json.dumps({"session": session,
                                "seq_len": len(tokens),
                                "last_token": tokens[-1]})),
            EchoResponse)
        return cntl

    def test_batched_decode_end_to_end_route_asserted(self):
        """N concurrent Decode RPCs share the step loop: every reply
        bit-exact, batch occupancy > 1, and the route asserted through
        the /status serving block."""
        from examples.example_echo_pb2 import EchoRequest, EchoResponse
        m = _model()
        server, svc = self._decode_worker("serv-batched")
        ch = rpc.Channel()
        ch.init("mem://serv-batched",
                options=rpc.ChannelOptions(timeout_ms=30000))
        try:
            # 200-step sessions: lifetimes of several ms, far beyond
            # client-thread start stagger even under suite-wide CPU
            # contention — the roster genuinely overlaps (a 12-step
            # variant measured occupancy exactly 1.0 on a loaded host)
            specs = {f"b{i}": ([(3 * i + j) % 997
                                for j in range(24 + 8 * i)], 200)
                     for i in range(6)}
            for s, (tokens, _) in specs.items():
                assert not self._load_session(ch, s, tokens).failed()
            results = {}
            lock = threading.Lock()

            def decode(s, steps):
                cntl = rpc.Controller()
                resp = ch.call_method("Decode.Decode", cntl,
                                      EchoRequest(message=json.dumps(
                                          {"session": s,
                                           "steps": steps})),
                                      EchoResponse)
                with lock:
                    results[s] = (cntl.failed(), cntl.error_text,
                                  json.loads(resp.message)["tokens"]
                                  if not cntl.failed() else None)

            threads = [threading.Thread(target=decode, args=(s, steps))
                       for s, (_, steps) in specs.items()]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for s, (tokens, steps) in specs.items():
                failed, err, toks = results[s]
                assert not failed, (s, err)
                assert toks == m.reference_generate(tokens, steps), s
            d = svc.describe_serving()
            assert d["scheduler"]["retired"] == 6
            assert d["scheduler"]["batch_occupancy_avg"] > 1.0
            assert svc.live_sessions() == 0    # released on completion
            # the /status page carries the serving block
            ctype, body = server._builtin.dispatch("status")
            blk = json.loads(body)["serving"]["Decode"]
            assert blk["scheduler"]["steps"] > 0
            assert blk["pool"]["blocks_total"] > 0
        finally:
            ch.close()
            svc.close()
            server.stop()

    def test_sync_mode_matches_batch_mode(self):
        from examples.example_echo_pb2 import EchoRequest, EchoResponse
        m = _model()
        server, svc = self._decode_worker("serv-sync")
        ch = rpc.Channel()
        ch.init("mem://serv-sync",
                options=rpc.ChannelOptions(timeout_ms=30000))
        try:
            tokens = [(17 * j) % 499 for j in range(40)]
            want = m.reference_generate(tokens, 9)
            for mode in ("sync", "batch"):
                s = f"m-{mode}"
                assert not self._load_session(ch, s, tokens).failed()
                cntl = rpc.Controller()
                body = {"session": s, "steps": 9}
                if mode == "sync":
                    body["mode"] = "sync"
                resp = ch.call_method("Decode.Decode", cntl,
                                      EchoRequest(message=json.dumps(
                                          body)), EchoResponse)
                assert not cntl.failed(), cntl.error_text
                assert json.loads(resp.message)["tokens"] == want, mode
        finally:
            ch.close()
            svc.close()
            server.stop()

    def test_pool_saturated_sheds_with_retry_hint(self):
        from brpc_tpu.rpc import errors
        from brpc_tpu.serving import KvPoolOptions
        m = _model()
        server, svc = self._decode_worker(
            "serv-sat", pool_options=KvPoolOptions(
                bytes_per_token=m.KV_LAYERS * m.KV_DMODEL,
                num_blocks=2, block_tokens=8))
        ch = rpc.Channel()
        ch.init("mem://serv-sat",
                options=rpc.ChannelOptions(timeout_ms=30000,
                                           max_retry=0))
        try:
            # interactive KV owns the pool; a batch load is SHED with a
            # retry hint, not failed into the unknown
            assert not self._load_session(ch, "inter", [1] * 16,
                                          priority=0).failed()
            cntl = self._load_session(ch, "batch", [2] * 8, priority=3,
                                      tenant="bulk")
            assert cntl.failed() and cntl.error_code_ == errors.ELIMIT
            assert cntl.retry_after_ms > 0
            assert svc.live_sessions() == 1
        finally:
            ch.close()
            svc.close()
            server.stop()

    def test_idle_worker_reclaims_parked_session_without_traffic(self):
        """THE ISSUE-14 regression at the RPC level: LoadKv parks a
        session, NO further traffic of any kind arrives, and the
        worker's pool reclaims it by timer."""
        from brpc_tpu.serving import KvPoolOptions
        m = _model()
        server, svc = self._decode_worker(
            "serv-idle", pool_options=KvPoolOptions(
                bytes_per_token=m.KV_LAYERS * m.KV_DMODEL,
                num_blocks=8, block_tokens=8, ttl_s=0.15,
                sweep_interval_s=0.05))
        ch = rpc.Channel()
        ch.init("mem://serv-idle",
                options=rpc.ChannelOptions(timeout_ms=30000))
        try:
            assert not self._load_session(ch, "parked",
                                          [3] * 12).failed()
            assert svc.live_sessions() == 1
            deadline = time.monotonic() + 5.0
            while svc.live_sessions() and time.monotonic() < deadline:
                time.sleep(0.02)
            assert svc.live_sessions() == 0, \
                "parked session not reclaimed on an idle worker"
            assert svc.sessions_expired >= 1
        finally:
            ch.close()
            svc.close()
            server.stop()

    def test_rpc_press_serving_mode(self):
        """The open-loop session generator (tools/rpc_press --serving):
        mixed tenants at a fixed arrival rate, per-tenant tokens/s in
        the summary, and the in-process pool/scheduler occupancy
        reported through the serving status block."""
        import io

        import jax
        from brpc_tpu.tools.rpc_press import run_press_serving
        from examples.disagg_serving.workers import (start_decode_worker,
                                                     start_prefill_worker,
                                                     start_router)
        devs = jax.devices()
        prefill = start_prefill_worker("ici://7", device=devs[7])
        decode = start_decode_worker("mem://press-dec")
        router = start_router("mem://press-router", "ici://7",
                              ["mem://press-dec"])
        try:
            res = run_press_serving(
                "mem://press-router", duration=1.5, arrival_rps=40.0,
                batch_ratio=2, seq_range="16-32", steps_range="4-16",
                out=io.StringIO())
            assert res["issued"] >= 20, res
            for tenant in ("inter", "bulk"):
                c = res["per_tenant"][tenant]
                assert c["ok"] > 0 and c["failures"] == 0, res
                assert c["session_tokens_per_s_p50"] > 0, res
            assert res["tokens_per_s"] > 0
            blk = next(v for k, v in res["serving_status"].items()
                       if "Decode" in k)
            assert blk["pool"]["blocks_total"] > 0
            assert blk["scheduler"]["steps"] > 0
            # the ISSUE-16 prefix block rides the summary (same
            # in-process gate as serving_status)
            pfx = next(v for k, v in res["kv_prefix"].items()
                       if "Decode" in k)
            assert pfx["sharing_ratio"] >= 1.0
            assert pfx["unlocked_fills"] > 0     # the default route
            for key in ("shared_blocks", "prefix_hits", "cow_splits"):
                assert key in pfx
            # the ISSUE-19 tiers block rides the same gate
            tiers = next(v for k, v in res["kv_tiers"].items()
                         if "Decode" in k)
            for key in ("demotions", "restores", "restore_p50_us",
                        "spilled_sessions", "migration"):
                assert key in tiers
            assert tiers["migration"]["scope"] == "process"
        finally:
            for server in (router, prefill, decode):
                for svc in server._services.values():
                    if hasattr(svc, "close"):
                        svc.close()
                server.stop()

    def test_lalb_router_shifts_load_to_fast_worker(self):
        """The divided-weight loop: feedback drives selection — a slow
        worker's share collapses."""
        from brpc_tpu.serving import LoadAwareRouter
        router = LoadAwareRouter(["mem://lalb-fast", "mem://lalb-slow"])
        try:
            for _ in range(40):
                router.feedback("mem://lalb-fast", 0, 1000)
                router.feedback("mem://lalb-slow", 0, 50000)
            picks = {"mem://lalb-fast": 0, "mem://lalb-slow": 0}
            for _ in range(300):
                url = router.pick()
                picks[url] += 1
                router.feedback(url, 0,
                                1000 if url.endswith("fast") else 50000)
            assert picks["mem://lalb-fast"] > 0.65 * 300, picks
            d = router.describe()
            assert d["balancer"] == "la"
            assert d["weights"]["mem://lalb-fast"] > \
                d["weights"]["mem://lalb-slow"]
        finally:
            router.close()

    def test_router_retries_dead_decode_worker(self):
        """A Generate whose chosen decode worker is DEAD re-prefills
        against another one — zero client-visible failures (the elastic
        chaos contract's unit half)."""
        import jax
        from examples.disagg_serving.workers import (start_decode_worker,
                                                     start_prefill_worker,
                                                     start_router)
        from examples.example_echo_pb2 import EchoRequest, EchoResponse
        m = _model()
        devs = jax.devices()
        prefill = start_prefill_worker("ici://6", device=devs[6])
        alive = start_decode_worker("mem://rr-alive")
        dead = start_decode_worker("mem://rr-dead")
        router = start_router("mem://rr-router", "ici://6",
                              ["mem://rr-dead", "mem://rr-alive"])
        servers = [router, prefill, alive]
        try:
            # the dead worker stops before any traffic: whichever
            # attempt picks it fails and the router must recover
            for svc in dead._services.values():
                if hasattr(svc, "close"):
                    svc.close()
            dead.stop()
            ch = rpc.Channel()
            ch.init("mem://rr-router",
                    options=rpc.ChannelOptions(timeout_ms=60000))
            tokens = [(7 * j) % 499 for j in range(32)]
            for _ in range(4):
                cntl = rpc.Controller()
                resp = ch.call_method(
                    "Router.Generate", cntl,
                    EchoRequest(message=json.dumps(
                        {"tokens": tokens, "steps": 6})), EchoResponse)
                assert not cntl.failed(), cntl.error_text
                out = json.loads(resp.message)
                assert out["tokens"] == m.reference_generate(tokens, 6)
                assert out["decode_worker"] == "mem://rr-alive"
            ch.close()
        finally:
            for server in servers:
                for svc in server._services.values():
                    if hasattr(svc, "close"):
                        svc.close()
                server.stop()


# ---------------------------------------------------------------------------
# Autoscaler units (injected clock + load).
# ---------------------------------------------------------------------------

class TestAutoscaler:
    def _mk(self, loads, size0=1, **kw):
        from brpc_tpu.serving import (AutoscalerOptions,
                                      LoadThresholdAutoscaler)
        state = {"size": size0, "ups": 0, "downs": 0, "i": 0}

        def load_fn():
            i = min(state["i"], len(loads) - 1)
            state["i"] += 1
            return loads[i]

        def up():
            state["size"] += 1
            state["ups"] += 1
            return True

        def down():
            state["size"] -= 1
            state["downs"] += 1
            return True

        opts = AutoscalerOptions(**kw)
        a = LoadThresholdAutoscaler(load_fn, lambda: state["size"],
                                    up, down, options=opts)
        return a, state

    def test_hysteresis_and_cooldown(self):
        a, st = self._mk([0.9, 0.9, 0.9, 0.9, 0.9],
                         samples_to_scale=2, cooldown_s=10.0,
                         max_size=4)
        assert a.tick(now=0.0) is None      # 1 high sample: not yet
        assert a.tick(now=1.0) == "up"      # 2 consecutive: scale
        assert st["size"] == 2
        assert a.tick(now=2.0) is None      # cooldown holds
        assert a.tick(now=3.0) is None
        # sustained high load keeps accumulating through the cooldown:
        # the next action fires the moment the cooldown lifts
        assert a.tick(now=12.0) == "up"
        assert a.tick(now=13.0) is None     # new cooldown holds again
        assert st["ups"] == 2

    def test_scale_down_and_min_size(self):
        a, st = self._mk([0.1] * 6, size0=2, samples_to_scale=2,
                         cooldown_s=0.0, min_size=1)
        assert a.tick(now=0.0) is None
        assert a.tick(now=1.0) == "down"
        assert st["size"] == 1
        # at min_size: low load never goes below
        assert a.tick(now=2.0) is None
        assert a.tick(now=3.0) is None
        assert st["size"] == 1

    def test_max_size_and_mid_band_resets_runs(self):
        a, st = self._mk([0.9, 0.5, 0.9, 0.9], samples_to_scale=2,
                         cooldown_s=0.0, max_size=2)
        assert a.tick(now=0.0) is None
        assert a.tick(now=1.0) is None      # mid-band sample reset
        assert a.tick(now=2.0) is None
        assert a.tick(now=3.0) == "up"
        assert st["size"] == 2
        d = a.describe()
        assert d["scale_ups"] == 1 and d["size"] == 2
        assert "load" in d["last"]


# ---------------------------------------------------------------------------
# 2-process shm claim-to-pool (ISSUE 15): the KV payload crosses the
# fabric's shared-memory ring and the zero-copy CLAIM is consumed
# DIRECTLY into the decode worker's pool blocks — route asserted on
# both layers (rpc_fabric_route shm bytes AND serving_kv_load_adopted),
# decode byte-exact against the single-process reference.
# ---------------------------------------------------------------------------

_KV_SHM_CHILD = r"""
import json, os, sys, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1]); coord = sys.argv[2]
from brpc_tpu.ici.fabric import FabricNode, FabricSocket
node = FabricNode.initialize(coord, num_processes=2, process_id=pid)
kv = node._kv
import brpc_tpu.policy
from brpc_tpu import rpc, ici
from brpc_tpu.rpc.socket import list_sockets
from brpc_tpu.ici.route import route_stats
from examples.disagg_serving import model as m
from examples.example_echo_pb2 import EchoRequest, EchoResponse
mesh = ici.IciMesh(); ici.IciMesh.set_default(mesh)

SEQ, STEPS, N = 512, 7, 4
PAYLOAD = m.kv_nbytes(SEQ)

def fabric_socks():
    return [s for s in list_sockets() if isinstance(s, FabricSocket)]

if pid == 0:
    from brpc_tpu.serving import KvPoolOptions, kv_load_stats
    from examples.disagg_serving.workers import DecodeService
    server = rpc.Server()
    svc = DecodeService(pool_options=KvPoolOptions(
        bytes_per_token=m.KV_LAYERS * m.KV_DMODEL, num_blocks=256,
        block_tokens=16, use_timers=False))
    server.add_service(svc)
    assert server.start("ici://0") == 0
    kv.key_value_set("kvshm_srv_up", "1")
    kv.wait_at_barrier("kvshm_done", 180000)
    # route truth, decode-worker side: the claims came off the shm
    # ring AND landed in the pool via the adopted route (no per-session
    # host materialization)
    socks = fabric_socks()
    assert socks and socks[0].shm_bound(), "server socket has no shm ring"
    assert socks[0].shm_bytes_claimed >= N * PAYLOAD, \
        socks[0].shm_bytes_claimed
    st = kv_load_stats()
    # host-bulk sessions rode the ring and were consumed in place
    # (adopted); the device-payload session re-emerged as a DEVICE
    # array on this side and scattered.  NOTHING materialized.
    assert st["adopted"] >= N, st
    assert st["scattered"] >= 1, st
    assert st["materialized"] == 0, st
    # exactly one copy pass per session, either route
    assert st["copy_bytes"] == \
        (st["adopted"] + st["scattered"]) * PAYLOAD, st
    blk = svc.describe_serving()
    assert blk["kv_load"]["adopted"] >= N
    svc.close(); server.stop()
    print("KVSHM0_OK", flush=True)
else:
    kv.blocking_key_value_get("kvshm_srv_up", 60000)
    local_dev = next(i for i, d in enumerate(jax.devices())
                     if d.process_index == pid)
    ch = rpc.Channel()
    ch.init("ici://0", options=rpc.ChannelOptions(timeout_ms=120000,
                                                  max_retry=0))
    # i < N: the KV crosses as HOST bulk bytes — the ring carries them
    # and the receiver's zero-copy claim is consumed straight into the
    # pool (adopted).  i == N: the device-payload shape — the fabric
    # re-emerges it as a DEVICE array on the server, which scatters.
    for i in range(N + 1):
        tokens = [(11 * i + j) %% 997 for j in range(SEQ)]
        payload = m.toy_kv_blocks(tokens, device=jax.devices()[local_dev])
        jax.block_until_ready(payload)
        cntl = rpc.Controller()
        if i < N:
            cntl.request_attachment.append(np.asarray(payload).tobytes())
        else:
            cntl.request_attachment.append_device_array(payload)
        ch.call_method("Decode.LoadKv", cntl, EchoRequest(
            message=json.dumps({"session": "s%%d" %% i, "seq_len": SEQ,
                                "last_token": tokens[-1]})), EchoResponse)
        assert not cntl.failed(), cntl.error_text
        dc = rpc.Controller()
        resp = ch.call_method("Decode.Decode", dc, EchoRequest(
            message=json.dumps({"session": "s%%d" %% i,
                                "steps": STEPS, "mode": "sync"})),
            EchoResponse)
        assert not dc.failed(), dc.error_text
        got = json.loads(resp.message)["tokens"]
        assert got == m.reference_generate(tokens, STEPS), \
            "claim-to-pool decode mismatch at session %%d" %% i
    s = fabric_socks()[0]
    assert s.shm_bound(), "client socket has no shm ring"
    assert s.shm_bytes_sent >= N * PAYLOAD, s.shm_bytes_sent
    rs = route_stats()
    assert rs.get("shm", {}).get("bytes", 0) >= N * PAYLOAD, rs
    kv.wait_at_barrier("kvshm_done", 180000)
    ch.close()
    print("KVSHM1_OK", flush=True)
"""


def test_kv_shm_claim_lands_in_pool_2proc():
    """The adopted route end to end across TWO processes: prefill-side
    KV bytes ride the fabric's shm ring, the receiver's zero-copy ring
    claim scatters straight into PagedKvPool blocks (adopted counter +
    shm route counters assert both layers), and sync decode reproduces
    the single-process reference bit-exact."""
    from test_fabric import _run_pair
    outs = _run_pair(_KV_SHM_CHILD % {"repo": REPO}, timeout=300)
    assert "KVSHM0_OK" in outs[0]
    assert "KVSHM1_OK" in outs[1]


# ---------------------------------------------------------------------------
# Elastic chaos: scale-up + kill + revive + scale-down mid-traffic, one
# subprocess hosting a real (1-member) pod so the epoch is observable.
# ---------------------------------------------------------------------------

_ELASTIC_CHAOS_CHILD = r"""
import json, os, sys, threading, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")
coord = sys.argv[1]

from brpc_tpu.ici.fabric import FabricNode
node = FabricNode.initialize(coord, num_processes=1, process_id=0)
import brpc_tpu.policy
from brpc_tpu import rpc, ici
from brpc_tpu.ici.pod import Pod
mesh = ici.IciMesh(); ici.IciMesh.set_default(mesh)
pod = Pod.join("serving-chaos")

from brpc_tpu.serving import (AutoscalerOptions, BatchSchedulerOptions,
                              KvPoolOptions, LoadThresholdAutoscaler)
from examples.disagg_serving.model import (KV_DMODEL, KV_LAYERS, VOCAB,
                                           reference_generate)
from examples.disagg_serving.workers import (DecodeService,
                                             start_prefill_worker,
                                             start_router)
from examples.example_echo_pb2 import EchoRequest, EchoResponse

BPT = KV_LAYERS * KV_DMODEL

def mk_decode(dev_url):
    server = rpc.Server()
    svc = DecodeService(
        pool_options=KvPoolOptions(bytes_per_token=BPT, num_blocks=512,
                                   block_tokens=16),
        sched_options=BatchSchedulerOptions(vocab=VOCAB, max_batch=4))
    server.add_service(svc)
    assert server.start(dev_url) == 0
    return server, svc

prefill = start_prefill_worker("ici://0")
dec_a, svc_a = mk_decode("ici://1")
router = start_router("mem://chaos-router", "ici://0", ["ici://1"])
rsvc = next(iter(router._services.values()))
epoch0 = pod.epoch(refresh=True)

# ---- elastic mechanism: the autoscaler's scale callbacks ----------------
workers = {"ici://1": (dec_a, svc_a)}
wlock = threading.Lock()

def current_load():
    with wlock:
        svcs = [s for (_, s) in workers.values()]
    if not svcs:
        return 1.0
    load = 0.0
    for s in svcs:
        d = s.scheduler.describe()
        load += (d["active"] + sum(d["pending_by_band"])) \
            / max(d["max_batch"], 1)
    return load / len(svcs)

def scale_up():
    with wlock:
        if "ici://2" in workers:
            return False
        server, svc = mk_decode("ici://2")
        workers["ici://2"] = (server, svc)
    rsvc.add_decode_target("ici://2")
    return True

def scale_down():
    with wlock:
        if "ici://2" not in workers:
            return False
        server, svc = workers.pop("ici://2")
    rsvc.remove_decode_target("ici://2")
    time.sleep(0.1)
    server.stop(grace_s=1.0)
    svc.close()
    return True

def size_fn():
    with wlock:
        return len(workers)

scaler = LoadThresholdAutoscaler(
    current_load, size_fn, scale_up, scale_down,
    options=AutoscalerOptions(high_water=0.75, low_water=0.1,
                              interval_s=0.1, samples_to_scale=2,
                              cooldown_s=1.5, min_size=1, max_size=2),
    pod=pod)
scaler.start()

# ---- traffic ------------------------------------------------------------
stop_evt = threading.Event()
stats = {"ok": 0, "shed": 0, "fail": 0, "mismatch": 0}
slock = threading.Lock()
ch_opts = rpc.ChannelOptions(timeout_ms=30000)

def client(wid, priority, pace_s, steps):
    ch = rpc.Channel(); ch.init("mem://chaos-router", options=ch_opts)
    i = 0
    while not stop_evt.is_set():
        tokens = [(wid * 31 + i * 7 + j) %% 997 for j in range(24)]
        i += 1
        cntl = rpc.Controller()
        cntl.priority = priority
        cntl.tenant = "inter" if priority == 0 else "bulk"
        resp = ch.call_method("Router.Generate", cntl,
                              EchoRequest(message=json.dumps(
                                  {"tokens": tokens, "steps": steps})),
                              EchoResponse)
        with slock:
            if cntl.failed():
                if cntl.error_code_ == rpc.errors.ELIMIT:
                    stats["shed"] += 1
                else:
                    stats["fail"] += 1
                    sys.stderr.write("CLIENT FAIL: %%s %%s\n"
                                     %% (cntl.error_code_,
                                        cntl.error_text))
            else:
                toks = json.loads(resp.message)["tokens"]
                if toks == reference_generate(tokens, steps):
                    stats["ok"] += 1
                else:
                    stats["mismatch"] += 1
        if pace_s:
            time.sleep(pace_s)
    ch.close()

# batch sessions are LONG (400 tokens): they live tens of steps in the
# roster, so 6 concurrent batch clients genuinely saturate max_batch=4
# and the load signal (roster + queue pressure) crosses the high-water
# mark — the toy decode is otherwise too fast to ever look loaded
threads = [threading.Thread(target=client, args=(w, 0, 0.05, 6))
           for w in range(2)]
threads += [threading.Thread(target=client, args=(10 + w, 3, 0.0, 400))
            for w in range(6)]
for t in threads: t.start()

def wait_for(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    raise AssertionError("timeout waiting for " + what)

try:
    # phase 1: the batch flood pushes load over the high-water mark and
    # the autoscaler scales decode ici://2 up (epoch bump via advertise)
    wait_for(lambda: scaler.scale_ups.get_value() >= 1, 30.0,
             "scale-up (load=%%s)" %% current_load())
    wait_for(lambda: "ici://2" in rsvc._router.targets(), 5.0,
             "router membership")
    time.sleep(1.0)

    # phase 2: KILL decode A mid-traffic (no drain).  In-flight
    # sessions on A fail server-side; the router re-prefills them on B
    # — zero client-visible failures.
    dec_a.stop(grace_s=0)
    svc_a.close()
    rsvc.remove_decode_target("ici://1")
    with wlock:
        workers.pop("ici://1", None)
    time.sleep(1.5)

    # phase 3: REVIVE A (restart on the same device; advertise bumps
    # the epoch again) and hand it back to the router
    dec_a2, svc_a2 = mk_decode("ici://1")
    with wlock:
        workers["ici://1"] = (dec_a2, svc_a2)
    rsvc.add_decode_target("ici://1")
    time.sleep(1.0)
finally:
    # phase 4: drop the batch flood; load falls under the low-water
    # mark and the autoscaler scales ici://2 back down
    stop_evt.set()
for t in threads: t.join()
wait_for(lambda: scaler.scale_downs.get_value() >= 1, 20.0,
         "scale-down (load=%%s)" %% current_load())

scaler.stop()
epoch1 = pod.epoch(refresh=True)
desc = pod.describe()
assert "autoscaler" in desc, "autoscaler missing from pod describe"

result = {
    "ok": stats["ok"], "shed": stats["shed"], "fail": stats["fail"],
    "mismatch": stats["mismatch"],
    "epoch_delta": epoch1 - epoch0,
    "scale_ups": scaler.scale_ups.get_value(),
    "scale_downs": scaler.scale_downs.get_value(),
    "router": rsvc.describe_serving()["router"],
}
print("CHAOS_RESULT " + json.dumps(result), flush=True)

for server, svc in list(workers.values()):
    svc.close(); server.stop()
for svc in router._services.values():
    if hasattr(svc, "close"): svc.close()
router.stop()
for svc in prefill._services.values():
    if hasattr(svc, "close"): svc.close()
prefill.stop()
pod.leave()
"""


class TestElasticChaosServing:
    def test_scale_up_kill_revive_scale_down_under_traffic(self):
        """The tier-1 elastic chaos leg: a 1-member pod serving mixed
        interactive/batch traffic scales a decode worker up on load,
        survives a KILL of the original worker, revives it, and scales
        back down — zero client-visible failures, every completion
        bit-exact, the epoch delta covering every membership
        transition."""
        from netalloc import alloc_port
        coord = f"127.0.0.1:{alloc_port('serving_chaos')}"
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env.pop("JAX_NUM_PROCESSES", None)
        proc = subprocess.Popen(
            [sys.executable, "-c",
             _ELASTIC_CHAOS_CHILD % {"repo": REPO}, coord],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        try:
            out, _ = proc.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
        assert proc.returncode == 0, out[-4000:]
        line = next(l for l in out.splitlines()
                    if l.startswith("CHAOS_RESULT "))
        res = json.loads(line[len("CHAOS_RESULT "):])
        # zero client-visible failures; batch sheds allowed (that IS
        # the absorb-the-pressure contract), mismatches never
        assert res["fail"] == 0, res
        assert res["mismatch"] == 0, res
        assert res["ok"] > 20, res
        assert res["scale_ups"] >= 1 and res["scale_downs"] >= 1, res
        # every transition moved the epoch: initial 3 advertises are in
        # epoch0; up(+1) kill-withdraw(+1) revive(+1) down(+>=1)
        assert res["epoch_delta"] >= 4, res
        # the router retried around the kill rather than surfacing it
        assert res["router"]["generate_failures"] == 0, res
