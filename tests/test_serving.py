"""Production serving subsystem (brpc_tpu/serving, ISSUE 14).

Five legs:

  * **PagedKvPool units** — block accounting, byte-exact custody,
    admission-aware eviction order (band before weight before LRU, the
    protected-band fence), pins, and the TIMER-DRIVEN expiry sweep (the
    ISSUE-14 bugfix regression: a parked session on an otherwise-idle
    worker is reclaimed with zero new traffic);
  * **ContinuousBatchScheduler units** (manual stepping) — per-step
    admit/retire, tokens bit-exact against the single-process reference
    under staggered joins, interactive preemption preserving progress,
    deadline expiry in the batch queue, compiled-step parity;
  * **service level** — the rebuilt disaggregated workers: batched
    decode end-to-end with the route asserted through the /status
    serving block, LALB prefill→decode routing, pool-saturation sheds
    with retry hints, and the idle-reclaim regression over a real RPC;
  * **autoscaler units** — watermark/hysteresis/cooldown decisions on
    an injected clock;
  * **elastic chaos** (tier-1, one subprocess with a real pod) —
    scale-up + kill + revive + scale-down mid-traffic: zero
    client-visible failures, every completion bit-exact, the pod epoch
    delta asserted.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import brpc_tpu.policy  # noqa: F401
from brpc_tpu import rpc

pytestmark = pytest.mark.serving

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _model():
    from examples.disagg_serving import model
    return model


def _rows(tokens):
    """Prompt → token-major pool rows (the LoadKv transpose)."""
    m = _model()
    kv = np.asarray(m.toy_kv_blocks(tokens))
    seq = len(tokens)
    return kv.reshape(m.KV_LAYERS, seq, m.KV_DMODEL).transpose(
        1, 0, 2).reshape(seq, m.KV_LAYERS * m.KV_DMODEL)


def _mk_pool(num_blocks=32, block_tokens=8, ttl_s=120.0,
             use_timers=False, now=None, **kw):
    from brpc_tpu.serving import KvPoolOptions, PagedKvPool
    m = _model()
    opts = KvPoolOptions(bytes_per_token=m.KV_LAYERS * m.KV_DMODEL,
                         num_blocks=num_blocks,
                         block_tokens=block_tokens, ttl_s=ttl_s,
                         use_timers=use_timers, **kw)
    return PagedKvPool(opts, now=now)


def _mk_sched(pool, max_batch=8, **kw):
    from brpc_tpu.serving import (BatchSchedulerOptions,
                                  ContinuousBatchScheduler)
    m = _model()
    kw.setdefault("auto_start", False)
    return ContinuousBatchScheduler(
        pool, BatchSchedulerOptions(vocab=m.VOCAB, max_batch=max_batch,
                                    **kw))


class _Sink:
    """Collects one StepRequest outcome."""

    def __init__(self):
        self.tokens = None
        self.error = None

    def emit(self, tokens):
        self.tokens = list(tokens)

    def fail(self, code, text, retry_after_ms):
        self.error = (code, text, retry_after_ms)


def _submit(sched, session, steps, priority=None, tenant="",
            deadline_us=None):
    from brpc_tpu.serving import StepRequest
    sink = _Sink()
    sched.submit(StepRequest(session, steps, sink.emit, sink.fail,
                             priority=priority, tenant=tenant,
                             deadline_us=deadline_us))
    return sink


# ---------------------------------------------------------------------------
# Paged KV pool.
# ---------------------------------------------------------------------------

class TestPagedKvPool:
    def test_load_materialize_byte_exact_and_accounting(self):
        pool = _mk_pool(num_blocks=16, block_tokens=8)
        try:
            t1 = [3 * j % 97 for j in range(20)]     # 3 blocks
            t2 = [5 * j % 89 for j in range(8)]      # 1 block
            r1, r2 = _rows(t1), _rows(t2)
            pool.load("a", r1, last_token=t1[-1])
            pool.load("b", r2, last_token=t2[-1])
            d = pool.describe()
            assert d["blocks_used"] == 4 and d["sessions"] == 2
            assert np.array_equal(pool.materialize("a"), r1)
            assert np.array_equal(pool.materialize("b"), r2)
            s = pool.get("a")
            assert s.seq_len == 20 and s.acc == int(
                r1.sum(dtype=np.int64))
            assert pool.release("a") and not pool.release("a")
            assert pool.describe()["blocks_used"] == 1
        finally:
            pool.close()

    def test_partial_tail_block_zeroed(self):
        # a partially-filled tail block must not leak the previous
        # tenant's bytes or reduction sums
        pool = _mk_pool(num_blocks=2, block_tokens=8)
        try:
            full = [7] * 16                           # both blocks, full
            pool.load("x", _rows(full), last_token=7)
            pool.release("x")
            short = [11] * 9                          # 2 blocks, 7 stale
            s = pool.load("y", _rows(short), last_token=11)
            tail_blk = int(s.blocks[1])
            assert pool._pos_sums[tail_blk, 1:].sum() == 0
            assert np.array_equal(pool.materialize("y"), _rows(short))
        finally:
            pool.close()

    def test_lru_eviction_within_band_and_touch(self):
        pool = _mk_pool(num_blocks=4, block_tokens=8)
        try:
            for name in ("old", "mid", "new"):
                pool.load(name, _rows([1] * 8), last_token=1,
                          priority=2)
                time.sleep(0.002)
            pool.touch("old")                 # now "mid" is LRU
            pool.load("D", _rows([2] * 16), last_token=2, priority=2)
            assert pool.get("mid") is None
            assert pool.get("old") is not None
            assert pool.evicted_reason("mid") == "pressure"
        finally:
            pool.close()

    def test_batch_evicted_before_interactive(self):
        pool = _mk_pool(num_blocks=3, block_tokens=8)
        try:
            pool.load("inter", _rows([1] * 8), last_token=1, priority=0)
            time.sleep(0.002)
            pool.load("batch", _rows([2] * 8), last_token=2, priority=3)
            # interactive is OLDER, but the batch band absorbs pressure
            pool.load("new", _rows([3] * 16), last_token=3, priority=1)
            assert pool.get("batch") is None
            assert pool.get("inter") is not None
        finally:
            pool.close()

    def test_tenant_weight_tiebreak_from_admission(self):
        from brpc_tpu.rpc.admission import AdmissionOptions
        from brpc_tpu.serving import KvPoolOptions, PagedKvPool
        m = _model()
        adm = AdmissionOptions(tenant_weights={"gold": 8, "bronze": 1})
        opts = KvPoolOptions.from_admission(
            adm, bytes_per_token=m.KV_LAYERS * m.KV_DMODEL,
            num_blocks=3, block_tokens=8, use_timers=False)
        assert opts.tenant_weights == {"gold": 8, "bronze": 1}
        pool = PagedKvPool(opts)
        try:
            # same band; bronze is NEWER but lighter — evicted first
            pool.load("g", _rows([1] * 8), last_token=1, priority=2,
                      tenant="gold")
            time.sleep(0.002)
            pool.load("b", _rows([2] * 8), last_token=2, priority=2,
                      tenant="bronze")
            pool.load("n", _rows([3] * 16), last_token=3, priority=2)
            assert pool.get("b") is None
            assert pool.get("g") is not None
            assert any(k.startswith("evicted_pressure[bronze]")
                       for k in pool.describe()["by_tenant"])
        finally:
            pool.close()

    def test_requester_cannot_evict_more_protected_band(self):
        from brpc_tpu.serving import PoolSaturated
        pool = _mk_pool(num_blocks=2, block_tokens=8)
        try:
            pool.load("inter", _rows([1] * 16), last_token=1,
                      priority=0)
            with pytest.raises(PoolSaturated):
                pool.load("batch", _rows([2] * 8), last_token=2,
                          priority=3)
            assert pool.get("inter") is not None
        finally:
            pool.close()

    def test_pinned_never_evicted_or_expired(self):
        from brpc_tpu.serving import PoolSaturated
        pool = _mk_pool(num_blocks=2, block_tokens=8, ttl_s=0.0)
        try:
            pool.load("run", _rows([1] * 16), last_token=1, priority=3)
            assert pool.pin("run")
            with pytest.raises(PoolSaturated):
                pool.load("x", _rows([2] * 8), last_token=2, priority=0)
            assert pool.expire_idle() == 0    # pinned: ttl ignored
            pool.unpin("run")
            assert pool.expire_idle() == 1
        finally:
            pool.close()

    def test_timer_sweep_reclaims_idle_session_without_traffic(self):
        """THE ISSUE-14 regression: expiry is timer-driven — a parked
        session on an otherwise-idle pool is reclaimed on time with
        ZERO further loads or decodes (the old example swept only
        inside LoadKv)."""
        pool = _mk_pool(num_blocks=4, block_tokens=8, ttl_s=0.15,
                        use_timers=True, sweep_interval_s=0.05)
        try:
            pool.load("parked", _rows([1] * 8), last_token=1)
            deadline = time.monotonic() + 5.0
            while pool.sessions() and time.monotonic() < deadline:
                time.sleep(0.02)
            assert pool.sessions() == 0, "idle session never reclaimed"
            assert pool.expirations.get_value() >= 1
            assert pool.describe()["blocks_free"] == 4
        finally:
            pool.close()

    def test_reload_of_pinned_session_refused(self):
        """Re-prefilling a session that is PINNED in the step roster is
        refused (SessionBusy): freeing a rostered session's blocks
        would hand them to the new bytes mid-program — the running
        gather would read the replacement's KV (review finding)."""
        from brpc_tpu.serving import SessionBusy
        pool = _mk_pool(num_blocks=8, block_tokens=8)
        try:
            r1 = _rows([1] * 8)
            pool.load("s", r1, last_token=1)
            assert pool.pin("s")
            with pytest.raises(SessionBusy):
                pool.load("s", _rows([2] * 8), last_token=2)
            # the rostered table is untouched
            assert np.array_equal(pool.materialize("s"), r1)
            pool.unpin("s")
            pool.load("s", _rows([2] * 8), last_token=2)  # now fine
            assert np.array_equal(pool.materialize("s"), _rows([2] * 8))
        finally:
            pool.close()

    def test_zero_length_session_rejected(self):
        pool = _mk_pool()
        try:
            with pytest.raises(ValueError):
                pool.load("empty", np.zeros(
                    (0, pool.options.bytes_per_token), np.uint8),
                    last_token=0)
        finally:
            pool.close()

    def test_manual_expiry_with_injected_clock(self):
        clock = [100.0]
        pool = _mk_pool(num_blocks=4, block_tokens=8, ttl_s=10.0,
                        now=lambda: clock[0])
        try:
            pool.load("s", _rows([1] * 8), last_token=1)
            clock[0] = 109.0
            assert pool.expire_idle() == 0
            clock[0] = 111.0
            assert pool.expire_idle() == 1
            assert pool.evicted_reason("s") == "expired"
        finally:
            pool.close()


# ---------------------------------------------------------------------------
# Continuous-batching scheduler (manual stepping).
# ---------------------------------------------------------------------------

class TestContinuousBatchScheduler:
    def _load(self, pool, session, tokens, **kw):
        pool.load(session, _rows(tokens), last_token=tokens[-1], **kw)

    def test_tokens_bit_exact_with_staggered_joins(self):
        m = _model()
        pool = _mk_pool(num_blocks=32, block_tokens=8)
        sched = _mk_sched(pool, max_batch=8)
        try:
            specs = {f"s{i}": ([(7 * i + j) % 997
                                for j in range(16 + 11 * i)], 5 + 3 * i)
                     for i in range(3)}
            sinks = {}
            for s, (tokens, steps) in specs.items():
                self._load(pool, s, tokens)
                sinks[s] = _submit(sched, s, steps)
            for _ in range(4):
                sched.step_once()
            # a session JOINS mid-stream, between steps
            late = [(13 * j) % 499 for j in range(21)]
            specs["late"] = (late, 6)
            self._load(pool, "late", late)
            sinks["late"] = _submit(sched, "late", 6)
            for _ in range(20):
                sched.step_once()
            for s, (tokens, steps) in specs.items():
                assert sinks[s].tokens == m.reference_generate(
                    tokens, steps), f"session {s} diverged"
            d = sched.describe()
            assert d["retired"] == 4 and d["steps"] > 0
            assert d["batch_occupancy_avg"] > 1.0
        finally:
            sched.stop()
            pool.close()

    def test_max_batch_admits_per_step(self):
        pool = _mk_pool()
        sched = _mk_sched(pool, max_batch=2)
        try:
            sinks = []
            for i in range(3):
                tokens = [(i + j) % 97 for j in range(8)]
                self._load(pool, f"s{i}", tokens)
                sinks.append(_submit(sched, f"s{i}", 2))
            assert sched.step_once() == 2          # roster capped at 2
            assert sched.active() == 2 and sched.queued() == 1
            sched.step_once()                      # first two retire
            assert sched.step_once() == 1          # third admitted
            sched.step_once()
            assert all(s.tokens is not None for s in sinks)
        finally:
            sched.stop()
            pool.close()

    def test_interactive_preemption_preserves_progress(self):
        m = _model()
        pool = _mk_pool()
        sched = _mk_sched(pool, max_batch=1, interactive_priority_max=1)
        try:
            batch_toks = [3 * j % 97 for j in range(16)]
            self._load(pool, "batch", batch_toks, priority=3)
            b = _submit(sched, "batch", 10, priority=3)
            for _ in range(3):
                sched.step_once()
            assert sched.active() == 1
            inter_toks = [5 * j % 89 for j in range(8)]
            self._load(pool, "inter", inter_toks, priority=0)
            i = _submit(sched, "inter", 4, priority=0)
            # next boundary: batch preempted mid-decode, interactive in
            sched.step_once()
            assert sched.preempted.get_value() == 1
            for _ in range(12):
                sched.step_once()
            assert i.tokens == m.reference_generate(inter_toks, 4)
            # the preempted session RESUMED from its next token
            assert b.tokens == m.reference_generate(batch_toks, 10)
        finally:
            sched.stop()
            pool.close()

    def test_deadline_expired_in_queue(self):
        from brpc_tpu.rpc import errors
        pool = _mk_pool()
        sched = _mk_sched(pool, max_batch=4)
        try:
            self._load(pool, "s", [1] * 8)
            sink = _submit(sched, "s", 4,
                           deadline_us=time.monotonic_ns() // 1000 - 10)
            sched.step_once()
            assert sink.error is not None
            assert sink.error[0] == errors.ERPCTIMEDOUT
            assert sched.expired.get_value() == 1
        finally:
            sched.stop()
            pool.close()

    def test_unknown_and_evicted_session_refusals(self):
        from brpc_tpu.rpc import errors
        pool = _mk_pool(num_blocks=1, block_tokens=8)
        sched = _mk_sched(pool)
        try:
            sink = _submit(sched, "ghost", 4)
            sched.step_once()
            assert sink.error[0] == errors.EREQUEST
            self._load(pool, "victim", [1] * 8, priority=3)
            self._load(pool, "usurper", [2] * 8, priority=0)  # evicts
            sink2 = _submit(sched, "victim", 4)
            sched.step_once()
            assert sink2.error[0] == errors.ELIMIT
            assert "re-prefill" in sink2.error[1]
        finally:
            sched.stop()
            pool.close()

    def test_duplicate_submit_refused_and_custody_safe(self):
        """A retry storm re-issuing a Decode whose first copy is still
        running is REFUSED: two roster entries on one session would let
        the first completion release the pool blocks the second still
        gathers through (cross-tenant bytes after block reuse — the
        soak caught this as a token mismatch)."""
        from brpc_tpu.rpc import errors
        m = _model()
        pool = _mk_pool()
        sched = _mk_sched(pool, max_batch=4)
        try:
            tokens = [9 * j % 97 for j in range(12)]
            self._load(pool, "dup", tokens)
            first = _submit(sched, "dup", 6)
            second = _submit(sched, "dup", 6)
            assert second.error is not None
            assert second.error[0] == errors.EREQUEST
            assert "duplicate" in second.error[1]
            for _ in range(8):
                sched.step_once()
            assert first.tokens == m.reference_generate(tokens, 6)
            # ownership released at completion: a FRESH submit works
            third = _submit(sched, "dup", 3)
            for _ in range(5):
                sched.step_once()
            assert third.tokens == m.reference_generate(tokens, 3)
        finally:
            sched.stop()
            pool.close()

    def test_compiled_step_parity(self):
        """The jit-compiled XLA step produces the numpy step's tokens
        bit for bit (the TPU-pod shape, parity-pinned)."""
        from brpc_tpu.butil import flags as fl
        m = _model()
        pool = _mk_pool(num_blocks=32, block_tokens=8)
        sched = _mk_sched(pool, max_batch=4)
        saved = fl.get_flag("serving_compiled_step")
        fl.set_flag("serving_compiled_step", True)
        try:
            sinks = {}
            specs = {}
            for i in range(3):
                tokens = [(11 * i + j) % 499 for j in range(10 + 7 * i)]
                specs[f"c{i}"] = (tokens, 6)
                self._load(pool, f"c{i}", tokens)
                sinks[f"c{i}"] = _submit(sched, f"c{i}", 6)
            for _ in range(10):
                sched.step_once()
            for s, (tokens, steps) in specs.items():
                assert sinks[s].tokens == m.reference_generate(
                    tokens, steps)
            assert sched.describe()["compiled_step"] is True
        finally:
            fl.set_flag("serving_compiled_step", saved)
            sched.stop()
            pool.close()

    def test_step_loop_survives_a_step_exception(self):
        """One bad roster must not wedge the worker: the loop fails the
        crashed roster with EINTERNAL and keeps serving (review
        finding: an unguarded step thread died permanently and every
        later Decode queued forever)."""
        from brpc_tpu.rpc import errors
        m = _model()
        pool = _mk_pool()
        sched = _mk_sched(pool, max_batch=4, auto_start=True)
        try:
            boom = {"armed": True}
            orig = sched._step_numpy

            def exploding(bt):
                if boom["armed"]:
                    boom["armed"] = False
                    raise RuntimeError("injected step fault")
                return orig(bt)

            sched._step_numpy = exploding
            tokens = [3 * j % 97 for j in range(8)]
            self._load(pool, "crash", tokens)
            sink = _submit(sched, "crash", 4)
            deadline = time.monotonic() + 5.0
            while sink.error is None and time.monotonic() < deadline:
                time.sleep(0.01)
            assert sink.error is not None, "crashed roster never failed"
            assert sink.error[0] == errors.EINTERNAL
            # the loop is ALIVE: a fresh session decodes bit-exact
            self._load(pool, "after", tokens)
            sink2 = _submit(sched, "after", 4)
            deadline = time.monotonic() + 5.0
            while sink2.tokens is None and sink2.error is None \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert sink2.tokens == m.reference_generate(tokens, 4)
        finally:
            sched.stop()
            pool.close()

    def test_stop_fails_pending_with_elogoff(self):
        from brpc_tpu.rpc import errors
        pool = _mk_pool()
        sched = _mk_sched(pool)
        try:
            self._load(pool, "s", [1] * 8)
            sink = _submit(sched, "s", 4)
            sched.stop()
            assert sink.error[0] == errors.ELOGOFF
            late = _submit(sched, "s", 4)
            assert late.error[0] == errors.ELOGOFF
        finally:
            pool.close()


# ---------------------------------------------------------------------------
# Service level: the rebuilt disaggregated workers.
# ---------------------------------------------------------------------------

class TestServingServices:
    def _decode_worker(self, name, **kw):
        from examples.disagg_serving.workers import DecodeService
        server = rpc.Server()
        svc = DecodeService(**kw)
        server.add_service(svc)
        assert server.start(f"mem://{name}") == 0
        return server, svc

    def _load_session(self, ch, session, tokens, priority=None,
                      tenant=""):
        from examples.example_echo_pb2 import EchoRequest, EchoResponse
        m = _model()
        kv = np.asarray(m.toy_kv_blocks(tokens)).tobytes()
        cntl = rpc.Controller()
        if priority is not None:
            cntl.priority = priority
        if tenant:
            cntl.tenant = tenant
        cntl.request_attachment.append(kv)
        ch.call_method("Decode.LoadKv", cntl, EchoRequest(
            message=json.dumps({"session": session,
                                "seq_len": len(tokens),
                                "last_token": tokens[-1]})),
            EchoResponse)
        return cntl

    def test_batched_decode_end_to_end_route_asserted(self):
        """N concurrent Decode RPCs share the step loop: every reply
        bit-exact, batch occupancy > 1, and the route asserted through
        the /status serving block."""
        from examples.example_echo_pb2 import EchoRequest, EchoResponse
        m = _model()
        server, svc = self._decode_worker("serv-batched")
        ch = rpc.Channel()
        ch.init("mem://serv-batched",
                options=rpc.ChannelOptions(timeout_ms=30000))
        try:
            # 200-step sessions: lifetimes of several ms, far beyond
            # client-thread start stagger even under suite-wide CPU
            # contention — the roster genuinely overlaps (a 12-step
            # variant measured occupancy exactly 1.0 on a loaded host)
            specs = {f"b{i}": ([(3 * i + j) % 997
                                for j in range(24 + 8 * i)], 200)
                     for i in range(6)}
            for s, (tokens, _) in specs.items():
                assert not self._load_session(ch, s, tokens).failed()
            results = {}
            lock = threading.Lock()

            def decode(s, steps):
                cntl = rpc.Controller()
                resp = ch.call_method("Decode.Decode", cntl,
                                      EchoRequest(message=json.dumps(
                                          {"session": s,
                                           "steps": steps})),
                                      EchoResponse)
                with lock:
                    results[s] = (cntl.failed(), cntl.error_text,
                                  json.loads(resp.message)["tokens"]
                                  if not cntl.failed() else None)

            threads = [threading.Thread(target=decode, args=(s, steps))
                       for s, (_, steps) in specs.items()]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for s, (tokens, steps) in specs.items():
                failed, err, toks = results[s]
                assert not failed, (s, err)
                assert toks == m.reference_generate(tokens, steps), s
            d = svc.describe_serving()
            assert d["scheduler"]["retired"] == 6
            assert d["scheduler"]["batch_occupancy_avg"] > 1.0
            assert svc.live_sessions() == 0    # released on completion
            # the /status page carries the serving block
            ctype, body = server._builtin.dispatch("status")
            blk = json.loads(body)["serving"]["Decode"]
            assert blk["scheduler"]["steps"] > 0
            assert blk["pool"]["blocks_total"] > 0
        finally:
            ch.close()
            svc.close()
            server.stop()

    def test_sync_mode_matches_batch_mode(self):
        from examples.example_echo_pb2 import EchoRequest, EchoResponse
        m = _model()
        server, svc = self._decode_worker("serv-sync")
        ch = rpc.Channel()
        ch.init("mem://serv-sync",
                options=rpc.ChannelOptions(timeout_ms=30000))
        try:
            tokens = [(17 * j) % 499 for j in range(40)]
            want = m.reference_generate(tokens, 9)
            for mode in ("sync", "batch"):
                s = f"m-{mode}"
                assert not self._load_session(ch, s, tokens).failed()
                cntl = rpc.Controller()
                body = {"session": s, "steps": 9}
                if mode == "sync":
                    body["mode"] = "sync"
                resp = ch.call_method("Decode.Decode", cntl,
                                      EchoRequest(message=json.dumps(
                                          body)), EchoResponse)
                assert not cntl.failed(), cntl.error_text
                assert json.loads(resp.message)["tokens"] == want, mode
        finally:
            ch.close()
            svc.close()
            server.stop()

    def test_pool_saturated_sheds_with_retry_hint(self):
        from brpc_tpu.rpc import errors
        from brpc_tpu.serving import KvPoolOptions
        m = _model()
        server, svc = self._decode_worker(
            "serv-sat", pool_options=KvPoolOptions(
                bytes_per_token=m.KV_LAYERS * m.KV_DMODEL,
                num_blocks=2, block_tokens=8))
        ch = rpc.Channel()
        ch.init("mem://serv-sat",
                options=rpc.ChannelOptions(timeout_ms=30000,
                                           max_retry=0))
        try:
            # interactive KV owns the pool; a batch load is SHED with a
            # retry hint, not failed into the unknown
            assert not self._load_session(ch, "inter", [1] * 16,
                                          priority=0).failed()
            cntl = self._load_session(ch, "batch", [2] * 8, priority=3,
                                      tenant="bulk")
            assert cntl.failed() and cntl.error_code_ == errors.ELIMIT
            assert cntl.retry_after_ms > 0
            assert svc.live_sessions() == 1
        finally:
            ch.close()
            svc.close()
            server.stop()

    def test_idle_worker_reclaims_parked_session_without_traffic(self):
        """THE ISSUE-14 regression at the RPC level: LoadKv parks a
        session, NO further traffic of any kind arrives, and the
        worker's pool reclaims it by timer."""
        from brpc_tpu.serving import KvPoolOptions
        m = _model()
        server, svc = self._decode_worker(
            "serv-idle", pool_options=KvPoolOptions(
                bytes_per_token=m.KV_LAYERS * m.KV_DMODEL,
                num_blocks=8, block_tokens=8, ttl_s=0.15,
                sweep_interval_s=0.05))
        ch = rpc.Channel()
        ch.init("mem://serv-idle",
                options=rpc.ChannelOptions(timeout_ms=30000))
        try:
            assert not self._load_session(ch, "parked",
                                          [3] * 12).failed()
            assert svc.live_sessions() == 1
            deadline = time.monotonic() + 5.0
            while svc.live_sessions() and time.monotonic() < deadline:
                time.sleep(0.02)
            assert svc.live_sessions() == 0, \
                "parked session not reclaimed on an idle worker"
            assert svc.sessions_expired >= 1
        finally:
            ch.close()
            svc.close()
            server.stop()

    def test_rpc_press_serving_mode(self):
        """The open-loop session generator (tools/rpc_press --serving):
        mixed tenants at a fixed arrival rate, per-tenant tokens/s in
        the summary, and the in-process pool/scheduler occupancy
        reported through the serving status block."""
        import io

        import jax
        from brpc_tpu.tools.rpc_press import run_press_serving
        from examples.disagg_serving.workers import (start_decode_worker,
                                                     start_prefill_worker,
                                                     start_router)
        devs = jax.devices()
        prefill = start_prefill_worker("ici://7", device=devs[7])
        decode = start_decode_worker("mem://press-dec")
        router = start_router("mem://press-router", "ici://7",
                              ["mem://press-dec"])
        try:
            res = run_press_serving(
                "mem://press-router", duration=1.5, arrival_rps=40.0,
                batch_ratio=2, seq_range="16-32", steps_range="4-16",
                out=io.StringIO())
            assert res["issued"] >= 20, res
            for tenant in ("inter", "bulk"):
                c = res["per_tenant"][tenant]
                assert c["ok"] > 0 and c["failures"] == 0, res
                assert c["session_tokens_per_s_p50"] > 0, res
            assert res["tokens_per_s"] > 0
            blk = next(v for k, v in res["serving_status"].items()
                       if "Decode" in k)
            assert blk["pool"]["blocks_total"] > 0
            assert blk["scheduler"]["steps"] > 0
        finally:
            for server in (router, prefill, decode):
                for svc in server._services.values():
                    if hasattr(svc, "close"):
                        svc.close()
                server.stop()

    def test_lalb_router_shifts_load_to_fast_worker(self):
        """The divided-weight loop: feedback drives selection — a slow
        worker's share collapses."""
        from brpc_tpu.serving import LoadAwareRouter
        router = LoadAwareRouter(["mem://lalb-fast", "mem://lalb-slow"])
        try:
            for _ in range(40):
                router.feedback("mem://lalb-fast", 0, 1000)
                router.feedback("mem://lalb-slow", 0, 50000)
            picks = {"mem://lalb-fast": 0, "mem://lalb-slow": 0}
            for _ in range(300):
                url = router.pick()
                picks[url] += 1
                router.feedback(url, 0,
                                1000 if url.endswith("fast") else 50000)
            assert picks["mem://lalb-fast"] > 0.65 * 300, picks
            d = router.describe()
            assert d["balancer"] == "la"
            assert d["weights"]["mem://lalb-fast"] > \
                d["weights"]["mem://lalb-slow"]
        finally:
            router.close()

    def test_router_retries_dead_decode_worker(self):
        """A Generate whose chosen decode worker is DEAD re-prefills
        against another one — zero client-visible failures (the elastic
        chaos contract's unit half)."""
        import jax
        from examples.disagg_serving.workers import (start_decode_worker,
                                                     start_prefill_worker,
                                                     start_router)
        from examples.example_echo_pb2 import EchoRequest, EchoResponse
        m = _model()
        devs = jax.devices()
        prefill = start_prefill_worker("ici://6", device=devs[6])
        alive = start_decode_worker("mem://rr-alive")
        dead = start_decode_worker("mem://rr-dead")
        router = start_router("mem://rr-router", "ici://6",
                              ["mem://rr-dead", "mem://rr-alive"])
        servers = [router, prefill, alive]
        try:
            # the dead worker stops before any traffic: whichever
            # attempt picks it fails and the router must recover
            for svc in dead._services.values():
                if hasattr(svc, "close"):
                    svc.close()
            dead.stop()
            ch = rpc.Channel()
            ch.init("mem://rr-router",
                    options=rpc.ChannelOptions(timeout_ms=60000))
            tokens = [(7 * j) % 499 for j in range(32)]
            for _ in range(4):
                cntl = rpc.Controller()
                resp = ch.call_method(
                    "Router.Generate", cntl,
                    EchoRequest(message=json.dumps(
                        {"tokens": tokens, "steps": 6})), EchoResponse)
                assert not cntl.failed(), cntl.error_text
                out = json.loads(resp.message)
                assert out["tokens"] == m.reference_generate(tokens, 6)
                assert out["decode_worker"] == "mem://rr-alive"
            ch.close()
        finally:
            for server in servers:
                for svc in server._services.values():
                    if hasattr(svc, "close"):
                        svc.close()
                server.stop()


# ---------------------------------------------------------------------------
# Autoscaler units (injected clock + load).
# ---------------------------------------------------------------------------

class TestAutoscaler:
    def _mk(self, loads, size0=1, **kw):
        from brpc_tpu.serving import (AutoscalerOptions,
                                      LoadThresholdAutoscaler)
        state = {"size": size0, "ups": 0, "downs": 0, "i": 0}

        def load_fn():
            i = min(state["i"], len(loads) - 1)
            state["i"] += 1
            return loads[i]

        def up():
            state["size"] += 1
            state["ups"] += 1
            return True

        def down():
            state["size"] -= 1
            state["downs"] += 1
            return True

        opts = AutoscalerOptions(**kw)
        a = LoadThresholdAutoscaler(load_fn, lambda: state["size"],
                                    up, down, options=opts)
        return a, state

    def test_hysteresis_and_cooldown(self):
        a, st = self._mk([0.9, 0.9, 0.9, 0.9, 0.9],
                         samples_to_scale=2, cooldown_s=10.0,
                         max_size=4)
        assert a.tick(now=0.0) is None      # 1 high sample: not yet
        assert a.tick(now=1.0) == "up"      # 2 consecutive: scale
        assert st["size"] == 2
        assert a.tick(now=2.0) is None      # cooldown holds
        assert a.tick(now=3.0) is None
        # sustained high load keeps accumulating through the cooldown:
        # the next action fires the moment the cooldown lifts
        assert a.tick(now=12.0) == "up"
        assert a.tick(now=13.0) is None     # new cooldown holds again
        assert st["ups"] == 2

    def test_scale_down_and_min_size(self):
        a, st = self._mk([0.1] * 6, size0=2, samples_to_scale=2,
                         cooldown_s=0.0, min_size=1)
        assert a.tick(now=0.0) is None
        assert a.tick(now=1.0) == "down"
        assert st["size"] == 1
        # at min_size: low load never goes below
        assert a.tick(now=2.0) is None
        assert a.tick(now=3.0) is None
        assert st["size"] == 1

    def test_max_size_and_mid_band_resets_runs(self):
        a, st = self._mk([0.9, 0.5, 0.9, 0.9], samples_to_scale=2,
                         cooldown_s=0.0, max_size=2)
        assert a.tick(now=0.0) is None
        assert a.tick(now=1.0) is None      # mid-band sample reset
        assert a.tick(now=2.0) is None
        assert a.tick(now=3.0) == "up"
        assert st["size"] == 2
        d = a.describe()
        assert d["scale_ups"] == 1 and d["size"] == 2
        assert "load" in d["last"]


# ---------------------------------------------------------------------------
# Elastic chaos: scale-up + kill + revive + scale-down mid-traffic, one
# subprocess hosting a real (1-member) pod so the epoch is observable.
# ---------------------------------------------------------------------------

_ELASTIC_CHAOS_CHILD = r"""
import json, os, sys, threading, time
sys.path.insert(0, %(repo)r)
sys.path.insert(0, os.path.join(%(repo)r, "tests"))
import jax
jax.config.update("jax_platforms", "cpu")
coord = sys.argv[1]

from brpc_tpu.ici.fabric import FabricNode
node = FabricNode.initialize(coord, num_processes=1, process_id=0)
import brpc_tpu.policy
from brpc_tpu import rpc, ici
from brpc_tpu.ici.pod import Pod
mesh = ici.IciMesh(); ici.IciMesh.set_default(mesh)
pod = Pod.join("serving-chaos")

from brpc_tpu.serving import (AutoscalerOptions, BatchSchedulerOptions,
                              KvPoolOptions, LoadThresholdAutoscaler)
from examples.disagg_serving.model import (KV_DMODEL, KV_LAYERS, VOCAB,
                                           reference_generate)
from examples.disagg_serving.workers import (DecodeService,
                                             start_prefill_worker,
                                             start_router)
from examples.example_echo_pb2 import EchoRequest, EchoResponse

BPT = KV_LAYERS * KV_DMODEL

def mk_decode(dev_url):
    server = rpc.Server()
    svc = DecodeService(
        pool_options=KvPoolOptions(bytes_per_token=BPT, num_blocks=512,
                                   block_tokens=16),
        sched_options=BatchSchedulerOptions(vocab=VOCAB, max_batch=4))
    server.add_service(svc)
    assert server.start(dev_url) == 0
    return server, svc

prefill = start_prefill_worker("ici://0")
dec_a, svc_a = mk_decode("ici://1")
router = start_router("mem://chaos-router", "ici://0", ["ici://1"])
rsvc = next(iter(router._services.values()))
epoch0 = pod.epoch(refresh=True)

# ---- elastic mechanism: the autoscaler's scale callbacks ----------------
workers = {"ici://1": (dec_a, svc_a)}
wlock = threading.Lock()

def current_load():
    with wlock:
        svcs = [s for (_, s) in workers.values()]
    if not svcs:
        return 1.0
    load = 0.0
    for s in svcs:
        d = s.scheduler.describe()
        load += (d["active"] + sum(d["pending_by_band"])) \
            / max(d["max_batch"], 1)
    return load / len(svcs)

def scale_up():
    with wlock:
        if "ici://2" in workers:
            return False
        server, svc = mk_decode("ici://2")
        workers["ici://2"] = (server, svc)
    rsvc.add_decode_target("ici://2")
    return True

def scale_down():
    with wlock:
        if "ici://2" not in workers:
            return False
        server, svc = workers.pop("ici://2")
    rsvc.remove_decode_target("ici://2")
    time.sleep(0.1)
    server.stop(grace_s=1.0)
    svc.close()
    return True

def size_fn():
    with wlock:
        return len(workers)

scaler = LoadThresholdAutoscaler(
    current_load, size_fn, scale_up, scale_down,
    options=AutoscalerOptions(high_water=0.75, low_water=0.1,
                              interval_s=0.1, samples_to_scale=2,
                              cooldown_s=1.5, min_size=1, max_size=2),
    pod=pod)
scaler.start()

# ---- traffic ------------------------------------------------------------
stop_evt = threading.Event()
stats = {"ok": 0, "shed": 0, "fail": 0, "mismatch": 0}
slock = threading.Lock()
ch_opts = rpc.ChannelOptions(timeout_ms=30000)

def client(wid, priority, pace_s, steps):
    ch = rpc.Channel(); ch.init("mem://chaos-router", options=ch_opts)
    i = 0
    while not stop_evt.is_set():
        tokens = [(wid * 31 + i * 7 + j) %% 997 for j in range(24)]
        i += 1
        cntl = rpc.Controller()
        cntl.priority = priority
        cntl.tenant = "inter" if priority == 0 else "bulk"
        resp = ch.call_method("Router.Generate", cntl,
                              EchoRequest(message=json.dumps(
                                  {"tokens": tokens, "steps": steps})),
                              EchoResponse)
        with slock:
            if cntl.failed():
                if cntl.error_code_ == rpc.errors.ELIMIT:
                    stats["shed"] += 1
                else:
                    stats["fail"] += 1
                    sys.stderr.write("CLIENT FAIL: %%s %%s\n"
                                     %% (cntl.error_code_,
                                        cntl.error_text))
            else:
                toks = json.loads(resp.message)["tokens"]
                if toks == reference_generate(tokens, steps):
                    stats["ok"] += 1
                else:
                    stats["mismatch"] += 1
        if pace_s:
            time.sleep(pace_s)
    ch.close()

# batch sessions are LONG (400 tokens): they live tens of steps in the
# roster, so 6 concurrent batch clients genuinely saturate max_batch=4
# and the load signal (roster + queue pressure) crosses the high-water
# mark — the toy decode is otherwise too fast to ever look loaded
threads = [threading.Thread(target=client, args=(w, 0, 0.05, 6))
           for w in range(2)]
threads += [threading.Thread(target=client, args=(10 + w, 3, 0.0, 400))
            for w in range(6)]
for t in threads: t.start()

def wait_for(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    raise AssertionError("timeout waiting for " + what)

try:
    # phase 1: the batch flood pushes load over the high-water mark and
    # the autoscaler scales decode ici://2 up (epoch bump via advertise)
    wait_for(lambda: scaler.scale_ups.get_value() >= 1, 30.0,
             "scale-up (load=%%s)" %% current_load())
    wait_for(lambda: "ici://2" in rsvc._router.targets(), 5.0,
             "router membership")
    time.sleep(1.0)

    # phase 2: KILL decode A mid-traffic (no drain).  In-flight
    # sessions on A fail server-side; the router re-prefills them on B
    # — zero client-visible failures.
    dec_a.stop(grace_s=0)
    svc_a.close()
    rsvc.remove_decode_target("ici://1")
    with wlock:
        workers.pop("ici://1", None)
    time.sleep(1.5)

    # phase 3: REVIVE A (restart on the same device; advertise bumps
    # the epoch again) and hand it back to the router
    dec_a2, svc_a2 = mk_decode("ici://1")
    with wlock:
        workers["ici://1"] = (dec_a2, svc_a2)
    rsvc.add_decode_target("ici://1")
    time.sleep(1.0)
finally:
    # phase 4: drop the batch flood; load falls under the low-water
    # mark and the autoscaler scales ici://2 back down
    stop_evt.set()
for t in threads: t.join()
wait_for(lambda: scaler.scale_downs.get_value() >= 1, 20.0,
         "scale-down (load=%%s)" %% current_load())

scaler.stop()
epoch1 = pod.epoch(refresh=True)
desc = pod.describe()
assert "autoscaler" in desc, "autoscaler missing from pod describe"

result = {
    "ok": stats["ok"], "shed": stats["shed"], "fail": stats["fail"],
    "mismatch": stats["mismatch"],
    "epoch_delta": epoch1 - epoch0,
    "scale_ups": scaler.scale_ups.get_value(),
    "scale_downs": scaler.scale_downs.get_value(),
    "router": rsvc.describe_serving()["router"],
}
print("CHAOS_RESULT " + json.dumps(result), flush=True)

for server, svc in list(workers.values()):
    svc.close(); server.stop()
for svc in router._services.values():
    if hasattr(svc, "close"): svc.close()
router.stop()
for svc in prefill._services.values():
    if hasattr(svc, "close"): svc.close()
prefill.stop()
pod.leave()
"""


class TestElasticChaosServing:
    def test_scale_up_kill_revive_scale_down_under_traffic(self):
        """The tier-1 elastic chaos leg: a 1-member pod serving mixed
        interactive/batch traffic scales a decode worker up on load,
        survives a KILL of the original worker, revives it, and scales
        back down — zero client-visible failures, every completion
        bit-exact, the epoch delta covering every membership
        transition."""
        from netalloc import alloc_port
        coord = f"127.0.0.1:{alloc_port('serving_chaos')}"
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env.pop("JAX_NUM_PROCESSES", None)
        proc = subprocess.Popen(
            [sys.executable, "-c",
             _ELASTIC_CHAOS_CHILD % {"repo": REPO}, coord],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        try:
            out, _ = proc.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
        assert proc.returncode == 0, out[-4000:]
        line = next(l for l in out.splitlines()
                    if l.startswith("CHAOS_RESULT "))
        res = json.loads(line[len("CHAOS_RESULT "):])
        # zero client-visible failures; batch sheds allowed (that IS
        # the absorb-the-pressure contract), mismatches never
        assert res["fail"] == 0, res
        assert res["mismatch"] == 0, res
        assert res["ok"] > 20, res
        assert res["scale_ups"] >= 1 and res["scale_downs"] >= 1, res
        # every transition moved the epoch: initial 3 advertises are in
        # epoch0; up(+1) kill-withdraw(+1) revive(+1) down(+>=1)
        assert res["epoch_delta"] >= 4, res
        # the router retried around the kill rather than surfacing it
        assert res["router"]["generate_failures"] == 0, res
