"""mcpack v2 codec + ubrpc protocol tests (reference:
test/brpc_ubrpc2pb_protocol_unittest.cpp and the mcpack2pb test suite —
golden byte layouts + in-process adaptor round trips)."""
import os
import struct

import pytest

import brpc_tpu.policy  # noqa: F401
from brpc_tpu import rpc
from brpc_tpu.rpc import errors
from brpc_tpu.codec.mcpack import (FIELD_BOOL, FIELD_INT8, FIELD_INT32,
                                   FIELD_OBJECT, FIELD_SHORT_MASK,
                                   FIELD_STRING, McpackError,
                                   mcpack_decode, mcpack_encode,
                                   dict_to_pb, pb_to_dict)
from brpc_tpu.policy.ubrpc import UbrpcAdaptor
from tests.echo_pb2 import EchoRequest, EchoResponse

_seq = [0]


def unique_name(prefix):
    _seq[0] += 1
    return f"{prefix}-{_seq[0]}"


class TestMcpackCodec:
    def test_roundtrip_scalars(self):
        doc = {"i8": 5, "neg": -7, "i32": 70000, "i64": 1 << 40,
               "u64": (1 << 63) + 1, "f": 2.5, "s": "hello", "b": True,
               "raw": b"\x00\x01", "n": None}
        assert mcpack_decode(mcpack_encode(doc)) == doc

    def test_roundtrip_nested(self):
        doc = {"obj": {"inner": {"x": 1}}, "arr": [1, "two", {"three": 3}],
               "empty_obj": {}, "empty_arr": []}
        assert mcpack_decode(mcpack_encode(doc)) == doc

    def test_golden_top_level_head(self):
        # top-level object: FieldLongHead(type=0x10, name_size=0, u32 size)
        raw = mcpack_encode({})
        assert raw[0] == FIELD_OBJECT
        assert raw[1] == 0                       # unnamed
        assert struct.unpack("<I", raw[2:6])[0] == 4   # just ItemsHead
        assert struct.unpack("<I", raw[6:10])[0] == 0  # zero items

    def test_golden_fixed_int(self):
        # {"a": 1} → item: fixed head (0x11, name_size=2) "a\0" 0x01
        raw = mcpack_encode({"a": 1})
        item = raw[10:]
        assert item[0] == FIELD_INT8
        assert item[1] == 2
        assert item[2:4] == b"a\x00"
        assert item[4] == 1

    def test_golden_short_string(self):
        # short strings: type|0x80, value includes trailing NUL
        raw = mcpack_encode({"s": "hi"})
        item = raw[10:]
        assert item[0] == (FIELD_STRING | FIELD_SHORT_MASK)
        assert item[1] == 2                      # "s\0"
        assert item[2] == 3                      # "hi\0"
        assert item[3:5] == b"s\x00"
        assert item[5:8] == b"hi\x00"

    def test_golden_bool(self):
        raw = mcpack_encode({"b": False})
        item = raw[10:]
        assert item[0] == FIELD_BOOL
        assert item[4] == 0

    def test_long_string(self):
        s = "x" * 1000
        assert mcpack_decode(mcpack_encode({"s": s}))["s"] == s

    def test_long_binary(self):
        b = bytes(range(256)) * 5
        assert mcpack_decode(mcpack_encode({"b": b}))["b"] == b

    def test_int_width_selection(self):
        for v, t in ((1, FIELD_INT8), (300, 0x12), (70000, FIELD_INT32),
                     ((1 << 40), 0x18), ((1 << 63) + 1, 0x28)):
            raw = mcpack_encode({"v": v})
            assert raw[10] == t, (v, hex(raw[10]))

    def test_isoarray_decode(self):
        # hand-build an isoarray of int32s: long head + IsoItemsHead
        items = struct.pack("<iii", 10, 20, 30)
        body = bytes([FIELD_INT32]) + items
        field = bytes([0x30, 2]) + struct.pack("<I", len(body)) + b"a\x00" \
            + body
        inner = struct.pack("<I", 1) + field
        raw = bytes([FIELD_OBJECT, 0]) + struct.pack("<I", len(inner)) + inner
        assert mcpack_decode(raw) == {"a": [10, 20, 30]}

    def test_truncated_raises(self):
        raw = mcpack_encode({"a": 1})
        with pytest.raises(McpackError):
            mcpack_decode(raw[:-2])

    def test_pb_bridge_roundtrip(self):
        req = EchoRequest(message="bridged", sleep_us=42)
        d = pb_to_dict(req)
        assert d == {"message": "bridged", "sleep_us": 42}
        req2 = dict_to_pb(mcpack_decode(mcpack_encode(d)), EchoRequest())
        assert req2.message == "bridged" and req2.sleep_us == 42

    def test_pb_bridge_maps(self):
        from tests.echo_pb2 import TagBag, EchoResponse as ER
        bag = TagBag()
        bag.counts["a"] = 1
        bag.counts["b"] = 2
        bag.nested["x"].message = "deep"
        bag.ids.extend([7, 8])
        d = pb_to_dict(bag)
        assert d["counts"] == {"a": 1, "b": 2}
        assert d["nested"] == {"x": {"message": "deep"}}
        bag2 = dict_to_pb(mcpack_decode(mcpack_encode(d)), TagBag())
        assert dict(bag2.counts) == {"a": 1, "b": 2}
        assert bag2.nested["x"].message == "deep"
        assert list(bag2.ids) == [7, 8]


class EchoService(rpc.Service):
    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()

    @rpc.method(EchoRequest, EchoResponse)
    def Fail(self, cntl, request, response, done):
        cntl.set_failed(errors.EINTERNAL, "ubrpc failure")
        done()


class TestUbrpc:
    @pytest.fixture()
    def ubrpc_server(self):
        server = rpc.Server()
        server.add_service(EchoService())
        server.add_service(UbrpcAdaptor())
        target = f"mem://{unique_name('ubrpc')}"
        assert server.start(target) == 0
        yield target
        server.stop()

    @pytest.mark.parametrize("proto", ["ubrpc_mcpack2", "ubrpc_compack"])
    def test_echo(self, ubrpc_server, proto):
        ch = rpc.Channel()
        assert ch.init(ubrpc_server,
                       options=rpc.ChannelOptions(protocol=proto)) == 0
        cntl = rpc.Controller()
        resp = ch.call_method("EchoService.Echo", cntl,
                              EchoRequest(message="ub!"), EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "ub!"

    def test_error_propagates(self, ubrpc_server):
        ch = rpc.Channel()
        assert ch.init(ubrpc_server, options=rpc.ChannelOptions(
            protocol="ubrpc_mcpack2", max_retry=0)) == 0
        cntl = rpc.Controller()
        ch.call_method("EchoService.Fail", cntl,
                       EchoRequest(message="x"), EchoResponse)
        assert cntl.failed()
        assert cntl.error_code == errors.EINTERNAL
        assert "ubrpc failure" in cntl.error_text

    def test_unknown_method(self, ubrpc_server):
        ch = rpc.Channel()
        assert ch.init(ubrpc_server, options=rpc.ChannelOptions(
            protocol="ubrpc_mcpack2", max_retry=0)) == 0
        cntl = rpc.Controller()
        ch.call_method("EchoService.Nope", cntl,
                       EchoRequest(message="x"), EchoResponse)
        assert cntl.failed()
        assert cntl.error_code == errors.ENOMETHOD

    @pytest.mark.parametrize("bad_body", [
        b"\xde\xad\xbe\xef",                               # not mcpack
        None,                                              # filled in test
    ])
    def test_malformed_response_fails_not_hangs(self, bad_body):
        # a server replying garbage (or shape-invalid mcpack) must complete
        # the call with ERESPONSE — never leave the cid locked
        from brpc_tpu.codec.mcpack import mcpack_encode as enc
        from brpc_tpu.policy.nshead import NsheadService
        if bad_body is None:
            bad_body = enc({"content": [{"id": 1, "error": {"code": {}}}]})

        class BadServer(NsheadService):
            def process_nshead_request(self, server, cntl, request,
                                       response, done):
                response.body.append(bad_body)
                done()

        server = rpc.Server()
        server.add_service(BadServer())
        target = f"mem://{unique_name('ubrpc-bad')}"
        assert server.start(target) == 0
        try:
            ch = rpc.Channel()
            assert ch.init(target, options=rpc.ChannelOptions(
                protocol="ubrpc_mcpack2", max_retry=0,
                timeout_ms=3000)) == 0
            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert cntl.failed()
            assert cntl.error_code in (errors.ERESPONSE, errors.EINTERNAL)
        finally:
            server.stop()

    def test_early_request_error_echoes_cid(self):
        # an envelope rejected before dispatch must still echo the caller's
        # id so the client reports the server's EREQUEST, not an id mismatch
        server = rpc.Server()
        server.add_service(EchoService())
        server.add_service(UbrpcAdaptor())
        target = f"mem://{unique_name('ubrpc-early')}"
        assert server.start(target) == 0
        try:
            ch = rpc.Channel()
            assert ch.init(target, options=rpc.ChannelOptions(
                protocol="ubrpc_mcpack2", max_retry=0)) == 0
            cntl = rpc.Controller()
            # missing method → server-side EREQUEST before dispatch
            ch.call_method("EchoService.", cntl,
                           EchoRequest(message="x"), EchoResponse)
            assert cntl.failed()
            assert cntl.error_code == errors.EREQUEST
            assert "service_name/method" in cntl.error_text
        finally:
            server.stop()

    def test_tcp_roundtrip(self):
        server = rpc.Server()
        server.add_service(EchoService())
        server.add_service(UbrpcAdaptor())
        assert server.start("127.0.0.1:0") == 0
        try:
            ch = rpc.Channel()
            assert ch.init(f"127.0.0.1:{server.listen_port}",
                           options=rpc.ChannelOptions(
                               protocol="ubrpc_mcpack2")) == 0
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="ub-tcp"),
                                  EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "ub-tcp"
        finally:
            server.stop()


class TestMcpackGenerator:
    """tools/mcpack2py.py — the generated-code half of mcpack2pb
    (reference generator.cpp): emitted per-message codecs must produce
    bytes IDENTICAL to the runtime descriptor bridge, both formats."""

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _gen(self, extra=()):
        import sys as _sys
        tools = os.path.join(self.REPO, "tools")
        if tools not in _sys.path:
            _sys.path.insert(0, tools)
        from mcpack2py import generate_module_source
        from tests.echo_pb2 import EchoRequest, EchoResponse, TagBag
        src = generate_module_source(
            [EchoRequest, EchoResponse, TagBag, *extra])
        ns = {}
        exec(compile(src, "<generated>", "exec"), ns)
        return ns, src

    def _corpus(self):
        from tests.echo_pb2 import EchoRequest, TagBag
        m1 = EchoRequest(message="hello", sleep_us=250)
        m2 = EchoRequest()                       # all defaults
        m3 = TagBag()
        m3.counts["alpha"] = 3
        m3.counts["beta"] = -7
        m3.nested["x"].message = "deep"
        m3.ids.extend([1, 2, 1 << 40])
        return [("EchoRequest", m1), ("EchoRequest", m2), ("TagBag", m3)]

    def test_generated_bytes_match_runtime_bridge(self):
        from brpc_tpu.codec.mcpack import pb_to_mcpack
        ns, _src = self._gen()
        for name, msg in self._corpus():
            for compack in (False, True):
                gen = ns[f"encode_{name}"](msg, compack=compack)
                ref = pb_to_mcpack(msg, compack=compack)
                assert gen == ref, (name, compack, gen.hex(), ref.hex())

    def test_generated_decode_roundtrips(self):
        ns, _src = self._gen()
        for name, msg in self._corpus():
            blob = ns[f"encode_{name}"](msg)
            out = ns[f"decode_{name}"](blob, type(msg)())
            assert out == msg, (name, out, msg)

    def test_generated_source_is_static(self):
        """The emitted code is straight-line field access — no runtime
        descriptor walks (the point of the generator)."""
        _ns, src = self._gen()
        assert "DESCRIPTOR" not in src
        assert "ListFields" not in src
        assert "def encode_TagBag" in src
        assert '_dict_brpc_tpu_test_EchoResponse' in src  # nested closure

    def test_explicit_presence_fields(self):
        """proto3 `optional` and oneof scalars set to their DEFAULT value
        must still be emitted (HasField semantics, not truthiness) —
        byte-identical to the runtime bridge."""
        from brpc_tpu.codec.mcpack import pb_to_mcpack
        from tests.presence_pb2 import PresenceProbe
        ns, _src = self._gen(extra=[PresenceProbe])
        cases = []
        m = PresenceProbe()
        m.flag = 0                      # explicitly set to default
        m.pick_num = 0                  # oneof member at default
        cases.append(m)
        m2 = PresenceProbe(name="n")    # flag unset, oneof = pick_str
        m2.pick_str = ""
        cases.append(m2)
        cases.append(PresenceProbe())   # nothing set
        for msg in cases:
            gen = ns["encode_PresenceProbe"](msg)
            ref = pb_to_mcpack(msg)
            assert gen == ref, (msg, gen.hex(), ref.hex())
            out = ns["decode_PresenceProbe"](gen, PresenceProbe())
            assert out == msg

    def test_cli_writes_module(self, tmp_path):
        import subprocess, sys as _sys
        out = tmp_path / "gen_codec.py"
        proc = subprocess.run(
            [_sys.executable, "tools/mcpack2py.py",
             "tests.echo_pb2:EchoRequest", "-o", str(out)],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert proc.returncode == 0, proc.stderr
        assert "encode_EchoRequest" in out.read_text()
