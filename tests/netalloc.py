"""Seeded port / UDS-path allocator (no jax dependency).

N-process tests (the chaos harness, the pod suite, the fabric bench)
need coordinator ports and unix-socket paths that (a) are DETERMINISTIC
per test — a failure reproduces with the same addresses — and (b) can't
collide when several pytest processes run the same suite on one host
(parallel CI).  The allocator hashes (tag, pid) into a seeded probe
sequence and bind-verifies each candidate, so two workers land on
disjoint ports by seed and the bind check catches any residual clash.

Lives outside conftest.py so the N-process harnesses in test_pod.py can
be imported by ``__graft_entry__.dryrun_multichip`` from a parent that
lacks the 8-device virtual mesh conftest asserts at import time (the
child processes set up their own jax environments).
"""
import hashlib
import os
import socket as _socket
import tempfile

_PORT_LO, _PORT_HI = 21000, 59000


def alloc_port(tag: str = "") -> int:
    """A free TCP port, seeded by (tag, pid): deterministic per test
    within a run, disjoint across parallel pytest processes."""
    seed = f"{tag}|{os.getpid()}"
    h = int.from_bytes(hashlib.sha1(seed.encode()).digest()[:4], "big")
    span = _PORT_HI - _PORT_LO
    for i in range(256):
        port = _PORT_LO + (h + i * 131) % span
        s = _socket.socket()
        try:
            s.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", port))
            return port
        except OSError:
            continue
        finally:
            s.close()
    s = _socket.socket()            # exhausted the seeded probes: any port
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def alloc_uds(tag: str = "") -> str:
    """A unix-socket path seeded the same way (unused on disk)."""
    seed = f"{tag}|{os.getpid()}"
    h = hashlib.sha1(seed.encode()).hexdigest()[:12]
    for i in range(64):
        path = os.path.join(tempfile.gettempdir(),
                            f"brpc_tpu_{h}_{i}.sock")
        if not os.path.exists(path):
            return path
    return tempfile.mktemp(prefix=f"brpc_tpu_{h}_", suffix=".sock")
