"""Thrift framed protocol tests (reference WITH_THRIFT support,
test pattern: codec golden checks + in-process server)."""
import pytest

import brpc_tpu.policy
from brpc_tpu import rpc
from brpc_tpu.policy import thrift as tproto
from brpc_tpu.policy.thrift import TType, ThriftMessage, ThriftService

_seq = [6000]


def unique(p):
    _seq[0] += 1
    return f"{p}-{_seq[0]}"


ARG_SPEC = {1: ("name", TType.STRING), 2: ("id", TType.I32),
            3: ("scores", TType.LIST, (TType.DOUBLE, None))}
RESULT_SPEC = {1: ("greeting", TType.STRING), 2: ("total", TType.DOUBLE)}


class TestCodec:
    def test_struct_roundtrip(self):
        w = tproto._Writer()
        values = {"name": b"alice", "id": 7, "scores": [1.5, 2.5]}
        tproto.write_struct(w, values, ARG_SPEC)
        out = tproto.read_struct(tproto._Reader(w.getvalue()), ARG_SPEC)
        assert out["name"] == b"alice"
        assert out["id"] == 7
        assert out["scores"] == [1.5, 2.5]

    def test_nested_struct_and_map(self):
        inner = {1: ("x", TType.I64)}
        spec = {1: ("child", TType.STRUCT, inner),
                2: ("tags", TType.MAP,
                    ((TType.STRING, None), (TType.I32, None)))}
        w = tproto._Writer()
        tproto.write_struct(w, {"child": {"x": 99},
                                "tags": {b"a": 1, b"b": 2}}, spec)
        out = tproto.read_struct(tproto._Reader(w.getvalue()), spec)
        assert out["child"]["x"] == 99
        assert out["tags"] == {b"a": 1, b"b": 2}

    def test_unknown_fields_skipped(self):
        w = tproto._Writer()
        tproto.write_struct(w, {"name": b"n", "id": 3}, ARG_SPEC)
        # read with a narrower spec: unknown fields must be skipped safely
        out = tproto.read_struct(tproto._Reader(w.getvalue()),
                                 {2: ("id", TType.I32)})
        assert out == {"id": 3}

    def test_message_framing(self):
        raw = tproto.pack_message("Greet", tproto.MSG_CALL, 42, b"PAYLOAD")
        import struct
        assert struct.unpack(">i", raw[:4])[0] == len(raw) - 4
        r = tproto._Reader(raw[4:])
        ver = r.u32()
        assert (ver & 0xFF) == tproto.MSG_CALL
        assert r.string() == b"Greet"
        assert r.i32() == 42


def make_service():
    svc = ThriftService()

    def greet(args):
        total = sum(args.get("scores", []))
        return {"greeting": f"hello {args['name'].decode()}",
                "total": total}

    svc.add_method("Greet", greet, ARG_SPEC, RESULT_SPEC)
    return svc


class TestThriftEndToEnd:
    def _start(self):
        server = rpc.Server()
        server.add_service(make_service())
        name = unique("thrift")
        assert server.start(f"mem://{name}") == 0
        ch = rpc.Channel()
        ch.init(f"mem://{name}",
                options=rpc.ChannelOptions(protocol="thrift",
                                           timeout_ms=5000))
        return server, ch

    def test_call(self):
        server, ch = self._start()
        try:
            req = ThriftMessage("Greet",
                                {"name": "bob", "id": 1,
                                 "scores": [1.0, 2.0, 3.5]},
                                ARG_SPEC, RESULT_SPEC)
            cntl = rpc.Controller()
            resp = ch.call_method("Greet", cntl, req, None)
            assert not cntl.failed(), cntl.error_text
            assert resp.values["greeting"] == b"hello bob"
            assert resp.values["total"] == 6.5
        finally:
            server.stop()

    def test_unknown_method_is_exception(self):
        server, ch = self._start()
        try:
            req = ThriftMessage("Nope", {}, {}, RESULT_SPEC)
            cntl = rpc.Controller()
            ch.call_method("Nope", cntl, req, None)
            assert cntl.failed()
            assert "unknown method" in cntl.error_text
        finally:
            server.stop()

    def test_handler_exception_propagates(self):
        svc = ThriftService()
        svc.add_method("Boom", lambda args: 1 / 0, {}, RESULT_SPEC)
        server = rpc.Server()
        server.add_service(svc)
        name = unique("thrift")
        assert server.start(f"mem://{name}") == 0
        try:
            ch = rpc.Channel()
            ch.init(f"mem://{name}",
                    options=rpc.ChannelOptions(protocol="thrift",
                                               timeout_ms=5000))
            cntl = rpc.Controller()
            ch.call_method("Boom", cntl, ThriftMessage("Boom", {}, {}, {}),
                           None)
            assert cntl.failed()
            assert "ZeroDivisionError" in cntl.error_text
        finally:
            server.stop()
