"""Flagship integration: channel + naming + LB + circuit breaker +
health-check revival across server death (the reference's multi-server
in-process cluster pattern, SURVEY.md §4)."""
import threading
import time

import pytest

import brpc_tpu.policy
from brpc_tpu import rpc
from brpc_tpu.butil import flags as _flags
from brpc_tpu.rpc import errors
from tests.echo_pb2 import EchoRequest, EchoResponse

_seq = [8000]


def unique(p):
    _seq[0] += 1
    return f"{p}-{_seq[0]}"


class TaggedEcho(rpc.Service):
    SERVICE_NAME = "EchoService"

    def __init__(self, tag):
        self.tag = tag
        self.calls = 0

    @rpc.method(EchoRequest, EchoResponse)
    def Echo(self, cntl, request, response, done):
        self.calls += 1
        response.message = self.tag
        done()


class TestClusterLifecycle:
    def test_lb_spread_failover_and_revival(self, tmp_path):
        names = [unique("cluster") for _ in range(3)]
        servers = {}
        svcs = {}
        for i, name in enumerate(names):
            s = rpc.Server()
            svc = TaggedEcho(f"s{i}")
            s.add_service(svc)
            assert s.start(f"mem://{name}") == 0
            servers[name] = s
            svcs[name] = svc
        listing = tmp_path / "cluster"
        listing.write_text("".join(f"mem://{n}\n" for n in names))

        ch = rpc.Channel()
        assert ch.init(f"file://{listing}", "rr",
                       rpc.ChannelOptions(timeout_ms=500, max_retry=3)) == 0

        def call_ok():
            cntl = rpc.Controller()
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="x"), EchoResponse)
            return (not cntl.failed()), (resp.message if resp else None)

        # 1) traffic spreads over all three
        results = [call_ok() for _ in range(30)]
        assert all(ok for ok, _ in results)
        assert all(svc.calls > 0 for svc in svcs.values())

        # 2) kill one server: every call still succeeds via retry+exclusion
        dead = names[0]
        servers[dead].stop()
        ok_count = sum(1 for _ in range(30) if call_ok()[0])
        assert ok_count == 30

        # 3) revive it (same name): health check revives the endpoint and
        #    traffic returns
        s = rpc.Server()
        svc_new = TaggedEcho("s0-reborn")
        s.add_service(svc_new)
        assert s.start(f"mem://{dead}") == 0
        servers[dead] = s
        deadline = time.time() + 10
        while svc_new.calls == 0 and time.time() < deadline:
            call_ok()
            time.sleep(0.02)
        assert svc_new.calls > 0, "revived server never got traffic back"
        for s in servers.values():
            s.stop()

    def test_locality_aware_channel(self, tmp_path):
        names = [unique("la") for _ in range(2)]

        class SlowEcho(TaggedEcho):
            def __init__(self, tag, delay):
                super().__init__(tag)
                self.delay = delay

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                self.calls += 1
                time.sleep(self.delay)
                response.message = self.tag
                done()

        servers = []
        fast = SlowEcho("fast", 0.0)
        slow = SlowEcho("slow", 0.02)
        for name, svc in zip(names, (fast, slow)):
            s = rpc.Server()
            s.add_service(svc)
            assert s.start(f"mem://{name}") == 0
            servers.append(s)
        listing = tmp_path / "cluster"
        listing.write_text("".join(f"mem://{n}\n" for n in names))
        ch = rpc.Channel()
        assert ch.init(f"file://{listing}", "la",
                       rpc.ChannelOptions(timeout_ms=2000)) == 0
        for _ in range(60):
            cntl = rpc.Controller()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="x"), EchoResponse)
        assert fast.calls > slow.calls   # locality-aware shifted traffic
        for s in servers:
            s.stop()


class TestCancel:
    def test_cancel_inflight(self):
        name = unique("cancel")

        class SlowService(rpc.Service):
            SERVICE_NAME = "EchoService"

            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                time.sleep(0.3)
                response.message = "late"
                done()

        server = rpc.Server()
        server.add_service(SlowService())
        assert server.start(f"mem://{name}") == 0
        try:
            ch = rpc.Channel()
            ch.init(f"mem://{name}",
                    options=rpc.ChannelOptions(timeout_ms=5000, max_retry=0))
            cntl = rpc.Controller()
            done_evt = threading.Event()
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="x"), EchoResponse,
                           lambda c: done_evt.set())
            time.sleep(0.05)
            cntl.cancel()
            assert done_evt.wait(5)
            assert cntl.error_code == errors.ECANCELED
            time.sleep(0.4)      # late response must be dropped silently
        finally:
            server.stop()
