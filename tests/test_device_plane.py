"""Device data plane: payloads cross the mesh through compiled XLA
programs (ici/device_plane.py — the rdma_endpoint.cpp:771 analogue).

Covers the QP lifecycle (post_send → descriptor → post_recv rendezvous →
completion), program-cache reuse, both kernels (shard_map+ppermute and
the Pallas remote-DMA variant in interpret mode), the match-timeout
reaper, chaos-forced degradation + recovery, and the full RPC stack
crossing the 8-device virtual CPU mesh through the plane with no host
staging in the datapath (asserted on the transfer/byte counters).
"""
import sys
import time

import numpy as np
import pytest

import brpc_tpu.policy  # noqa: F401  (registers protocols)
from brpc_tpu import rpc
from brpc_tpu.butil import flags as fl
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.ici import device_plane as dp
from brpc_tpu.ici.mesh import IciMesh
from brpc_tpu.rpc import fault_injection as fi

sys.path.insert(0, "tests")
from echo_pb2 import EchoRequest, EchoResponse  # noqa: E402


@pytest.fixture()
def plane_on():
    """Engage the plane on this host-memory mesh with a low threshold,
    restoring every flag after."""
    saved = {n: fl.get_flag(n) for n in
             ("ici_device_plane", "ici_device_plane_host_mesh",
              "ici_device_plane_threshold", "ici_device_plane_kernel",
              "ici_device_plane_match_timeout_s")}
    fl.set_flag("ici_device_plane", True)
    fl.set_flag("ici_device_plane_host_mesh", True)
    fl.set_flag("ici_device_plane_threshold", 1024)
    yield dp.plane()
    for n, v in saved.items():
        fl.set_flag(n, v)


def _payload(nbytes, dev, mod=251):
    import jax
    import jax.numpy as jnp
    arr = jax.device_put(jnp.arange(nbytes, dtype=jnp.uint8) % mod,
                         IciMesh.default().device(dev))
    jax.block_until_ready(arr)
    return arr


class TestQPLifecycle:
    def test_post_recv_rendezvous_moves_payload(self, plane_on):
        plane = plane_on
        arr = _payload(8192, 2)
        t = plane.post_send(arr, 2, 5)
        assert t.state == dp.POSTED
        assert plane.pending_sends() >= 1
        got = plane.post_recv(t.uuid)
        assert got is t                      # both sides share the WR
        assert t.wait(30) == 0
        assert t.state == dp.COMPLETE
        np.testing.assert_array_equal(np.asarray(t.out), np.asarray(arr))
        # delivered RESIDENT on the destination chip
        assert dp.mesh_index_of(t.out) == 5
        # the lifecycle timeline was recorded (rpcz annotation source)
        d = t.describe()
        assert d["posted_to_matched_us"] >= 0
        assert d["matched_to_complete_us"] >= 0

    def test_source_pin_releases_exactly_once_at_completion(self, plane_on):
        plane = plane_on
        arr = _payload(4096, 1)
        released = []
        t = plane.post_send(arr, 1, 3)
        t.add_source_release(lambda: released.append(1))
        assert released == []               # pinned while POSTED
        plane.post_recv(t.uuid)
        assert t.wait(30) == 0
        assert released == [1]
        # registering after completion fires immediately, still once each
        t.add_source_release(lambda: released.append(2))
        assert released == [1, 2]

    def test_counters_track_bytes_and_transfers(self, plane_on):
        plane = plane_on
        before = plane.stats()
        arr = _payload(2048, 0)
        t = plane.post_send(arr, 0, 4)
        plane.post_recv(t.uuid)
        assert t.wait(30) == 0
        after = plane.stats()
        assert after["transfers"] == before["transfers"] + 1
        assert after["bytes_sent"] == before["bytes_sent"] + 2048
        assert after["bytes_recv"] == before["bytes_recv"] + 2048

    def test_same_device_post_is_refused(self, plane_on):
        arr = _payload(2048, 3)
        with pytest.raises(dp.DevicePlaneError):
            plane_on.post_send(arr, 3, 3)


class TestProgramCache:
    def test_repeated_shapes_reuse_the_compiled_program(self, plane_on):
        plane = plane_on
        misses0 = plane.stats()["program_cache_misses"]
        for _ in range(4):
            arr = _payload(3072, 1)
            t = plane.post_send(arr, 1, 2)
            plane.post_recv(t.uuid)
            assert t.wait(30) == 0
        # one compile for four transfers of the same (shape, route)
        assert plane.stats()["program_cache_misses"] == misses0 + 1
        # a new size on the same route compiles exactly one more
        arr = _payload(5120, 1)
        t = plane.post_send(arr, 1, 2)
        plane.post_recv(t.uuid)
        assert t.wait(30) == 0
        assert plane.stats()["program_cache_misses"] == misses0 + 2

    def test_pallas_remote_dma_kernel_variant(self, plane_on):
        """The hand-scheduled make_async_remote_copy kernel (interpret
        mode on this CPU mesh — the exact TPU control flow)."""
        plane = plane_on
        fl.set_flag("ici_device_plane_kernel", "pallas")
        arr = _payload(2048, 2)
        t = plane.post_send(arr, 2, 6)
        plane.post_recv(t.uuid)
        assert t.wait(60) == 0
        np.testing.assert_array_equal(np.asarray(t.out), np.asarray(arr))
        assert dp.mesh_index_of(t.out) == 6


class TestFailureModes:
    def test_match_timeout_fails_only_that_transfer(self, plane_on):
        """A posted send whose recv never arrives (peer died between
        descriptor and rendezvous) reaps after the match timeout: THAT
        transfer fails and its pin releases; the plane keeps serving."""
        plane = plane_on
        fl.set_flag("ici_device_plane_match_timeout_s", 0.05)
        released = []
        orphan = plane.post_send(_payload(2048, 1), 1, 7)
        orphan.add_source_release(lambda: released.append(1))
        time.sleep(0.1)
        timeouts0 = plane.stats()["match_timeouts"]
        plane._sweep_stale()
        assert orphan.state == dp.FAILED
        assert "match timeout" in orphan.error
        assert orphan.wait(1) != 0
        assert released == [1]
        assert plane.stats()["match_timeouts"] == timeouts0 + 1
        with pytest.raises(KeyError):
            plane.post_recv(orphan.uuid)    # reaped: rendezvous refused
        # an unrelated transfer is untouched
        fl.set_flag("ici_device_plane_match_timeout_s", 30.0)
        t = plane.post_send(_payload(2048, 1), 1, 7)
        plane.post_recv(t.uuid)
        assert t.wait(30) == 0

    def test_chaos_forced_post_failure_degrades_then_recovers(
            self, plane_on):
        plane = plane_on
        f0 = plane.stats()["fallbacks"]
        arr = _payload(2048, 3)
        with fi.inject_fabric(
                fi.FabricFaultPlan(device_plane_fail_posts=2)) as plan:
            for _ in range(2):
                with pytest.raises(dp.DevicePlaneError):
                    plane.post_send(arr, 3, 4)
            # budget exhausted: the plane serves again even mid-plan
            t = plane.post_send(arr, 3, 4)
            plane.post_recv(t.uuid)
            assert t.wait(30) == 0
        assert plan.injected["device_plane"] == 2
        assert plane.stats()["fallbacks"] == f0 + 2

    def test_ineligible_payloads_never_touch_the_plane(self, plane_on):
        assert not dp.eligible(512)          # below threshold
        fl.set_flag("ici_device_plane", False)
        assert not dp.eligible(1 << 20)      # master switch off
        fl.set_flag("ici_device_plane", True)
        fl.set_flag("ici_device_plane_host_mesh", False)
        assert not dp.eligible(1 << 20)      # host mesh not opted in


class TestSocketIntegration:
    """A device-resident payload written to a Socket crosses the mesh
    through the compiled program — the acceptance criterion."""

    def _echo_server(self, addr):
        class EchoService(rpc.Service):
            @rpc.method(EchoRequest, EchoResponse)
            def Echo(self, cntl, request, response, done):
                response.message = request.message
                if len(cntl.request_attachment):
                    cntl.response_attachment.append(cntl.request_attachment)
                done()

        opts = rpc.ServerOptions()
        opts.usercode_inline = True
        server = rpc.Server(opts)
        server.add_service(EchoService())
        assert server.start(addr) == 0
        return server

    def test_rpc_attachment_crosses_via_compiled_program(self, plane_on):
        """Full RPC stack (native fast plane): a non-resident 64KB
        attachment relocates through the device plane both directions,
        asserted on the transfer/byte counters — no device_put staging."""
        plane = plane_on
        server = self._echo_server("ici://0")
        try:
            ch = rpc.Channel()
            ch.init("ici://0", options=rpc.ChannelOptions(
                timeout_ms=30000, max_retry=0))
            n = 64 * 1024
            payload = _payload(n, 1)
            before = plane.stats()
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            assert cntl.request_attachment.device_bytes() == n
            resp = ch.call_method("EchoService.Echo", cntl,
                                  EchoRequest(message="dp"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "dp"
            got = np.frombuffer(cntl.response_attachment.to_bytes(),
                                dtype=np.uint8)
            np.testing.assert_array_equal(got, np.asarray(payload))
            after = plane.stats()
            # request leg (1 -> 0) and response bounce (0 -> 1)
            assert after["transfers"] >= before["transfers"] + 2
            assert after["bytes_sent"] >= before["bytes_sent"] + 2 * n
        finally:
            server.stop()

    def test_small_payload_keeps_the_device_put_path(self, plane_on):
        """Below-threshold payloads keep the lower-fixed-cost path; the
        plane's counters must not move."""
        plane = plane_on
        server = self._echo_server("ici://1")
        try:
            ch = rpc.Channel()
            ch.init("ici://1", options=rpc.ChannelOptions(
                timeout_ms=30000, max_retry=0))
            before = plane.stats()["transfers"]
            payload = _payload(512, 2)       # < 1024 threshold
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="s"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert plane.stats()["transfers"] == before
        finally:
            server.stop()

    def test_chaos_refusal_falls_back_to_device_put_rpc_succeeds(
            self, plane_on):
        """Chaos-forced plane death: the RPC still completes (device_put
        fallback in the same frame), counted as a fallback; with the
        plan gone the next RPC rides the plane again — degrade AND
        recover, no socket death."""
        plane = plane_on
        server = self._echo_server("ici://2")
        try:
            ch = rpc.Channel()
            ch.init("ici://2", options=rpc.ChannelOptions(
                timeout_ms=30000, max_retry=0))
            payload = _payload(8192, 3)
            f0 = plane.stats()["fallbacks"]
            t0 = plane.stats()["transfers"]
            with fi.inject_fabric(
                    fi.FabricFaultPlan(device_plane_fail_posts=64)):
                cntl = rpc.Controller()
                cntl.request_attachment.append_device_array(payload)
                ch.call_method("EchoService.Echo", cntl,
                               EchoRequest(message="c"), EchoResponse)
                assert not cntl.failed(), cntl.error_text
                got = np.frombuffer(cntl.response_attachment.to_bytes(),
                                    dtype=np.uint8)
                np.testing.assert_array_equal(got, np.asarray(payload))
            assert plane.stats()["fallbacks"] > f0
            assert plane.stats()["transfers"] == t0      # plane bypassed
            # plan uninstalled: the same route uses the plane again
            cntl = rpc.Controller()
            cntl.request_attachment.append_device_array(payload)
            ch.call_method("EchoService.Echo", cntl,
                           EchoRequest(message="r"), EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert plane.stats()["transfers"] > t0
        finally:
            server.stop()

    def test_python_ici_socket_routes_through_plane(self, plane_on):
        """The Python-plane IciSocket (streaming / non-tpu_std wire):
        a DEVICE block in a written IOBuf crosses via the plane and is
        delivered as a resident DEVICE block, in order."""
        from brpc_tpu.ici.transport import ici_connect, ici_listen, \
            ici_unlisten
        plane = plane_on
        mesh = IciMesh.default()
        accepted = []
        ici_listen(7, accepted.append, mesh)
        try:
            client = ici_connect(mesh.endpoint(7), local_dev=4)
            serv = accepted[0]
            n = 16 * 1024
            payload = _payload(n, 4)
            before = plane.stats()["transfers"]
            buf = IOBuf(b"hdr:")
            buf.append_device_array(payload)
            assert client.write(buf) == 0
            deadline = time.monotonic() + 10
            while len(serv._inbox) < 4 + n and time.monotonic() < deadline:
                time.sleep(0.01)
            assert len(serv._inbox) == 4 + n
            assert plane.stats()["transfers"] == before + 1
            # the delivered device ref is resident on the server's chip
            dev_refs = serv._inbox.device_refs()
            assert len(dev_refs) == 1
            assert dp.mesh_index_of(dev_refs[0].block.data) == 7
            got = serv._inbox.to_bytes()
            assert got[:4] == b"hdr:"
            np.testing.assert_array_equal(
                np.frombuffer(got[4:], dtype=np.uint8), np.asarray(payload))
        finally:
            from brpc_tpu.rpc import errors
            for s in accepted + ([client] if "client" in locals() else []):
                s.set_failed(errors.ECLOSE, "test teardown")
            ici_unlisten(7)


class TestBuiltinPage:
    def test_ici_page_reports_plane_stats(self, plane_on):
        server = rpc.Server()
        from brpc_tpu.rpc.builtin.services import BuiltinDispatcher
        disp = BuiltinDispatcher(server)
        ctype, body = disp.dispatch("ici")
        assert ctype == "application/json"
        import json
        page = json.loads(body)
        assert "device_plane" in page
        assert "transfers" in page["device_plane"]
        assert "transport" in page
